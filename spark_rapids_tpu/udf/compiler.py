"""UDF compiler: CPython bytecode -> expression IR.

TPU-native analog of the reference's udf-compiler module, which decompiles
Scala lambda *JVM* bytecode into Catalyst expressions so UDFs run as
regular accelerated expressions instead of opaque black boxes
(ref: udf-compiler/.../LambdaReflection.scala:35, CFG.scala:44-137,
Instruction.scala:199-954, State.scala:79, CatalystExpressionBuilder.scala:45).

Here the user language is Python, so we symbolically execute *CPython*
bytecode (via `dis`).  Values on the simulated operand stack are nodes of
our expression IR; a RETURN_VALUE yields the compiled expression tree.
Conditional jumps fork the interpreter down both arms and merge results
with `If(cond, then, else)` — the same branch-to-expression conversion the
reference performs on JVM ifeq/goto (ref Instruction.scala, case IFEQ).

Compilation is best-effort: anything outside the supported subset (loops,
closures over mutable state, unknown calls, side effects) raises
`UdfCompileError`, and the caller falls back to running the UDF as an
opaque Python function through ArrowEvalPythonExec — exactly the
reference's fallback contract (compile failure leaves the original UDF in
place, LogicalPlanRules.scala:29).
"""

from __future__ import annotations

import dis
import math
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import types as t
from ..expr import arithmetic as ar
from ..expr import cast as ca
from ..expr import conditional as cond
from ..expr import mathexpr as mx
from ..expr import predicates as pr
from ..expr import strings as st
from ..expr.core import Expression, Literal


class UdfCompileError(Exception):
    """The function is outside the compilable subset."""


# Python value -> IR literal (only immutable scalar constants)
def _const(value: Any) -> Expression:
    if value is None or isinstance(value, (bool, int, float, str)):
        return Literal(value)
    raise UdfCompileError(f"unsupported constant {value!r}")


def _add(lhs: Expression, rhs: Expression) -> Expression:
    if isinstance(lhs.data_type(), t.StringType) or \
            isinstance(rhs.data_type(), t.StringType):
        return st.Concat(lhs, rhs)
    return ar.Add(lhs, rhs)


def _binary(opname: str, lhs: Expression, rhs: Expression) -> Expression:
    if opname in ("+", "+="):
        return _add(lhs, rhs)
    if opname in ("-", "-="):
        return ar.Subtract(lhs, rhs)
    if opname in ("*", "*="):
        if isinstance(rhs.data_type(), t.IntegralType) and \
                isinstance(lhs.data_type(), t.StringType):
            return st.StringRepeat(lhs, rhs)
        return ar.Multiply(lhs, rhs)
    if opname in ("/", "/="):
        # Python / is true division = Spark Divide on doubles
        return ar.Divide(_as_double(lhs), _as_double(rhs))
    if opname in ("//", "//="):
        return ar.IntegralDivide(lhs, rhs)
    if opname in ("%", "%="):
        return ar.Remainder(lhs, rhs)
    if opname in ("**", "**="):
        return mx.Pow(lhs, rhs)
    raise UdfCompileError(f"unsupported binary op {opname!r}")


def _as_double(e: Expression) -> Expression:
    if isinstance(e.data_type(), t.DoubleType):
        return e
    return ca.Cast(e, t.DOUBLE)


_COMPARES = {
    "==": pr.EqualTo,
    "!=": lambda a, b: pr.Not(pr.EqualTo(a, b)),
    "<": pr.LessThan,
    "<=": pr.LessThanOrEqual,
    ">": pr.GreaterThan,
    ">=": pr.GreaterThanOrEqual,
}


# -- call translation --------------------------------------------------------

def _call_builtin(fn: Any, args: List[Expression]) -> Expression:
    import builtins
    if fn is builtins.abs and len(args) == 1:
        return ar.Abs(args[0])
    if fn is builtins.max and len(args) >= 2:
        return ar.Greatest(*args)
    if fn is builtins.min and len(args) >= 2:
        return ar.Least(*args)
    if fn is builtins.len and len(args) == 1:
        return st.Length(args[0])
    if fn is builtins.float and len(args) == 1:
        return ca.Cast(args[0], t.DOUBLE)
    if fn is builtins.int and len(args) == 1:
        # Python int() truncates toward zero = Spark cast to long
        return ca.Cast(args[0], t.LONG)
    if fn is builtins.bool and len(args) == 1:
        return ca.Cast(args[0], t.BOOLEAN)
    if fn is builtins.str and len(args) == 1:
        return ca.Cast(args[0], t.STRING)
    if fn is builtins.round:
        if len(args) == 1:
            # Python round() is HALF_EVEN = Spark bround(x, 0)
            return mx.BRound(args[0], 0)
        if len(args) == 2 and isinstance(args[1], Literal) and \
                isinstance(args[1].value, int):
            return mx.BRound(args[0], args[1].value)
    raise UdfCompileError(f"unsupported builtin {fn!r}")


_MATH_FNS = {
    math.sqrt: mx.Sqrt, math.exp: mx.Exp, math.expm1: mx.Expm1,
    math.sin: mx.Sin, math.cos: mx.Cos, math.tan: mx.Tan,
    math.asin: mx.Asin, math.acos: mx.Acos, math.atan: mx.Atan,
    math.sinh: mx.Sinh, math.cosh: mx.Cosh, math.tanh: mx.Tanh,
    math.log10: mx.Log10, math.log1p: mx.Log1p,
    math.floor: mx.Floor, math.ceil: mx.Ceil,
    math.degrees: mx.ToDegrees, math.radians: mx.ToRadians,
    math.fabs: ar.Abs,
}


def _call_function(fn: Any, args: List[Expression]) -> Expression:
    if fn in _MATH_FNS:
        if len(args) != 1:
            raise UdfCompileError(f"{fn} arity")
        return _MATH_FNS[fn](args[0])
    if fn is math.log:
        if len(args) == 1:
            return mx.Log(args[0])
        raise UdfCompileError("math.log with base")
    if fn is math.pow:
        return mx.Pow(args[0], args[1])
    if fn is math.atan2:
        return mx.Atan2(args[0], args[1])
    import builtins
    if getattr(builtins, getattr(fn, "__name__", ""), None) is fn:
        return _call_builtin(fn, args)
    raise UdfCompileError(f"unsupported call target {fn!r}")


def _call_method(obj: Expression, name: str, args: List[Expression]) -> Expression:
    if not isinstance(obj.data_type(), t.StringType):
        raise UdfCompileError(f"method {name!r} on non-string")
    if name == "upper" and not args:
        return st.Upper(obj)
    if name == "lower" and not args:
        return st.Lower(obj)
    if name == "strip" and not args:
        return st.Trim(obj)
    if name == "lstrip" and not args:
        return st.TrimLeft(obj)
    if name == "rstrip" and not args:
        return st.TrimRight(obj)
    if name == "startswith" and len(args) == 1:
        return st.StartsWith(obj, args[0])
    if name == "endswith" and len(args) == 1:
        return st.EndsWith(obj, args[0])
    if name == "replace" and len(args) == 2:
        return st.StringReplace(obj, args[0], args[1])
    if name == "find" and len(args) == 1:
        # str.find is 0-based, -1 on miss; locate is 1-based, 0 on miss
        return ar.Subtract(st.StringLocate(args[0], obj, Literal(1)),
                           Literal(1))
    raise UdfCompileError(f"unsupported string method {name!r}")


# py3.10 has per-operator binary opcodes; 3.11+ folds them into BINARY_OP
# with an argrepr symbol — map the legacy names onto the same symbols so
# one _binary() serves every interpreter version.
_LEGACY_BINARY = {
    "BINARY_ADD": "+", "INPLACE_ADD": "+",
    "BINARY_SUBTRACT": "-", "INPLACE_SUBTRACT": "-",
    "BINARY_MULTIPLY": "*", "INPLACE_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "INPLACE_TRUE_DIVIDE": "/",
    "BINARY_FLOOR_DIVIDE": "//", "INPLACE_FLOOR_DIVIDE": "//",
    "BINARY_MODULO": "%", "INPLACE_MODULO": "%",
    "BINARY_POWER": "**", "INPLACE_POWER": "**",
}

# 3.11+ LOAD_GLOBAL carries a "push NULL first" flag in the low arg bit;
# on 3.10 the arg is just a name index and must not be misread as a flag
_LOAD_GLOBAL_PUSHES_NULL = sys.version_info >= (3, 11)


# -- stack markers -----------------------------------------------------------

class _Null:
    """CPython NULL stack sentinel (call protocol)."""


class _Method:
    """A bound-method load: (receiver expression, method name)."""

    def __init__(self, obj: Expression, name: str):
        self.obj = obj
        self.name = name


class _Global:
    """A loaded module/global that is not yet an expression (e.g. math)."""

    def __init__(self, value: Any):
        self.value = value


# -- the symbolic interpreter ------------------------------------------------

_MAX_STEPS = 4000


class _Interp:
    def __init__(self, code, arg_exprs: Dict[str, Expression],
                 globals_: Dict[str, Any]):
        self.instructions = list(dis.get_instructions(code))
        self.by_offset = {ins.offset: i for i, ins in
                          enumerate(self.instructions)}
        self.arg_exprs = arg_exprs
        self.globals = globals_
        self.steps = 0

    def run(self, idx: int, stack: List[Any],
            local_vars: Dict[str, Any]) -> Expression:
        """Symbolically execute from instruction `idx`; returns the
        expression produced by the RETURN reached on this path."""
        stack = list(stack)
        local_vars = dict(local_vars)
        while True:
            self.steps += 1
            if self.steps > _MAX_STEPS:
                raise UdfCompileError("bytecode too complex")
            if idx >= len(self.instructions):
                raise UdfCompileError("fell off bytecode")
            ins = self.instructions[idx]
            op = ins.opname

            if op in ("RESUME", "NOP", "PRECALL", "CACHE", "EXTENDED_ARG",
                      "COPY_FREE_VARS", "MAKE_CELL"):
                idx += 1
            elif op == "LOAD_DEREF":
                name = ins.argval
                if name not in self.arg_exprs:
                    raise UdfCompileError(f"unbound closure var {name!r}")
                stack.append(self.arg_exprs[name])
                idx += 1
            elif op in ("LOAD_FAST", "LOAD_FAST_CHECK"):
                name = ins.argval
                if name in local_vars:
                    stack.append(local_vars[name])
                elif name in self.arg_exprs:
                    stack.append(self.arg_exprs[name])
                else:
                    raise UdfCompileError(f"unbound local {name!r}")
                idx += 1
            elif op == "STORE_FAST":
                local_vars[ins.argval] = stack.pop()
                idx += 1
            elif op == "LOAD_CONST":
                stack.append(_const(ins.argval))
                idx += 1
            elif op == "RETURN_CONST":
                return _const(ins.argval)
            elif op == "RETURN_VALUE":
                v = stack.pop()
                if not isinstance(v, Expression):
                    raise UdfCompileError(f"returning non-expression {v!r}")
                return v
            elif op == "LOAD_GLOBAL":
                name = ins.argval
                if name not in self.globals:
                    import builtins
                    if not hasattr(builtins, name):
                        raise UdfCompileError(f"unknown global {name!r}")
                    val = getattr(builtins, name)
                else:
                    val = self.globals[name]
                if _LOAD_GLOBAL_PUSHES_NULL and ins.arg & 1:
                    # 3.11+: NULL is pushed below the callable
                    stack.append(_Null())
                if val is None or isinstance(val, (bool, int, float, str)):
                    # plain global constant: fold to a literal so
                    # `lambda x: x + SOME_CONST` compiles like a closure
                    stack.append(_const(val))
                else:
                    stack.append(_Global(val))
                idx += 1
            elif op == "PUSH_NULL":
                stack.append(_Null())
                idx += 1
            elif op == "LOAD_ATTR":
                obj = stack.pop()
                name = ins.argval
                if ins.arg & 1:  # method-load form: [method, self] or
                    # [NULL, attr] with the first item deeper on the stack
                    if isinstance(obj, _Global):
                        stack.append(_Null())
                        stack.append(_Global(getattr(obj.value, name)))
                    elif isinstance(obj, Expression):
                        stack.append(_Method(obj, name))
                        stack.append(obj)
                    else:
                        raise UdfCompileError(f"attr on {obj!r}")
                else:
                    if isinstance(obj, _Global):
                        stack.append(_Global(getattr(obj.value, name)))
                    else:
                        raise UdfCompileError(f"attr on {obj!r}")
                idx += 1
            elif op == "LOAD_METHOD":
                obj = stack.pop()
                name = ins.argval
                if isinstance(obj, _Global):
                    stack.append(_Null())
                    stack.append(_Global(getattr(obj.value, name)))
                elif isinstance(obj, Expression):
                    stack.append(_Method(obj, name))
                    stack.append(obj)
                else:
                    raise UdfCompileError(f"method on {obj!r}")
                idx += 1
            elif op == "CALL":
                argc = ins.arg
                args = stack[len(stack) - argc:]
                del stack[len(stack) - argc:]
                upper = stack.pop()   # callable (plain) or self (method)
                deeper = stack.pop()  # NULL (plain) or the unbound method
                if not all(isinstance(a, Expression) for a in args):
                    raise UdfCompileError("non-expression call args")
                if isinstance(deeper, _Method):
                    stack.append(_call_method(deeper.obj, deeper.name, args))
                elif isinstance(deeper, _Null) and isinstance(upper, _Global):
                    stack.append(_call_function(upper.value, args))
                else:
                    raise UdfCompileError(f"calling {deeper!r}/{upper!r}")
                idx += 1
            elif op == "CALL_FUNCTION":
                # py3.10 plain call: [callable, args...] with no NULL
                argc = ins.arg
                args = stack[len(stack) - argc:]
                del stack[len(stack) - argc:]
                target = stack.pop()
                if not all(isinstance(a, Expression) for a in args):
                    raise UdfCompileError("non-expression call args")
                if not isinstance(target, _Global):
                    raise UdfCompileError(f"calling {target!r}")
                stack.append(_call_function(target.value, args))
                idx += 1
            elif op == "CALL_METHOD":
                # py3.10 method call: [pair..., args...] where pair is what
                # LOAD_METHOD pushed — (NULL, fn) or (_Method, self)
                argc = ins.arg
                args = stack[len(stack) - argc:]
                del stack[len(stack) - argc:]
                upper = stack.pop()
                deeper = stack.pop()
                if not all(isinstance(a, Expression) for a in args):
                    raise UdfCompileError("non-expression call args")
                if isinstance(deeper, _Method):
                    stack.append(_call_method(deeper.obj, deeper.name, args))
                elif isinstance(deeper, _Null) and isinstance(upper, _Global):
                    stack.append(_call_function(upper.value, args))
                else:
                    raise UdfCompileError(f"calling {deeper!r}/{upper!r}")
                idx += 1
            elif op in _LEGACY_BINARY:
                rhs, lhs = stack.pop(), stack.pop()
                if not (isinstance(lhs, Expression)
                        and isinstance(rhs, Expression)):
                    raise UdfCompileError("binary op on non-expressions")
                stack.append(_binary(_LEGACY_BINARY[op], lhs, rhs))
                idx += 1
            elif op == "BINARY_OP":
                rhs, lhs = stack.pop(), stack.pop()
                if not (isinstance(lhs, Expression)
                        and isinstance(rhs, Expression)):
                    raise UdfCompileError("binary op on non-expressions")
                stack.append(_binary(ins.argrepr, lhs, rhs))
                idx += 1
            elif op == "COMPARE_OP":
                rhs, lhs = stack.pop(), stack.pop()
                sym = ins.argval if isinstance(ins.argval, str) \
                    else ins.argrepr
                sym = sym.strip()
                if sym not in _COMPARES:
                    raise UdfCompileError(f"compare {sym!r}")
                stack.append(_COMPARES[sym](lhs, rhs))
                idx += 1
            elif op == "IS_OP":
                rhs, lhs = stack.pop(), stack.pop()
                if isinstance(rhs, Literal) and rhs.value is None:
                    e = pr.IsNull(lhs)
                elif isinstance(lhs, Literal) and lhs.value is None:
                    e = pr.IsNull(rhs)
                else:
                    raise UdfCompileError("is on non-None")
                stack.append(pr.Not(e) if ins.arg == 1 else e)
                idx += 1
            elif op == "CONTAINS_OP":
                container, item = stack.pop(), stack.pop()
                if not (isinstance(container, Expression)
                        and isinstance(item, Expression)):
                    raise UdfCompileError("in on non-expressions")
                if isinstance(container.data_type(), t.StringType):
                    e = st.Contains(container, item)
                else:
                    raise UdfCompileError("in on non-string")
                stack.append(pr.Not(e) if ins.arg == 1 else e)
                idx += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(ar.UnaryMinus(stack.pop()))
                idx += 1
            elif op == "UNARY_NOT":
                stack.append(pr.Not(stack.pop()))
                idx += 1
            elif op == "COPY":
                stack.append(stack[-ins.arg])
                idx += 1
            elif op == "DUP_TOP":
                stack.append(stack[-1])
                idx += 1
            elif op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                idx += 1
            elif op == "ROT_TWO":
                stack[-1], stack[-2] = stack[-2], stack[-1]
                idx += 1
            elif op == "POP_TOP":
                stack.pop()
                idx += 1
            elif op in ("JUMP_FORWARD", "JUMP_ABSOLUTE"):
                target = self.by_offset[ins.argval]
                if target <= idx:  # 3.10 spells loop back-edges this way
                    raise UdfCompileError("loops are not compilable")
                idx = target
            elif op == "JUMP_BACKWARD":
                raise UdfCompileError("loops are not compilable")
            elif op in ("JUMP_IF_FALSE_OR_POP", "JUMP_IF_TRUE_OR_POP"):
                # short-circuit and/or: on jump the operand VALUE stays on
                # the stack; on fallthrough it is popped.  Fork both arms
                # and select with If on the operand's truthiness.
                operand = stack.pop()
                if not isinstance(operand, Expression):
                    raise UdfCompileError("branching on non-expression")
                pred = _as_predicate(operand)
                fall_e = self.run(idx + 1, stack, local_vars)
                jump_e = self.run(self.by_offset[ins.argval],
                                  stack + [operand], local_vars)
                if op == "JUMP_IF_FALSE_OR_POP":
                    return cond.If(pred, fall_e, jump_e)
                return cond.If(pred, jump_e, fall_e)
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                pred = stack.pop()
                if not isinstance(pred, Expression):
                    raise UdfCompileError("branching on non-expression")
                if op == "POP_JUMP_IF_NONE":
                    pred = pr.Not(pr.IsNull(pred))
                elif op == "POP_JUMP_IF_NOT_NONE":
                    pred = pr.IsNull(pred)
                elif op == "POP_JUMP_IF_TRUE":
                    pred = pr.Not(_as_predicate(pred))
                else:
                    pred = _as_predicate(pred)
                # pred now means "take the fallthrough arm"
                then_e = self.run(idx + 1, stack, local_vars)
                else_e = self.run(self.by_offset[ins.argval], stack,
                                  local_vars)
                return cond.If(pred, then_e, else_e)
            else:
                raise UdfCompileError(f"unsupported opcode {op}")


def _as_predicate(e: Expression) -> Expression:
    dt = e.data_type()
    if isinstance(dt, t.BooleanType):
        return e
    if isinstance(dt, (t.StringType, t.BinaryType)):
        # Python truthiness of a string: non-empty
        return pr.GreaterThan(st.Length(e), Literal(0))
    return pr.Not(pr.EqualTo(e, Literal(0)))


def compile_udf(fn, arg_exprs: Sequence[Expression]) -> Expression:
    """Compile a Python function of N scalar args applied to N column
    expressions into a single expression tree, or raise UdfCompileError."""
    try:
        code = fn.__code__
    except AttributeError:
        raise UdfCompileError("not a Python function")
    if code.co_flags & 0x08 or code.co_flags & 0x04:  # *args/**kwargs
        raise UdfCompileError("varargs UDF")
    if fn.__defaults__ or getattr(fn, "__kwdefaults__", None):
        raise UdfCompileError("default arguments")
    if code.co_freevars:
        # closures over plain constants are fine; resolve cell contents
        cells = {}
        for name, cell in zip(code.co_freevars, fn.__closure__ or ()):
            try:
                cells[name] = _const(cell.cell_contents)
            except UdfCompileError:
                raise UdfCompileError(f"closure over non-constant {name!r}")
    else:
        cells = {}
    names = code.co_varnames[:code.co_argcount]
    if len(names) != len(arg_exprs):
        raise UdfCompileError(
            f"arity mismatch: {len(names)} params, {len(arg_exprs)} args")
    env = dict(zip(names, arg_exprs))
    interp = _Interp(code, env, dict(fn.__globals__))
    interp.arg_exprs.update(cells)
    result = interp.run(0, [], {})
    result.data_type()  # force type check now, not at eval time
    return result


def try_compile_udf(fn, arg_exprs: Sequence[Expression]
                    ) -> Optional[Expression]:
    try:
        return compile_udf(fn, arg_exprs)
    except UdfCompileError:
        return None
    except Exception:
        return None
