"""Native (columnar) UDF interface — the RapidsUDF analog.

The reference lets users supply a *columnar* UDF implementation
(`sql-plugin/src/main/java/com/nvidia/spark/RapidsUDF.java`:
`evaluateColumnar(ColumnVector... args)`) that runs native CUDA code and
skips row-by-row evaluation entirely.  The TPU-native equivalent: the user
implements `evaluate_columnar(xp, n_rows, *cols)` over our DeviceColumn
layout using `xp` (jax.numpy on TPU, numpy on the CPU fallback engine) or
a Pallas kernel — the function traces into the enclosing operator's XLA
computation, so it fuses with the surrounding expressions (better than the
reference, where a native UDF is still a separate kernel launch).
"""

from __future__ import annotations

from typing import Sequence

from .. import types as t
from ..columnar.device import DeviceColumn
from ..expr.core import (ColumnValue, EvalContext, Expression, ScalarValue,
                         evaluator, make_column, scalar_to_column)


class TpuUDF:
    """User-facing columnar UDF base (ref RapidsUDF.java).

    Subclass and implement `evaluate_columnar`.  Inputs arrive as
    DeviceColumns (fixed capacity, validity masks); return a DeviceColumn
    of the same capacity, or an (data, validity) tuple.
    """

    #: result type; override or pass to constructor
    return_type: t.DataType = t.DOUBLE

    def __init__(self, return_type: t.DataType = None):
        if return_type is not None:
            self.return_type = return_type

    @property
    def name(self) -> str:
        return type(self).__name__

    def evaluate_columnar(self, xp, n_rows, *cols: DeviceColumn):
        raise NotImplementedError


class NativeUDFExpression(Expression):
    """Expression node wrapping a TpuUDF (ref GpuUserDefinedFunction.scala
    branch that dispatches to RapidsUDF.evaluateColumnar)."""

    def __init__(self, udf: TpuUDF, children: Sequence[Expression]):
        self.udf = udf
        self.children = tuple(children)

    def data_type(self):
        return self.udf.return_type

    @property
    def pretty_name(self):
        return self.udf.name


@evaluator(NativeUDFExpression)
def _eval_native_udf(e: NativeUDFExpression, ctx: EvalContext):
    cols = []
    for c in e.children:
        v = c.eval(ctx)
        if isinstance(v, ScalarValue):
            v = scalar_to_column(ctx, v)
        cols.append(v.col)
    out = e.udf.evaluate_columnar(ctx.xp, ctx.batch.num_rows, *cols)
    if isinstance(out, tuple):
        data, validity = out
        return make_column(ctx, e.udf.return_type, data, validity)
    if isinstance(out, DeviceColumn):
        return ColumnValue(out)
    return make_column(ctx, e.udf.return_type, out, None)
