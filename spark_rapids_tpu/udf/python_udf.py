"""Opaque Python UDF expression + host evaluation.

The fallback when the bytecode compiler can't translate a UDF: the
function runs as real Python over Arrow-materialized columns, host-side —
the analog of the reference's Python UDF path, which ships Arrow batches
to external Python workers (ref: sql-plugin/.../execution/python/
GpuArrowEvalPythonExec.scala:58-260, python/rapids/worker.py:22).

Our executors *are* Python processes, so no process hop or IPC is needed:
"send Arrow to the Python worker" degenerates to materializing the input
DeviceColumns as pyarrow arrays and calling the function.  Scalar UDFs map
row-by-row over pylists; pandas UDFs get/return `pandas.Series` — the same
two flavors PySpark exposes (udf / pandas_udf).
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import column_to_arrow, column_to_device
from ..columnar.interop import to_arrow_type
from ..expr.core import (ColumnValue, EvalContext, EvalError, Expression,
                         ScalarValue, evaluator, scalar_to_column)


class PythonUDF(Expression):
    """An uncompiled Python UDF call (scalar or pandas/vectorized)."""

    def __init__(self, fn: Callable, return_type: t.DataType,
                 children: Sequence[Expression], vectorized: bool = False,
                 name: str = ""):
        self.fn = fn
        self.return_type = return_type
        self.children = tuple(children)
        self.vectorized = vectorized
        self._name = name or getattr(fn, "__name__", "udf")

    def data_type(self):
        return self.return_type

    @property
    def pretty_name(self):
        return self._name


@evaluator(PythonUDF)
def _eval_python_udf(e: PythonUDF, ctx: EvalContext):
    if ctx.xp is not np:
        # device-side tracing cannot run opaque Python; the overrides
        # engine routes batches through ArrowEvalPythonExec instead
        raise EvalError("PythonUDF must be evaluated on the host")
    n = int(ctx.batch.num_rows)
    arrs = []
    for c in e.children:
        v = c.eval(ctx)
        if isinstance(v, ScalarValue):
            v = scalar_to_column(ctx, v)
        arrs.append(column_to_arrow(v.col, n))
    out_at = to_arrow_type(e.return_type)
    if e.vectorized:
        import pandas as pd
        series = [a.to_pandas() for a in arrs]
        result = e.fn(*series)
        if not isinstance(result, pd.Series):
            result = pd.Series(result)
        out = pa.Array.from_pandas(result, type=out_at)
    else:
        cols = [a.to_pylist() for a in arrs]
        result = [e.fn(*row) for row in zip(*cols)] if arrs else \
            [e.fn() for _ in range(n)]
        out = pa.array(result, type=out_at)
    col = column_to_device(out, e.return_type, ctx.capacity, xp=np)
    return ColumnValue(col)
