"""UDF layer (= reference L6).

- `compiler`: Python-bytecode -> expression IR translation
  (ref udf-compiler/).
- `native`: columnar TpuUDF interface (ref RapidsUDF.java).
- `python_udf`: opaque Python/pandas UDF expression + host evaluation
  (ref sql-plugin execution/python/).
- `examples`: cosine_similarity / string_word_count parity examples
  (ref udf-examples/).
"""

from .compiler import UdfCompileError, compile_udf, try_compile_udf
from .native import NativeUDFExpression, TpuUDF
from .python_udf import PythonUDF
