"""Example native UDFs — parity with the reference's udf-examples module.

The reference ships its only first-party native code here: C++/CUDA
implementations of cosine_similarity and string_word_count exposed through
RapidsUDF JNI (ref: udf-examples/src/main/cpp/src/{cosine_similarity.cu,
string_word_count.cu,CosineSimilarityJni.cpp}).  The TPU-native versions
are columnar JAX functions; CosineSimilarity additionally demonstrates a
Pallas kernel path on real TPU hardware (the "hand-written kernel" slot),
falling back to plain lax ops under jit on CPU.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from .native import TpuUDF
from ..ops.scan import cumsum_fast


class CosineSimilarity(TpuUDF):
    """Cosine similarity between two fixed-width float vectors per row.

    Inputs are array<float> columns stored as (rows, width) dense data with
    per-row validity (ref cosine_similarity.cu computes the same reduction
    per row-pair with a warp per row).
    """

    return_type = t.DOUBLE

    def evaluate_columnar(self, xp, n_rows, a: DeviceColumn,
                          b: DeviceColumn):
        av, bv = a.data.astype(xp.float32), b.data.astype(xp.float32)
        if av.ndim == 1:  # scalar columns degenerate to 1-wide vectors
            av, bv = av[:, None], bv[:, None]
        dot = (av * bv).sum(axis=1)
        na = xp.sqrt((av * av).sum(axis=1))
        nb = xp.sqrt((bv * bv).sum(axis=1))
        denom = na * nb
        sim = xp.where(denom > 0, dot / xp.where(denom > 0, denom, 1.0), 0.0)
        return sim.astype(xp.float64), a.validity & b.validity


class StringWordCount(TpuUDF):
    """Whitespace-separated word count of a string column
    (ref string_word_count.cu: counts space->non-space transitions)."""

    return_type = t.INT

    def evaluate_columnar(self, xp, n_rows, s: DeviceColumn):
        chars = s.data  # uint8 byte tensor
        offs = s.offsets
        is_space = (chars == ord(" ")) | (chars == ord("\t")) | \
            (chars == ord("\n")) | (chars == ord("\r"))
        nonspace = ~is_space
        prev = xp.concatenate([xp.ones((1,), dtype=bool), is_space[:-1]])
        starts = (nonspace & prev).astype(xp.int32)
        csum = xp.concatenate([xp.zeros((1,), dtype=xp.int32),
                               cumsum_fast(xp, starts, dtype=xp.int32)])
        # word starts strictly inside each row's span; a row beginning
        # mid-buffer needs its own boundary treated as a word start
        lo = offs[:-1]
        hi = offs[1:]
        inner = csum[hi] - csum[lo]
        first_byte_nonspace = nonspace[xp.clip(lo, 0, chars.shape[0] - 1)] & \
            (hi > lo)
        prev_byte = xp.clip(lo - 1, 0, chars.shape[0] - 1)
        prev_nonspace = nonspace[prev_byte] & (lo > 0)
        # if the row starts with a non-space byte but the previous buffer
        # byte was also non-space, csum missed this row's first word
        missed = first_byte_nonspace & prev_nonspace
        counts = inner + missed.astype(xp.int32)
        return counts.astype(xp.int32), s.validity
