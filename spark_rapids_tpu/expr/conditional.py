"""Conditional expressions: If, CaseWhen, Coalesce, NullIf, Nvl.

Ref: sql-plugin/.../conditionalExpressions.scala, nullExpressions.scala.
On TPU every branch evaluates eagerly and blends with `where` — branches are
cheap vector ops and XLA fuses the blend; this matches how cuDF evaluates
both sides too (no short-circuit on columnar data).
"""

from __future__ import annotations

from .. import types as t
from .arithmetic import cast_data, promote
from .core import (ColumnValue, EvalContext, Expression, ScalarValue,
                   and_validity, data_of, evaluator, make_column,
                   validity_of)
from .predicates import _bool_parts
from ..ops.scan import cumsum_fast


def _common_type(exprs):
    out = None
    for e in exprs:
        dt = e.data_type()
        if isinstance(dt, t.NullType):
            continue
        out = dt if out is None else promote(out, dt)
    return out if out is not None else t.NULL


def _value_parts(ctx: EvalContext, v, src: t.DataType, out: t.DataType):
    """(data[cap], validity[cap]) of a value cast to `out`."""
    xp = ctx.xp
    if isinstance(out, (t.StringType, t.BinaryType)):
        raise NotImplementedError("string conditional handled separately")
    d = data_of(v, ctx)
    if not isinstance(src, t.NullType):
        d = cast_data(ctx, d, src, out)
    else:
        d = xp.zeros((ctx.capacity,), dtype=t.to_np_dtype(out))
    if not hasattr(d, "shape") or getattr(d, "shape", ()) == ():
        d = xp.full((ctx.capacity,), d, dtype=t.to_np_dtype(out))
    val = validity_of(v, ctx)
    if val is None:
        val = xp.ones((ctx.capacity,), dtype=bool)
    elif val is False:
        val = xp.zeros((ctx.capacity,), dtype=bool)
    return d, val


class If(Expression):
    def __init__(self, pred, if_true, if_false):
        self.children = (pred, if_true, if_false)

    def data_type(self):
        return _common_type(self.children[1:])

    def sql(self):
        p, a, b = self.children
        return f"if({p.sql()}, {a.sql()}, {b.sql()})"


@evaluator(If)
def _eval_if(e: If, ctx: EvalContext):
    xp = ctx.xp
    out = e.data_type()
    pd, pv = _bool_parts(ctx, e.children[0].eval(ctx))
    cond = pd & pv  # null predicate -> false branch (Spark)
    if isinstance(out, (t.StringType, t.BinaryType)):
        return _string_select(ctx, [cond], [e.children[1]], e.children[2], out)
    ad, av = _value_parts(ctx, e.children[1].eval(ctx),
                          e.children[1].data_type(), out)
    bd, bv = _value_parts(ctx, e.children[2].eval(ctx),
                          e.children[2].data_type(), out)
    return make_column(ctx, out, xp.where(cond, ad, bd),
                       xp.where(cond, av, bv))


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 ... ELSE d END.
    children = [c1, v1, c2, v2, ..., (else)]"""

    def __init__(self, branches, else_value=None):
        from .core import Literal
        kids = []
        for c, v in branches:
            kids += [c, v]
        if else_value is None:
            else_value = Literal(None, t.NULL)
        kids.append(else_value)
        self.children = tuple(kids)
        self.n_branches = len(branches)

    def branches(self):
        return [(self.children[2 * i], self.children[2 * i + 1])
                for i in range(self.n_branches)]

    def else_value(self):
        return self.children[-1]

    def data_type(self):
        vals = [v for _, v in self.branches()] + [self.else_value()]
        return _common_type(vals)


@evaluator(CaseWhen)
def _eval_case(e: CaseWhen, ctx: EvalContext):
    xp = ctx.xp
    out = e.data_type()
    conds = []
    taken = xp.zeros((ctx.capacity,), dtype=bool)
    for c, _ in e.branches():
        pd, pv = _bool_parts(ctx, c.eval(ctx))
        fire = pd & pv & ~taken
        conds.append(fire)
        taken = taken | fire
    if isinstance(out, (t.StringType, t.BinaryType)):
        return _string_select(ctx, conds, [v for _, v in e.branches()],
                              e.else_value(), out)
    dd, dv = _value_parts(ctx, e.else_value().eval(ctx),
                          e.else_value().data_type(), out)
    data, validity = dd, dv
    for fire, (_, v) in zip(conds, e.branches()):
        vd, vv = _value_parts(ctx, v.eval(ctx), v.data_type(), out)
        data = xp.where(fire, vd, data)
        validity = xp.where(fire, vv, validity)
    return make_column(ctx, out, data, validity)


class Coalesce(Expression):
    def __init__(self, *children):
        self.children = tuple(children)

    def data_type(self):
        return _common_type(self.children)


@evaluator(Coalesce)
def _eval_coalesce(e: Coalesce, ctx: EvalContext):
    xp = ctx.xp
    out = e.data_type()
    if isinstance(out, (t.StringType, t.BinaryType)):
        # select first non-null: express as cascade of If on IsNotNull
        from .predicates import IsNotNull
        expr = e.children[-1]
        for c in reversed(e.children[:-1]):
            expr = If(IsNotNull(c), c, expr)
        return expr.eval(ctx)
    data = xp.zeros((ctx.capacity,), dtype=t.to_np_dtype(out))
    validity = xp.zeros((ctx.capacity,), dtype=bool)
    for c in e.children:
        vd, vv = _value_parts(ctx, c.eval(ctx), c.data_type(), out)
        take = ~validity & vv
        data = xp.where(take, vd, data)
        validity = validity | vv
    return make_column(ctx, out, data, validity)


class NullIf(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self):
        return self.children[0].data_type()


@evaluator(NullIf)
def _eval_nullif(e: NullIf, ctx: EvalContext):
    from .predicates import EqualTo
    eq = EqualTo(e.children[0], e.children[1])
    pd, pv = _bool_parts(ctx, eq.eval(ctx))
    v = e.children[0].eval(ctx)
    out = e.data_type()
    if isinstance(out, (t.StringType, t.BinaryType)):
        col = _as_string_column(ctx, v, out)
        validity = col.col.validity & ~(pd & pv)
        from ..columnar.device import DeviceColumn
        return ColumnValue(DeviceColumn(out, data=col.col.data,
                                        offsets=col.col.offsets,
                                        validity=validity))
    d, val = _value_parts(ctx, v, out, out)
    return make_column(ctx, out, d, val & ~(pd & pv))


class Nvl(Coalesce):
    def __init__(self, left, right):
        super().__init__(left, right)


# ---------------------------------------------------------------------------
# string select support
# ---------------------------------------------------------------------------

def _as_string_column(ctx: EvalContext, v, dtype) -> ColumnValue:
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    if isinstance(v, ColumnValue):
        return v
    if hasattr(v.value, "shape"):
        # ParamLiteral string: traced uint8 chars — tile on device
        # (length is static, it rides the jit key via the array shape)
        arr = xp.asarray(v.value, dtype=xp.uint8)
        ln = int(arr.shape[0])
        cap = ctx.capacity
        return ColumnValue(DeviceColumn(
            dtype,
            data=xp.tile(arr, cap) if ln else xp.zeros((1,), xp.uint8),
            offsets=xp.arange(cap + 1, dtype=xp.int32) * xp.int32(ln),
            validity=xp.ones((cap,), dtype=bool)))
    s = v.value if isinstance(v.value, bytes) else (
        v.value.encode() if isinstance(v.value, str) else None)
    cap = ctx.capacity
    if s is None:
        return ColumnValue(DeviceColumn(
            dtype, data=xp.zeros((1,), dtype=xp.uint8),
            offsets=xp.zeros((cap + 1,), dtype=xp.int32),
            validity=xp.zeros((cap,), dtype=bool)))
    import numpy as np
    sarr = np.frombuffer(s, dtype=np.uint8)
    ln = len(s)
    offsets = xp.arange(cap + 1, dtype=xp.int32) * xp.int32(ln)
    chars = xp.asarray(np.tile(sarr, cap)) if ln else xp.zeros((1,), xp.uint8)
    return ColumnValue(DeviceColumn(dtype, data=chars, offsets=offsets,
                                    validity=xp.ones((cap,), dtype=bool)))


def _string_select(ctx: EvalContext, conds, values, else_value, out):
    """Blend string columns: pick per-row source then gather spans."""
    from ..columnar.device import DeviceColumn, bucket_for
    from ..ops.strings import gather_strings
    xp = ctx.xp
    cols = [_as_string_column(ctx, v.eval(ctx), out) for v in values]
    ecol = _as_string_column(ctx, else_value.eval(ctx), out)
    cap = ctx.capacity
    # choose source index per row: 0..n-1 branches, n = else
    n = len(cols)
    src = xp.full((cap,), n, dtype=xp.int32)
    for i in reversed(range(n)):
        src = xp.where(conds[i], xp.int32(i), src)
    all_cols = cols + [ecol]
    # concatenate char buffers, then per-row gather the right span
    offs_list = [c.col.offsets for c in all_cols]
    chars_list = [c.col.data for c in all_cols]
    char_caps = [c.col.data.shape[0] for c in all_cols]
    total_cap = int(sum(char_caps))
    from ..ops.strings import concat_char_buffers
    base = 0
    # build per-row source offsets into the concatenated buffer
    big_chars = xp.concatenate(chars_list)
    row = xp.arange(cap, dtype=xp.int32)
    starts = xp.zeros((cap,), dtype=xp.int32)
    lens = xp.zeros((cap,), dtype=xp.int32)
    validity = xp.zeros((cap,), dtype=bool)
    for i, c in enumerate(all_cols):
        sel = src == i
        o = c.col.offsets
        starts = xp.where(sel, o[:-1] + xp.int32(base), starts)
        lens = xp.where(sel, o[1:] - o[:-1], lens)
        validity = xp.where(sel, c.col.validity, validity)
        base += int(c.col.data.shape[0])
    # gather: emulate gather_strings with explicit starts/lens
    out_char_cap = max(int(c.col.data.shape[0]) for c in all_cols)
    new_offs = xp.concatenate([
        xp.zeros((1,), xp.int32),
        cumsum_fast(xp, xp.where(validity, lens, 0), dtype=xp.int32)])
    p = xp.arange(out_char_cap, dtype=xp.int32)
    prow = xp.clip(xp.searchsorted(new_offs[1:], p, side="right"),
                   0, cap - 1).astype(xp.int32)
    src_pos = xp.clip(starts[prow] + (p - new_offs[prow]), 0,
                      big_chars.shape[0] - 1)
    new_chars = xp.where(p < new_offs[-1], big_chars[src_pos],
                         xp.zeros((), dtype=xp.uint8))
    return ColumnValue(DeviceColumn(out, data=new_chars, offsets=new_offs,
                                    validity=validity))
