"""Date/time expressions (UTC only, like the reference's timezone gate —
GpuOverrides tags non-UTC sessions off the GPU).

Ref: org/apache/spark/sql/rapids/datetimeExpressions.scala.
DATE is int32 days since epoch; TIMESTAMP is int64 micros since epoch.
Field extraction uses branch-free civil-calendar arithmetic
(expr/cast.py's Hinnant algorithms), fully vectorized.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from .cast import _civil_from_days, _days_from_civil
from .core import (EvalContext, Expression, and_validity, data_of,
                   evaluator, make_column, validity_of)

MICROS_PER_DAY = np.int64(86400000000)


class DateTimeUnary(Expression):
    out_type = t.INT

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.out_type


def _days_of(e, ctx):
    """child -> (days int64, validity)."""
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    dt = e.children[0].data_type()
    if isinstance(dt, t.TimestampType):
        days = xp.floor_divide(d, MICROS_PER_DAY)
    else:
        days = d.astype(xp.int64) if hasattr(d, "astype") else np.int64(d)
    return days, validity_of(v, ctx)


def _micros_of(e, ctx):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    return d, validity_of(v, ctx)


class Year(DateTimeUnary):
    pass


class Month(DateTimeUnary):
    pass


class DayOfMonth(DateTimeUnary):
    pass


class Quarter(DateTimeUnary):
    pass


class DayOfWeek(DateTimeUnary):
    """1 = Sunday ... 7 = Saturday (Spark)."""


class WeekDay(DateTimeUnary):
    """0 = Monday ... 6 = Sunday (Spark)."""


class DayOfYear(DateTimeUnary):
    pass


class LastDay(DateTimeUnary):
    out_type = t.DATE


def _ymd(xp, days):
    return _civil_from_days(xp, days.astype(xp.int64))


@evaluator(Year)
def _eval_year(e, ctx):
    days, val = _days_of(e, ctx)
    y, m, d = _ymd(ctx.xp, days)
    return make_column(ctx, t.INT, y.astype(np.int32), val)


@evaluator(Month)
def _eval_month(e, ctx):
    days, val = _days_of(e, ctx)
    y, m, d = _ymd(ctx.xp, days)
    return make_column(ctx, t.INT, m.astype(np.int32), val)


@evaluator(DayOfMonth)
def _eval_dom(e, ctx):
    days, val = _days_of(e, ctx)
    y, m, d = _ymd(ctx.xp, days)
    return make_column(ctx, t.INT, d.astype(np.int32), val)


@evaluator(Quarter)
def _eval_quarter(e, ctx):
    days, val = _days_of(e, ctx)
    y, m, d = _ymd(ctx.xp, days)
    return make_column(ctx, t.INT, ((m - 1) // 3 + 1).astype(np.int32), val)


@evaluator(DayOfWeek)
def _eval_dow(e, ctx):
    xp = ctx.xp
    days, val = _days_of(e, ctx)
    # 1970-01-01 was a Thursday; Sunday=1
    dow = xp.mod(days + 4, 7) + 1
    return make_column(ctx, t.INT, dow.astype(np.int32), val)


@evaluator(WeekDay)
def _eval_weekday(e, ctx):
    xp = ctx.xp
    days, val = _days_of(e, ctx)
    wd = xp.mod(days + 3, 7)  # Monday=0
    return make_column(ctx, t.INT, wd.astype(np.int32), val)


@evaluator(DayOfYear)
def _eval_doy(e, ctx):
    xp = ctx.xp
    days, val = _days_of(e, ctx)
    y, m, d = _ymd(xp, days)
    jan1 = _days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d))
    return make_column(ctx, t.INT, (days - jan1 + 1).astype(np.int32), val)


@evaluator(LastDay)
def _eval_lastday(e, ctx):
    xp = ctx.xp
    days, val = _days_of(e, ctx)
    y, m, d = _ymd(xp, days)
    ny = xp.where(m == 12, y + 1, y)
    nm = xp.where(m == 12, xp.ones_like(m), m + 1)
    first_next = _days_from_civil(xp, ny, nm, xp.ones_like(d))
    return make_column(ctx, t.DATE, (first_next - 1).astype(np.int32), val)


class TimePartUnary(DateTimeUnary):
    pass


class Hour(TimePartUnary):
    pass


class Minute(TimePartUnary):
    pass


class Second(TimePartUnary):
    pass


def _time_part(e, ctx, div, mod):
    xp = ctx.xp
    micros, val = _micros_of(e, ctx)
    tod = xp.mod(micros, MICROS_PER_DAY)
    part = xp.mod(tod // np.int64(div), np.int64(mod))
    return make_column(ctx, t.INT, part.astype(np.int32), val)


@evaluator(Hour)
def _eval_hour(e, ctx):
    return _time_part(e, ctx, 3600000000, 24)


@evaluator(Minute)
def _eval_minute(e, ctx):
    return _time_part(e, ctx, 60000000, 60)


@evaluator(Second)
def _eval_second(e, ctx):
    return _time_part(e, ctx, 1000000, 60)


class DateBinary(Expression):
    def __init__(self, left, right):
        self.children = (left, right)


class DateAdd(DateBinary):
    def data_type(self):
        return t.DATE


class DateSub(DateBinary):
    def data_type(self):
        return t.DATE


class DateDiff(DateBinary):
    def data_type(self):
        return t.INT


@evaluator(DateAdd)
def _eval_dateadd(e, ctx):
    xp = ctx.xp
    lv, rv = e.children[0].eval(ctx), e.children[1].eval(ctx)
    days = data_of(lv, ctx)
    delta = data_of(rv, ctx)
    sign = -1 if isinstance(e, DateSub) else 1
    out = (days.astype(xp.int64) if hasattr(days, "astype") else days) + \
        sign * (delta.astype(xp.int64) if hasattr(delta, "astype")
                else np.int64(delta))
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return make_column(ctx, t.DATE, out.astype(np.int32), v)


from .core import _EVALUATORS  # noqa: E402
_EVALUATORS[DateSub] = _eval_dateadd


@evaluator(DateDiff)
def _eval_datediff(e, ctx):
    xp = ctx.xp
    lv, rv = e.children[0].eval(ctx), e.children[1].eval(ctx)
    a = data_of(lv, ctx)
    b = data_of(rv, ctx)
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    out = (a.astype(xp.int64) if hasattr(a, "astype") else np.int64(a)) - \
        (b.astype(xp.int64) if hasattr(b, "astype") else np.int64(b))
    return make_column(ctx, t.INT, out.astype(np.int32), v)


class AddMonths(DateBinary):
    def data_type(self):
        return t.DATE


@evaluator(AddMonths)
def _eval_addmonths(e, ctx):
    xp = ctx.xp
    lv, rv = e.children[0].eval(ctx), e.children[1].eval(ctx)
    days = data_of(lv, ctx)
    months = data_of(rv, ctx)
    if not hasattr(months, "astype"):
        months = np.int64(months)
    y, m, d = _civil_from_days(xp, days.astype(xp.int64))
    tot = y * 12 + (m - 1) + months.astype(xp.int64)
    ny = tot // 12
    nm = xp.mod(tot, 12) + 1
    # clamp day to the target month's last day
    ny2 = xp.where(nm == 12, ny + 1, ny)
    nm2 = xp.where(nm == 12, xp.ones_like(nm), nm + 1)
    last = _days_from_civil(xp, ny2, nm2, xp.ones_like(d)) - 1
    _, _, last_d = _civil_from_days(xp, last)
    nd = xp.minimum(d, last_d)
    out = _days_from_civil(xp, ny, nm, nd)
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return make_column(ctx, t.DATE, out.astype(np.int32), v)


class TruncDate(Expression):
    """trunc(date, fmt) — fmt literal: year/yyyy/yy/month/mon/mm/week/quarter."""

    def __init__(self, child, fmt: str):
        self.children = (child,)
        self.fmt = fmt.lower()

    def data_type(self):
        return t.DATE


@evaluator(TruncDate)
def _eval_trunc(e: TruncDate, ctx):
    xp = ctx.xp
    days, val = _days_of(e, ctx)
    y, m, d = _civil_from_days(xp, days.astype(xp.int64))
    f = e.fmt
    if f in ("year", "yyyy", "yy"):
        out = _days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d))
    elif f in ("month", "mon", "mm"):
        out = _days_from_civil(xp, y, m, xp.ones_like(d))
    elif f == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        out = _days_from_civil(xp, y, qm, xp.ones_like(d))
    elif f == "week":
        wd = xp.mod(days + 3, 7)  # Monday=0
        out = days - wd
    else:
        raise NotImplementedError(f"trunc format {f}")
    return make_column(ctx, t.DATE, out.astype(np.int32), val)


class UnixTimestampBase(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        return t.LONG


class ToUnixTimestamp(UnixTimestampBase):
    pass


@evaluator(ToUnixTimestamp)
def _eval_tounix(e, ctx):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    dt = e.children[0].data_type()
    if isinstance(dt, t.DateType):
        secs = d.astype(xp.int64) * np.int64(86400)
    else:
        secs = xp.floor_divide(d, np.int64(1000000))
    return make_column(ctx, t.LONG, secs, validity_of(v, ctx))


class FromUnixTime(Expression):
    """from_unixtime(sec) -> timestamp (format handling via cast)."""

    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        return t.TIMESTAMP


@evaluator(FromUnixTime)
def _eval_fromunix(e, ctx):
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    return make_column(ctx, t.TIMESTAMP,
                       d.astype(ctx.xp.int64) * np.int64(1000000),
                       validity_of(v, ctx))


class TimeAdd(Expression):
    """timestamp + interval (interval as literal micros)."""

    def __init__(self, child, interval_micros: int):
        self.children = (child,)
        self.interval = int(interval_micros)

    def data_type(self):
        return t.TIMESTAMP


@evaluator(TimeAdd)
def _eval_timeadd(e: TimeAdd, ctx):
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    return make_column(ctx, t.TIMESTAMP, d + np.int64(e.interval),
                       validity_of(v, ctx))


def parse_duration_micros(s: str, allow_nonpositive: bool = False
                          ) -> int:
    """'10 minutes' / '1 hour' / '30 seconds' -> microseconds (the subset
    of CalendarInterval strings time windows accept; month/year units are
    rejected exactly like Spark's TimeWindow analysis rule).  Start-time
    offsets may be zero or negative (allow_nonpositive)."""
    units = {
        "microsecond": 1, "millisecond": 1000, "second": 1_000_000,
        "minute": 60_000_000, "hour": 3_600_000_000,
        "day": 86_400_000_000, "week": 7 * 86_400_000_000,
    }
    total = 0
    toks = s.strip().lower().replace("interval", "").split()
    if len(toks) % 2 != 0 or not toks:
        raise ValueError(f"cannot parse window duration {s!r}")
    for i in range(0, len(toks), 2):
        n, unit = toks[i], toks[i + 1].rstrip("s")
        if unit not in units:
            raise ValueError(
                f"window duration unit {unit!r} not supported "
                f"(month/year windows are not fixed-length)")
        total += int(n) * units[unit]
    if total <= 0 and not allow_nonpositive:
        raise ValueError(f"window duration must be positive: {s!r}")
    return total


class TimeWindow(Expression):
    """window(ts, windowDuration[, slideDuration[, startTime]]) -> struct
    with start/end timestamps (ref
    org/apache/spark/sql/rapids/TimeWindow.scala).  Tumbling windows
    evaluate directly; sliding windows lower through an Expand of
    per-slide copies (`copy_index` selects which overlapping window a
    copy computes — Spark's TimeWindowing analysis rule does exactly
    this), built by dataframe._lower_sliding_windows."""

    def __init__(self, child: Expression, window_micros: int,
                 slide_micros=None, start_micros: int = 0,
                 copy_index=None):
        self.children = (child,)
        self.window = int(window_micros)
        self.slide = int(slide_micros if slide_micros is not None
                         else window_micros)
        self.start = int(start_micros)
        self.copy_index = copy_index

    def data_type(self):
        return t.StructType([t.StructField("start", t.TIMESTAMP),
                             t.StructField("end", t.TIMESTAMP)])

    def sql(self):
        return f"window({self.children[0].sql()}, {self.window}us)"

    @property
    def is_tumbling(self):
        return self.slide == self.window


@evaluator(TimeWindow)
def _eval_time_window(e: TimeWindow, ctx):
    from ..columnar.device import DeviceColumn
    from .core import ColumnValue
    if not e.is_tumbling and e.copy_index is None:
        raise NotImplementedError(
            "sliding time windows evaluate through the Expand lowering "
            "(dataframe._lower_sliding_windows); a bare sliding window "
            "expression has no single value per row")
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    ts = data_of(v, ctx)
    valid = validity_of(v, ctx)
    if valid is None:
        valid = xp.ones((ctx.capacity,), dtype=bool)
    sl = np.int64(e.slide)
    copy = int(e.copy_index or 0)
    # numpy/jnp mod follows the divisor's sign, so this floors correctly
    # for pre-epoch timestamps too; copy i selects the i-th overlapping
    # window walking backwards from the last slide boundary <= ts
    ws = ts - (ts - np.int64(e.start)) % sl - np.int64(copy) * sl
    start = DeviceColumn(t.TIMESTAMP, data=ws, validity=valid)
    end = DeviceColumn(t.TIMESTAMP, data=ws + np.int64(e.window),
                       validity=valid)
    return ColumnValue(DeviceColumn(e.data_type(), validity=valid,
                                    children=(start, end)))


class UnixTimestamp(ToUnixTimestamp):
    """unix_timestamp(ts) — same kernel as to_unix_timestamp
    (ref GpuUnixTimestamp; the two Spark classes share GpuToTimestamp)."""


@evaluator(UnixTimestamp)
def _eval_unixts(e, ctx):
    return _eval_tounix(e, ctx)


class DateFormatClass(Expression):
    """date_format(ts, fmt) — host-evaluated (strftime rendering);
    registered with a host-fallback reason like the regex family
    (ref GpuDateFormatClass)."""

    def __init__(self, child, fmt):
        self.children = (child,)
        self.fmt = fmt

    def data_type(self):
        return t.STRING

    def sql(self):
        return f"date_format({self.children[0].sql()}, '{self.fmt}')"


class DateAddInterval(Expression):
    """date + calendar interval — the interval type is not modeled on
    device; host-fallback (ref GpuDateAddInterval)."""

    def __init__(self, child, months: int = 0, days: int = 0):
        self.children = (child,)
        self.months = months
        self.days = days

    def data_type(self):
        return t.DATE

    def sql(self):
        return (f"date_add_interval({self.children[0].sql()}, "
                f"{self.months} months {self.days} days)")
