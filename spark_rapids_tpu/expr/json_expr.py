"""JSON expressions: get_json_object.

Ref: GpuGetJsonObject.scala (the reference binds cudf's JSONPath kernel).
TPU realization: JSON parsing is irregular byte work with no fixed-shape
device form, so this evaluates on host like the regex family — the
overrides engine keeps the projection on CPU (unregistered expressions
fall back with a tag reason, the reference's incompat pattern).

Supported JSONPath subset (same surface cudf documents): `$`, `.field`,
`['field']`, `[index]`.  Invalid JSON or an unmatched path yields NULL;
string results are unquoted, nested results are re-serialized compactly —
matching Spark's GetJsonObject behavior.
"""

from __future__ import annotations

import json
import re
from typing import Any, List, Optional

from .. import types as t
from .core import (ColumnValue, EvalContext, Expression, evaluator,
                   make_column)

_PATH_TOKEN = re.compile(
    r"\.(?P<field>[^.\[\]]+)|\[(?P<index>\d+)\]|\['(?P<qfield>[^']*)'\]")


def parse_json_path(path: str) -> Optional[List[Any]]:
    """'$.a[0].b' -> ['a', 0, 'b']; None when the path is malformed."""
    if not path.startswith("$"):
        return None
    rest = path[1:]
    toks: List[Any] = []
    pos = 0
    while pos < len(rest):
        m = _PATH_TOKEN.match(rest, pos)
        if m is None:
            return None
        if m.group("field") is not None:
            toks.append(m.group("field"))
        elif m.group("qfield") is not None:
            toks.append(m.group("qfield"))
        else:
            toks.append(int(m.group("index")))
        pos = m.end()
    return toks


def extract_json_path(doc: str, toks: List[Any]) -> Optional[str]:
    try:
        cur = json.loads(doc)
    except (ValueError, TypeError):
        return None
    for tk in toks:
        if isinstance(tk, int):
            if not isinstance(cur, list) or tk >= len(cur):
                return None
            cur = cur[tk]
        else:
            if not isinstance(cur, dict) or tk not in cur:
                return None
            cur = cur[tk]
    if cur is None:
        return None
    if isinstance(cur, str):
        return cur
    if isinstance(cur, bool):
        return "true" if cur else "false"
    if isinstance(cur, (dict, list)):
        return json.dumps(cur, separators=(",", ":"))
    return json.dumps(cur)


class GetJsonObject(Expression):
    def __init__(self, child: Expression, path: Expression):
        self.children = (child, path)

    def data_type(self):
        return t.STRING

    def sql(self):
        return (f"get_json_object({self.children[0].sql()}, "
                f"{self.children[1].sql()})")


@evaluator(GetJsonObject)
def _eval_get_json_object(e: GetJsonObject, ctx: EvalContext):
    from .regex import (_host_only, _pattern_of, build_string_column,
                        np_string_rows)
    from .strings import _string_input
    _host_only(ctx, "get_json_object")
    path = _pattern_of(e.children[1])
    toks = parse_json_path(path) if path is not None else None
    rows = np_string_rows(_string_input(ctx, e.children[0].eval(ctx)),
                          ctx.capacity)
    if toks is None:
        out: List[Optional[str]] = [None] * ctx.capacity
    else:
        out = [extract_json_path(r, toks) if r is not None else None
               for r in rows]
    return build_string_column(ctx, out)
