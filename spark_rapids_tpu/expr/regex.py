"""Regular-expression string functions: rlike / regexp_extract /
regexp_replace / split.

Ref: stringFunctions.scala GpuRLike/GpuRegExpExtract/GpuRegExpReplace —
the reference runs these through cuDF's regex engine with a transpiled
pattern subset, marking unsupported patterns incompat.  A TPU has no
regex engine, so these expressions are host-evaluated (the CPU engine's
numpy path) and tagged off the TPU — precisely how the reference treats
ops its device cannot run (GpuOverrides.scala:97-100 incompat
machinery).  Java-regex dialect differences from Python's `re` are
documented per expression; anchors/character classes used by typical
Spark workloads behave identically.
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from .core import (ColumnValue, EvalContext, Expression, ScalarValue,
                   evaluator, make_column, validity_of)
from .strings import _literal_bytes


def np_string_rows(col: DeviceColumn, cap: int) -> List[Optional[str]]:
    """Decode a (host) string column to per-row Python strings."""
    offs = np.asarray(col.offsets)
    chars = np.asarray(col.data)
    valid = np.asarray(col.validity) if col.validity is not None else \
        np.ones(cap, dtype=bool)
    out: List[Optional[str]] = []
    for i in range(cap):
        if not valid[i]:
            out.append(None)
            continue
        out.append(bytes(chars[offs[i]:offs[i + 1]]).decode(
            "utf-8", "replace"))
    return out


def build_string_column(ctx: EvalContext, rows: List[Optional[str]]
                        ) -> ColumnValue:
    xp = ctx.xp
    cap = ctx.capacity
    enc = [r.encode("utf-8") if r is not None else b"" for r in rows]
    lens = np.array([len(b) for b in enc], dtype=np.int32)
    offs = np.zeros(cap + 1, dtype=np.int32)
    np.cumsum(lens, out=offs[1:])
    data = b"".join(enc)
    chars = np.frombuffer(data, dtype=np.uint8).copy() if data else \
        np.zeros(1, dtype=np.uint8)
    validity = np.array([r is not None for r in rows], dtype=bool)
    return ColumnValue(DeviceColumn(
        t.STRING, data=xp.asarray(chars), validity=xp.asarray(validity),
        offsets=xp.asarray(offs)))


def _pattern_of(e: Expression) -> Optional[str]:
    b = _literal_bytes(e)
    return b.decode("utf-8") if b is not None else None


def _host_only(ctx: EvalContext, name: str):
    if ctx.xp is not np:
        from .core import EvalError
        raise EvalError(f"{name} evaluates on host only (no TPU regex "
                        f"engine); tagging keeps it off the device")


class RLike(Expression):
    """str RLIKE pattern (Java regex `find` semantics)."""

    def __init__(self, child: Expression, pattern: Expression):
        self.children = (child, pattern)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"{self.children[0].sql()} RLIKE {self.children[1].sql()}"


@evaluator(RLike)
def _eval_rlike(e: RLike, ctx: EvalContext):
    _host_only(ctx, "rlike")
    pat = _pattern_of(e.children[1])
    if pat is None:
        from .core import EvalError
        raise EvalError("rlike requires a literal pattern")
    rx = re.compile(pat)
    v = e.children[0].eval(ctx)
    rows = np_string_rows(v.col, ctx.capacity)
    data = np.array([bool(rx.search(r)) if r is not None else False
                     for r in rows], dtype=bool)
    validity = np.array([r is not None for r in rows], dtype=bool)
    return make_column(ctx, t.BOOLEAN, data, validity)


class RegExpExtract(Expression):
    """regexp_extract(str, pattern, idx) — '' when no match (Spark)."""

    def __init__(self, child: Expression, pattern: Expression,
                 idx: Expression):
        self.children = (child, pattern, idx)

    def data_type(self):
        return t.STRING

    def sql(self):
        return (f"regexp_extract({self.children[0].sql()}, "
                f"{self.children[1].sql()}, {self.children[2].sql()})")


@evaluator(RegExpExtract)
def _eval_regexp_extract(e: RegExpExtract, ctx: EvalContext):
    _host_only(ctx, "regexp_extract")
    pat = _pattern_of(e.children[1])
    iv = e.children[2].eval(ctx)
    idx = int(iv.value) if isinstance(iv, ScalarValue) else None
    if pat is None or idx is None:
        from .core import EvalError
        raise EvalError("regexp_extract requires literal pattern and index")
    rx = re.compile(pat)
    v = e.children[0].eval(ctx)
    rows = np_string_rows(v.col, ctx.capacity)
    out: List[Optional[str]] = []
    for r in rows:
        if r is None:
            out.append(None)
            continue
        m = rx.search(r)
        if m is None:
            out.append("")
        else:
            g = m.group(idx)
            out.append(g if g is not None else "")
    return build_string_column(ctx, out)


class RegExpReplace(Expression):
    def __init__(self, child: Expression, pattern: Expression,
                 replacement: Expression):
        self.children = (child, pattern, replacement)

    def data_type(self):
        return t.STRING

    def sql(self):
        return (f"regexp_replace({self.children[0].sql()}, "
                f"{self.children[1].sql()}, {self.children[2].sql()})")


def _java_replacement_to_python(rep: str, n_groups: int = 99) -> str:
    """Translate a Java Matcher.appendReplacement replacement string to
    Python re.sub semantics: in Java, backslash makes the next char
    literal, $N is a group reference ($0 = whole match; digits are taken
    only while they still form a group number <= the pattern's group
    count, so '$12' with one group is group 1 then literal '2'), and
    ${name} references a named group.  Python wants \\g<N>/\\g<name> and
    a doubled backslash for a literal one.  Must scan left-to-right — a
    single regex pass mis-pairs backslashes.

    Deliberate dialect difference: where Java throws
    IllegalArgumentException (bare '$', unterminated '${', trailing
    backslash), this translator emits the characters literally instead of
    failing the whole query — lenient like the reference's incompat ops
    (ref GpuOverrides.scala:97-100 marks such corners incompat rather
    than matching exception-for-exception)."""
    out = []
    i, n = 0, len(rep)
    while i < n:
        c = rep[i]
        if c == "\\":
            nxt = rep[i + 1] if i + 1 < n else "\\"
            out.append("\\\\" if nxt == "\\" else nxt)
            i += 2
        elif c == "$":
            if i + 1 < n and rep[i + 1] == "{":
                end = rep.find("}", i + 2)
                if end > i + 2:
                    out.append(rf"\g<{rep[i + 2:end]}>")
                    i = end + 1
                    continue
                out.append("$")     # unterminated ${: Java throws; literal
                i += 1
            elif i + 1 < n and rep[i + 1].isdigit():
                num = int(rep[i + 1])
                j = i + 2
                while j < n and rep[j].isdigit() and \
                        num * 10 + int(rep[j]) <= n_groups:
                    num = num * 10 + int(rep[j])
                    j += 1
                out.append(rf"\g<{num}>")
                i = j
            else:                   # bare $: Java throws; keep literal
                out.append("$")
                i += 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


@evaluator(RegExpReplace)
def _eval_regexp_replace(e: RegExpReplace, ctx: EvalContext):
    _host_only(ctx, "regexp_replace")
    pat = _pattern_of(e.children[1])
    rep = _pattern_of(e.children[2])
    if pat is None or rep is None:
        from .core import EvalError
        raise EvalError("regexp_replace requires literal pattern/replacement")
    rx = re.compile(pat)
    py_rep = _java_replacement_to_python(rep, rx.groups)
    v = e.children[0].eval(ctx)
    rows = np_string_rows(v.col, ctx.capacity)
    out = [rx.sub(py_rep, r) if r is not None else None for r in rows]
    return build_string_column(ctx, out)


class StringSplit(Expression):
    """split(str, regex, limit) -> array<string> (Spark semantics:
    limit<=0 keeps all, trailing empties preserved for limit<0)."""

    def __init__(self, child: Expression, pattern: Expression,
                 limit: Expression):
        self.children = (child, pattern, limit)

    def data_type(self):
        return t.ArrayType(t.STRING)

    def sql(self):
        return (f"split({self.children[0].sql()}, "
                f"{self.children[1].sql()})")


@evaluator(StringSplit)
def _eval_string_split(e: StringSplit, ctx: EvalContext):
    _host_only(ctx, "split")
    xp = ctx.xp
    pat = _pattern_of(e.children[1])
    lv = e.children[2].eval(ctx)
    limit = int(lv.value) if isinstance(lv, ScalarValue) else -1
    if pat is None:
        from .core import EvalError
        raise EvalError("split requires a literal pattern")
    rx = re.compile(pat)
    v = e.children[0].eval(ctx)
    rows = np_string_rows(v.col, ctx.capacity)
    pieces: List[List[str]] = []
    for r in rows:
        if r is None:
            pieces.append([])
            continue
        parts = rx.split(r, maxsplit=limit - 1 if limit > 0 else 0)
        if limit == 0:
            while parts and parts[-1] == "":
                parts.pop()
        pieces.append(parts)
    cap = ctx.capacity
    counts = np.array([len(p) for p in pieces], dtype=np.int32)
    offsets = np.zeros(cap + 1, dtype=np.int32)
    np.cumsum(counts, out=offsets[1:])
    flat: List[Optional[str]] = [s for p in pieces for s in p]
    # build the child in element space
    from ..columnar.device import DeviceBatch
    n_elem = int(offsets[-1])
    ectx = EvalContext(np, DeviceBatch(
        [DeviceColumn(t.INT, data=np.zeros(max(n_elem, 1), np.int32),
                      validity=np.ones(max(n_elem, 1), bool))],
        np.int32(n_elem)))
    child = build_string_column(ectx, flat or [""]).col
    validity = np.array([r is not None for r in rows], dtype=bool)
    return ColumnValue(DeviceColumn(
        t.ArrayType(t.STRING), validity=xp.asarray(validity),
        offsets=xp.asarray(offsets), children=(child,)))
