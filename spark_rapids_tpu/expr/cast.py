"""Cast expression + the cast capability matrix.

Ref: sql-plugin/.../GpuCast.scala (1.4k LoC) and CastChecks
(TypeChecks.scala:1255).  `CAST_MATRIX` mirrors the reference's per
(from,to) support table: pairs not present fall back to CPU via tagging,
exactly how the reference keeps unsupported casts off the GPU.

Device-side string formatting/parsing is done with fixed-width byte-matrix
kernels (ops/strings.pack_rows / window_bytes): int64 has at most 20 digits,
dates are exactly 10 bytes — static shapes, fully vectorized.

Semantics (match Spark, not C/numpy):
  * float -> integral saturates (Java d.toInt), NaN -> 0;
  * integral -> narrower integral wraps bits (Java i.toByte);
  * string -> numeric yields NULL on malformed input (non-ANSI);
  * date<->timestamp via UTC days/micros.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from ..ops import strings as sops
from .arithmetic import cast_data
from .core import (ColumnValue, EvalContext, Expression, ScalarValue,
                   and_validity, data_of, evaluator, make_column,
                   validity_of)

_INT_INFO = {
    t.ByteType: (np.int8, -128, 127),
    t.ShortType: (np.int16, -32768, 32767),
    t.IntegerType: (np.int32, -(2**31), 2**31 - 1),
    t.LongType: (np.int64, -(2**63), 2**63 - 1),
}


class Cast(Expression):
    def __init__(self, child: Expression, to: t.DataType, ansi: bool = False):
        self.children = (child,)
        self.to = to
        self.ansi = ansi

    @property
    def child(self):
        return self.children[0]

    def data_type(self):
        return self.to

    def sql(self):
        return f"CAST({self.child.sql()} AS {self.to.name})"


# which (from, to) pairs run on TPU; others are tagged off (CPU fallback)
def _dec_overflow_ok(xp, data, precision: int):
    """Validity mask for decimal target-precision overflow, or None when
    no value can overflow.  Exact beyond 18 digits via Python ints on the
    object-array (CPU-oracle) path; an int64 lane can never exceed 19
    digits so wider targets need no check."""
    if precision > 18 and getattr(data, "dtype", None) != object:
        return None
    limit = 10 ** precision if precision > 18 else \
        np.int64(10 ** precision)
    return (data < limit) & (data > -limit)


def cast_supported_on_tpu(src: t.DataType, dst: t.DataType) -> bool:
    if src == dst:
        return True
    if isinstance(src, t.DecimalType) and not src.is64:
        # cast kernels read the low word only; >18-digit inputs keep their
        # operator on the CPU (the reference is decimal64-only)
        return False
    if isinstance(dst, t.DecimalType) and not dst.is64:
        # a >18-digit destination can exceed int64 during the scale-up
        # multiply; only same/down-scale decimal sources are overflow-free
        # on the low-word kernels (the internal aggregation-buffer casts
        # are exactly this shape and bypass tagging anyway)
        if not (isinstance(src, t.DecimalType) and dst.scale <= src.scale):
            return False
    flat = (t.BooleanType, t.ByteType, t.ShortType, t.IntegerType, t.LongType,
            t.FloatType, t.DoubleType, t.DecimalType)
    if isinstance(src, flat) and isinstance(dst, flat):
        return True
    if isinstance(src, t.NullType):
        return True
    if isinstance(src, flat) and isinstance(dst, t.StringType):
        # float/double -> string needs shortest-repr formatting: CPU
        return not isinstance(src, (t.FloatType, t.DoubleType))
    if isinstance(src, t.StringType) and isinstance(dst, flat):
        return not isinstance(dst, t.DecimalType)
    if isinstance(src, (t.DateType, t.TimestampType)) and \
            isinstance(dst, (t.DateType, t.TimestampType)):
        return True
    if isinstance(src, t.TimestampType) and isinstance(dst, flat):
        return True
    if isinstance(src, flat) and isinstance(dst, t.TimestampType):
        return True
    if isinstance(src, t.DateType) and isinstance(dst, t.StringType):
        return True
    if isinstance(src, t.StringType) and isinstance(dst, t.DateType):
        return True
    return False


@evaluator(Cast)
def _eval_cast(e: Cast, ctx: EvalContext):
    src = e.child.data_type()
    dst = e.to
    v = e.child.eval(ctx)
    if src == dst:
        return v
    val = validity_of(v, ctx)

    if isinstance(src, t.NullType):
        from .core import all_null_column
        return all_null_column(ctx, dst)

    if isinstance(src, t.StringType):
        return _cast_from_string(e, ctx, v, dst)
    if isinstance(dst, t.StringType):
        return _cast_to_string(e, ctx, v, src)

    xp = ctx.xp
    d = data_of(v, ctx)
    if not hasattr(d, "astype"):
        # scalar input (e.g. a cast wrapped around a literal): promote to a
        # 0-d array so the array cast paths below apply uniformly
        d = xp.asarray(d, dtype=t.to_np_dtype(src))

    # ---- temporal ----------------------------------------------------------
    if isinstance(src, t.DateType) and isinstance(dst, t.TimestampType):
        return make_column(ctx, dst, d.astype(np.int64) * np.int64(86400000000), val)
    if isinstance(src, t.TimestampType) and isinstance(dst, t.DateType):
        days = xp.floor_divide(d, np.int64(86400000000)).astype(np.int32)
        return make_column(ctx, dst, days, val)
    if isinstance(src, t.TimestampType):
        # micros -> seconds for integral/floating (Spark)
        if t.is_integral(dst):
            secs = xp.floor_divide(d, np.int64(1000000))
            return _int_to_int(ctx, secs, t.LONG, dst, val)
        if t.is_floating(dst):
            return make_column(ctx, dst,
                               (d / 1e6).astype(t.to_np_dtype(dst)), val)
    if isinstance(dst, t.TimestampType):
        if t.is_integral(src):
            return make_column(ctx, dst, d.astype(np.int64) * np.int64(1000000), val)
        if t.is_floating(src):
            return make_column(ctx, dst, (d * 1e6).astype(np.int64), val)
        if isinstance(src, t.BooleanType):
            return make_column(ctx, dst, d.astype(np.int64) * np.int64(1000000), val)

    # ---- boolean -----------------------------------------------------------
    if isinstance(dst, t.BooleanType):
        if isinstance(src, t.DecimalType):
            return make_column(ctx, dst, d != 0, val)
        return make_column(ctx, dst, d != 0, val)
    if isinstance(src, t.BooleanType):
        if isinstance(dst, t.DecimalType):
            one = np.int64(10 ** dst.scale)
            return make_column(ctx, dst, d.astype(np.int64) * one, val)
        return make_column(ctx, dst, d.astype(t.to_np_dtype(dst)), val)

    # ---- decimal -----------------------------------------------------------
    if isinstance(dst, t.DecimalType):
        if isinstance(src, t.DecimalType):
            data = cast_data(ctx, d, src, dst)
            # overflow of target precision -> null (non-ANSI)
            ok = _dec_overflow_ok(xp, data, dst.precision)
            return make_column(ctx, dst, data,
                               val if ok is None else
                               and_validity(ctx, val, ok))
        if t.is_integral(src):
            from .arithmetic import cast_data as _cd
            data = _cd(ctx, d, src, dst)
            ok = _dec_overflow_ok(xp, data, dst.precision)
            return make_column(ctx, dst, data,
                               val if ok is None else
                               and_validity(ctx, val, ok))
        if t.is_floating(src):
            scaled = d * (10.0 ** dst.scale)
            data = _round_half_up_float(xp, scaled).astype(np.int64)
            limit = np.int64(10 ** min(dst.precision, 18))
            ok = (~xp.isnan(d)) & (data < limit) & (data > -limit)
            return make_column(ctx, dst, xp.where(ok, data, 0),
                               and_validity(ctx, val, ok))
    if isinstance(src, t.DecimalType):
        if t.is_integral(dst):
            whole = _trunc_div(xp, d, np.int64(10 ** src.scale))
            return _int_to_int(ctx, whole, t.LONG, dst, val)
        if t.is_floating(dst):
            return make_column(ctx, dst,
                               (d / (10.0 ** src.scale)).astype(
                                   t.to_np_dtype(dst)), val)

    # ---- numeric -----------------------------------------------------------
    if t.is_floating(src) and t.is_integral(dst):
        npdt, lo, hi = _INT_INFO[type(dst)]
        nan = xp.isnan(d)
        clipped = xp.clip(xp.where(nan, 0.0, d), float(lo), float(hi))
        return make_column(ctx, dst, clipped.astype(npdt), val)
    if t.is_integral(src) and t.is_integral(dst):
        return _int_to_int(ctx, d, src, dst, val)
    return make_column(ctx, dst, d.astype(t.to_np_dtype(dst)), val)


def _trunc_div(xp, a, b):
    return xp.where(a < 0, -((-a) // b), a // b)


def _round_half_up_float(xp, d):
    return xp.where(d >= 0, xp.floor(d + 0.5), xp.ceil(d - 0.5))


def _int_to_int(ctx, d, src, dst, val):
    npdt, _, _ = _INT_INFO[type(dst)]
    return make_column(ctx, dst, d.astype(npdt), val)  # Java-style bit wrap


# ---------------------------------------------------------------------------
# to-string kernels
# ---------------------------------------------------------------------------

_ZERO = np.uint8(ord("0"))


def _int_digits(xp, d):
    """(bytes[cap,20] left-aligned, lens) decimal text of int64 values."""
    cap = d.shape[0]
    neg = d < 0
    # magnitude as uint64 handles int64 min
    mag = xp.where(neg, (-(d.astype(xp.int64))).astype(xp.uint64),
                   d.astype(xp.uint64))
    k = xp.arange(20, dtype=xp.uint64)
    pow10 = xp.asarray(np.power(np.uint64(10), (19 - np.arange(20)).astype(np.uint64)))
    digits = ((mag[:, None] // pow10[None, :]) % xp.uint64(10)).astype(xp.uint8)
    nonzero = digits != 0
    any_nz = nonzero.any(axis=1)
    first_nz = xp.argmax(nonzero, axis=1).astype(xp.int32)
    first_nz = xp.where(any_nz, first_nz, 19)  # "0" for value 0
    ndig = 20 - first_nz
    lens = ndig + neg.astype(xp.int32)
    # left-align: out[r, j] = '-'? at j=0 if neg; digit at j - neg + first_nz
    j = xp.arange(21, dtype=xp.int32)
    srcj = j[None, :] - neg.astype(xp.int32)[:, None] + first_nz[:, None]
    srcj_c = xp.clip(srcj, 0, 19)
    dig_bytes = digits[xp.arange(cap, dtype=xp.int32)[:, None], srcj_c] + _ZERO
    out = xp.where((j[None, :] == 0) & neg[:, None], xp.uint8(ord("-")),
                   dig_bytes)
    return out, lens


def _cast_to_string(e: Cast, ctx: EvalContext, v, src):
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    d = data_of(v, ctx)
    val = validity_of(v, ctx)
    cap = ctx.capacity
    if val is None:
        val = xp.ones((cap,), dtype=bool)
    elif val is False:
        val = xp.zeros((cap,), dtype=bool)

    if isinstance(src, t.BooleanType):
        # "true" / "false"
        mat = xp.zeros((cap, 5), dtype=xp.uint8)
        tb = xp.asarray(np.frombuffer(b"true\0", dtype=np.uint8))
        fb = xp.asarray(np.frombuffer(b"false", dtype=np.uint8))
        mat = xp.where(d.astype(bool)[:, None], tb[None, :], fb[None, :])
        lens = xp.where(d.astype(bool), 4, 5).astype(xp.int32)
        char_cap = _str_char_cap(cap, 5)
        offs, chars = sops.pack_rows(xp, mat, lens, val, char_cap)
        return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=offs,
                                        validity=val))

    if isinstance(src, t.DateType):
        y, m, day = _civil_from_days(xp, d.astype(xp.int64))
        mat = xp.stack([
            (y // 1000) % 10, (y // 100) % 10, (y // 10) % 10, y % 10,
            xp.full((cap,), -3, xp.int64),
            (m // 10) % 10, m % 10,
            xp.full((cap,), -3, xp.int64),
            (day // 10) % 10, day % 10], axis=1)
        mat = (mat + np.int64(ord("0"))).astype(xp.uint8)  # -3+48 = 45 '-'
        lens = xp.full((cap,), 10, dtype=xp.int32)
        char_cap = _str_char_cap(cap, 10)
        offs, chars = sops.pack_rows(xp, mat, lens, val, char_cap)
        return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=offs,
                                        validity=val))

    if isinstance(src, t.DecimalType):
        unscaled = d
        s = src.scale
        mat, lens = _int_digits(xp, unscaled)
        if s == 0:
            char_cap = _str_char_cap(cap, 21)
            offs, chars = sops.pack_rows(xp, mat, lens, val, char_cap)
            return ColumnValue(DeviceColumn(t.STRING, data=chars,
                                            offsets=offs, validity=val))
        # insert '.' s digits from the right; ensure leading 0 before point
        return _decimal_to_string(ctx, unscaled, s, val)

    if t.is_integral(src) or isinstance(src, t.TimestampType):
        mat, lens = _int_digits(xp, d.astype(xp.int64))
        char_cap = _str_char_cap(cap, 21)
        offs, chars = sops.pack_rows(xp, mat, lens, val, char_cap)
        return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=offs,
                                        validity=val))
    raise NotImplementedError(f"cast {src} -> string on TPU")


def _decimal_to_string(ctx, unscaled, scale, val):
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    cap = ctx.capacity
    neg = unscaled < 0
    mag = xp.abs(unscaled).astype(xp.uint64)
    ipart = (mag // xp.uint64(10 ** scale)).astype(xp.int64)
    fpart = (mag % xp.uint64(10 ** scale)).astype(xp.int64)
    imat, ilens = _int_digits(xp, ipart)
    # width = sign + ilen + 1 + scale
    W = 21 + 1 + scale
    j = xp.arange(W, dtype=xp.int32)
    signw = neg.astype(xp.int32)
    total = signw + ilens + 1 + scale
    out = xp.zeros((cap, W), dtype=xp.uint8)
    is_sign = (j[None, :] == 0) & neg[:, None]
    in_int = (j[None, :] >= signw[:, None]) & \
        (j[None, :] < (signw + ilens)[:, None])
    int_src = xp.clip(j[None, :] - signw[:, None], 0, 20)
    is_dot = j[None, :] == (signw + ilens)[:, None]
    in_frac = (j[None, :] > (signw + ilens)[:, None]) & \
        (j[None, :] < total[:, None])
    fk = xp.clip(j[None, :] - (signw + ilens)[:, None] - 1, 0, max(scale - 1, 0))
    fpow = xp.asarray((10 ** (scale - 1 - np.arange(max(scale, 1))))
                      .astype(np.int64))
    fdig = ((fpart[:, None] // fpow[None, :]) % 10).astype(xp.uint8) + _ZERO
    rowidx = xp.arange(cap, dtype=xp.int32)[:, None]
    out = xp.where(is_sign, xp.uint8(ord("-")), out)
    out = xp.where(in_int, imat[rowidx, int_src], out)
    out = xp.where(is_dot, xp.uint8(ord(".")), out)
    out = xp.where(in_frac, fdig[rowidx, fk], out)
    char_cap = _str_char_cap(cap, W)
    offs, chars = sops.pack_rows(xp, out, total, val, char_cap)
    return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=offs,
                                    validity=val))


def _str_char_cap(cap, width):
    from ..columnar.device import DEFAULT_CHAR_BUCKETS, bucket_for
    return bucket_for(cap * width, DEFAULT_CHAR_BUCKETS)


# ---------------------------------------------------------------------------
# from-string kernels
# ---------------------------------------------------------------------------

def _cast_from_string(e: Cast, ctx: EvalContext, v, dst):
    xp = ctx.xp
    col = v.col if isinstance(v, ColumnValue) else None
    if col is None:
        raise NotImplementedError("scalar string cast")
    val = validity_of(v, ctx)
    W = 24
    b, lens = sops.window_bytes(xp, col.offsets, col.data, W)
    # trim ASCII whitespace on both ends (Spark trims before parsing)
    is_ws = (b == 32) | ((b >= 9) & (b <= 13))
    pos = xp.arange(W, dtype=xp.int32)
    inlen = pos[None, :] < lens[:, None]
    nonws = (~is_ws) & inlen
    any_c = nonws.any(axis=1)
    start = xp.argmax(nonws, axis=1).astype(xp.int32)
    end = (W - xp.argmax(nonws[:, ::-1], axis=1)).astype(xp.int32)
    start = xp.where(any_c, start, 0)
    end = xp.where(any_c, end, 0)
    tl = end - start
    rowidx = xp.arange(b.shape[0], dtype=xp.int32)[:, None]
    tb = b[rowidx, xp.clip(start[:, None] + pos[None, :], 0, W - 1)]
    tb = xp.where(pos[None, :] < tl[:, None], tb, xp.zeros((), xp.uint8))

    if isinstance(dst, t.BooleanType):
        return _parse_bool(ctx, tb, tl, val)
    if isinstance(dst, t.DateType):
        return _parse_date(ctx, tb, tl, val)
    if t.is_integral(dst) or isinstance(dst, t.TimestampType):
        longs, ok = _parse_long(xp, tb, tl)
        okv = and_validity(ctx, val, ok)
        if isinstance(dst, t.TimestampType):
            return make_column(ctx, dst, longs * np.int64(1000000), okv)
        return _int_to_int(ctx, longs, t.LONG, dst, okv)
    if t.is_floating(dst):
        d, ok = _parse_float(xp, tb, tl)
        return make_column(ctx, dst, d.astype(t.to_np_dtype(dst)),
                           and_validity(ctx, val, ok))
    raise NotImplementedError(f"cast string -> {dst} on TPU")


def _parse_bool(ctx, tb, tl, val):
    xp = ctx.xp

    def is_word(word: bytes):
        wb = np.frombuffer(word.ljust(tb.shape[1], b"\0"), dtype=np.uint8)
        lower = xp.where((tb >= 65) & (tb <= 90), tb + 32, tb)
        return (tl == len(word)) & (lower == xp.asarray(wb)).all(axis=1) | \
            ((tl == len(word)) &
             (xp.where(xp.arange(tb.shape[1]) < tl[:, None], lower, 0)
              == xp.asarray(wb)).all(axis=1))

    lower = xp.where((tb >= 65) & (tb <= 90), tb + 32, tb)

    def word_eq(word: bytes):
        wb = np.frombuffer(word.ljust(tb.shape[1], b"\0"), dtype=np.uint8)
        return (tl == len(word)) & (lower == xp.asarray(wb)).all(axis=1)

    true_m = word_eq(b"true") | word_eq(b"t") | word_eq(b"yes") | \
        word_eq(b"y") | word_eq(b"1")
    false_m = word_eq(b"false") | word_eq(b"f") | word_eq(b"no") | \
        word_eq(b"n") | word_eq(b"0")
    ok = true_m | false_m
    return make_column(ctx, t.BOOLEAN, true_m, and_validity(ctx, val, ok))


def _parse_long(xp, tb, tl):
    W = tb.shape[1]
    pos = xp.arange(W, dtype=xp.int32)
    neg = tb[:, 0] == ord("-")
    plus = tb[:, 0] == ord("+")
    shift = (neg | plus).astype(xp.int32)
    ndig = tl - shift
    digpos = pos[None, :] + shift[:, None]
    rowidx = xp.arange(tb.shape[0], dtype=xp.int32)[:, None]
    db = tb[rowidx, xp.clip(digpos, 0, W - 1)]
    in_d = pos[None, :] < ndig[:, None]
    is_digit = (db >= ord("0")) & (db <= ord("9"))
    ok = (ndig >= 1) & (ndig <= 19) & (is_digit | ~in_d).all(axis=1)
    dvals = xp.where(in_d, (db - ord("0")).astype(xp.int64),
                     xp.zeros((), xp.int64))
    # value = sum d_j * 10^(ndig-1-j)
    p10 = xp.asarray(np.concatenate([
        np.power(np.int64(10), np.arange(18, -1, -1)), np.zeros(max(W - 19, 0),
                                                                np.int64)]))
    expo = xp.clip(ndig[:, None] - 1 - pos[None, :], 0, 18)
    mult = xp.asarray(np.power(np.int64(10), np.arange(19)))[expo]
    value = xp.sum(xp.where(in_d, dvals * mult, 0), axis=1)
    value = xp.where(neg, -value, value)
    return value, ok


def _parse_float(xp, tb, tl):
    """Parse [sign] digits [. digits] [e sign digits] — no inf/nan words."""
    W = tb.shape[1]
    pos = xp.arange(W, dtype=xp.int32)
    rowidx = xp.arange(tb.shape[0], dtype=xp.int32)[:, None]
    neg = tb[:, 0] == ord("-")
    plus = tb[:, 0] == ord("+")
    shift = (neg | plus).astype(xp.int32)
    in_s = pos[None, :] < tl[:, None]
    is_digit = (tb >= ord("0")) & (tb <= ord("9"))
    is_dot = tb == ord(".")
    is_e = (tb == ord("e")) | (tb == ord("E"))
    # locate dot and e
    dot_any = (is_dot & in_s).any(axis=1)
    dot_pos = xp.where(dot_any, xp.argmax(is_dot & in_s, axis=1),
                       tl).astype(xp.int32)
    e_any = (is_e & in_s).any(axis=1)
    e_pos = xp.where(e_any, xp.argmax(is_e & in_s, axis=1), tl).astype(xp.int32)
    mant_end = xp.minimum(e_pos, tl)
    # integer part digits: [shift, min(dot,mant_end)); frac: (dot, mant_end)
    int_end = xp.minimum(dot_pos, mant_end)
    ip = pos[None, :]
    in_int = (ip >= shift[:, None]) & (ip < int_end[:, None])
    in_frac = (ip > dot_pos[:, None]) & (ip < mant_end[:, None])
    dval = xp.where(is_digit, (tb - ord("0")).astype(xp.float64), 0.0)
    int_w = xp.where(in_int, dval, 0.0)
    # value of int part: digits weighted by 10^(int_end-1-j)
    ie = xp.clip(int_end[:, None] - 1 - ip, -1, W)
    int_val = xp.sum(xp.where(in_int, int_w * xp.power(10.0, ie.astype(xp.float64)), 0.0), axis=1)
    fe = xp.clip(ip - dot_pos[:, None], 1, W).astype(xp.float64)
    frac_val = xp.sum(xp.where(in_frac, dval * xp.power(10.0, -fe), 0.0), axis=1)
    mant = int_val + frac_val
    # exponent
    e_start = e_pos + 1
    eneg = tb[rowidx[:, 0], xp.clip(e_start, 0, W - 1)] == ord("-")
    epl = tb[rowidx[:, 0], xp.clip(e_start, 0, W - 1)] == ord("+")
    es = e_start + (eneg | epl).astype(xp.int32)
    in_exp = (ip >= es[:, None]) & (ip < tl[:, None])
    ee = xp.clip(tl[:, None] - 1 - ip, 0, 8)
    exp_val = xp.sum(xp.where(in_exp, dval * xp.power(10.0, ee.astype(xp.float64)), 0.0),
                     axis=1).astype(xp.float64)
    exp_val = xp.where(e_any, xp.where(eneg, -exp_val, exp_val), 0.0)
    value = xp.where(neg, -mant, mant) * xp.power(10.0, exp_val)
    # validity: digits present; all chars are legal; single dot/e
    legal = is_digit | is_dot | is_e | (tb == ord("-")) | (tb == ord("+"))
    has_digit = (is_digit & in_s).any(axis=1)
    ok = has_digit & (xp.where(in_s, legal, True)).all(axis=1) & \
        (xp.sum((is_dot & in_s), axis=1) <= 1) & \
        (xp.sum((is_e & in_s), axis=1) <= 1) & (tl >= 1)
    ok = ok & (~e_any | (is_digit[rowidx[:, 0], xp.clip(tl - 1, 0, W - 1)]))
    return value, ok


def _parse_date(ctx, tb, tl, val):
    """yyyy-MM-dd; the 3.0 dialect (shims lenient_string_to_date) also
    accepts unpadded yyyy-M-d forms, matching Spark 3.0's loose parser
    vs the 3.1+ strict ISO requirement (ref per-shim date parsing)."""
    from ..shims import active_shim
    xp = ctx.xp
    W = tb.shape[1]
    is_digit = (tb >= ord("0")) & (tb <= ord("9"))
    dash = tb == ord("-")
    dv = (tb - ord("0")).astype(xp.int64)
    y = dv[:, 0] * 1000 + dv[:, 1] * 100 + dv[:, 2] * 10 + dv[:, 3]
    # strict: positions 0-3 digits, 4 dash, 5-6 digits, 7 dash, 8-9 digits
    strict = (tl == 10) & is_digit[:, 0] & is_digit[:, 1] & is_digit[:, 2] & \
        is_digit[:, 3] & dash[:, 4] & is_digit[:, 5] & is_digit[:, 6] & \
        dash[:, 7] & is_digit[:, 8] & is_digit[:, 9]
    m = dv[:, 5] * 10 + dv[:, 6]
    d = dv[:, 8] * 10 + dv[:, 9]
    ok = strict
    if active_shim().lenient_string_to_date() and W >= 10:
        # enumerate the three unpadded shapes: y-M-d, y-MM-d, y-M-dd
        prefix_ok = is_digit[:, 0] & is_digit[:, 1] & is_digit[:, 2] & \
            is_digit[:, 3] & dash[:, 4]
        for mlen, dlen in ((1, 1), (2, 1), (1, 2)):
            L = 4 + 1 + mlen + 1 + dlen
            shape = prefix_ok & (tl == L) & dash[:, 5 + mlen]
            for i in range(mlen):
                shape = shape & is_digit[:, 5 + i]
            for i in range(dlen):
                shape = shape & is_digit[:, 6 + mlen + i]
            lm = dv[:, 5] if mlen == 1 else dv[:, 5] * 10 + dv[:, 6]
            ld = dv[:, 6 + mlen] if dlen == 1 else \
                dv[:, 6 + mlen] * 10 + dv[:, 7 + mlen]
            m = xp.where(shape, lm, m)
            d = xp.where(shape, ld, d)
            ok = ok | shape
    ok = ok & (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31)
    days = _days_from_civil(xp, y, m, d)
    return make_column(ctx, t.DATE, days.astype(np.int32),
                       and_validity(ctx, val, ok))


# ---------------------------------------------------------------------------
# civil-calendar math (Howard Hinnant's algorithms; pure int vector math)
# ---------------------------------------------------------------------------

def _days_from_civil(xp, y, m, d):
    y = y - (m <= 2)
    era = xp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _civil_from_days(xp, z):
    z = z + 719468
    era = xp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d
