"""Expression-registry tail: the remaining reference rules
(ref GpuOverrides.scala:727-3048) that are thin wrappers, plan-internal
markers, or small kernels — NaN handling, null guards, decimal plumbing,
timestamp conversions, input-file block metadata.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from .core import (ColumnValue, EvalContext, Expression,
                   ScalarValue, evaluator, make_column)


def _as_col(ctx: EvalContext, v, dt):
    """Materialize a scalar value as a column (the idiom every evaluator
    in this package uses for mixed scalar/column children)."""
    if isinstance(v, ColumnValue):
        return v
    return make_column(ctx, dt, v.value if v.value is not None else 0,
                       None if v.value is not None else False)


def _col_validity(ctx: EvalContext, col):
    return col.validity if col.validity is not None else \
        ctx.xp.ones((col.capacity,), dtype=bool)



class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN (ref GpuNaNvl, arithmetic.scala)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def data_type(self):
        # Spark's nanvl(float, float) is float; anything else widens to
        # double (ref GpuNaNvl type signature)
        if all(isinstance(c.data_type(), t.FloatType)
               for c in self.children):
            return t.FLOAT
        return t.DOUBLE

    def sql(self):
        return f"nanvl({self.children[0].sql()}, {self.children[1].sql()})"


@evaluator(NaNvl)
def _eval_nanvl(e: NaNvl, ctx: EvalContext):
    xp = ctx.xp
    a = e.children[0].eval(ctx)
    b = e.children[1].eval(ctx)
    ac = _as_col(ctx, a, e.children[0].data_type())
    bc = _as_col(ctx, b, e.children[1].data_type())
    use_b = xp.isnan(ac.col.data)
    av = _col_validity(ctx, ac.col)
    bv = _col_validity(ctx, bc.col)
    out_t = e.data_type()
    np_t = np.float32 if isinstance(out_t, t.FloatType) else np.float64
    data = xp.where(use_b, bc.col.data.astype(np_t),
                    ac.col.data.astype(np_t))
    valid = xp.where(use_b, bv, av)
    return make_column(ctx, out_t, data, valid)


class InSet(Expression):
    """IN over a literal value set — the optimizer's large-list variant of
    In (ref GpuInSet, GpuOverrides.scala)."""

    def __init__(self, child: Expression, values):
        self.children = (child,)
        self.values = tuple(values)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"{self.children[0].sql()} IN ({len(self.values)} values)"


@evaluator(InSet)
def _eval_inset(e: InSet, ctx: EvalContext):
    # delegate to In's comparison machinery — it already handles string
    # children, literal widening, and the null-in-list semantics
    from .core import Literal
    from .predicates import In
    dt = e.children[0].data_type()
    lits = [Literal(v, dt) if v is not None else Literal(None, dt)
            for v in e.values]
    return In(e.children[0], lits).eval(ctx)


class AtLeastNNonNulls(Expression):
    """Used by df.dropna (ref GpuAtLeastNNonNulls)."""

    def __init__(self, n: int, children):
        self.n = int(n)
        self.children = tuple(children)

    def data_type(self):
        return t.BOOLEAN

    @property
    def nullable(self):
        return False

    def sql(self):
        cs = ", ".join(c.sql() for c in self.children)
        return f"atleastnnonnulls({self.n}, {cs})"


@evaluator(AtLeastNNonNulls)
def _eval_at_least_n(e: AtLeastNNonNulls, ctx: EvalContext):
    xp = ctx.xp
    cap = ctx.batch.capacity
    count = xp.zeros((cap,), dtype=np.int32)
    for ch in e.children:
        v = ch.eval(ctx)
        c = _as_col(ctx, v, ch.data_type())
        ok = _col_validity(ctx, c.col)
        if isinstance(ch.data_type(), (t.DoubleType, t.FloatType)):
            ok = ok & ~xp.isnan(c.col.data)
        count = count + ok.astype(np.int32)
    return make_column(ctx, t.BOOLEAN, count >= e.n, None)


class _PassThrough(Expression):
    """Plan-internal marker wrappers: evaluate to their child unchanged."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type()

    def sql(self):
        return self.children[0].sql()


class KnownNotNull(_PassThrough):
    """Optimizer non-null assertion (ref GpuKnownNotNull)."""

    @property
    def nullable(self):
        return False


class KnownFloatingPointNormalized(_PassThrough):
    """Marker above NormalizeNaNAndZero (ref GpuKnownFloatingPointNormalized)."""


class PromotePrecision(_PassThrough):
    """Decimal precision promotion marker — the cast below it already
    produced the target type (ref GpuPromotePrecision)."""


@evaluator(KnownNotNull)
@evaluator(KnownFloatingPointNormalized)
@evaluator(PromotePrecision)
def _eval_passthrough(e: _PassThrough, ctx: EvalContext):
    return e.children[0].eval(ctx)


class UnscaledValue(Expression):
    """decimal -> raw unscaled long (ref GpuUnscaledValue) — the decimal64
    lane IS the unscaled value, so this is a relabel."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return t.LONG

    def sql(self):
        return f"unscaledvalue({self.children[0].sql()})"


@evaluator(UnscaledValue)
def _eval_unscaled(e: UnscaledValue, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    c = _as_col(ctx, v, e.children[0].data_type())
    return make_column(ctx, t.LONG, c.col.data.astype(np.int64),
                       _col_validity(ctx, c.col))


class MakeDecimal(Expression):
    """long unscaled -> decimal (ref GpuMakeDecimal)."""

    def __init__(self, child: Expression, precision: int, scale: int):
        self.children = (child,)
        self.precision = int(precision)
        self.scale = int(scale)

    def data_type(self):
        return t.DecimalType(self.precision, self.scale)

    def sql(self):
        return (f"makedecimal({self.children[0].sql()}, "
                f"{self.precision}, {self.scale})")


@evaluator(MakeDecimal)
def _eval_make_decimal(e: MakeDecimal, ctx: EvalContext):
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    c = _as_col(ctx, v, e.children[0].data_type())
    valid = _col_validity(ctx, c.col)
    data = c.col.data.astype(np.int64)
    if e.precision >= 19:
        ok = valid  # every int64 unscaled value fits precision >= 19
    else:
        bound = np.int64(10 ** e.precision)
        ok = valid & (data > -bound) & (data < bound)
    col = DeviceColumn(e.data_type(),
                       data=xp.where(ok, data, xp.zeros_like(data)),
                       validity=ok)
    if not e.data_type().is64:
        col.data_hi = xp.where(data < 0, xp.full_like(data, -1),
                               xp.zeros_like(data))
    return ColumnValue(col)


class CheckOverflow(Expression):
    """Null out decimal values beyond the target precision
    (ref GpuCheckOverflow, nullOnOverflow mode)."""

    def __init__(self, child: Expression, precision: int, scale: int,
                 null_on_overflow: bool = True):
        self.children = (child,)
        self.precision = int(precision)
        self.scale = int(scale)
        self.null_on_overflow = null_on_overflow

    def data_type(self):
        return t.DecimalType(self.precision, self.scale)

    def sql(self):
        return f"checkoverflow({self.children[0].sql()})"


@evaluator(CheckOverflow)
def _eval_check_overflow(e: CheckOverflow, ctx: EvalContext):
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    c = _as_col(ctx, v, e.children[0].data_type())
    valid = _col_validity(ctx, c.col)
    if e.precision > 18:
        # 128-bit bound checks live in the cast kernels; pass through
        return ColumnValue(DeviceColumn(e.data_type(), data=c.col.data,
                                        data_hi=c.col.data_hi,
                                        validity=valid))
    bound = np.int64(10 ** e.precision)
    data = c.col.data.astype(np.int64)
    ok = valid & (data > -bound) & (data < bound)
    return ColumnValue(DeviceColumn(
        e.data_type(), data=xp.where(ok, data, xp.zeros_like(data)),
        validity=ok))


class PreciseTimestampConversion(Expression):
    """Exact timestamp <-> long conversion the window TimeAdd rewrite
    uses (ref GpuPreciseTimestampConversion)."""

    def __init__(self, child: Expression, from_type, to_type):
        self.children = (child,)
        self._from = from_type
        self._to = to_type

    def data_type(self):
        return self._to

    def sql(self):
        return f"precisetimestampconversion({self.children[0].sql()})"


@evaluator(PreciseTimestampConversion)
def _eval_precise_ts(e: PreciseTimestampConversion, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    c = _as_col(ctx, v, e.children[0].data_type())
    # both directions are identity on the micros lane
    return make_column(ctx, e.data_type(), c.col.data.astype(np.int64),
                       _col_validity(ctx, c.col))


class InputFileBlockStart(Expression):
    """Byte offset of the current input block; whole-file reads start at
    0 (ref GpuInputFileBlockStart; the PERFILE reader reads whole files)."""

    children = ()

    def data_type(self):
        return t.LONG

    def sql(self):
        return "input_file_block_start()"


class InputFileBlockLength(Expression):
    """Length of the current block = the whole file under PERFILE reads
    (ref GpuInputFileBlockLength)."""

    children = ()

    def data_type(self):
        return t.LONG

    def sql(self):
        return "input_file_block_length()"


def _file_block(ctx, want_length: bool):
    import os
    from ..io.scan import current_input_file
    path = current_input_file()
    if want_length:
        try:
            val = os.path.getsize(path) if path else -1
        except OSError:
            val = -1
    else:
        val = 0 if path else -1
    return val


@evaluator(InputFileBlockStart)
def _eval_block_start(e, ctx: EvalContext):
    return make_column(ctx, t.LONG, np.int64(_file_block(ctx, False)), None)


@evaluator(InputFileBlockLength)
def _eval_block_length(e, ctx: EvalContext):
    return make_column(ctx, t.LONG, np.int64(_file_block(ctx, True)), None)
