"""Literal parameterization: hoist constant scalars out of traced
closures so structurally identical queries share one compiled program.

Ref: the reference plugin amortizes kernel setup across queries through
its process-wide execution layer; here the analogous win is collapsing
the jit key space.  A bound expression tree like ``v > 5`` bakes the
``5`` into the traced computation, so ``v > 9999`` — the same program
shape — compiles a second XLA program.  `parameterize_exprs` rewrites
eligible ``Literal`` nodes into `ParamLiteral` slots whose values ride
into the kernel as *traced scalar arguments*; the jit key then carries
only (slot, dtype) and the two queries dispatch to one executable.

Safety rules (wrong sharing is silently wrong results, so the pass is
deliberately conservative):

* only literals under whitelisted parents (plain comparisons, +/-/*
  arithmetic, If/CaseWhen value arms and IN item lists) are hoisted —
  those evaluators are pure array math with no host-side branching on
  the scalar's VALUE.  Divide/Pmod and friends stay value-keyed
  (zero-divisor handling), as do decimal / boolean literals (scale
  logic and ``bool()`` coercion concretize the value).
* string literals hoist as uint8 char arrays whose BYTE LENGTH stays
  in the jit key (array shape is static under tracing anyway); the
  string evaluators reachable from the whitelisted parents derive
  hashes / order keys / broadcast columns on DEVICE from the traced
  chars, so only same-length strings share a program — `'abc' = s`
  and `'xyz' = s` dispatch to one executable.
* non-null values only: null literals flow through evaluator validity
  short-circuits that branch on ``is_null``.
* a parameterized tree may key a jit entry ONLY where the parameter
  values are actually threaded as call arguments — `ParamLiteral`'s
  evaluator falls back to the baked value when no params are bound, so
  host-path (numpy) evaluation needs no threading, but a traced closure
  built from a parameterized tree without passing params would bake the
  first query's constants under a shared key.  The exec-side helpers in
  exec/basic.py are the reference wiring.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .. import types as t
from .core import (EvalContext, Expression, LeafExpression, Literal,
                   ScalarValue, evaluator)

# parents whose evaluators treat both operands as opaque array operands
# (promote + cast + xp op): safe to feed a traced scalar
from .arithmetic import Add, Multiply, Subtract
from .conditional import CaseWhen, If
from .predicates import (EqualNullSafe, EqualTo, GreaterThan,
                         GreaterThanOrEqual, In, LessThan,
                         LessThanOrEqual)

PARAM_PARENTS = (EqualTo, EqualNullSafe, LessThan, LessThanOrEqual,
                 GreaterThan, GreaterThanOrEqual,
                 Add, Subtract, Multiply,
                 # value arms blend via _value_parts / _string_select
                 # (xp.full / device gather — no host branching)
                 If, CaseWhen)

# value domains whose evaluators never concretize the scalar: fixed-
# width numerics and the day/microsecond integer encodings
_PARAM_DTYPES = (t.ByteType, t.ShortType, t.IntegerType, t.LongType,
                 t.FloatType, t.DoubleType, t.DateType, t.TimestampType)


class ParamLiteral(LeafExpression):
    """A literal hoisted to runtime-parameter slot `slot`.

    Keeps the original value so unparameterized evaluation (numpy host
    path, plan printing) behaves exactly like the `Literal` it
    replaced; the semantic signature deliberately EXCLUDES the value —
    that is the whole point."""

    def __init__(self, slot: int, dtype: t.DataType, value):
        self.slot = slot
        self.dtype = dtype
        self.value = value

    def data_type(self):
        return self.dtype

    @property
    def nullable(self):
        return False

    def _semantic_sig_(self):
        if isinstance(self.dtype, t.StringType):
            # byte length stays in the key: the chars ride as a traced
            # uint8 array whose (static) shape is the length anyway
            return ("ParamLiteral", self.slot, repr(self.dtype),
                    len(self.value))
        return ("ParamLiteral", self.slot, repr(self.dtype))

    def sql(self):
        return f"$param{self.slot}"


@evaluator(ParamLiteral)
def _eval_param_literal(e: ParamLiteral, ctx: EvalContext):
    params = getattr(ctx, "params", None)
    if params is not None:
        return ScalarValue(params[e.slot], e.dtype)
    return ScalarValue(e.value, e.dtype)


def _eligible(lit: Expression) -> bool:
    if type(lit) is not Literal or lit.value is None:
        return False
    if isinstance(lit.dtype, _PARAM_DTYPES):
        return True
    # strings hoist as char arrays (empty strings stay baked: a
    # zero-length traced operand buys nothing and the string kernels
    # assume at least one char of backing data)
    return isinstance(lit.dtype, t.StringType) and len(lit.value) > 0


def _np_param(lit):
    """The slot's call-time value: an np scalar typed from the literal's
    DataType (strings: the utf-8 chars as a uint8 array) so the jit
    dispatch signature is value-independent."""
    if isinstance(lit.dtype, t.StringType):
        return np.frombuffer(lit.value, dtype=np.uint8)
    return np.dtype(t.to_np_dtype(lit.dtype)).type(lit.value)


def _rewrite(e: Expression, values: List) -> Expression:
    new_children = []
    changed = False
    hoist = isinstance(e, PARAM_PARENTS)
    for c in e.children:
        if hoist and _eligible(c):
            values.append(_np_param(c))
            nc = ParamLiteral(len(values) - 1, c.dtype, c.value)
        else:
            nc = _rewrite(c, values)
        changed |= nc is not c
        new_children.append(nc)
    node = e.with_children(new_children) if changed else e
    if isinstance(e, In):
        # item literals ride `items`, not `children` — _eval_in's per-
        # item compare is the same promote+cast array math as the
        # binary comparisons, so they hoist under the same rules
        new_items, items_changed = [], False
        for it in e.items:
            if _eligible(it):
                values.append(_np_param(it))
                new_items.append(ParamLiteral(len(values) - 1,
                                              it.dtype, it.value))
                items_changed = True
            else:
                new_items.append(it)
        if items_changed:
            if node is e:
                node = e.with_children(list(e.children))
            node.items = tuple(new_items)
    return node


def parameterize_exprs(bound: Sequence[Expression]
                       ) -> Tuple[List[Expression], Tuple]:
    """Rewrite eligible literals in already-BOUND expression trees.

    Returns (trees, params): `trees` with `ParamLiteral` slots in slot
    order across the whole sequence, and `params` the matching tuple of
    np-typed scalar values to pass at call time.  `params` is empty
    when nothing was eligible — callers then keep the original
    value-baked jit wiring (and its value-carrying key)."""
    values: List = []
    out = [_rewrite(b, values) for b in bound]
    if not values:
        return list(bound), ()
    return out, tuple(values)


def param_values(trees: Sequence[Expression]) -> Tuple:
    """Re-derive the call-time parameter tuple from rewritten trees
    (slot order is the collection order of `parameterize_exprs`)."""
    lits: List[ParamLiteral] = []

    def visit(e: Expression):
        if isinstance(e, ParamLiteral):
            lits.append(e)
        for c in e.children:
            visit(c)
        # In keeps its literal list OUTSIDE children
        for it in getattr(e, "items", ()):
            visit(it)

    for b in trees:
        visit(b)
    lits.sort(key=lambda p: p.slot)
    return tuple(_np_param(p) for p in lits)
