"""Literal parameterization: hoist constant scalars out of traced
closures so structurally identical queries share one compiled program.

Ref: the reference plugin amortizes kernel setup across queries through
its process-wide execution layer; here the analogous win is collapsing
the jit key space.  A bound expression tree like ``v > 5`` bakes the
``5`` into the traced computation, so ``v > 9999`` — the same program
shape — compiles a second XLA program.  `parameterize_exprs` rewrites
eligible ``Literal`` nodes into `ParamLiteral` slots whose values ride
into the kernel as *traced scalar arguments*; the jit key then carries
only (slot, dtype) and the two queries dispatch to one executable.

Safety rules (wrong sharing is silently wrong results, so the pass is
deliberately conservative):

* only literals under whitelisted parents (plain comparisons and
  +/-/* arithmetic) are hoisted — those evaluators are pure array math
  with no host-side branching on the scalar's VALUE.  Divide/Pmod and
  friends stay value-keyed (zero-divisor handling), as do string /
  decimal / boolean literals (host-side key derivation, scale logic and
  ``bool()`` coercion all concretize the value).
* non-null values only: null literals flow through evaluator validity
  short-circuits that branch on ``is_null``.
* a parameterized tree may key a jit entry ONLY where the parameter
  values are actually threaded as call arguments — `ParamLiteral`'s
  evaluator falls back to the baked value when no params are bound, so
  host-path (numpy) evaluation needs no threading, but a traced closure
  built from a parameterized tree without passing params would bake the
  first query's constants under a shared key.  The exec-side helpers in
  exec/basic.py are the reference wiring.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .. import types as t
from .core import (EvalContext, Expression, LeafExpression, Literal,
                   ScalarValue, evaluator)

# parents whose evaluators treat both operands as opaque array operands
# (promote + cast + xp op): safe to feed a traced scalar
from .arithmetic import Add, Multiply, Subtract
from .predicates import (EqualNullSafe, EqualTo, GreaterThan,
                         GreaterThanOrEqual, LessThan, LessThanOrEqual)

PARAM_PARENTS = (EqualTo, EqualNullSafe, LessThan, LessThanOrEqual,
                 GreaterThan, GreaterThanOrEqual,
                 Add, Subtract, Multiply)

# value domains whose evaluators never concretize the scalar: fixed-
# width numerics and the day/microsecond integer encodings
_PARAM_DTYPES = (t.ByteType, t.ShortType, t.IntegerType, t.LongType,
                 t.FloatType, t.DoubleType, t.DateType, t.TimestampType)


class ParamLiteral(LeafExpression):
    """A literal hoisted to runtime-parameter slot `slot`.

    Keeps the original value so unparameterized evaluation (numpy host
    path, plan printing) behaves exactly like the `Literal` it
    replaced; the semantic signature deliberately EXCLUDES the value —
    that is the whole point."""

    def __init__(self, slot: int, dtype: t.DataType, value):
        self.slot = slot
        self.dtype = dtype
        self.value = value

    def data_type(self):
        return self.dtype

    @property
    def nullable(self):
        return False

    def _semantic_sig_(self):
        return ("ParamLiteral", self.slot, repr(self.dtype))

    def sql(self):
        return f"$param{self.slot}"


@evaluator(ParamLiteral)
def _eval_param_literal(e: ParamLiteral, ctx: EvalContext):
    params = getattr(ctx, "params", None)
    if params is not None:
        return ScalarValue(params[e.slot], e.dtype)
    return ScalarValue(e.value, e.dtype)


def _eligible(lit: Expression) -> bool:
    return (type(lit) is Literal and lit.value is not None
            and isinstance(lit.dtype, _PARAM_DTYPES))


def _np_param(lit: Literal):
    """The slot's call-time value: an np scalar typed from the literal's
    DataType so the jit dispatch signature is value-independent."""
    return np.dtype(t.to_np_dtype(lit.dtype)).type(lit.value)


def _rewrite(e: Expression, values: List) -> Expression:
    new_children = []
    changed = False
    hoist = isinstance(e, PARAM_PARENTS)
    for c in e.children:
        if hoist and _eligible(c):
            values.append(_np_param(c))
            nc = ParamLiteral(len(values) - 1, c.dtype, c.value)
        else:
            nc = _rewrite(c, values)
        changed |= nc is not c
        new_children.append(nc)
    return e.with_children(new_children) if changed else e


def parameterize_exprs(bound: Sequence[Expression]
                       ) -> Tuple[List[Expression], Tuple]:
    """Rewrite eligible literals in already-BOUND expression trees.

    Returns (trees, params): `trees` with `ParamLiteral` slots in slot
    order across the whole sequence, and `params` the matching tuple of
    np-typed scalar values to pass at call time.  `params` is empty
    when nothing was eligible — callers then keep the original
    value-baked jit wiring (and its value-carrying key)."""
    values: List = []
    out = [_rewrite(b, values) for b in bound]
    if not values:
        return list(bound), ()
    return out, tuple(values)


def param_values(trees: Sequence[Expression]) -> Tuple:
    """Re-derive the call-time parameter tuple from rewritten trees
    (slot order is the collection order of `parameterize_exprs`)."""
    lits: List[ParamLiteral] = []
    for b in trees:
        lits += b.collect(lambda e: isinstance(e, ParamLiteral))
    lits.sort(key=lambda p: p.slot)
    return tuple(np.dtype(t.to_np_dtype(p.dtype)).type(p.value)
                 for p in lits)
