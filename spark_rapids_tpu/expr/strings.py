"""String expressions over (offsets, bytes) tensors.

Ref: org/apache/spark/sql/rapids/stringFunctions.scala (+ GpuOverrides
string rules): Upper, Lower, Length, Substring, Concat, Trim family,
Contains/StartsWith/EndsWith, Like, StringReplace, StringRepeat, Reverse,
Lpad/Rpad, Locate/InStr, SubstringIndex.

All device kernels are O(char_cap)-style vectorized byte ops:
  * substring is UTF-8 character-correct via a global is-char-start prefix
    sum + per-row binary search;
  * literal search (contains/replace/locate) unrolls over the (static)
    needle bytes — one fused compare per needle byte;
  * replace builds the output with a per-input-byte contribution-length
    map (0 = inside a match, 1 = copied, R = match start emits the
    replacement) and a cumsum + searchsorted gather;
  * upper/lower handle ASCII exactly (non-ASCII passes through unchanged —
    gated behind incompatibleOps like the reference's corner-case ops).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
from ..ops.scan import cumsum_fast

from .. import types as t
from ..columnar.device import DEFAULT_CHAR_BUCKETS, DeviceColumn, bucket_for
from ..ops import strings as sops
from .core import (ColumnValue, EvalContext, Expression, Literal,
                   ScalarValue, and_validity, evaluator, make_column,
                   validity_of)


def _string_input(ctx: EvalContext, v, dtype=t.STRING) -> DeviceColumn:
    from .conditional import _as_string_column
    return _as_string_column(ctx, v, dtype).col


def _literal_bytes(e: Expression) -> Optional[bytes]:
    if isinstance(e, Literal) and isinstance(e.dtype, (t.StringType,
                                                       t.BinaryType)):
        v = e.value
        if v is None:
            return None
        return v if isinstance(v, bytes) else str(v).encode()
    return None


def _char_starts(xp, chars):
    """bool per byte: UTF-8 sequence start (not a continuation byte)."""
    return (chars & np.uint8(0xC0)) != np.uint8(0x80)


class StringUnary(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return t.STRING


class Upper(StringUnary):
    pass


class Lower(StringUnary):
    pass


def _case_map(e, ctx: EvalContext, upper: bool):
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    xp = ctx.xp
    c = col.data
    if upper:
        is_lo = (c >= ord("a")) & (c <= ord("z"))
        out = xp.where(is_lo, c - np.uint8(32), c)
    else:
        is_up = (c >= ord("A")) & (c <= ord("Z"))
        out = xp.where(is_up, c + np.uint8(32), c)
    return ColumnValue(DeviceColumn(t.STRING, data=out, offsets=col.offsets,
                                    validity=col.validity))


@evaluator(Upper)
def _eval_upper(e, ctx):
    return _case_map(e, ctx, True)


@evaluator(Lower)
def _eval_lower(e, ctx):
    return _case_map(e, ctx, False)


class Length(StringUnary):
    def data_type(self):
        return t.INT


@evaluator(Length)
def _eval_length(e, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    xp = ctx.xp
    # Spark length() counts characters, not bytes
    starts = _char_starts(xp, col.data).astype(xp.int32)
    pre = xp.concatenate([xp.zeros((1,), xp.int32), cumsum_fast(xp, starts,
                                                              dtype=xp.int32)])
    nchars = pre[col.offsets[1:]] - pre[col.offsets[:-1]]
    return make_column(ctx, t.INT, nchars.astype(np.int32), col.validity)


class Ascii(StringUnary):
    """ascii(s): code point of the FIRST character; 0 for empty strings
    (ref stringFunctions.scala GpuAscii).  Full UTF-8 decode of the lead
    sequence (1-4 bytes), matching Spark's behavior on non-ASCII."""

    def data_type(self):
        return t.INT


@evaluator(Ascii)
def _eval_ascii(e, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    xp = ctx.xp
    cap = max(int(col.data.shape[0]) - 1, 0)
    starts = col.offsets[:-1]
    lens = col.offsets[1:] - col.offsets[:-1]

    def byte_at(k):
        ok = lens > k
        idx = xp.clip(starts + k, 0, cap)
        return xp.where(ok, col.data[idx],
                        xp.zeros((), col.data.dtype)).astype(np.int32)

    b0, b1, b2, b3 = byte_at(0), byte_at(1), byte_at(2), byte_at(3)
    c1 = b0                                              # 0xxxxxxx
    c2 = ((b0 & 0x1F) << 6) | (b1 & 0x3F)                # 110xxxxx
    c3 = ((b0 & 0x0F) << 12) | ((b1 & 0x3F) << 6) | (b2 & 0x3F)
    c4 = ((b0 & 0x07) << 18) | ((b1 & 0x3F) << 12) |         ((b2 & 0x3F) << 6) | (b3 & 0x3F)
    out = xp.where(b0 < 0x80, c1,
                   xp.where(b0 < 0xE0, c2,
                            xp.where(b0 < 0xF0, c3, c4)))
    out = xp.where(lens == 0, xp.zeros_like(out), out)
    return make_column(ctx, t.INT, out.astype(np.int32), col.validity)


class BitLength(StringUnary):
    def data_type(self):
        return t.INT


@evaluator(BitLength)
def _eval_bitlength(e, ctx):
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    lens = (col.offsets[1:] - col.offsets[:-1]) * 8
    return make_column(ctx, t.INT, lens.astype(np.int32), col.validity)


class Substring(Expression):
    """substring(str, pos, len) — 1-based, character semantics, negative
    pos counts from the end (Spark)."""

    def __init__(self, child, pos, length=None):
        self.children = (child, pos) + ((length,) if length is not None
                                        else ())

    def data_type(self):
        return t.STRING


@evaluator(Substring)
def _eval_substring(e: Substring, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    pv = e.children[1].eval(ctx)
    from .core import data_of
    pos = data_of(pv, ctx)
    if hasattr(pos, "astype"):
        pos = pos.astype(xp.int64)
    ln = None
    if len(e.children) > 2:
        lv = e.children[2].eval(ctx)
        ln = data_of(lv, ctx)
        if hasattr(ln, "astype"):
            ln = ln.astype(xp.int64)
    starts = _char_starts(xp, col.data).astype(xp.int64)
    pre = xp.concatenate([xp.zeros((1,), xp.int64), cumsum_fast(xp, starts)])
    row_char0 = pre[col.offsets[:-1]]
    nchars = pre[col.offsets[1:]] - row_char0
    # resolve 1-based/negative pos to 0-based char index
    p = pos if hasattr(pos, "shape") and getattr(pos, "shape", ()) else \
        xp.full((ctx.capacity,), np.int64(pos))
    # Spark substringSQL: raw start may be negative; end derives from the
    # RAW start, then both clamp into [0, nchars]
    start_raw = xp.where(p > 0, p - 1, xp.where(p < 0, nchars + p,
                                                xp.zeros_like(nchars)))
    if ln is None:
        end_raw = nchars
    else:
        lnv = ln if hasattr(ln, "shape") and getattr(ln, "shape", ()) else \
            xp.full((ctx.capacity,), np.int64(ln))
        end_raw = start_raw + xp.maximum(lnv, 0)
    start_c = xp.clip(start_raw, 0, nchars)
    end_c = xp.clip(end_raw, start_c, nchars)
    # char index -> byte position: searchsorted over the global char prefix
    def char_to_byte(ci):
        # start byte of (0-based) global char index g: first p with
        # pre[p+1] >= g+1
        tgt = row_char0 + ci
        return xp.searchsorted(pre[1:], tgt + 1,
                               side="left").astype(xp.int32)
    b0 = char_to_byte(start_c)
    b1 = char_to_byte(end_c)
    b0 = xp.clip(b0, col.offsets[:-1], col.offsets[1:])
    b1 = xp.clip(b1, b0, col.offsets[1:])
    # gather spans [b0, b1)
    new_lens = (b1 - b0).astype(xp.int32)
    valid = col.validity if col.validity is not None else \
        xp.ones((ctx.capacity,), dtype=bool)
    new_offs = xp.concatenate([
        xp.zeros((1,), xp.int32),
        cumsum_fast(xp, xp.where(valid, new_lens, 0), dtype=xp.int32)])
    out_cap = int(col.data.shape[0])
    q = xp.arange(out_cap, dtype=xp.int32)
    row = xp.clip(xp.searchsorted(new_offs[1:], q, side="right"),
                  0, ctx.capacity - 1).astype(xp.int32)
    src = xp.clip(b0[row] + (q - new_offs[row]), 0, out_cap - 1)
    chars = xp.where(q < new_offs[-1], col.data[src],
                     xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=new_offs,
                                    validity=valid))


class Concat(Expression):
    def __init__(self, *children):
        self.children = tuple(children)

    def data_type(self):
        return t.STRING


class ConcatWs(Expression):
    def __init__(self, sep, *children):
        self.children = (sep,) + tuple(children)

    def data_type(self):
        return t.STRING

    @property
    def nullable(self):
        return self.children[0].nullable


@evaluator(Concat)
def _eval_concat(e: Concat, ctx: EvalContext):
    xp = ctx.xp
    cols = [_string_input(ctx, c.eval(ctx)) for c in e.children]
    cap = ctx.capacity
    validity = None
    for c in cols:
        cv = c.validity
        validity = cv if validity is None else (validity & cv) \
            if cv is not None else validity
    if validity is None:
        validity = xp.ones((cap,), dtype=bool)
    lens = [c.offsets[1:] - c.offsets[:-1] for c in cols]
    total_len = lens[0]
    for l in lens[1:]:
        total_len = total_len + l
    total_len = xp.where(validity, total_len, 0)
    new_offs = xp.concatenate([xp.zeros((1,), xp.int32),
                               cumsum_fast(xp, total_len, dtype=xp.int32)])
    out_cap = int(sum(int(c.data.shape[0]) for c in cols))
    out_cap = bucket_for(out_cap, DEFAULT_CHAR_BUCKETS)
    q = xp.arange(out_cap, dtype=xp.int32)
    row = xp.clip(xp.searchsorted(new_offs[1:], q, side="right"),
                  0, cap - 1).astype(xp.int32)
    local = q - new_offs[row]
    chars = xp.zeros((out_cap,), dtype=xp.uint8)
    prefix = xp.zeros((cap,), dtype=xp.int32)
    for c, l in zip(cols, lens):
        in_this = (local >= prefix[row]) & (local < (prefix + l)[row])
        src = xp.clip(c.offsets[:-1][row] + (local - prefix[row]), 0,
                      c.data.shape[0] - 1)
        chars = xp.where(in_this, c.data[src], chars)
        prefix = prefix + l
    chars = xp.where(q < new_offs[-1], chars, xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=new_offs,
                                    validity=validity))


class Trim(StringUnary):
    mode = "both"


class TrimLeft(Trim):
    mode = "left"


class TrimRight(Trim):
    mode = "right"


def _trim_impl(e: Trim, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    cap = ctx.capacity
    is_sp = col.data == np.uint8(32)
    nsp = xp.concatenate([xp.zeros((1,), xp.int64),
                          cumsum_fast(xp, (~is_sp).astype(xp.int64))])
    o0 = col.offsets[:-1].astype(xp.int64)
    o1 = col.offsets[1:].astype(xp.int64)
    if e.mode in ("both", "left"):
        # first nonspace at/after o0
        b0 = xp.searchsorted(nsp, nsp[o0] + 1, side="left") - 1
        b0 = xp.minimum(b0.astype(xp.int32), o1.astype(xp.int32))
    else:
        b0 = o0.astype(xp.int32)
    if e.mode in ("both", "right"):
        # last nonspace before o1: position p with nsp[p+1] == nsp[o1]
        b1 = xp.searchsorted(nsp, nsp[o1], side="left")
        b1 = xp.maximum(b1.astype(xp.int32), b0)
    else:
        b1 = o1.astype(xp.int32)
    empty = nsp[o1] == nsp[o0]  # all spaces
    b0 = xp.where(empty, o0.astype(xp.int32), b0)
    b1 = xp.where(empty, o0.astype(xp.int32), b1)
    valid = col.validity if col.validity is not None else \
        xp.ones((cap,), dtype=bool)
    new_lens = b1 - b0
    new_offs = xp.concatenate([
        xp.zeros((1,), xp.int32),
        cumsum_fast(xp, xp.where(valid, new_lens, 0), dtype=xp.int32)])
    out_cap = int(col.data.shape[0])
    q = xp.arange(out_cap, dtype=xp.int32)
    row = xp.clip(xp.searchsorted(new_offs[1:], q, side="right"),
                  0, cap - 1).astype(xp.int32)
    src = xp.clip(b0[row] + (q - new_offs[row]), 0, out_cap - 1)
    chars = xp.where(q < new_offs[-1], col.data[src], xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=new_offs,
                                    validity=valid))


evaluator(Trim)(_trim_impl)
from .core import _EVALUATORS  # noqa: E402
_EVALUATORS[TrimLeft] = _trim_impl
_EVALUATORS[TrimRight] = _trim_impl


class StringPredicate(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self):
        return t.BOOLEAN


class Contains(StringPredicate):
    pass


class StartsWith(StringPredicate):
    pass


class EndsWith(StringPredicate):
    pass


def _match_positions(xp, chars, needle: bytes, wildcard: int = -1):
    """bool per byte: needle matches starting at this byte (unrolled over
    the static needle).  Bytes equal to `wildcard` match anything."""
    n = chars.shape[0]
    m = xp.ones((n,), dtype=bool)
    for j, b in enumerate(needle):
        idx = xp.clip(xp.arange(n) + j, 0, n - 1)
        if b == wildcard:
            m = m & (xp.arange(n) + j < n)
        else:
            m = m & (chars[idx] == np.uint8(b)) & (xp.arange(n) + j < n)
    return m


def _contains_impl(e, ctx: EvalContext, kind: str):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    needle = _literal_bytes(e.children[1])
    if needle is None:
        if isinstance(e.children[1], Literal):
            return make_column(ctx, t.BOOLEAN,
                               xp.zeros((ctx.capacity,), bool), False)
        raise NotImplementedError("column needle requires literal")
    val = validity_of(v, ctx)
    o0 = col.offsets[:-1].astype(xp.int64)
    o1 = col.offsets[1:].astype(xp.int64)
    L = len(needle)
    if L == 0:
        return make_column(ctx, t.BOOLEAN,
                           xp.ones((ctx.capacity,), bool), val)
    m = _match_positions(xp, col.data, needle)
    if kind == "starts":
        data = (o1 - o0 >= L) & m[xp.clip(o0, 0, col.data.shape[0] - 1)]
    elif kind == "ends":
        p = xp.clip(o1 - L, 0, col.data.shape[0] - 1)
        data = (o1 - o0 >= L) & m[p]
    else:
        pre = xp.concatenate([xp.zeros((1,), xp.int64),
                              cumsum_fast(xp, m.astype(xp.int64))])
        hi = xp.clip(o1 - L + 1, o0, col.data.shape[0])
        data = (pre[hi] - pre[o0]) > 0
    return make_column(ctx, t.BOOLEAN, data, val)


@evaluator(Contains)
def _eval_contains(e, ctx):
    return _contains_impl(e, ctx, "contains")


@evaluator(StartsWith)
def _eval_startswith(e, ctx):
    return _contains_impl(e, ctx, "starts")


@evaluator(EndsWith)
def _eval_endswith(e, ctx):
    return _contains_impl(e, ctx, "ends")


class Like(Expression):
    """SQL LIKE with % wildcards (and _ only in fixed-length patterns)."""

    def __init__(self, child, pattern: Expression):
        self.children = (child, pattern)

    def data_type(self):
        return t.BOOLEAN

    def pattern_bytes(self):
        return _literal_bytes(self.children[1])


@evaluator(Like)
def _eval_like(e: Like, ctx: EvalContext):
    xp = ctx.xp
    pat = e.pattern_bytes()
    if pat is None:
        raise NotImplementedError("LIKE requires a literal pattern")
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    val = validity_of(v, ctx)
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(xp.int64)
    if b"_" in pat and b"%" not in pat:
        # fixed-length with single-char wildcards (byte-level)
        L = len(pat)
        b, _ = sops.window_bytes(xp, col.offsets, col.data, max(L, 1))
        ok = lens == L
        for j, pb in enumerate(pat):
            if pb != ord("_"):
                ok = ok & (b[:, j] == np.uint8(pb))
        return make_column(ctx, t.BOOLEAN, ok, val)
    wc = ord("_")
    parts = pat.split(b"%")
    first, last = parts[0], parts[-1]
    middles = [p for p in parts[1:-1] if p]
    min_len = sum(len(p) for p in parts)
    data = lens >= min_len
    o0 = col.offsets[:-1].astype(xp.int64)
    o1 = col.offsets[1:].astype(xp.int64)
    cur = o0 + 0
    if first:
        m = _match_positions(xp, col.data, first, wc)
        data = data & (lens >= len(first)) & \
            m[xp.clip(o0, 0, col.data.shape[0] - 1)]
        cur = o0 + len(first)
    # middle tokens must appear in order
    for tok in middles:
        m = _match_positions(xp, col.data, tok, wc)
        pre = xp.concatenate([xp.zeros((1,), xp.int64),
                              cumsum_fast(xp, m.astype(xp.int64))])
        limit = o1 - len(last) - len(tok) + 1
        limit = xp.clip(limit, cur, col.data.shape[0])
        found = (pre[limit] - pre[xp.clip(cur, 0, col.data.shape[0])]) > 0
        # next position after the first occurrence >= cur
        tgt = pre[xp.clip(cur, 0, col.data.shape[0])]
        nxt = xp.searchsorted(pre, tgt + 1, side="left") - 1
        cur = xp.where(found, nxt + len(tok), limit + 1)
        data = data & found
    if last and len(parts) > 1:
        m = _match_positions(xp, col.data, last, wc)
        p = xp.clip(o1 - len(last), 0, col.data.shape[0] - 1)
        data = data & (lens >= len(last)) & m[p] & \
            (o1 - len(last) >= cur)
    elif len(parts) == 1:
        data = data & (lens == len(pat))
    return make_column(ctx, t.BOOLEAN, data, val)


class StringReplace(Expression):
    def __init__(self, child, search, replace):
        self.children = (child, search, replace)

    def data_type(self):
        return t.STRING


def _pattern_self_overlaps(pat: bytes) -> bool:
    """True if the pattern can overlap itself (proper border exists)."""
    for k in range(1, len(pat)):
        if pat[:len(pat) - k] == pat[k:]:
            return True
    return False


@evaluator(StringReplace)
def _eval_replace(e: StringReplace, ctx: EvalContext):
    xp = ctx.xp
    search = _literal_bytes(e.children[1])
    repl = _literal_bytes(e.children[2])
    if search is None or repl is None:
        raise NotImplementedError("replace requires literal search/replace")
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    val = col.validity
    if len(search) == 0:
        return ColumnValue(col)
    if _pattern_self_overlaps(search):
        # greedy non-overlapping selection is sequential; keep off TPU
        raise NotImplementedError(
            "replace with self-overlapping pattern")
    n = int(col.data.shape[0])
    L, R = len(search), len(repl)
    m = _match_positions(xp, col.data, search)
    # constrain matches within one row's span
    q = xp.arange(n, dtype=xp.int32)
    row = xp.clip(xp.searchsorted(col.offsets[1:], q, side="right"),
                  0, ctx.capacity - 1).astype(xp.int32)
    m = m & ((q + L) <= col.offsets[1:][row])
    # contribution length per input byte
    in_match_tail = xp.zeros((n,), dtype=bool)
    for j in range(1, L):
        idx = xp.clip(xp.arange(n) - j, 0, n - 1)
        in_match_tail = in_match_tail | (m[idx] & (xp.arange(n) >= j))
    cl = xp.where(m, np.int32(R), xp.where(in_match_tail, np.int32(0),
                                           np.int32(1)))
    cpre = xp.concatenate([xp.zeros((1,), xp.int32),
                           cumsum_fast(xp, cl, dtype=xp.int32)])
    new_offs = cpre[col.offsets]
    out_cap = bucket_for(max(int(n * max(1, (R + L - 1) // L)), 1),
                         DEFAULT_CHAR_BUCKETS) if R > L else \
        bucket_for(max(n, 1), DEFAULT_CHAR_BUCKETS)
    p = xp.arange(out_cap, dtype=xp.int32)
    src = xp.clip(xp.searchsorted(cpre[1:], p, side="right"), 0,
                  n - 1).astype(xp.int32)
    within = p - cpre[src]
    rbytes = xp.asarray(np.frombuffer(repl.ljust(max(R, 1), b"\0"),
                                      dtype=np.uint8))
    out = xp.where(m[src], rbytes[xp.clip(within, 0, max(R - 1, 0))],
                   col.data[src])
    total = cpre[-1]
    out = xp.where(p < total, out, xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=out, offsets=new_offs,
                                    validity=val))


class StringRepeat(Expression):
    def __init__(self, child, times):
        self.children = (child, times)

    def data_type(self):
        return t.STRING


@evaluator(StringRepeat)
def _eval_repeat(e: StringRepeat, ctx: EvalContext):
    xp = ctx.xp
    from .core import data_of
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    tv = e.children[1].eval(ctx)
    times = data_of(tv, ctx)
    cap = ctx.capacity
    if not (hasattr(times, "shape") and getattr(times, "shape", ())):
        times = xp.full((cap,), np.int64(int(times)))
    times = xp.clip(times.astype(xp.int64), 0, 64)
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(xp.int64)
    valid = and_validity(ctx, col.validity, validity_of(tv, ctx))
    if valid is None:
        valid = xp.ones((cap,), dtype=bool)
    elif valid is False:
        valid = xp.zeros((cap,), dtype=bool)
    new_lens = xp.where(valid, lens * times, 0)
    new_offs = xp.concatenate([xp.zeros((1,), xp.int32),
                               cumsum_fast(xp, new_lens, dtype=xp.int64)
                               .astype(xp.int32)])
    out_cap = bucket_for(max(int(col.data.shape[0]) * 4, 1),
                         DEFAULT_CHAR_BUCKETS)
    q = xp.arange(out_cap, dtype=xp.int64)
    row = xp.clip(xp.searchsorted(new_offs[1:], q, side="right"),
                  0, cap - 1).astype(xp.int32)
    local = q - new_offs[row]
    ln = xp.maximum(lens[row], 1)
    src = xp.clip(col.offsets[:-1][row].astype(xp.int64) + local % ln, 0,
                  col.data.shape[0] - 1)
    chars = xp.where(q < new_offs[-1], col.data[src], xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=new_offs,
                                    validity=valid))


class Reverse(StringUnary):
    """Byte-wise reverse (exact for ASCII; gated for multi-byte UTF-8)."""


@evaluator(Reverse)
def _eval_reverse(e, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    n = int(col.data.shape[0])
    q = xp.arange(n, dtype=xp.int32)
    row = xp.clip(xp.searchsorted(col.offsets[1:], q, side="right"),
                  0, ctx.capacity - 1).astype(xp.int32)
    o0 = col.offsets[:-1][row]
    o1 = col.offsets[1:][row]
    src = xp.clip(o1 - 1 - (q - o0), 0, n - 1)
    in_span = q < col.offsets[-1]
    chars = xp.where(in_span, col.data[src], col.data)
    return ColumnValue(DeviceColumn(t.STRING, data=chars,
                                    offsets=col.offsets,
                                    validity=col.validity))


class StringLocate(Expression):
    """locate(substr, str, start=1): 1-based position, 0 = not found."""

    def __init__(self, substr, child, start=None):
        self.children = (substr, child) + ((start,) if start is not None
                                           else ())

    def data_type(self):
        return t.INT


@evaluator(StringLocate)
def _eval_locate(e: StringLocate, ctx: EvalContext):
    xp = ctx.xp
    needle = _literal_bytes(e.children[0])
    if needle is None:
        raise NotImplementedError("locate requires a literal substring")
    v = e.children[1].eval(ctx)
    col = _string_input(ctx, v)
    val = validity_of(v, ctx)
    o0 = col.offsets[:-1].astype(xp.int64)
    o1 = col.offsets[1:].astype(xp.int64)
    L = len(needle)
    if L == 0:
        return make_column(ctx, t.INT,
                           xp.ones((ctx.capacity,), np.int32), val)
    m = _match_positions(xp, col.data, needle)
    pre = xp.concatenate([xp.zeros((1,), xp.int64),
                          cumsum_fast(xp, m.astype(xp.int64))])
    start_off = o0
    if len(e.children) > 2:
        from .core import data_of
        sv = e.children[2].eval(ctx)
        s = data_of(sv, ctx)
        if not (hasattr(s, "shape") and getattr(s, "shape", ())):
            s = xp.full((ctx.capacity,), np.int64(int(s)))
        start_off = o0 + xp.clip(s.astype(xp.int64) - 1, 0, None)
    # first match position >= start_off
    base = pre[xp.clip(start_off, 0, col.data.shape[0])]
    first = xp.searchsorted(pre, base + 1, side="left") - 1
    limit = o1 - L
    found = (first <= limit) & (first >= start_off) & \
        (pre[xp.clip(o1 - L + 1, 0, col.data.shape[0])] - base > 0)
    posn = xp.where(found, first - o0 + 1, 0).astype(np.int32)
    return make_column(ctx, t.INT, posn, val)


class StringLPad(Expression):
    side = "left"

    def __init__(self, child, length, pad):
        self.children = (child, length, pad)

    def data_type(self):
        return t.STRING


class StringRPad(StringLPad):
    side = "right"


def _pad_impl(e: StringLPad, ctx: EvalContext):
    xp = ctx.xp
    from .core import data_of
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    pad = _literal_bytes(e.children[2]) or b" "
    lv = e.children[1].eval(ctx)
    target = data_of(lv, ctx)
    cap = ctx.capacity
    if not (hasattr(target, "shape") and getattr(target, "shape", ())):
        target = xp.full((cap,), np.int64(int(target)))
    target = xp.clip(target.astype(xp.int64), 0, 1 << 20)
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(xp.int64)
    valid = col.validity if col.validity is not None else \
        xp.ones((cap,), dtype=bool)
    new_lens = xp.where(valid, target, 0)
    new_offs = xp.concatenate([xp.zeros((1,), xp.int32),
                               cumsum_fast(xp, new_lens).astype(xp.int32)])
    out_cap = bucket_for(max(int(col.data.shape[0]) * 2, 1024),
                         DEFAULT_CHAR_BUCKETS)
    q = xp.arange(out_cap, dtype=xp.int64)
    row = xp.clip(xp.searchsorted(new_offs[1:], q, side="right"),
                  0, cap - 1).astype(xp.int32)
    local = q - new_offs[row]
    strlen = xp.minimum(lens[row], target[row])
    padlen = target[row] - strlen
    pb = xp.asarray(np.frombuffer(pad, dtype=np.uint8))
    if e.side == "left":
        in_pad = local < padlen
        src_str = col.offsets[:-1][row].astype(xp.int64) + (local - padlen)
        pad_idx = local % len(pad)
    else:
        in_pad = local >= strlen
        src_str = col.offsets[:-1][row].astype(xp.int64) + local
        pad_idx = (local - strlen) % len(pad)
    src_str = xp.clip(src_str, 0, col.data.shape[0] - 1)
    chars = xp.where(in_pad, pb[xp.clip(pad_idx, 0, len(pad) - 1)],
                     col.data[src_str])
    chars = xp.where(q < new_offs[-1], chars, xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=chars, offsets=new_offs,
                                    validity=valid))


evaluator(StringLPad)(_pad_impl)
_EVALUATORS[StringRPad] = _pad_impl


class InitCap(StringUnary):
    """Capitalize the first letter of each word (ASCII)."""


@evaluator(InitCap)
def _eval_initcap(e, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    c = col.data
    n = c.shape[0]
    prev = xp.concatenate([xp.full((1,), np.uint8(32)), c[:-1]])
    # word start: previous byte is space, or byte is at a row start
    row_start = xp.zeros((n,), dtype=bool)
    starts = xp.clip(col.offsets[:-1], 0, n - 1)
    if xp is np:
        row_start[starts] = True
    else:
        row_start = row_start.at[starts].set(True)
    word_start = (prev == 32) | row_start
    lo = xp.where((c >= 65) & (c <= 90), c + np.uint8(32), c)
    up = xp.where((c >= 97) & (c <= 122), c - np.uint8(32), c)
    out = xp.where(word_start, up, lo)
    return ColumnValue(DeviceColumn(t.STRING, data=out, offsets=col.offsets,
                                    validity=col.validity))


@evaluator(ConcatWs)
def _eval_concat_ws(e: ConcatWs, ctx: EvalContext):
    """concat_ws(sep, s1, s2, ...): null args are SKIPPED (unlike concat,
    which nulls the whole row); null separator -> null result
    (ref stringFunctions.scala GpuConcatWs semantics).  Host evaluation —
    the variable piece-skipping layout has no fixed-shape device form yet,
    so tagging keeps the projection on CPU like the regex family."""
    from .regex import _host_only, build_string_column, np_string_rows
    _host_only(ctx, "concat_ws")
    cap = ctx.capacity
    cols = [np_string_rows(_string_input(ctx, c.eval(ctx)), cap)
            for c in e.children]
    sep_rows, arg_rows = cols[0], cols[1:]
    out = []
    for i in range(cap):
        sep = sep_rows[i]
        if sep is None:
            out.append(None)
            continue
        out.append(sep.join(r[i] for r in arg_rows if r[i] is not None))
    return build_string_column(ctx, out)


class SubstringIndex(Expression):
    """substring_index(str, delim, count) (ref GpuSubstringIndex).

    Single-byte delimiters lower to a device occurrence scan; multi-byte
    delimiters need non-overlapping forward search (a sequential
    dependency) and stay on the host engine via tagging."""

    def __init__(self, child, delim, count):
        self.children = (child,)
        self.delim = delim
        self.count = int(count)

    def data_type(self):
        return t.STRING

    def delim_bytes(self) -> bytes:
        """The ONE definition of the delimiter's byte form — the tag rule
        and the evaluator both gate on its length, and divergence would
        turn a graceful host fallback into a runtime error."""
        return self.delim.encode() if isinstance(self.delim, str) \
            else bytes(self.delim)

    def sql(self):
        return (f"substring_index({self.children[0].sql()}, "
                f"'{self.delim}', {self.count})")


@evaluator(SubstringIndex)
def _eval_substring_index(e: SubstringIndex, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = _string_input(ctx, v)
    valid = col.validity if col.validity is not None else \
        xp.ones((ctx.capacity,), dtype=bool)
    delim = e.delim_bytes()
    cnt = e.count
    if xp is np:
        # host engine: python string semantics match Spark's indexOf scan
        out = []
        offs = np.asarray(col.offsets)
        chars = np.asarray(col.data)
        vm = np.asarray(valid)
        d = delim.decode("utf-8", "surrogateescape")
        for i in range(ctx.capacity):
            if not vm[i]:
                out.append("")
                continue
            sv = bytes(chars[offs[i]:offs[i + 1]]).decode(
                "utf-8", "surrogateescape")
            if cnt == 0 or not d:
                out.append("")
            elif cnt > 0:
                out.append(d.join(sv.split(d)[:cnt]))
            else:
                out.append(d.join(sv.split(d)[cnt:]))
        lens = np.array([len(o.encode("utf-8", "surrogateescape"))
                         for o in out], np.int32)
        new_offs = np.concatenate([np.zeros(1, np.int32),
                                   np.cumsum(lens, dtype=np.int32)])
        buf = b"".join(o.encode("utf-8", "surrogateescape") for o in out)
        cap_b = max(int(col.data.shape[0]), 1)
        data = np.zeros((cap_b,), np.uint8)
        data[:len(buf)] = np.frombuffer(buf, np.uint8)
        return ColumnValue(DeviceColumn(t.STRING, data=data,
                                        offsets=new_offs,
                                        validity=valid))
    if len(delim) != 1:
        from .core import EvalError
        raise EvalError("substring_index with multi-byte delimiter runs "
                        "on the host engine (tagging keeps it off the "
                        "device)")
    from ..ops.scan import cumsum_fast as _cs
    from ..ops.scan import fill_rows_from_starts
    char_cap = int(col.data.shape[0])
    cap = ctx.capacity
    b_row0 = col.offsets[:-1]
    b_row1 = col.offsets[1:]
    pos = xp.arange(char_cap, dtype=xp.int32)
    match = (col.data == np.uint8(delim[0])).astype(xp.int32)
    cm = _cs(xp, match)                  # inclusive global match count
    cmp_ = xp.concatenate([xp.zeros((1,), cm.dtype), cm])
    base = cmp_[xp.clip(b_row0, 0, char_cap)]
    total = cmp_[xp.clip(b_row1, 0, char_cap)] - base
    if cnt == 0:
        b0 = b_row0
        b1 = b_row0
    else:
        q = xp.full((cap,), np.int32(cnt)) if cnt > 0 else \
            (total + np.int32(cnt + 1)).astype(xp.int32)
        # char -> row, then per-char occurrence ordinal within its row
        spans = b_row1 - b_row0
        crow = xp.clip(
            fill_rows_from_starts(xp, b_row0.astype(xp.int32), spans > 0,
                                  char_cap), 0, cap - 1)
        occ = cm - base[crow]            # inclusive ordinal at match chars
        want = q[crow]
        hit = (match > 0) & (occ == want) & (pos < b_row1[crow]) & \
            (pos >= b_row0[crow])
        cand = xp.where(hit, pos, np.int32(2**31 - 1))
        import jax
        hitpos = jax.ops.segment_min(
            cand, crow, num_segments=cap)    # int32 scatter (~free)
        found = hitpos < np.int32(2**31 - 1)
        if cnt > 0:
            b0 = b_row0
            b1 = xp.where(found, xp.clip(hitpos, 0, char_cap), b_row1)
            b1 = xp.clip(b1, b_row0, b_row1)
        else:
            # q <= 0 means fewer occurrences than |cnt|: whole string
            b0 = xp.where((q > 0) & found,
                          xp.clip(hitpos + 1, 0, char_cap), b_row0)
            b0 = xp.clip(b0, b_row0, b_row1)
            b1 = b_row1
    new_lens = (b1 - b0).astype(xp.int32)
    new_offs = xp.concatenate([
        xp.zeros((1,), xp.int32),
        _cs(xp, xp.where(valid, new_lens, 0), dtype=xp.int32)])
    q2 = xp.arange(char_cap, dtype=xp.int32)
    row = xp.clip(fill_rows_from_starts(xp, new_offs[:-1].astype(xp.int32),
                                        new_lens > 0, char_cap),
                  0, cap - 1)
    src = xp.clip(b0[row] + (q2 - new_offs[row]), 0, char_cap - 1)
    chars = xp.where(q2 < new_offs[-1], col.data[src],
                     xp.zeros((), xp.uint8))
    return ColumnValue(DeviceColumn(t.STRING, data=chars,
                                    offsets=new_offs, validity=valid))
