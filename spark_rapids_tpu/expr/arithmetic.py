"""Arithmetic expressions with Spark SQL semantics.

Ref: org/apache/spark/sql/rapids/arithmetic.scala and the rules registered
in GpuOverrides.scala (Add, Subtract, Multiply, Divide, IntegralDivide,
Remainder, Pmod, UnaryMinus, Abs, ...).

Semantics notes (match Spark, not numpy defaults):
  * integral overflow wraps in non-ANSI mode, errors in ANSI mode;
  * x / 0, x % 0 -> NULL in non-ANSI mode (never inf/nan for integrals);
  * Divide always produces double (analyzer casts) or decimal;
  * decimal add/sub rescale to max scale; multiply adds scales.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .. import types as t
from .core import (ColumnValue, EvalContext, Expression, ScalarValue, Value,
                   and_validity, data_of, evaluator, make_column, validity_of)


# ---------------------------------------------------------------------------
# numeric type promotion (Spark's findTightestCommonType subset)
# ---------------------------------------------------------------------------

_INT_ORDER = [t.ByteType, t.ShortType, t.IntegerType, t.LongType]


def promote(a: t.DataType, b: t.DataType) -> t.DataType:
    if a == b:
        return a
    if isinstance(a, t.NullType):
        return b
    if isinstance(b, t.NullType):
        return a
    if isinstance(a, t.DoubleType) or isinstance(b, t.DoubleType):
        return t.DOUBLE
    if isinstance(a, t.FloatType) or isinstance(b, t.FloatType):
        return t.FLOAT
    if isinstance(a, t.DecimalType) and isinstance(b, t.DecimalType):
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return t.DecimalType(min(intd + scale, t.MAX_DECIMAL128_PRECISION), scale)
    if isinstance(a, t.DecimalType) and t.is_integral(b):
        return promote(a, _decimal_of_integral(b))
    if isinstance(b, t.DecimalType) and t.is_integral(a):
        return promote(_decimal_of_integral(a), b)
    if t.is_integral(a) and t.is_integral(b):
        ia = _INT_ORDER.index(type(a))
        ib = _INT_ORDER.index(type(b))
        return a if ia >= ib else b
    raise TypeError(f"cannot promote {a} and {b}")


def _decimal_of_integral(dt: t.DataType) -> t.DecimalType:
    p = {t.ByteType: 3, t.ShortType: 5, t.IntegerType: 10, t.LongType: 20}[type(dt)]
    return t.DecimalType(min(p, 38), 0)


def cast_data(ctx: EvalContext, data, src: t.DataType, dst: t.DataType):
    """Numeric representation change (no bounds checking — plain widen)."""
    if src == dst:
        return data
    xp = ctx.xp
    if isinstance(dst, t.DecimalType):
        if isinstance(src, t.DecimalType):
            if dst.scale == src.scale:
                return data
            if dst.scale > src.scale:
                k = dst.scale - src.scale
                return _widen_for(data, k, dst.precision > 18) * _pow10(k)
            return _div_round_half_up(xp, data, _pow10(src.scale - dst.scale))
        # integral -> decimal
        d64 = data.astype(np.int64)
        return _widen_for(d64, dst.scale,
                          dst.precision > 18) * _pow10(dst.scale)
    if isinstance(src, t.DecimalType):
        # decimal -> floating
        return data.astype(t.to_np_dtype(dst)) / (10.0 ** src.scale)
    if hasattr(data, "astype"):
        return data.astype(t.to_np_dtype(dst))
    return np.array(data, dtype=t.to_np_dtype(dst))[()]


def _pow10(k: int):
    """10**k as a multiplier: np.int64 while it fits (fast path), plain
    Python int beyond (object-array exact path on the CPU engine)."""
    return np.int64(10 ** k) if k <= 18 else 10 ** k


def _widen_for(data, k: int, force: bool = False):
    """Promote an int64 numpy array to an exact object array before a
    10**k multiply that could exceed 64 bits (CPU-oracle path; the TPU
    path is gated away from these shapes by TypeSig/cast tagging).
    `force` widens regardless of k — for results wider than 18 digits."""
    if (k > 18 or force) and isinstance(data, np.ndarray)             and data.dtype != object:
        return data.astype(object)
    return data


def _div_round_half_up(xp, num, den):
    """Integer divide rounding half away from zero (Spark decimal rounding)."""
    q = num // den
    r = num - q * den
    adj = (2 * xp.abs(r) >= den).astype(num.dtype) * xp.where(
        (num < 0), np.int64(-1), np.int64(1))
    # careful: python floor div on negatives; implement HALF_UP on magnitude
    trunc = xp.where(num < 0, -((-num) // den), num // den)
    r2 = xp.abs(num) - xp.abs(trunc) * den
    round_up = (2 * r2 >= den)
    mag = xp.abs(trunc) + round_up.astype(num.dtype)
    return xp.where(num < 0, -mag, mag)


class BinaryArithmetic(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def data_type(self):
        return promote(self.left.data_type(), self.right.data_type())

    def sql(self):
        return f"({self.children[0].sql()} {self.symbol} {self.children[1].sql()})"

    def result_decimal_type(self) -> Optional[t.DecimalType]:
        return None


def _binary_inputs(e: BinaryArithmetic, ctx: EvalContext,
                   out_type: t.DataType) -> Tuple:
    lv = e.left.eval(ctx)
    rv = e.right.eval(ctx)
    ld = cast_data(ctx, data_of(lv, ctx), lv.dtype if isinstance(lv, ColumnValue)
                   else e.left.data_type(), out_type)
    rd = cast_data(ctx, data_of(rv, ctx), rv.dtype if isinstance(rv, ColumnValue)
                   else e.right.data_type(), out_type)
    validity = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return ld, rd, validity


def _decimal_binary_type(op: str, lt: t.DecimalType, rt: t.DecimalType) -> t.DecimalType:
    """Spark DecimalPrecision result types."""
    p1, s1, p2, s2 = lt.precision, lt.scale, rt.precision, rt.scale
    if op in ("add", "sub"):
        scale = max(s1, s2)
        prec = max(p1 - s1, p2 - s2) + scale + 1
    elif op == "mul":
        scale = s1 + s2
        prec = p1 + p2 + 1
    elif op == "div":
        scale = max(6, s1 + p2 + 1)
        prec = p1 - s1 + s2 + scale
    elif op in ("mod",):
        scale = max(s1, s2)
        prec = min(p1 - s1, p2 - s2) + scale
    else:
        raise ValueError(op)
    return t.DecimalType(min(prec, t.MAX_DECIMAL128_PRECISION), min(scale, 38))


def _as_decimal(dt: t.DataType) -> t.DecimalType:
    return dt if isinstance(dt, t.DecimalType) else _decimal_of_integral(dt)


class Add(BinaryArithmetic):
    symbol = "+"

    def data_type(self):
        lt, rt = self.left.data_type(), self.right.data_type()
        if isinstance(lt, t.DecimalType) or isinstance(rt, t.DecimalType):
            return _decimal_binary_type("add", _as_decimal(lt), _as_decimal(rt))
        return promote(lt, rt)


class Subtract(BinaryArithmetic):
    symbol = "-"

    def data_type(self):
        lt, rt = self.left.data_type(), self.right.data_type()
        if isinstance(lt, t.DecimalType) or isinstance(rt, t.DecimalType):
            return _decimal_binary_type("sub", _as_decimal(lt), _as_decimal(rt))
        return promote(lt, rt)


class Multiply(BinaryArithmetic):
    symbol = "*"

    def data_type(self):
        lt, rt = self.left.data_type(), self.right.data_type()
        if isinstance(lt, t.DecimalType) or isinstance(rt, t.DecimalType):
            return _decimal_binary_type("mul", _as_decimal(lt), _as_decimal(rt))
        return promote(lt, rt)


@evaluator(Add)
def _eval_add(e: Add, ctx: EvalContext):
    out = e.data_type()
    if isinstance(out, t.DecimalType):
        return _decimal_addsub(e, ctx, out, +1)
    ld, rd, v = _binary_inputs(e, ctx, out)
    return make_column(ctx, out, ld + rd, v)


@evaluator(Subtract)
def _eval_sub(e: Subtract, ctx: EvalContext):
    out = e.data_type()
    if isinstance(out, t.DecimalType):
        return _decimal_addsub(e, ctx, out, -1)
    ld, rd, v = _binary_inputs(e, ctx, out)
    return make_column(ctx, out, ld - rd, v)


def _decimal_addsub(e: BinaryArithmetic, ctx: EvalContext,
                    out: t.DecimalType, sign: int):
    lv, rv = e.left.eval(ctx), e.right.eval(ctx)
    lt = _as_decimal(e.left.data_type())
    rt = _as_decimal(e.right.data_type())
    scale = out.scale
    ld = cast_data(ctx, data_of(lv, ctx), lt, t.DecimalType(38, scale))
    rd = cast_data(ctx, data_of(rv, ctx), rt, t.DecimalType(38, scale))
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    data = ld + rd if sign > 0 else ld - rd
    return make_column(ctx, out, data, v)


@evaluator(Multiply)
def _eval_mul(e: Multiply, ctx: EvalContext):
    out = e.data_type()
    if isinstance(out, t.DecimalType):
        lv, rv = e.left.eval(ctx), e.right.eval(ctx)
        v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
        ld = data_of(lv, ctx)
        rd = data_of(rv, ctx)
        if not hasattr(ld, "astype"):
            ld = np.int64(ld)
        if not hasattr(rd, "astype"):
            rd = np.int64(rd)
        ld = _widen_for(ld, 0, out.precision > 18)
        rd = _widen_for(rd, 0, out.precision > 18)
        return make_column(ctx, out, ld * rd, v)
    ld, rd, v = _binary_inputs(e, ctx, out)
    return make_column(ctx, out, ld * rd, v)


class Divide(BinaryArithmetic):
    symbol = "/"

    def data_type(self):
        lt, rt = self.left.data_type(), self.right.data_type()
        if isinstance(lt, t.DecimalType) or isinstance(rt, t.DecimalType):
            return _decimal_binary_type("div", _as_decimal(lt), _as_decimal(rt))
        return t.DOUBLE


@evaluator(Divide)
def _eval_div(e: Divide, ctx: EvalContext):
    xp = ctx.xp
    out = e.data_type()
    if isinstance(out, t.DecimalType):
        lv, rv = e.left.eval(ctx), e.right.eval(ctx)
        lt, rt = _as_decimal(e.left.data_type()), _as_decimal(e.right.data_type())
        ld, rd = data_of(lv, ctx), data_of(rv, ctx)
        if not hasattr(ld, "astype"):
            ld = np.int64(ld)
        if not hasattr(rd, "astype"):
            rd = np.int64(rd)
        # value = l*10^-s1 / (r*10^-s2) scaled to out.scale:
        #   unscaled = l * 10^(out.scale - s1 + s2) / r   (HALF_UP)
        shift = out.scale - lt.scale + rt.scale
        num = _widen_for(ld, max(shift, 0),
                         out.precision > 18) * _pow10(max(shift, 0))
        den = _widen_for(rd, max(-shift, 0)) * _pow10(max(-shift, 0))
        zero = den == 0
        den_safe = xp.where(zero, xp.ones_like(den), den)
        sign = xp.where((num < 0) != (den_safe < 0), -1, 1).astype(np.int64)
        q = _div_round_half_up(xp, xp.abs(num), xp.abs(den_safe)) * sign
        v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx),
                         None if not hasattr(zero, "shape") or zero.shape == ()
                         else ~zero)
        if (not hasattr(zero, "shape")) or zero.shape == ():
            if bool(zero):
                v = False
        return make_column(ctx, out, q, v)
    ld, rd, v = _binary_inputs(e, ctx, t.DOUBLE)
    rzero = rd == 0
    rd_safe = xp.where(rzero, xp.ones_like(rd), rd) if hasattr(rd, "shape") and rd.shape else (1.0 if rd == 0 else rd)
    data = ld / rd_safe
    if hasattr(rzero, "shape") and rzero.shape:
        v = and_validity(ctx, v, ~rzero)
    elif bool(rzero):
        v = False
    return make_column(ctx, out, data, v)


class IntegralDivide(BinaryArithmetic):
    symbol = "div"

    def data_type(self):
        return t.LONG


@evaluator(IntegralDivide)
def _eval_idiv(e: IntegralDivide, ctx: EvalContext):
    xp = ctx.xp
    ld, rd, v = _binary_inputs(e, ctx, t.LONG)
    rzero = rd == 0
    scalar_zero = not (hasattr(rzero, "shape") and rzero.shape)
    rd_safe = (1 if scalar_zero and bool(rzero) else rd) if scalar_zero \
        else xp.where(rzero, xp.ones_like(rd), rd)
    # Spark truncates toward zero; numpy // floors
    q = xp.where(xp.asarray((ld < 0) != (rd_safe < 0)),
                 -(xp.abs(ld) // xp.abs(rd_safe)),
                 xp.abs(ld) // xp.abs(rd_safe)).astype(np.int64)
    if scalar_zero:
        if bool(rzero):
            v = False
    else:
        v = and_validity(ctx, v, ~rzero)
    return make_column(ctx, t.LONG, q, v)


class Remainder(BinaryArithmetic):
    symbol = "%"


@evaluator(Remainder)
def _eval_rem(e: Remainder, ctx: EvalContext):
    xp = ctx.xp
    out = e.data_type()
    ld, rd, v = _binary_inputs(e, ctx, out)
    rzero = rd == 0
    scalar_zero = not (hasattr(rzero, "shape") and rzero.shape)
    rd_safe = (1 if scalar_zero and bool(rzero) else rd) if scalar_zero \
        else xp.where(rzero, xp.ones_like(rd), rd)
    # Spark remainder takes the sign of the dividend (C semantics), numpy mod
    # takes the divisor's.  fmod has C semantics.
    if isinstance(out, (t.FloatType, t.DoubleType)):
        data = xp.fmod(ld, rd_safe)
    else:
        data = ld - (xp.where((ld < 0) != (rd_safe < 0),
                              -(xp.abs(ld) // xp.abs(rd_safe)),
                              xp.abs(ld) // xp.abs(rd_safe))) * rd_safe
    if scalar_zero:
        if bool(rzero):
            v = False
    else:
        v = and_validity(ctx, v, ~rzero)
    return make_column(ctx, out, data, v)


class Pmod(BinaryArithmetic):
    symbol = "pmod"


@evaluator(Pmod)
def _eval_pmod(e: Pmod, ctx: EvalContext):
    xp = ctx.xp
    out = e.data_type()
    ld, rd, v = _binary_inputs(e, ctx, out)
    rzero = rd == 0
    scalar_zero = not (hasattr(rzero, "shape") and rzero.shape)
    rd_safe = (1 if scalar_zero and bool(rzero) else rd) if scalar_zero \
        else xp.where(rzero, xp.ones_like(rd), rd)
    # Spark pmod: r = C-style remainder(a, n); if r < 0 then r + n else r
    if isinstance(out, (t.FloatType, t.DoubleType)):
        r = xp.fmod(ld, rd_safe)
    else:
        trunc = xp.where(xp.asarray((ld < 0) != (rd_safe < 0)),
                         -(xp.abs(ld) // xp.abs(rd_safe)),
                         xp.abs(ld) // xp.abs(rd_safe))
        r = ld - trunc * rd_safe
    data = xp.where(r < 0, r + rd_safe, r)
    if scalar_zero:
        if bool(rzero):
            v = False
    else:
        v = and_validity(ctx, v, ~rzero)
    return make_column(ctx, out, data, v)


class UnaryMinus(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type()

    def sql(self):
        return f"(- {self.children[0].sql()})"


@evaluator(UnaryMinus)
def _eval_neg(e: UnaryMinus, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    return make_column(ctx, e.data_type(), -data_of(v, ctx),
                       validity_of(v, ctx))


class UnaryPositive(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type()


@evaluator(UnaryPositive)
def _eval_pos(e: UnaryPositive, ctx: EvalContext):
    return e.children[0].eval(ctx)


class Abs(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type()


@evaluator(Abs)
def _eval_abs(e: Abs, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    return make_column(ctx, e.data_type(), ctx.xp.abs(data_of(v, ctx)),
                       validity_of(v, ctx))


class Greatest(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def data_type(self):
        out = self.children[0].data_type()
        for c in self.children[1:]:
            out = promote(out, c.data_type())
        return out


class Least(Greatest):
    pass


def _eval_extreme(e, ctx: EvalContext, is_max: bool):
    # Spark: skips nulls; null only if all null
    xp = ctx.xp
    out = e.data_type()
    best = None
    best_valid = None
    for c in e.children:
        v = c.eval(ctx)
        src = v.dtype if isinstance(v, ColumnValue) else c.data_type()
        d = cast_data(ctx, data_of(v, ctx), src, out)
        val = validity_of(v, ctx)
        if val is None:
            val = xp.ones((ctx.capacity,), dtype=bool)
        elif val is False:
            val = xp.zeros((ctx.capacity,), dtype=bool)
        if not hasattr(d, "shape") or d.shape == ():
            d = xp.full((ctx.capacity,), d, dtype=t.to_np_dtype(out))
        if best is None:
            best, best_valid = d, val
        else:
            take_new = val & (~best_valid |
                              ((d > best) if is_max else (d < best)))
            best = xp.where(take_new, d, best)
            best_valid = best_valid | val
    return make_column(ctx, out, best, best_valid)


@evaluator(Greatest)
def _eval_greatest(e, ctx):
    return _eval_extreme(e, ctx, True)


@evaluator(Least)
def _eval_least(e, ctx):
    return _eval_extreme(e, ctx, False)
