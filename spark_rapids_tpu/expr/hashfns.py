"""Hash functions: Spark-compatible Murmur3 (hash()) and partition hashing.

Ref: org/apache/spark/sql/rapids/HashFunctions.scala, GpuMurmur3Hash;
the reference gets these from cudf and keeps bit-parity with Spark so that
hash partitioning matches between CPU and GPU — the same property this
implementation preserves between our CPU and TPU engines.

Spark's hash() is Murmur3_x86_32 with seed 42 over the value's Spark
representation: int-family widened to 4-byte int, long/timestamp as two
4-byte halves, double via Double.doubleToLongBits, strings over UTF-8
bytes.  Fixed-width inputs vectorize directly; strings process 4-byte
little-endian blocks with a bounded fori_loop, all rows in parallel.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .. import types as t
from .core import (ColumnValue, EvalContext, Expression, data_of, evaluator,
                   make_column, validity_of)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
SEED = np.uint32(42)


def _rotl(xp, x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k1(xp, k1):
    k1 = (k1 * _C1).astype(xp.uint32)
    k1 = _rotl(xp, k1, 15)
    return (k1 * _C2).astype(xp.uint32)


def _mix_h1(xp, h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(xp, h1, 13)
    return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(xp.uint32)


def _fmix(xp, h1, length):
    h1 = h1 ^ length.astype(xp.uint32)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(xp.uint32)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(xp.uint32)
    return h1 ^ (h1 >> np.uint32(16))


def hash_int32(xp, values, seed):
    """Murmur3 of a 4-byte int block (Spark hashInt)."""
    k1 = _mix_k1(xp, values.astype(xp.uint32))
    h1 = _mix_h1(xp, seed, k1)
    return _fmix(xp, h1, xp.full_like(h1, np.uint32(4)))


def hash_int64(xp, values, seed):
    """Spark hashLong: low word then high word."""
    v = values.astype(xp.uint64)
    lo = (v & xp.uint64(0xFFFFFFFF)).astype(xp.uint32)
    hi = (v >> xp.uint64(32)).astype(xp.uint32)
    h1 = _mix_h1(xp, seed, _mix_k1(xp, lo))
    h1 = _mix_h1(xp, h1, _mix_k1(xp, hi))
    return _fmix(xp, h1, xp.full_like(h1, np.uint32(8)))


def hash_bytes(xp, offsets, chars, seed_arr):
    """Per-row Murmur3 over byte spans (Spark hashUnsafeBytes).

    Processes 4-byte little-endian blocks; all rows advance together in a
    bounded loop over the max block count (traced while_loop on TPU)."""
    cap = offsets.shape[0] - 1
    lens = (offsets[1:] - offsets[:-1]).astype(xp.int64)
    nblocks = (lens // 4).astype(xp.int32)
    max_blocks = int(chars.shape[0] // 4) if xp is np else None

    def read_u32(block_i):
        base = offsets[:-1].astype(xp.int64) + block_i * 4
        b = [chars[xp.clip(base + j, 0, chars.shape[0] - 1)].astype(
            xp.uint32) for j in range(4)]
        return (b[0] | (b[1] << np.uint32(8)) | (b[2] << np.uint32(16))
                | (b[3] << np.uint32(24)))

    h1 = seed_arr
    if xp is np:
        mb = int(nblocks.max()) if cap else 0
        for i in range(mb):
            active = i < nblocks
            k1 = _mix_k1(np, read_u32(np.int64(i)))
            h1 = np.where(active, _mix_h1(np, h1, k1), h1)
    else:
        import jax

        def body(i, h):
            active = i < nblocks
            k1 = _mix_k1(xp, read_u32(i.astype(xp.int64)))
            return xp.where(active, _mix_h1(xp, h, k1), h)
        # traced upper bound lowers to while_loop; all rows step together
        h1 = jax.lax.fori_loop(0, jnp_max_int(xp, nblocks), body, h1)
    # tail bytes (Spark processes them one at a time as signed ints)
    tail_len = (lens % 4).astype(xp.int32)
    base = offsets[:-1].astype(xp.int64) + nblocks.astype(xp.int64) * 4
    for j in range(3):
        tb = chars[xp.clip(base + j, 0, chars.shape[0] - 1)]
        signed = tb.astype(xp.int8).astype(xp.int32).astype(xp.uint32)
        k1 = _mix_k1(xp, signed)
        h1 = xp.where(j < tail_len, _mix_h1(xp, h1, k1), h1)
    return _fmix(xp, h1, lens.astype(xp.uint32))


def jnp_max_int(xp, arr):
    # dynamic loop bound: fori_loop accepts traced upper bounds
    return xp.max(arr).astype(xp.int32) if arr.shape[0] else 0


def hash_column(xp, col, seed_arr, cap):
    """Spark-compatible hash of one column, folding into per-row seeds.
    Null rows leave the seed unchanged (Spark semantics)."""
    dtype = col.dtype
    validity = col.validity
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        h = hash_bytes(xp, col.offsets, col.data, seed_arr)
    elif isinstance(dtype, (t.LongType, t.TimestampType)):
        h = hash_int64(xp, col.data, seed_arr)
    elif isinstance(dtype, t.DoubleType):
        d = col.data
        d = xp.where(d == 0.0, xp.zeros_like(d), d)  # -0.0 -> 0.0
        bits = d.view(xp.int64) if hasattr(d, "view") else d.view(np.int64)
        h = hash_int64(xp, bits, seed_arr)
    elif isinstance(dtype, t.FloatType):
        d = col.data
        d = xp.where(d == 0.0, xp.zeros_like(d), d)
        bits = d.view(xp.int32) if hasattr(d, "view") else d.view(np.int32)
        h = hash_int32(xp, bits, seed_arr)
    elif isinstance(dtype, t.BooleanType):
        h = hash_int32(xp, col.data.astype(xp.int32), seed_arr)
    elif isinstance(dtype, t.DecimalType):
        # decimal64: Spark hashes the unscaled long when precision <= 18
        h = hash_int64(xp, col.data, seed_arr)
    elif isinstance(dtype, t.StructType):
        h = seed_arr
        for ch in col.children:
            h = hash_column(xp, ch, h, cap)
    else:
        h = hash_int32(xp, col.data.astype(xp.int32), seed_arr)
    if validity is not None:
        h = xp.where(validity, h, seed_arr)
    return h


class Murmur3Hash(Expression):
    def __init__(self, children: List[Expression], seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    def data_type(self):
        return t.INT

    @property
    def nullable(self):
        return False


@evaluator(Murmur3Hash)
def _eval_murmur3(e: Murmur3Hash, ctx: EvalContext):
    xp = ctx.xp
    cap = ctx.capacity
    h = xp.full((cap,), np.uint32(e.seed), dtype=xp.uint32)
    for c in e.children:
        v = c.eval(ctx)
        if not isinstance(v, ColumnValue):
            from .core import make_column as mk
            v = mk(ctx, c.data_type(), v.value if v.value is not None else 0,
                   None if v.value is not None else False)
        h = hash_column(xp, v.col, h, cap)
    return make_column(ctx, t.INT, h.astype(np.int32), None)


class SparkPartitionID(Expression):
    children = ()

    def data_type(self):
        return t.INT

    @property
    def nullable(self):
        return False


class Md5(Expression):
    """MD5 digest hex string — host-only (CPU engine), tagged off TPU like
    the reference tags unsupported exprs."""

    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        return t.STRING


class Rand(Expression):
    """rand([seed]): uniform [0,1) per row, deterministic in
    (seed, partition, row position).

    Ref: GpuOverrides registers rand (GpuRand); Spark's XORShiftRandom
    stream is NOT reproduced bit-for-bit (marked incompat, the
    reference's own pattern for sequence-sensitive ops) — but the CPU and
    TPU engines here produce IDENTICAL values, so differential tests and
    retried tasks agree."""

    children = ()

    def __init__(self, seed: int = 0):
        self.seed = int(seed)

    def data_type(self):
        return t.DOUBLE

    @property
    def nullable(self):
        return False

    def sql(self):
        return f"rand({self.seed})"


class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row position within the partition
    (ref GpuMonotonicallyIncreasingID.scala)."""

    children = ()

    def data_type(self):
        return t.LONG

    @property
    def nullable(self):
        return False

    def sql(self):
        return "monotonically_increasing_id()"


class InputFileName(Expression):
    """input_file_name(): path of the file feeding the current batch.

    Host-only: the value is per-file host metadata, not device data (the
    reference routes plans containing it through InputFileBlockRule.scala
    to keep the scan+project together on one side; here the CPU fallback
    Project plays that role, and exchange boundaries reset the file to
    the empty string exactly like Spark reports no file)."""

    children = ()

    def data_type(self):
        return t.STRING

    @property
    def nullable(self):
        return False

    def sql(self):
        return "input_file_name()"


def _splitmix64(xp, z):
    """SplitMix64 finalizer — one uint64 in, one well-mixed uint64 out."""
    z = (z + np.uint64(0x9E3779B97F4A7C15)).astype(xp.uint64)
    z = ((z ^ (z >> np.uint64(30))) *
         np.uint64(0xBF58476D1CE4E5B9)).astype(xp.uint64)
    z = ((z ^ (z >> np.uint64(27))) *
         np.uint64(0x94D049BB133111EB)).astype(xp.uint64)
    return z ^ (z >> np.uint64(31))


@evaluator(MonotonicallyIncreasingID)
def _eval_mid(e: MonotonicallyIncreasingID, ctx: EvalContext):
    xp = ctx.xp
    base = ctx.row_base
    if not isinstance(base, (int, np.integer)):
        base = base.astype(np.int64)
    data = (xp.arange(ctx.capacity, dtype=np.int64) + base)
    return make_column(ctx, t.LONG, data, None)


@evaluator(SparkPartitionID)
def _eval_spark_partition_id(e: SparkPartitionID, ctx: EvalContext):
    xp = ctx.xp
    base = ctx.row_base
    if isinstance(base, (int, np.integer)):
        pid = np.int32(int(base) >> 33)
        data = xp.full((ctx.capacity,), pid, dtype=np.int32)
    else:
        data = xp.broadcast_to((base >> np.int64(33)).astype(np.int32),
                               (ctx.capacity,))
    return make_column(ctx, t.INT, data, None)


@evaluator(Rand)
def _eval_rand(e: Rand, ctx: EvalContext):
    xp = ctx.xp
    base = ctx.row_base
    if not isinstance(base, (int, np.integer)):
        base = base.astype(np.int64)
    pos = (xp.arange(ctx.capacity, dtype=np.int64) + base)\
        .astype(np.uint64)
    mixed = _splitmix64(xp, pos ^ np.uint64(e.seed & 0xFFFFFFFFFFFFFFFF))
    # top 53 bits -> [0, 1)
    data = (mixed >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)
    return make_column(ctx, t.DOUBLE, data, None)


@evaluator(Md5)
def _eval_md5(e: Md5, ctx: EvalContext):
    import hashlib

    from .regex import _host_only, build_string_column
    _host_only(ctx, "md5")
    v = e.children[0].eval(ctx)
    if not isinstance(v, ColumnValue):
        v = make_column(ctx, e.children[0].data_type(),
                        v.value if v.value is not None else 0,
                        None if v.value is not None else False)
    offs = np.asarray(v.col.offsets)
    chars = np.asarray(v.col.data)
    valid = np.asarray(v.col.validity) if v.col.validity is not None \
        else np.ones(ctx.capacity, dtype=bool)
    out = []
    for i in range(ctx.capacity):
        if not valid[i]:
            out.append(None)
        else:
            raw = bytes(chars[offs[i]:offs[i + 1]])
            out.append(hashlib.md5(raw).hexdigest())
    return build_string_column(ctx, out)


@evaluator(InputFileName)
def _eval_input_file_name(e: InputFileName, ctx: EvalContext):
    from ..io.scan import current_input_file
    from .regex import _host_only, build_string_column
    _host_only(ctx, "input_file_name")
    return build_string_column(
        ctx, [current_input_file()] * ctx.capacity)
