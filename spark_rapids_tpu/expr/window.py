"""Window expressions.

Ref: sql-plugin/.../GpuWindowExpression.scala (1.4k) + GpuWindowExec.scala
(running vs partitioned paths, frame specs).

A WindowExpression pairs a window function (ranking / lead-lag / aggregate)
with a WindowSpec (partition keys, ordering, frame).  Frames supported on
TPU round 1: ROWS UNBOUNDED PRECEDING..CURRENT ROW (running), UNBOUNDED..
UNBOUNDED (whole partition), and bounded ROWS frames for sum/count/avg/
min/max via prefix/scan kernels.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .. import types as t
from .aggregates import AggregateFunction
from .core import Expression

UNBOUNDED_PRECEDING = -(2**31)
UNBOUNDED_FOLLOWING = 2**31
CURRENT_ROW = 0


class WindowSpec:
    def __init__(self, partition_by: List[Expression] = None,
                 order_by: List[Tuple[Expression, bool, bool]] = None,
                 frame: Optional[Tuple[str, int, int]] = None):
        self.partition_by = partition_by or []
        self.order_by = order_by or []
        # frame: (kind, start, end) — kind 'rows' | 'range'
        self.frame = frame

    def effective_frame(self, is_ranking: bool) -> Tuple[str, int, int]:
        if self.frame is not None:
            return self.frame
        if self.order_by and not is_ranking:
            # Spark default with ORDER BY: range unbounded preceding..current
            return ("range", UNBOUNDED_PRECEDING, CURRENT_ROW)
        return ("rows", UNBOUNDED_PRECEDING, UNBOUNDED_FOLLOWING)


class Window:
    """pyspark-style builder: Window.partition_by(...).order_by(...)."""

    unboundedPreceding = UNBOUNDED_PRECEDING
    unboundedFollowing = UNBOUNDED_FOLLOWING
    currentRow = CURRENT_ROW

    @staticmethod
    def partition_by(*cols) -> "WindowBuilder":
        return WindowBuilder().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> "WindowBuilder":
        return WindowBuilder().order_by(*cols)

    orderBy = order_by


class WindowBuilder:
    def __init__(self):
        self.spec = WindowSpec()

    def partition_by(self, *cols):
        from ..api.dataframe import _to_expr
        self.spec.partition_by = [_to_expr(c) for c in cols]
        return self

    partitionBy = partition_by

    def order_by(self, *cols):
        from ..api.column import Column
        from ..api.dataframe import _to_expr
        orders = []
        for c in cols:
            if isinstance(c, Column) and c._sort_order is not None:
                orders.append((c.expr, *c._sort_order))
            else:
                orders.append((_to_expr(c), True, True))
        self.spec.order_by = orders
        return self

    orderBy = order_by

    def rows_between(self, start: int, end: int):
        self.spec.frame = ("rows", start, end)
        return self

    rowsBetween = rows_between

    def range_between(self, start: int, end: int):
        self.spec.frame = ("range", start, end)
        return self

    rangeBetween = range_between


class WindowFunction(Expression):
    is_ranking = False


class RowNumber(WindowFunction):
    is_ranking = True

    def data_type(self):
        return t.INT

    @property
    def nullable(self):
        return False


class Rank(RowNumber):
    pass


class DenseRank(RowNumber):
    pass


class Lead(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1,
                 default=None):
        self.children = (child,)
        self.offset = offset
        self.default = default

    def data_type(self):
        return self.children[0].data_type()


class Lag(Lead):
    pass


class PercentRank(RowNumber):
    """(rank - 1) / (partition rows - 1); 0.0 for single-row partitions
    (ref GpuWindowExpression percent_rank support)."""

    def data_type(self):
        from .. import types as t
        return t.DOUBLE


class CumeDist(RowNumber):
    """rows with order key <= current / partition rows
    (ref cume_dist window support)."""

    def data_type(self):
        from .. import types as t
        return t.DOUBLE


class NTile(WindowFunction):
    is_ranking = True

    def __init__(self, n: int):
        self.children = ()
        self.n = n

    def data_type(self):
        return t.INT


class WindowExpression(Expression):
    def __init__(self, func, spec: WindowSpec, name: str = None):
        self.children = (func,)
        self.func = func
        self.spec = spec
        self.name = name or f"{type(func).__name__.lower()}_w"

    def with_children(self, children):
        # func mirrors children[0] (same discipline as
        # AggregateExpression): rebuilds must not diverge the two
        c = super().with_children(children)
        c.func = c.children[0]
        return c

    def data_type(self):
        return self.func.data_type()

    def resolved_type(self, names, dtypes):
        from .aggregates import bind_aggregate, AggregateExpression
        from .core import bind_expression
        f = self.func
        if isinstance(f, AggregateFunction):
            ae = bind_aggregate(AggregateExpression(f), names, dtypes)
            return ae.func.data_type()
        if isinstance(f, (Lead, Lag)):
            return bind_expression(f.children[0], names, dtypes).data_type()
        return f.data_type()

    def sql(self):
        return self.name
