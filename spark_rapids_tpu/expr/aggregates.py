"""Aggregate functions.

Ref: sql-plugin/.../AggregateFunctions.scala (Sum/Count/Average/Min/Max/
First/Last/M2-based stddev-variance/Pivot, collect_*).

Model (mirrors Spark's declarative aggregates, realized as segmented
reductions): each function declares
  * update stage:  per-buffer (input expression, segmented op)
  * merge stage:   per-buffer segmented op over the partial buffers
  * evaluate:      final result expression over the merged buffers.

Segmented ops: sum / min / max / first / last / countvalid (count of
non-null rows).  Group validity comes back as a per-buffer non-null count.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .. import types as t
from .arithmetic import _as_decimal, _decimal_binary_type, cast_data
from .cast import Cast
from .core import (ColumnValue, EvalContext, Expression, Literal,
                   make_column)

PARTIAL = "Partial"
FINAL = "Final"
COMPLETE = "Complete"


class AggregateFunction(Expression):
    """Base declarative aggregate."""

    def __init__(self, child: Optional[Expression] = None):
        self.children = (child,) if child is not None else ()

    @property
    def child(self):
        return self.children[0]

    # update stage: list of (input expression over child schema, op)
    def update(self) -> List[Tuple[Expression, str]]:
        raise NotImplementedError

    # buffer SQL types, same arity as update()
    def buffer_types(self) -> List[t.DataType]:
        raise NotImplementedError

    # merge ops over buffers, same arity
    def merge_ops(self) -> List[str]:
        raise NotImplementedError

    # evaluate final value from merged buffer columns
    def evaluate(self, ctx: EvalContext, buffers: List[ColumnValue]
                 ) -> ColumnValue:
        raise NotImplementedError


class Sum(AggregateFunction):
    def data_type(self):
        ct = self.child.data_type()
        if isinstance(ct, t.DecimalType):
            return t.DecimalType(min(ct.precision + 10, 38), ct.scale)
        if t.is_integral(ct):
            return t.LONG
        return t.DOUBLE

    def update(self):
        return [(Cast(self.child, self.data_type()), "sum")]

    def buffer_types(self):
        return [self.data_type()]

    def merge_ops(self):
        return ["sum"]

    def evaluate(self, ctx, buffers):
        return buffers[0]


class Count(AggregateFunction):
    """count(expr) or count(*) (child=None)."""

    def data_type(self):
        return t.LONG

    @property
    def nullable(self):
        return False

    def update(self):
        target = self.children[0] if self.children else Literal(1, t.INT)
        return [(target, "countvalid")]

    def buffer_types(self):
        return [t.LONG]

    def merge_ops(self):
        return ["sum"]

    def evaluate(self, ctx, buffers):
        b = buffers[0]
        # count is never null; empty merge slots count 0
        xp = ctx.xp
        data = b.col.data
        return make_column(ctx, t.LONG, data, None)


class Average(AggregateFunction):
    def data_type(self):
        ct = self.child.data_type()
        if isinstance(ct, t.DecimalType):
            return t.DecimalType(min(ct.precision + 4, 38),
                                 min(ct.scale + 4, 38))
        return t.DOUBLE

    def _sum_type(self):
        ct = self.child.data_type()
        if isinstance(ct, t.DecimalType):
            return t.DecimalType(min(ct.precision + 10, 38), ct.scale)
        return t.DOUBLE

    def update(self):
        return [(Cast(self.child, self._sum_type()), "sum"),
                (self.child, "countvalid")]

    def buffer_types(self):
        return [self._sum_type(), t.LONG]

    def merge_ops(self):
        return ["sum", "sum"]

    def evaluate(self, ctx, buffers):
        xp = ctx.xp
        s, c = buffers[0], buffers[1]
        cnt = c.col.data
        nonzero = cnt > 0
        safe = xp.where(nonzero, cnt, xp.ones_like(cnt))
        out = self.data_type()
        if isinstance(out, t.DecimalType):
            st = self._sum_type()
            shift = out.scale - st.scale
            num = s.col.data * np.int64(10 ** max(shift, 0))
            from .arithmetic import _div_round_half_up
            sign = xp.where((num < 0), -1, 1).astype(np.int64)
            q = _div_round_half_up(xp, xp.abs(num), safe) * sign
            return make_column(ctx, out, q, nonzero & (s.col.validity
                               if s.col.validity is not None else nonzero))
        data = s.col.data / safe
        return make_column(ctx, out, data, nonzero)


class Min(AggregateFunction):
    op = "min"

    def data_type(self):
        return self.child.data_type()

    def update(self):
        return [(self.child, self.op)]

    def buffer_types(self):
        return [self.data_type()]

    def merge_ops(self):
        return [self.op]

    def evaluate(self, ctx, buffers):
        return buffers[0]


class Max(Min):
    op = "max"


class First(AggregateFunction):
    op = "first"

    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def data_type(self):
        return self.child.data_type()

    def update(self):
        return [(self.child, self.op if self.ignore_nulls
                 else self.op + "_any")]

    def buffer_types(self):
        return [self.data_type()]

    def merge_ops(self):
        return [self.op if self.ignore_nulls else self.op + "_any"]

    def evaluate(self, ctx, buffers):
        return buffers[0]


class Last(First):
    op = "last"


class CollectList(AggregateFunction):
    """collect_list(x): gather non-null values per group into an array
    (ref AggregateFunctions.scala GpuCollectList).  TPU realization: the
    sort+segment kernel makes each group's rows contiguous, so collection
    is a stable compaction + per-segment offset build — no host loop."""

    update_op = "collect_list"
    merge_op = "collect_concat"

    def data_type(self):
        return t.ArrayType(self.child.data_type())

    @property
    def nullable(self):
        return False  # empty group yields [], not null

    def update(self):
        return [(self.child, self.update_op)]

    def buffer_types(self):
        return [self.data_type()]

    def merge_ops(self):
        return [self.merge_op]

    def evaluate(self, ctx, buffers):
        return buffers[0]


class CollectSet(CollectList):
    """collect_set(x): like collect_list but deduped per group by value
    words (ref GpuCollectSet; element order is unspecified, same as
    Spark)."""

    update_op = "collect_set"
    merge_op = "collect_concat_set"


class _MomentAgg(AggregateFunction):
    """Shared buffers for variance/stddev: (n, sum, sumsq) — merge-friendly
    linear statistics (the reference keeps Welford M2; we trade a little
    precision for pure-sum merges and document it)."""

    ddof = 1  # sample

    def data_type(self):
        return t.DOUBLE

    def update(self):
        from .arithmetic import Multiply
        x = Cast(self.child, t.DOUBLE)
        return [(self.child, "countvalid"), (x, "sum"),
                (Multiply(x, x), "sum")]

    def buffer_types(self):
        return [t.LONG, t.DOUBLE, t.DOUBLE]

    def merge_ops(self):
        return ["sum", "sum", "sum"]

    def _moments(self, ctx, buffers):
        xp = ctx.xp
        n = buffers[0].col.data.astype(xp.float64)
        s = buffers[1].col.data
        ss = buffers[2].col.data
        m2 = ss - xp.where(n > 0, s * s / xp.maximum(n, 1.0), 0.0)
        m2 = xp.maximum(m2, 0.0)
        return n, s, m2

    def _var(self, ctx, buffers, ddof):
        from ..shims import active_shim
        xp = ctx.xp
        n, _, m2 = self._moments(ctx, buffers)
        denom = n - ddof
        ok = denom > 0
        var = xp.where(ok, m2 / xp.maximum(denom, 1.0), 0.0)
        if active_shim().legacy_statistical_aggregate():
            # Spark 3.0 dialect: divide-by-zero yields NaN, not null
            # (ref shims legacy statistical aggregate handling)
            has_rows = n > 0
            var = xp.where(ok, var,
                           xp.where(has_rows, xp.full_like(var, np.nan),
                                    var))
            ok = has_rows
        return var, ok


class VarianceSamp(_MomentAgg):
    def evaluate(self, ctx, buffers):
        var, ok = self._var(ctx, buffers, 1)
        return make_column(ctx, t.DOUBLE, var, ok)


class VariancePop(_MomentAgg):
    def evaluate(self, ctx, buffers):
        var, ok = self._var(ctx, buffers, 0)
        return make_column(ctx, t.DOUBLE, var, ok)


class StddevSamp(_MomentAgg):
    def evaluate(self, ctx, buffers):
        var, ok = self._var(ctx, buffers, 1)
        return make_column(ctx, t.DOUBLE, ctx.xp.sqrt(var), ok)


class StddevPop(_MomentAgg):
    def evaluate(self, ctx, buffers):
        var, ok = self._var(ctx, buffers, 0)
        return make_column(ctx, t.DOUBLE, ctx.xp.sqrt(var), ok)


class PivotFirst(AggregateFunction):
    """pivot_first(pivotColumn, valueColumn, pivotValue): the first valid
    valueColumn row whose pivotColumn equals pivotValue — one instance
    per pivot value is how a pivot aggregate lowers
    (ref AggregateFunctions.scala GpuPivotFirst, registered at
    GpuOverrides.scala:2034-2060).

    TPU realization: the conditional mask fuses into the update
    expression (IF(p <=> v, x, NULL)) and the existing "first" segmented
    reduce picks the surviving row — no per-value imperative buffers,
    XLA fuses all N masks of a pivot into the one kernel pass."""

    def __init__(self, pivot: Expression, value: Expression, pivot_value):
        self.children = (pivot, value)
        self.pivot_value = pivot_value

    @property
    def value_expr(self):
        return self.children[1]

    def data_type(self):
        return self.children[1].data_type()

    def sql(self):
        return (f"pivot_first({self.children[0].sql()}, "
                f"{self.children[1].sql()}, {self.pivot_value!r})")

    def _masked(self):
        from .conditional import If
        from .predicates import EqualNullSafe
        return If(EqualNullSafe(self.children[0],
                                Literal(self.pivot_value)),
                  self.children[1], Literal(None, t.NULL))

    def update(self):
        return [(self._masked(), "first")]

    def buffer_types(self):
        return [self.data_type()]

    def merge_ops(self):
        return ["first"]

    def evaluate(self, ctx, buffers):
        return buffers[0]


class ApproximatePercentile(AggregateFunction):
    """approx_percentile(col, percentage[, accuracy])
    (ref ApproximatePercentile via GpuOverrides; the reference runs a
    t-digest on the GPU).

    TPU realization: the collect_list kernel already materializes each
    group's values contiguously, so the percentile is EXACT — one
    lexsort by (group, value) and a gather at rank ceil(p*n)-1 (the
    inverted-CDF element Spark's sketch approximates).  Trading the
    sketch for a sort is the right call on this hardware: the sort is
    the same fused kernel the aggregate already paid for."""

    def __init__(self, child: Expression, percentage: float,
                 accuracy: int = 10000):
        super().__init__(child)
        self.percentage = float(percentage)
        self.accuracy = int(accuracy)

    def data_type(self):
        ct = self.child.data_type()
        return ct if t.is_numeric(ct) else t.DOUBLE

    def sql(self):
        return (f"approx_percentile({self.child.sql()}, "
                f"{self.percentage})")

    def update(self):
        return [(self.child, "collect_list")]

    def buffer_types(self):
        return [t.ArrayType(self.child.data_type())]

    def merge_ops(self):
        return ["collect_concat"]

    def evaluate(self, ctx, buffers):
        from ..ops import segmented as seg
        xp = ctx.xp
        arr = buffers[0].col
        offs = arr.offsets.astype(np.int64)
        child = arr.children[0]
        ccap = child.capacity
        pos = xp.arange(ccap, dtype=np.int64)
        total = offs[-1]
        in_range = pos < total
        seg_of = (xp.searchsorted(offs[1:], pos, side="right")
                  .astype(np.int64))
        seg_word = xp.where(in_range, seg_of,
                            np.int64(ccap)).astype(xp.uint64)
        vwords = seg.key_words_for_column(xp, child, in_range,
                                          for_grouping=False)
        order = seg.lexsort(xp, [seg_word] + vwords[1:], ccap)
        sorted_data = child.data[order]
        n = offs[1:] - offs[:-1]
        # inverted-CDF rank: ceil(p*n) - 1, clamped into the group
        k = xp.ceil(self.percentage * n.astype(np.float64)) \
            .astype(np.int64) - 1
        k = xp.clip(k, 0, xp.maximum(n - 1, 0))
        idx = xp.clip(offs[:-1] + k, 0, max(ccap - 1, 0)).astype(np.int32)
        data = sorted_data[idx]
        valid = n > 0
        return make_column(ctx, self.data_type(),
                           xp.where(valid, data, xp.zeros_like(data)),
                           valid)


def bind_aggregate(ae: "AggregateExpression", names, dtypes
                   ) -> "AggregateExpression":
    """Bind the function's child expressions against an input schema."""
    import copy
    from .core import bind_expression
    fn = ae.func
    if fn.children:
        f2 = copy.copy(fn)
        f2.children = tuple(bind_expression(c, names, dtypes)
                            for c in fn.children)
    else:
        f2 = fn
    return AggregateExpression(f2, ae.name)


class AggregateExpression(Expression):
    """An aggregate function + mode + output name binding."""

    def __init__(self, func: AggregateFunction, name: Optional[str] = None):
        self.children = (func,)
        self.func = func
        self.name = name or func.sql()

    def with_children(self, children):
        # func mirrors children[0]; a transform_up rebuild must not leave
        # the two diverged (scalar-subquery substitution walks through
        # aggregate arguments)
        c = super().with_children(children)
        c.func = c.children[0]
        return c

    def data_type(self):
        return self.func.data_type()

    def sql(self):
        return self.name
