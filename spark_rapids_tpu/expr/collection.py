"""Collection / generator expressions.

Ref: org/apache/spark/sql/rapids/collectionOperations.scala (Size,
ArrayContains, SortArray, ...), GpuGenerateExec generators (GpuExplode,
GpuPosExplode in GpuGenerateExec.scala:560).

Generators (Explode/PosExplode) are evaluated by GenerateExec, not by
`eval` — they declare their per-row output schema via `generator_output`.
Scalar collection functions evaluate over the (offsets, child) span
encoding of device array columns.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from .core import (ColumnValue, EvalContext, Expression, ScalarValue,
                   and_validity, data_of, evaluator, make_column,
                   validity_of)


class Generator(Expression):
    """Base for expressions that produce multiple output rows per input row
    (ref Spark's Generator / GpuGenerateExec)."""

    def generator_output(self) -> Tuple[List[str], List[t.DataType]]:
        raise NotImplementedError


class Explode(Generator):
    """explode(array) -> one row per element (ref GpuExplode)."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = (child,)
        self.outer = outer
        self._out_names = ["col"]

    @property
    def child(self):
        return self.children[0]

    def data_type(self):
        dt = self.child.data_type()
        if isinstance(dt, t.ArrayType):
            return dt.element_type
        raise TypeError(f"explode input must be array, got {dt.name}")

    def generator_output(self):
        return list(self._out_names), [self.data_type()]

    def sql(self):
        return f"explode({self.child.sql()})"


class PosExplode(Generator):
    """posexplode(array) -> (pos, col) rows (ref GpuPosExplode)."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = (child,)
        self.outer = outer
        self._out_names = ["pos", "col"]

    @property
    def child(self):
        return self.children[0]

    def data_type(self):
        dt = self.child.data_type()
        if isinstance(dt, t.ArrayType):
            return dt.element_type
        raise TypeError(f"posexplode input must be array, got {dt.name}")

    def generator_output(self):
        return list(self._out_names), [t.INT, self.data_type()]

    def sql(self):
        return f"posexplode({self.child.sql()})"


# ---------------------------------------------------------------------------
# Scalar collection functions
# ---------------------------------------------------------------------------

class Size(Expression):
    """size(array) — Spark returns -1 for null input in legacy mode."""

    def __init__(self, child: Expression, legacy_null: bool = True):
        self.children = (child,)
        self.legacy_null = legacy_null

    def data_type(self):
        return t.INT

    @property
    def nullable(self):
        return not self.legacy_null

    def sql(self):
        return f"size({self.children[0].sql()})"


@evaluator(Size)
def _eval_size(e: Size, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    xp = ctx.xp
    col = v.col
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(np.int32)
    valid = col.validity
    if e.legacy_null:
        data = xp.where(valid, lens, xp.full((), -1, dtype=np.int32))
        return make_column(ctx, t.INT, data, None)
    return make_column(ctx, t.INT, lens, valid)


class ArrayContains(Expression):
    def __init__(self, arr: Expression, value: Expression):
        self.children = (arr, value)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return (f"array_contains({self.children[0].sql()}, "
                f"{self.children[1].sql()})")


@evaluator(ArrayContains)
def _eval_array_contains(e: ArrayContains, ctx: EvalContext):
    xp = ctx.xp
    arr = e.children[0].eval(ctx)
    needle = e.children[1].eval(ctx)
    col = arr.col
    child = col.children[0]
    if isinstance(child.dtype, (t.StringType, t.BinaryType, t.ArrayType,
                                t.StructType)):
        from .core import EvalError
        raise EvalError("array_contains over nested/string elements "
                        "not supported")
    cap = col.offsets.shape[0] - 1
    child_cap = child.data.shape[0]
    # element -> owning row
    p = xp.arange(child_cap, dtype=np.int32)
    row = xp.clip(xp.searchsorted(col.offsets[1:], p, side="right"),
                  0, cap - 1).astype(np.int32)
    in_span = p < col.offsets[-1]
    nv = data_of(needle, ctx)
    if isinstance(needle, ColumnValue):
        needle_per_elem = nv[row]
        needle_valid = needle.col.validity[row] \
            if needle.col.validity is not None else None
    else:
        needle_per_elem = nv
        needle_valid = None
    elem_valid = child.validity if child.validity is not None else \
        xp.ones((child_cap,), bool)
    hit = in_span & elem_valid & \
        (child.data.astype(np.float64) == needle_per_elem) \
        if child.dtype in (t.FLOAT, t.DOUBLE) else \
        in_span & elem_valid & (child.data == needle_per_elem)
    if needle_valid is not None:
        hit = hit & needle_valid
    # any hit per row via segment max
    found = xp.zeros((cap,), bool)
    if xp is np:
        np.maximum.at(found, row, hit)
    else:
        found = found.at[row].max(hit)
    # null semantics: null array -> null; null needle -> null;
    # no hit but array has null element -> null
    has_null_elem = xp.zeros((cap,), bool)
    null_elem = in_span & ~elem_valid
    if xp is np:
        np.maximum.at(has_null_elem, row, null_elem)
    else:
        has_null_elem = has_null_elem.at[row].max(null_elem)
    valid = and_validity(ctx, validity_of(arr, ctx),
                         validity_of(needle, ctx))
    if valid is None:
        valid = xp.ones((cap,), bool)
    valid = valid & ~(~found & has_null_elem)
    return make_column(ctx, t.BOOLEAN, found, valid)


class SortArray(Expression):
    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending

    def data_type(self):
        return self.children[0].data_type()

    def sql(self):
        return f"sort_array({self.children[0].sql()}, {self.ascending})"


@evaluator(SortArray)
def _eval_sort_array(e: SortArray, ctx: EvalContext):
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = v.col
    child = col.children[0]
    if isinstance(child.dtype, (t.StringType, t.BinaryType, t.ArrayType,
                                t.StructType)):
        from .core import EvalError
        raise EvalError("sort_array over nested/string elements "
                        "not supported")
    cap = col.offsets.shape[0] - 1
    child_cap = child.data.shape[0]
    p = xp.arange(child_cap, dtype=np.int32)
    row = xp.clip(xp.searchsorted(col.offsets[1:], p, side="right"),
                  0, cap - 1).astype(np.int64)
    in_span = p < col.offsets[-1]
    elem_valid = child.validity if child.validity is not None else \
        xp.ones((child_cap,), bool)
    # segmented sort: key = (row, null flag (nulls first asc), value).
    # Integer elements keep integer keys (float64 would collapse values
    # above 2^53); descending integers flip via bitwise-not (~x = -x-1,
    # exactly order-reversing with no int64-min overflow).
    data = child.data
    if xp.issubdtype(data.dtype, xp.integer) or data.dtype == bool:
        vals = data.astype(np.int64)
        if not e.ascending:
            vals = ~vals
    else:
        vals = data.astype(np.float64) if data.dtype != np.float64 else data
        # Spark orders NaN greater than any value
        if not e.ascending:
            vals = xp.where(xp.isnan(vals), -np.inf, -vals)
        else:
            vals = xp.where(xp.isnan(vals), np.inf, vals)
    null_key = xp.where(elem_valid, 1, 0) if e.ascending else \
        xp.where(elem_valid, 0, 1)
    order = xp.lexsort((vals, null_key, xp.where(in_span, row, cap)))
    new_data = data[order]
    new_valid_elems = elem_valid[order]
    new_child = DeviceColumn(child.dtype, data=new_data,
                             validity=new_valid_elems)
    out = DeviceColumn(col.dtype, validity=col.validity,
                       offsets=col.offsets, children=(new_child,))
    return ColumnValue(out)


class MapKeys(Expression):
    """map_keys(m) -> array<K> (ref GpuMapKeys, collectionOperations.scala)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return t.ArrayType(self.children[0].data_type().key_type)

    def sql(self):
        return f"map_keys({self.children[0].sql()})"


class MapValues(Expression):
    """map_values(m) -> array<V> (ref GpuMapValues)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return t.ArrayType(self.children[0].data_type().value_type)

    def sql(self):
        return f"map_values({self.children[0].sql()})"


class MapEntries(Expression):
    """map_entries(m) -> array<struct<key,value>> (ref GpuMapEntries)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        mt = self.children[0].data_type()
        return t.ArrayType(t.StructType([
            t.StructField("key", mt.key_type),
            t.StructField("value", mt.value_type)]))

    def sql(self):
        return f"map_entries({self.children[0].sql()})"


@evaluator(MapKeys)
def _eval_map_keys(e: MapKeys, ctx: EvalContext):
    m = e.children[0].eval(ctx).col
    return ColumnValue(DeviceColumn(e.data_type(), offsets=m.offsets,
                                    validity=m.validity,
                                    children=(m.children[0],)))


@evaluator(MapValues)
def _eval_map_values(e: MapValues, ctx: EvalContext):
    m = e.children[0].eval(ctx).col
    return ColumnValue(DeviceColumn(e.data_type(), offsets=m.offsets,
                                    validity=m.validity,
                                    children=(m.children[1],)))


@evaluator(MapEntries)
def _eval_map_entries(e: MapEntries, ctx: EvalContext):
    m = e.children[0].eval(ctx).col
    kcol, vcol = m.children
    entry_type = e.data_type().element_type
    struct_child = DeviceColumn(entry_type, children=(kcol, vcol))
    return ColumnValue(DeviceColumn(e.data_type(), offsets=m.offsets,
                                    validity=m.validity,
                                    children=(struct_child,)))


class GetMapValue(Expression):
    """m[key] for a scalar key (ref GpuGetMapValue, complexTypeExtractors)."""

    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    def data_type(self):
        return self.children[0].data_type().value_type

    def sql(self):
        return f"{self.children[0].sql()}[{self.children[1].sql()}]"


@evaluator(GetMapValue)
def _eval_get_map_value(e: GetMapValue, ctx: EvalContext):
    from ..ops.scan import child_row_ids
    from ..ops.gather import gather_column
    from ..ops import segmented as seg2
    xp = ctx.xp
    m = e.children[0].eval(ctx).col
    keyv = e.children[1].eval(ctx)
    kcol = m.children[0]
    vcol = m.children[1]
    child_cap = kcol.capacity
    cap = m.capacity
    pos = xp.arange(child_cap, dtype=xp.int32)
    crow, in_range = child_row_ids(xp, m.offsets, cap, child_cap)
    from .core import ScalarValue
    if isinstance(keyv, ScalarValue):
        if keyv.value is None:
            return make_column(ctx, e.data_type(), 0, False)
        if isinstance(kcol.dtype, (t.StringType, t.BinaryType)):
            # compare every kv key against the literal's bytes
            lit = keyv.value.encode() if isinstance(keyv.value, str) \
                else bytes(keyv.value)
            lens = kcol.offsets[1:] - kcol.offsets[:-1]
            match = lens == len(lit)
            for j, b in enumerate(lit):
                at = xp.clip(kcol.offsets[:-1] + j, 0,
                             kcol.data.shape[0] - 1)
                match = match & (kcol.data[at] == np.uint8(b))
        else:
            kd = kcol.data
            match = kd == xp.asarray(keyv.value, dtype=kd.dtype)
    else:
        kd = kcol.data
        match = kd == keyv.col.data[crow]
        kv_valid = keyv.col.validity
        if kv_valid is not None:
            match = match & kv_valid[crow]
    kvalid = kcol.validity
    if kvalid is not None:
        match = match & kvalid
    match = match & in_range
    # last occurrence wins (Spark's GetMapValue semantics)
    idx, cnt = seg2.segment_reduce(xp, "last", pos, crow, cap, match,
                                   sorted_ids=True)
    found = cnt > 0
    out = gather_column(xp, vcol, xp.clip(idx, 0, child_cap - 1).astype(
        xp.int32), found & (m.validity if m.validity is not None else
                            xp.ones((cap,), bool)))
    return ColumnValue(out)


class ArrayMax(Expression):
    """array_max(a) (ref GpuArrayMax, collectionOperations.scala)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type().element_type

    def sql(self):
        return f"array_max({self.children[0].sql()})"


class ArrayMin(ArrayMax):
    def sql(self):
        return f"array_min({self.children[0].sql()})"


def _eval_array_extreme(e, ctx: EvalContext, op: str):
    from ..ops.scan import child_row_ids
    from ..ops import segmented as seg2
    xp = ctx.xp
    a = e.children[0].eval(ctx).col
    child = a.children[0]
    child_cap = child.capacity
    cap = a.capacity
    crow, in_range = child_row_ids(xp, a.offsets, cap, child_cap)
    contrib = in_range
    if child.validity is not None:
        contrib = contrib & child.validity
    out, cnt = seg2.segment_reduce(xp, op, child.data, crow, cap, contrib,
                                   sorted_ids=True)
    valid = (cnt > 0)
    if a.validity is not None:
        valid = valid & a.validity
    return make_column(ctx, e.data_type(),
                       xp.where(valid, out, xp.zeros_like(out)), valid)


@evaluator(ArrayMax)
def _eval_array_max(e: ArrayMax, ctx: EvalContext):
    return _eval_array_extreme(e, ctx, "max")


@evaluator(ArrayMin)
def _eval_array_min(e: ArrayMin, ctx: EvalContext):
    return _eval_array_extreme(e, ctx, "min")


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) from flat key/value expressions
    (ref GpuCreateMap, complexTypeCreator.scala)."""

    def __init__(self, children):
        assert len(children) >= 2 and len(children) % 2 == 0
        self.children = tuple(children)

    def data_type(self):
        return t.MapType(self.children[0].data_type(),
                         self.children[1].data_type())

    def sql(self):
        return f"map({', '.join(c.sql() for c in self.children)})"


@evaluator(CreateMap)
def _eval_create_map(e: CreateMap, ctx: EvalContext):
    xp = ctx.xp
    cap = ctx.batch.capacity
    npairs = len(e.children) // 2
    kvals, vvals = [], []
    from .core import make_column as mk
    for i in range(npairs):
        kv = e.children[2 * i].eval(ctx)
        vv = e.children[2 * i + 1].eval(ctx)
        if not isinstance(kv, ColumnValue):
            kv = mk(ctx, e.children[2 * i].data_type(),
                    kv.value if kv.value is not None else 0,
                    None if kv.value is not None else False)
        if not isinstance(vv, ColumnValue):
            vv = mk(ctx, e.children[2 * i + 1].data_type(),
                    vv.value if vv.value is not None else 0,
                    None if vv.value is not None else False)
        kvals.append(kv.col)
        vvals.append(vv.col)
    # interleave per row: entry j of row i = (kj[i], vj[i])
    kdata = xp.stack([c.data for c in kvals], axis=1).reshape(-1)
    vdata = xp.stack([c.data for c in vvals], axis=1).reshape(-1)
    vval = xp.stack(
        [c.validity if c.validity is not None else
         xp.ones((cap,), bool) for c in vvals], axis=1).reshape(-1)
    offs = (xp.arange(cap + 1, dtype=xp.int32) * np.int32(npairs))
    dt = e.data_type()
    kcol = DeviceColumn(dt.key_type, data=kdata)
    vcol = DeviceColumn(dt.value_type, data=vdata, validity=vval)
    return ColumnValue(DeviceColumn(dt, offsets=offs,
                                    children=(kcol, vcol)))
