"""Collection / generator expressions.

Ref: org/apache/spark/sql/rapids/collectionOperations.scala (Size,
ArrayContains, SortArray, ...), GpuGenerateExec generators (GpuExplode,
GpuPosExplode in GpuGenerateExec.scala:560).

Generators (Explode/PosExplode) are evaluated by GenerateExec, not by
`eval` — they declare their per-row output schema via `generator_output`.
Scalar collection functions evaluate over the (offsets, child) span
encoding of device array columns.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from .. import types as t
from .core import (ColumnValue, EvalContext, Expression, ScalarValue,
                   and_validity, data_of, evaluator, make_column,
                   validity_of)


class Generator(Expression):
    """Base for expressions that produce multiple output rows per input row
    (ref Spark's Generator / GpuGenerateExec)."""

    def generator_output(self) -> Tuple[List[str], List[t.DataType]]:
        raise NotImplementedError


class Explode(Generator):
    """explode(array) -> one row per element (ref GpuExplode)."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = (child,)
        self.outer = outer
        self._out_names = ["col"]

    @property
    def child(self):
        return self.children[0]

    def data_type(self):
        dt = self.child.data_type()
        if isinstance(dt, t.ArrayType):
            return dt.element_type
        raise TypeError(f"explode input must be array, got {dt.name}")

    def generator_output(self):
        return list(self._out_names), [self.data_type()]

    def sql(self):
        return f"explode({self.child.sql()})"


class PosExplode(Generator):
    """posexplode(array) -> (pos, col) rows (ref GpuPosExplode)."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = (child,)
        self.outer = outer
        self._out_names = ["pos", "col"]

    @property
    def child(self):
        return self.children[0]

    def data_type(self):
        dt = self.child.data_type()
        if isinstance(dt, t.ArrayType):
            return dt.element_type
        raise TypeError(f"posexplode input must be array, got {dt.name}")

    def generator_output(self):
        return list(self._out_names), [t.INT, self.data_type()]

    def sql(self):
        return f"posexplode({self.child.sql()})"


# ---------------------------------------------------------------------------
# Scalar collection functions
# ---------------------------------------------------------------------------

class Size(Expression):
    """size(array) — Spark returns -1 for null input in legacy mode."""

    def __init__(self, child: Expression, legacy_null: bool = True):
        self.children = (child,)
        self.legacy_null = legacy_null

    def data_type(self):
        return t.INT

    @property
    def nullable(self):
        return not self.legacy_null

    def sql(self):
        return f"size({self.children[0].sql()})"


@evaluator(Size)
def _eval_size(e: Size, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    xp = ctx.xp
    col = v.col
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(np.int32)
    valid = col.validity
    if e.legacy_null:
        data = xp.where(valid, lens, xp.full((), -1, dtype=np.int32))
        return make_column(ctx, t.INT, data, None)
    return make_column(ctx, t.INT, lens, valid)


class ArrayContains(Expression):
    def __init__(self, arr: Expression, value: Expression):
        self.children = (arr, value)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return (f"array_contains({self.children[0].sql()}, "
                f"{self.children[1].sql()})")


@evaluator(ArrayContains)
def _eval_array_contains(e: ArrayContains, ctx: EvalContext):
    xp = ctx.xp
    arr = e.children[0].eval(ctx)
    needle = e.children[1].eval(ctx)
    col = arr.col
    child = col.children[0]
    if isinstance(child.dtype, (t.StringType, t.BinaryType, t.ArrayType,
                                t.StructType)):
        from .core import EvalError
        raise EvalError("array_contains over nested/string elements "
                        "not supported")
    cap = col.offsets.shape[0] - 1
    child_cap = child.data.shape[0]
    # element -> owning row
    p = xp.arange(child_cap, dtype=np.int32)
    row = xp.clip(xp.searchsorted(col.offsets[1:], p, side="right"),
                  0, cap - 1).astype(np.int32)
    in_span = p < col.offsets[-1]
    nv = data_of(needle, ctx)
    if isinstance(needle, ColumnValue):
        needle_per_elem = nv[row]
        needle_valid = needle.col.validity[row] \
            if needle.col.validity is not None else None
    else:
        needle_per_elem = nv
        needle_valid = None
    elem_valid = child.validity if child.validity is not None else \
        xp.ones((child_cap,), bool)
    hit = in_span & elem_valid & \
        (child.data.astype(np.float64) == needle_per_elem) \
        if child.dtype in (t.FLOAT, t.DOUBLE) else \
        in_span & elem_valid & (child.data == needle_per_elem)
    if needle_valid is not None:
        hit = hit & needle_valid
    # any hit per row via segment max
    found = xp.zeros((cap,), bool)
    if xp is np:
        np.maximum.at(found, row, hit)
    else:
        found = found.at[row].max(hit)
    # null semantics: null array -> null; null needle -> null;
    # no hit but array has null element -> null
    has_null_elem = xp.zeros((cap,), bool)
    null_elem = in_span & ~elem_valid
    if xp is np:
        np.maximum.at(has_null_elem, row, null_elem)
    else:
        has_null_elem = has_null_elem.at[row].max(null_elem)
    valid = and_validity(ctx, validity_of(arr, ctx),
                         validity_of(needle, ctx))
    if valid is None:
        valid = xp.ones((cap,), bool)
    valid = valid & ~(~found & has_null_elem)
    return make_column(ctx, t.BOOLEAN, found, valid)


class SortArray(Expression):
    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending

    def data_type(self):
        return self.children[0].data_type()

    def sql(self):
        return f"sort_array({self.children[0].sql()}, {self.ascending})"


@evaluator(SortArray)
def _eval_sort_array(e: SortArray, ctx: EvalContext):
    from ..columnar.device import DeviceColumn
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    col = v.col
    child = col.children[0]
    if isinstance(child.dtype, (t.StringType, t.BinaryType, t.ArrayType,
                                t.StructType)):
        from .core import EvalError
        raise EvalError("sort_array over nested/string elements "
                        "not supported")
    cap = col.offsets.shape[0] - 1
    child_cap = child.data.shape[0]
    p = xp.arange(child_cap, dtype=np.int32)
    row = xp.clip(xp.searchsorted(col.offsets[1:], p, side="right"),
                  0, cap - 1).astype(np.int64)
    in_span = p < col.offsets[-1]
    elem_valid = child.validity if child.validity is not None else \
        xp.ones((child_cap,), bool)
    # segmented sort: key = (row, null flag (nulls first asc), value).
    # Integer elements keep integer keys (float64 would collapse values
    # above 2^53); descending integers flip via bitwise-not (~x = -x-1,
    # exactly order-reversing with no int64-min overflow).
    data = child.data
    if xp.issubdtype(data.dtype, xp.integer) or data.dtype == bool:
        vals = data.astype(np.int64)
        if not e.ascending:
            vals = ~vals
    else:
        vals = data.astype(np.float64) if data.dtype != np.float64 else data
        # Spark orders NaN greater than any value
        if not e.ascending:
            vals = xp.where(xp.isnan(vals), -np.inf, -vals)
        else:
            vals = xp.where(xp.isnan(vals), np.inf, vals)
    null_key = xp.where(elem_valid, 1, 0) if e.ascending else \
        xp.where(elem_valid, 0, 1)
    order = xp.lexsort((vals, null_key, xp.where(in_span, row, cap)))
    new_data = data[order]
    new_valid_elems = elem_valid[order]
    new_child = DeviceColumn(child.dtype, data=new_data,
                             validity=new_valid_elems)
    out = DeviceColumn(col.dtype, validity=col.validity,
                       offsets=col.offsets, children=(new_child,))
    return ColumnValue(out)
