"""Expression IR core.

TPU-native re-design of the reference's expression layer (ref:
GpuExpression in sql-plugin/.../GpuExpressions.scala and the ~180
expression rules registered at GpuOverrides.scala:727-3048).

Design: one evaluator, two backends.  Every expression evaluates over an
`EvalContext` whose array module `xp` is either `numpy` (the CPU fallback
engine) or `jax.numpy` (the TPU path).  On TPU the whole operator's
expression tree traces into a single XLA computation, so elementwise ops
fuse — the structural advantage over the reference's one-JNI-kernel-per-
expression model (its AST fusion, GpuOverrides ENABLE_PROJECT_AST, is the
special case; here fusion is the default).

Null semantics follow Spark: values under a null are undefined (canonically
zero); each op combines child validity.  ANSI mode raises on overflow /
invalid input where Spark would.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn


class EvalError(Exception):
    """Runtime expression failure (ANSI errors, unsupported eval)."""


# ---------------------------------------------------------------------------
# Values
# ---------------------------------------------------------------------------

class ColumnValue:
    """A columnar evaluation result: wraps a DeviceColumn whose buffers are
    xp arrays (numpy on CPU, jax on TPU)."""

    __slots__ = ("col",)

    def __init__(self, col: DeviceColumn):
        self.col = col

    @property
    def dtype(self) -> t.DataType:
        return self.col.dtype


class ScalarValue:
    """A literal/scalar evaluation result."""

    __slots__ = ("value", "dtype")

    def __init__(self, value: Any, dtype: t.DataType):
        self.value = value  # python scalar / bytes / None
        self.dtype = dtype

    @property
    def is_null(self) -> bool:
        return self.value is None


Value = Any  # ColumnValue | ScalarValue


class EvalContext:
    """Evaluation context: the input batch + array backend.

    `xp` is numpy or jax.numpy; all evaluator code must go through it so the
    same semantics run on both engines.
    """

    __slots__ = ("xp", "batch", "ansi", "capacity", "lambda_bindings",
                 "row_base", "params")

    def __init__(self, xp, batch, ansi: bool = False, row_base=0,
                 params=None):
        self.xp = xp
        self.batch = batch  # DeviceBatch (buffers in xp-land)
        self.ansi = ansi
        self.capacity = batch.capacity if batch is not None else 0
        # hoisted-literal values for ParamLiteral slots (expr/params.py):
        # traced scalars on the TPU path so constant changes never
        # retrace; None -> evaluators fall back to the baked values
        self.params = params
        # name -> ColumnValue for in-scope lambda variables (higher-order
        # function bodies evaluate in array-element space)
        self.lambda_bindings = {}
        # (partition_id << 33) + running row offset — the positional seed
        # for monotonically_increasing_id / spark_partition_id / rand
        # (ref GpuMonotonicallyIncreasingID.scala's partition-packed
        # layout).  A traced scalar on the TPU path so per-batch offsets
        # never retrace.
        self.row_base = row_base

    def row_mask(self):
        return self.xp.arange(self.capacity, dtype=np.int32) < self.batch.num_rows


# ---------------------------------------------------------------------------
# Expression tree
# ---------------------------------------------------------------------------

class Expression:
    """Base expression node."""

    children: Tuple["Expression", ...] = ()

    def data_type(self) -> t.DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    @property
    def pretty_name(self) -> str:
        return type(self).__name__.lower()

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        import copy
        c = copy.copy(self)
        c.children = tuple(children)
        return c

    def transform_up(self, fn: Callable[["Expression"], "Expression"]) -> "Expression":
        new_children = [c.transform_up(fn) for c in self.children]
        node = self if all(a is b for a, b in zip(new_children, self.children)) \
            and len(new_children) == len(self.children) \
            else self.with_children(new_children)
        return fn(node)

    def collect(self, pred: Callable[["Expression"], bool]) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    def sql(self) -> str:
        args = ", ".join(c.sql() for c in self.children)
        return f"{self.pretty_name}({args})"

    def __repr__(self):
        return self.sql()

    # evaluation ------------------------------------------------------------
    def eval(self, ctx: EvalContext) -> Value:
        fn = _EVALUATORS.get(type(self))
        if fn is None:
            raise EvalError(f"no evaluator for {type(self).__name__}")
        return fn(self, ctx)


_EVALUATORS: Dict[Type[Expression], Callable[[Expression, EvalContext], Value]] = {}


def evaluator(cls: Type[Expression]):
    """Register an evaluation function for an expression class."""
    def deco(fn):
        _EVALUATORS[cls] = fn
        return fn
    return deco


class LeafExpression(Expression):
    children = ()


class Literal(LeafExpression):
    def __init__(self, value: Any, dtype: Optional[t.DataType] = None):
        import datetime
        import decimal as pydec
        if dtype is None:
            dtype = infer_literal_type(value)
        if isinstance(value, str):
            value = value.encode("utf-8")
        elif isinstance(value, datetime.datetime):
            epoch = datetime.datetime(1970, 1, 1,
                                      tzinfo=datetime.timezone.utc)
            if value.tzinfo is None:
                value = value.replace(tzinfo=datetime.timezone.utc)
            value = int((value - epoch).total_seconds() * 1e6)
        elif isinstance(value, datetime.date):
            value = (value - datetime.date(1970, 1, 1)).days
        elif isinstance(value, pydec.Decimal) and \
                isinstance(dtype, t.DecimalType):
            value = int(value.scaleb(dtype.scale))
        self.value = value
        self.dtype = dtype

    def data_type(self):
        return self.dtype

    @property
    def nullable(self):
        return self.value is None

    def sql(self):
        if self.value is None:
            return "NULL"
        if isinstance(self.dtype, t.StringType):
            return repr(self.value.decode("utf-8", "replace"))
        return str(self.value)


def infer_literal_type(value: Any) -> t.DataType:
    import datetime
    import decimal as pydec
    if value is None:
        return t.NULL
    if isinstance(value, bool):
        return t.BOOLEAN
    if isinstance(value, (int, np.integer)):
        return t.LONG if not (-(2**31) <= value < 2**31) else t.INT
    if isinstance(value, (float, np.floating)):
        return t.DOUBLE
    if isinstance(value, (str, bytes)):
        return t.STRING
    if isinstance(value, pydec.Decimal):
        sign, digits, exp = value.as_tuple()
        scale = max(-exp, 0)
        precision = max(len(digits), scale)
        return t.DecimalType(precision, scale)
    if isinstance(value, datetime.datetime):
        return t.TIMESTAMP
    if isinstance(value, datetime.date):
        return t.DATE
    raise TypeError(f"cannot infer literal type of {value!r}")


@evaluator(Literal)
def _eval_literal(e: Literal, ctx: EvalContext):
    return ScalarValue(e.value, e.dtype)


class AttributeReference(LeafExpression):
    """Unresolved column reference by name."""

    def __init__(self, name: str, dtype: Optional[t.DataType] = None):
        self.name = name
        self.dtype = dtype

    def data_type(self):
        if self.dtype is None:
            raise EvalError(f"unresolved attribute {self.name}")
        return self.dtype

    def sql(self):
        return self.name


class BoundReference(LeafExpression):
    """Column reference bound to an input ordinal (ref BoundReference)."""

    def __init__(self, ordinal: int, dtype: t.DataType, name: str = ""):
        self.ordinal = ordinal
        self.dtype = dtype
        self.name = name or f"input[{ordinal}]"

    def data_type(self):
        return self.dtype

    def sql(self):
        return self.name


@evaluator(BoundReference)
def _eval_bound(e: BoundReference, ctx: EvalContext):
    return ColumnValue(ctx.batch.columns[e.ordinal])


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.name = name

    @property
    def child(self):
        return self.children[0]

    def data_type(self):
        return self.child.data_type()

    @property
    def nullable(self):
        return self.child.nullable

    def sql(self):
        return f"{self.child.sql()} AS {self.name}"


@evaluator(Alias)
def _eval_alias(e: Alias, ctx: EvalContext):
    return e.child.eval(ctx)


def output_name(e: Expression) -> str:
    if isinstance(e, Alias):
        return e.name
    if isinstance(e, (AttributeReference, BoundReference)):
        return e.name
    return e.sql()


def bind_expression(expr: Expression, names: Sequence[str],
                    dtypes: Sequence[t.DataType]) -> Expression:
    """Replace AttributeReference by BoundReference against a schema."""
    index = {n: i for i, n in enumerate(names)}

    def fn(e: Expression) -> Expression:
        if isinstance(e, AttributeReference):
            if e.name not in index:
                raise EvalError(f"column {e.name!r} not in {list(names)}")
            i = index[e.name]
            return BoundReference(i, dtypes[i], e.name)
        return e
    return expr.transform_up(fn)


# ---------------------------------------------------------------------------
# Shared evaluation helpers (used by all expression modules)
# ---------------------------------------------------------------------------

def data_of(v: Value, ctx: EvalContext):
    """The raw data (xp array or python scalar) of a value.

    On the numpy (CPU-oracle) path, decimal128 columns materialize as
    exact Python-int object arrays combining both 64-bit lanes, so every
    downstream numpy op is arbitrary-precision — the CPU engine must be
    bit-correct where it plays Spark's role.  The TPU path never sees
    >64-bit decimals (TypeSig gating)."""
    if isinstance(v, ColumnValue):
        col = v.col
        if isinstance(col.dtype, t.DecimalType) and not col.dtype.is64 \
                and col.data_hi is not None \
                and isinstance(col.data, np.ndarray):
            lo_u = col.data.astype(np.uint64).astype(object)
            hi = col.data_hi.astype(object)
            return (hi << 64) + lo_u
        return v.col.data
    if v.value is None:
        return _zero_of(v.dtype)
    if isinstance(v.dtype, t.BooleanType):
        return bool(v.value)
    return v.value


def _zero_of(dtype: t.DataType):
    if isinstance(dtype, t.BooleanType):
        return False
    if isinstance(dtype, (t.FloatType, t.DoubleType)):
        return 0.0
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        return b""
    return 0


def validity_of(v: Value, ctx: EvalContext):
    """Validity mask (xp bool array), or None meaning all-valid, or False
    meaning all-null scalar."""
    if isinstance(v, ColumnValue):
        return v.col.validity
    return None if v.value is not None else False


def and_validity(ctx: EvalContext, *vals):
    """Combine child validities (Spark null propagation)."""
    out = None
    for v in vals:
        if v is None:
            continue
        if v is False:
            return ctx.xp.zeros((ctx.capacity,), dtype=bool)
        out = v if out is None else (out & v)
    return out


def make_column(ctx: EvalContext, dtype: t.DataType, data, validity) -> ColumnValue:
    xp = ctx.xp
    if validity is None:
        validity = xp.ones((ctx.capacity,), dtype=bool)
    elif validity is False:
        validity = xp.zeros((ctx.capacity,), dtype=bool)
    is_dec128 = isinstance(dtype, t.DecimalType) and not dtype.is64
    if not hasattr(data, "shape") or getattr(data, "shape", ()) == ():
        if is_dec128 and xp is np and not (-(2**63) <= int(data) < 2**63):
            data = np.full((ctx.capacity,), int(data), dtype=object)
        else:
            npdt = t.to_np_dtype(dtype) if not isinstance(
                dtype, (t.StringType, t.BinaryType)) else None
            if npdt is not None:
                data = xp.full((ctx.capacity,), data, dtype=npdt)
    # canonicalize: zero under nulls so downstream reductions are safe
    if not isinstance(dtype, (t.StringType, t.BinaryType, t.StructType,
                              t.ArrayType, t.MapType)):
        data = ctx.xp.where(validity, data, ctx.xp.zeros_like(data))
    if isinstance(dtype, t.DecimalType) and \
            getattr(data, "dtype", None) == object:
        # exact Python-int array (numpy CPU path) -> 64-bit lane pair
        mask = (1 << 64) - 1
        lo = np.array([int(x) & mask for x in data],
                      dtype=np.uint64).astype(np.int64)
        hi = np.array([int(x) >> 64 for x in data], dtype=np.int64)
        col = DeviceColumn(dtype, data=lo, validity=validity)
        if not dtype.is64:
            col.data_hi = hi
        return ColumnValue(col)
    col = DeviceColumn(dtype, data=data, validity=validity)
    if is_dec128:
        # expression kernels compute the low word; values are bounded to
        # 64 bits by TypeSig gating (the reference is decimal64-only,
        # RapidsConf.scala:565) — sign-extend so the 128-bit lanes agree
        # and exact 128-bit aggregation buffers can build on top
        col.data_hi = data.astype(xp.int64) >> np.int64(63)
    return ColumnValue(col)


def scalar_to_column(ctx: EvalContext, sv: "ScalarValue") -> ColumnValue:
    """Materialize a scalar as a full column (incl. string/null scalars,
    which make_column cannot broadcast)."""
    dtype = sv.dtype
    if sv.value is None or isinstance(dtype, t.NullType):
        return all_null_column(ctx, dtype)
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        xp = ctx.xp
        b = sv.value if isinstance(sv.value, bytes) else \
            str(sv.value).encode("utf-8")
        cap = ctx.capacity
        if b:
            unit = np.frombuffer(b, dtype=np.uint8)
            data = xp.asarray(np.tile(unit, max(cap, 1)))
        else:
            data = xp.zeros((1,), dtype=np.uint8)
        offsets = (xp.arange(cap + 1, dtype=np.int32) *
                   np.int32(len(b)))
        validity = xp.ones((cap,), dtype=bool)
        return ColumnValue(DeviceColumn(dtype, data=data, offsets=offsets,
                                        validity=validity))
    return make_column(ctx, dtype, sv.value, None)


def all_null_column(ctx: EvalContext, dtype: t.DataType) -> ColumnValue:
    xp = ctx.xp
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        return ColumnValue(DeviceColumn(
            dtype, data=xp.zeros((1,), dtype=np.uint8),
            offsets=xp.zeros((ctx.capacity + 1,), dtype=np.int32),
            validity=xp.zeros((ctx.capacity,), dtype=bool)))
    npdt = t.to_np_dtype(dtype) if not isinstance(dtype, t.NullType) else np.int8
    return ColumnValue(DeviceColumn(
        dtype, data=xp.zeros((ctx.capacity,), dtype=npdt),
        validity=xp.zeros((ctx.capacity,), dtype=bool)))
