"""Bitwise expressions (ref org/apache/spark/sql/rapids/bitwise.scala:
GpuBitwiseAnd/Or/Xor/Not, GpuShiftLeft/Right/RightUnsigned, registered
at GpuOverrides.scala bitwise rules).

TPU realization: straight elementwise integer ops — XLA fuses them into
surrounding kernels.  Shift distances follow Java semantics (masked by
the value width), matching Spark."""

from __future__ import annotations

import numpy as np

from .. import types as t
from .core import (EvalContext, Expression, and_validity, data_of,
                   evaluator, make_column, validity_of)


_INT_WIDTH = {t.ByteType: 1, t.ShortType: 2, t.IntegerType: 4,
              t.LongType: 8}


def _width(dt) -> int:
    return _INT_WIDTH.get(type(dt), 8)


class _BitwiseBinary(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def data_type(self):
        lt = self.children[0].data_type()
        rt = self.children[1].data_type()
        return lt if _width(lt) >= _width(rt) else rt


def _binary_ints(e, ctx):
    lv = e.children[0].eval(ctx)
    rv = e.children[1].eval(ctx)
    out_t = e.data_type()
    np_t = t.to_np_dtype(out_t)
    l = data_of(lv, ctx).astype(np_t)
    r = data_of(rv, ctx).astype(np_t)
    val = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return l, r, out_t, val


class BitwiseAnd(_BitwiseBinary):
    pass


class BitwiseOr(_BitwiseBinary):
    pass


class BitwiseXor(_BitwiseBinary):
    pass


@evaluator(BitwiseAnd)
def _eval_band(e, ctx: EvalContext):
    l, r, out_t, val = _binary_ints(e, ctx)
    return make_column(ctx, out_t, l & r, val)


@evaluator(BitwiseOr)
def _eval_bor(e, ctx: EvalContext):
    l, r, out_t, val = _binary_ints(e, ctx)
    return make_column(ctx, out_t, l | r, val)


@evaluator(BitwiseXor)
def _eval_bxor(e, ctx: EvalContext):
    l, r, out_t, val = _binary_ints(e, ctx)
    return make_column(ctx, out_t, l ^ r, val)


class BitwiseNot(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type()


@evaluator(BitwiseNot)
def _eval_bnot(e, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    return make_column(ctx, e.data_type(), ~data_of(v, ctx),
                       validity_of(v, ctx))


class _Shift(Expression):
    """value SHIFT amount; Java masks the shift distance by width-1."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def data_type(self):
        dt = self.children[0].data_type()
        return dt if isinstance(dt, t.LongType) else t.INT


def _shift_operands(e, ctx):
    lv = e.children[0].eval(ctx)
    rv = e.children[1].eval(ctx)
    out_t = e.data_type()
    np_t = t.to_np_dtype(out_t)
    width = 64 if isinstance(out_t, t.LongType) else 32
    l = data_of(lv, ctx).astype(np_t)
    sh = (data_of(rv, ctx).astype(np.int64) & (width - 1)).astype(np_t)
    val = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return l, sh, out_t, np_t, val


class ShiftLeft(_Shift):
    pass


class ShiftRight(_Shift):
    pass


class ShiftRightUnsigned(_Shift):
    pass


@evaluator(ShiftLeft)
def _eval_shl(e, ctx: EvalContext):
    l, sh, out_t, np_t, val = _shift_operands(e, ctx)
    return make_column(ctx, out_t, l << sh, val)


@evaluator(ShiftRight)
def _eval_shr(e, ctx: EvalContext):
    l, sh, out_t, np_t, val = _shift_operands(e, ctx)
    return make_column(ctx, out_t, l >> sh, val)   # arithmetic (signed)


@evaluator(ShiftRightUnsigned)
def _eval_shru(e, ctx: EvalContext):
    l, sh, out_t, np_t, val = _shift_operands(e, ctx)
    u_t = np.uint64 if np_t == np.int64 else np.uint32
    out = (l.view(u_t) >> sh.view(u_t)).view(np_t)   # logical shift
    return make_column(ctx, out_t, out, val)
