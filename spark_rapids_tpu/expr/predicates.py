"""Predicate expressions: comparisons, boolean logic, null tests.

Ref: org/apache/spark/sql/rapids/predicates.scala and GpuOverrides rules
(EqualTo, LessThan, And, Or, Not, IsNull, IsNotNull, IsNaN, In, InSet,
EqualNullSafe).

Spark semantics implemented here:
  * three-valued AND/OR (FALSE AND NULL = FALSE, TRUE OR NULL = TRUE);
  * NaN equals NaN and sorts greater than every other double (Spark's
    total order), unlike IEEE;
  * string comparisons via the byte-tensor kernels in ops/strings.py.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from ..ops import strings as sops
from .arithmetic import cast_data, promote
from .core import (ColumnValue, EvalContext, Expression, ScalarValue, Value,
                   and_validity, data_of, evaluator, make_column, validity_of)


def scalar_string_keys(s: bytes):
    """Host-side prefix words + rolling hashes of a constant string, matching
    ops/strings.py kernels bit-for-bit."""
    mod = 1 << 64
    h = []
    for base in (int(sops._HASH_BASE_1), int(sops._HASH_BASE_2)):
        acc, p = 0, 1
        for c in s:
            acc = (acc + (c + 1) * p) % mod
            p = (p * base) % mod
        h.append(np.uint64(acc))
    padded = s[:sops.PREFIX_BYTES].ljust(sops.PREFIX_BYTES, b"\0")
    words = [np.uint64(int.from_bytes(padded[i * 8:(i + 1) * 8], "big"))
             for i in range(sops.PREFIX_BYTES // 8)]
    return words, h[0], h[1], np.int32(len(s))


class BinaryComparison(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"({self.left.sql()} {self.symbol} {self.right.sql()})"


class EqualTo(BinaryComparison):
    symbol = "="


class EqualNullSafe(BinaryComparison):
    symbol = "<=>"

    @property
    def nullable(self):
        return False


class LessThan(BinaryComparison):
    symbol = "<"


class LessThanOrEqual(BinaryComparison):
    symbol = "<="


class GreaterThan(BinaryComparison):
    symbol = ">"


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="


def _is_string(dt):
    return isinstance(dt, (t.StringType, t.BinaryType))


def _cmp_inputs(e: BinaryComparison, ctx: EvalContext):
    lv, rv = e.left.eval(ctx), e.right.eval(ctx)
    lt, rt = e.left.data_type(), e.right.data_type()
    if _is_string(lt) or _is_string(rt):
        return lv, rv, None
    common = promote(lt, rt)
    ld = cast_data(ctx, data_of(lv, ctx), lt, common)
    rd = cast_data(ctx, data_of(rv, ctx), rt, common)
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return (ld, rd, common), v, lv  # tuple marker


def _float_like(dt):
    return isinstance(dt, (t.FloatType, t.DoubleType))


def _param_chars(xp, value):
    """A hoisted string parameter's traced uint8 chars as a 1-string
    column (offsets [0, len]); len is static (array shape)."""
    arr = xp.asarray(value, dtype=xp.uint8)
    offs = xp.asarray(np.array([0, int(arr.shape[0])], dtype=np.int32))
    return offs, arr


def _string_eq_data(ctx: EvalContext, lv: Value, rv: Value):
    xp = ctx.xp
    if isinstance(lv, ColumnValue) and isinstance(rv, ColumnValue):
        return sops.string_eq(xp, lv.col.offsets, lv.col.data,
                              rv.col.offsets, rv.col.data)
    col, scalar = (lv, rv) if isinstance(lv, ColumnValue) else (rv, lv)
    c1, c2 = sops.string_hashes(xp, col.col.offsets, col.col.data)
    lens = sops.lengths(xp, col.col.offsets)
    if hasattr(scalar.value, "shape"):
        # ParamLiteral string: chars are a traced array, so the hashes
        # must come from the device kernel, not host-side key derivation
        offs, arr = _param_chars(xp, scalar.value)
        s1, s2 = sops.string_hashes(xp, offs, arr)
        ln = np.int32(int(arr.shape[0]))
        return (lens == ln) & (c1 == s1[0]) & (c2 == s2[0])
    sval = scalar.value if isinstance(scalar.value, bytes) else \
        (scalar.value or b"")
    _, h1, h2, ln = scalar_string_keys(sval)
    return (lens == ln) & (c1 == h1) & (c2 == h2)


def _string_order_lt(ctx: EvalContext, lv: Value, rv: Value, or_equal: bool):
    """a < b (or <=) via prefix-word lexicographic compare."""
    xp = ctx.xp

    def keys(v):
        if isinstance(v, ColumnValue):
            cols = sops.order_keys(xp, v.col.offsets, v.col.data)
            return cols
        if hasattr(v.value, "shape"):  # ParamLiteral string (traced)
            offs, arr = _param_chars(xp, v.value)
            cols = sops.order_keys(xp, offs, arr)
            return [xp.broadcast_to(c, (ctx.capacity,)) for c in cols]
        words, _, _, ln = scalar_string_keys(
            v.value if isinstance(v.value, bytes) else b"")
        return [xp.full((ctx.capacity,), w, dtype=xp.uint64) for w in words] + \
            [xp.full((ctx.capacity,), np.uint64(int(ln)), dtype=xp.uint64)]

    ka, kb = keys(lv), keys(rv)
    lt = xp.zeros((ctx.capacity,), dtype=bool)
    eq = xp.ones((ctx.capacity,), dtype=bool)
    for a, b in zip(ka, kb):
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return (lt | eq) if or_equal else lt


@evaluator(EqualTo)
def _eval_eq(e: EqualTo, ctx: EvalContext):
    lt, rt = e.left.data_type(), e.right.data_type()
    if _is_string(lt) or _is_string(rt):
        lv, rv = e.left.eval(ctx), e.right.eval(ctx)
        data = _string_eq_data(ctx, lv, rv)
        v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
        return make_column(ctx, t.BOOLEAN, data, v)
    (ld, rd, common), v, _ = _cmp_inputs(e, ctx)
    xp = ctx.xp
    data = ld == rd
    if _float_like(common):
        data = data | (xp.isnan(ld) & xp.isnan(rd))  # Spark: NaN = NaN
    return make_column(ctx, t.BOOLEAN, data, v)


@evaluator(EqualNullSafe)
def _eval_eq_ns(e: EqualNullSafe, ctx: EvalContext):
    xp = ctx.xp
    lv, rv = e.left.eval(ctx), e.right.eval(ctx)
    va = validity_of(lv, ctx)
    vb = validity_of(rv, ctx)

    def norm(v):
        if v is None:
            return xp.ones((ctx.capacity,), dtype=bool)
        if v is False:
            return xp.zeros((ctx.capacity,), dtype=bool)
        return v
    va, vb = norm(va), norm(vb)
    lt, rt = e.left.data_type(), e.right.data_type()
    if _is_string(lt) or _is_string(rt):
        eq = _string_eq_data(ctx, lv, rv)
    else:
        common = promote(lt, rt)
        ld = cast_data(ctx, data_of(lv, ctx), lt, common)
        rd = cast_data(ctx, data_of(rv, ctx), rt, common)
        eq = ld == rd
        if _float_like(common):
            eq = eq | (xp.isnan(ld) & xp.isnan(rd))
    data = (va & vb & eq) | (~va & ~vb)
    return make_column(ctx, t.BOOLEAN, data, None)


def _eval_ordering(e: BinaryComparison, ctx: EvalContext, flip: bool,
                   or_equal: bool):
    lt_, rt_ = e.left.data_type(), e.right.data_type()
    if _is_string(lt_) or _is_string(rt_):
        lv, rv = e.left.eval(ctx), e.right.eval(ctx)
        a, b = (rv, lv) if flip else (lv, rv)
        data = _string_order_lt(ctx, a, b, or_equal)
        v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
        return make_column(ctx, t.BOOLEAN, data, v)
    (ld, rd, common), v, _ = _cmp_inputs(e, ctx)
    if flip:
        ld, rd = rd, ld
    xp = ctx.xp
    if _float_like(common):
        # Spark total order: NaN > everything, NaN == NaN
        a_nan, b_nan = xp.isnan(ld), xp.isnan(rd)
        lt = xp.where(a_nan, False, xp.where(b_nan, True, ld < rd))
        eqd = (ld == rd) | (a_nan & b_nan)
        data = (lt | eqd) if or_equal else lt
    else:
        data = (ld <= rd) if or_equal else (ld < rd)
    return make_column(ctx, t.BOOLEAN, data, v)


@evaluator(LessThan)
def _eval_lt(e, ctx):
    return _eval_ordering(e, ctx, flip=False, or_equal=False)


@evaluator(LessThanOrEqual)
def _eval_le(e, ctx):
    return _eval_ordering(e, ctx, flip=False, or_equal=True)


@evaluator(GreaterThan)
def _eval_gt(e, ctx):
    return _eval_ordering(e, ctx, flip=True, or_equal=False)


@evaluator(GreaterThanOrEqual)
def _eval_ge(e, ctx):
    return _eval_ordering(e, ctx, flip=True, or_equal=True)


# ---------------------------------------------------------------------------
# Boolean logic (three-valued)
# ---------------------------------------------------------------------------

class And(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"({self.children[0].sql()} AND {self.children[1].sql()})"


class Or(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"({self.children[0].sql()} OR {self.children[1].sql()})"


class Not(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"(NOT {self.children[0].sql()})"


def _bool_parts(ctx, v):
    xp = ctx.xp
    d = data_of(v, ctx)
    if not hasattr(d, "shape") or getattr(d, "shape", ()) == ():
        d = xp.full((ctx.capacity,), bool(d))
    val = validity_of(v, ctx)
    if val is None:
        val = xp.ones((ctx.capacity,), dtype=bool)
    elif val is False:
        val = xp.zeros((ctx.capacity,), dtype=bool)
    return d.astype(bool), val


@evaluator(And)
def _eval_and(e: And, ctx: EvalContext):
    da, va = _bool_parts(ctx, e.children[0].eval(ctx))
    db, vb = _bool_parts(ctx, e.children[1].eval(ctx))
    data = da & db & va & vb
    validity = (va & vb) | (va & ~da) | (vb & ~db)
    return make_column(ctx, t.BOOLEAN, data, validity)


@evaluator(Or)
def _eval_or(e: Or, ctx: EvalContext):
    da, va = _bool_parts(ctx, e.children[0].eval(ctx))
    db, vb = _bool_parts(ctx, e.children[1].eval(ctx))
    data = (da & va) | (db & vb)
    validity = (va & vb) | (va & da) | (vb & db)
    return make_column(ctx, t.BOOLEAN, data, validity)


@evaluator(Not)
def _eval_not(e: Not, ctx: EvalContext):
    d, v = _bool_parts(ctx, e.children[0].eval(ctx))
    return make_column(ctx, t.BOOLEAN, ~d & v, v)


# ---------------------------------------------------------------------------
# Null tests
# ---------------------------------------------------------------------------

class IsNull(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        return t.BOOLEAN

    @property
    def nullable(self):
        return False

    def sql(self):
        return f"({self.children[0].sql()} IS NULL)"


class IsNotNull(IsNull):
    def sql(self):
        return f"({self.children[0].sql()} IS NOT NULL)"


class IsNaN(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        return t.BOOLEAN

    @property
    def nullable(self):
        return False


@evaluator(IsNull)
def _eval_isnull(e: IsNull, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    val = validity_of(v, ctx)
    xp = ctx.xp
    if val is None:
        data = xp.zeros((ctx.capacity,), dtype=bool)
    elif val is False:
        data = xp.ones((ctx.capacity,), dtype=bool)
    else:
        data = ~val
    if type(e) is IsNotNull:
        data = ~data
    return make_column(ctx, t.BOOLEAN, data, None)


_EVAL_ISNOTNULL = _eval_isnull
from .core import _EVALUATORS  # noqa: E402
_EVALUATORS[IsNotNull] = _eval_isnull


@evaluator(IsNaN)
def _eval_isnan(e: IsNaN, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    data = ctx.xp.isnan(d) if _float_like(e.children[0].data_type()) else \
        ctx.xp.zeros((ctx.capacity,), dtype=bool)
    val = validity_of(v, ctx)
    # Spark IsNaN(null) = false (non-nullable output)
    if val is not None and val is not False:
        data = data & val
    elif val is False:
        data = ctx.xp.zeros((ctx.capacity,), dtype=bool)
    return make_column(ctx, t.BOOLEAN, data, None)


# ---------------------------------------------------------------------------
# IN
# ---------------------------------------------------------------------------

class In(Expression):
    """value IN (literals...) — Spark null semantics: NULL if value is null,
    or if no match and the list contains a null."""

    def __init__(self, value: Expression, items):
        self.children = (value,)
        self.items = tuple(items)  # Literal expressions

    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return (f"({self.children[0].sql()} IN "
                f"({', '.join(i.sql() for i in self.items)}))")


@evaluator(In)
def _eval_in(e: In, ctx: EvalContext):
    from .core import Literal
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    val = validity_of(v, ctx)
    has_null_item = any(i.value is None for i in e.items)
    matched = xp.zeros((ctx.capacity,), dtype=bool)
    dt = e.children[0].data_type()
    for item in e.items:
        if item.value is None:
            continue
        # eval (not .value): a ParamLiteral item resolves to the traced
        # call-time scalar when params are bound
        iv = item.eval(ctx)
        if _is_string(dt):
            eq = _string_eq_data(ctx, v, iv)
        else:
            common = promote(dt, item.dtype)
            ld = cast_data(ctx, data_of(v, ctx), dt, common)
            rd = cast_data(ctx, iv.value, item.dtype, common)
            eq = ld == rd
        matched = matched | eq
    if val is None:
        val = xp.ones((ctx.capacity,), dtype=bool)
    elif val is False:
        val = xp.zeros((ctx.capacity,), dtype=bool)
    validity = val & (matched | (xp.ones((ctx.capacity,), bool)
                                 if not has_null_item else matched))
    return make_column(ctx, t.BOOLEAN, matched & val, validity)
