"""Complex-type create/extract expressions.

Ref: org/apache/spark/sql/rapids/{complexTypeCreator,
complexTypeExtractors}.scala — CreateArray/CreateNamedStruct,
GetStructField/GetArrayItem/ElementAt registered in GpuOverrides.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from .core import (ColumnValue, EvalContext, Expression, ScalarValue,
                   and_validity, evaluator, make_column, scalar_to_column)


class GetStructField(Expression):
    def __init__(self, child: Expression, name: str,
                 ordinal: Optional[int] = None):
        self.children = (child,)
        self.name = name
        self.ordinal = ordinal

    def _resolve(self):
        st = self.children[0].data_type()
        if not isinstance(st, t.StructType):
            raise TypeError(
                f"field access `.{self.name}` requires a struct column, "
                f"got {st.name} (map key lookup is not supported)")
        if self.ordinal is not None:
            return self.ordinal, st.fields[self.ordinal].data_type
        for i, f in enumerate(st.fields):
            if f.name == self.name:
                return i, f.data_type
        raise KeyError(f"no field {self.name!r} in {st.name}")

    def data_type(self):
        return self._resolve()[1]

    def sql(self):
        return f"{self.children[0].sql()}.{self.name}"


@evaluator(GetStructField)
def _eval_get_struct_field(e: GetStructField, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    i, _ = e._resolve()
    col = v.col.children[i]
    # struct-level nulls mask the extracted child
    if v.col.validity is not None:
        validity = (col.validity & v.col.validity
                    if col.validity is not None else v.col.validity)
        col = DeviceColumn(col.dtype, data=col.data, validity=validity,
                           offsets=col.offsets, data_hi=col.data_hi,
                           children=col.children)
    return ColumnValue(col)


class GetArrayItem(Expression):
    """arr[index] — null when out of range (non-ANSI)."""

    def __init__(self, child: Expression, index: Expression):
        self.children = (child, index)

    def data_type(self):
        at = self.children[0].data_type()
        assert isinstance(at, t.ArrayType), at
        return at.element_type

    def sql(self):
        return f"{self.children[0].sql()}[{self.children[1].sql()}]"


class ElementAt(Expression):
    """element_at(arr, i): 1-based, negative counts from the end."""

    def __init__(self, child: Expression, index: Expression):
        self.children = (child, index)

    def data_type(self):
        at = self.children[0].data_type()
        assert isinstance(at, t.ArrayType), at
        return at.element_type

    def sql(self):
        return (f"element_at({self.children[0].sql()}, "
                f"{self.children[1].sql()})")


def _gather_element(ctx, arr_col: DeviceColumn, pos, in_range):
    """Gather element `pos` (absolute child index) per row."""
    from ..ops.gather import gather_column
    xp = ctx.xp
    child = arr_col.children[0]
    valid = in_range
    if arr_col.validity is not None:
        valid = valid & arr_col.validity
    idx = xp.clip(pos, 0, child.capacity - 1).astype(np.int32)
    return ColumnValue(gather_column(xp, child, idx, valid))


@evaluator(GetArrayItem)
def _eval_get_array_item(e: GetArrayItem, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    iv = e.children[1].eval(ctx)
    from .core import data_of
    i = data_of(iv, ctx)
    col = v.col
    lens = col.offsets[1:] - col.offsets[:-1]
    in_range = (i >= 0) & (i < lens)
    pos = col.offsets[:-1] + i
    from .core import validity_of
    iv_valid = validity_of(iv, ctx)
    if iv_valid is not None:
        in_range = in_range & iv_valid
    return _gather_element(ctx, col, pos, in_range)


@evaluator(ElementAt)
def _eval_element_at(e: ElementAt, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    iv = e.children[1].eval(ctx)
    from .core import data_of, validity_of
    i = data_of(iv, ctx)
    col = v.col
    lens = col.offsets[1:] - col.offsets[:-1]
    pos_from_start = col.offsets[:-1] + (i - 1)
    pos_from_end = col.offsets[1:] + i
    pos = xp.where(i > 0, pos_from_start, pos_from_end)
    in_range = ((i > 0) & (i <= lens)) | ((i < 0) & (-i <= lens))
    iv_valid = validity_of(iv, ctx)
    if iv_valid is not None:
        in_range = in_range & iv_valid
    return _gather_element(ctx, col, pos, in_range)


class CreateArray(Expression):
    def __init__(self, children: List[Expression]):
        self.children = tuple(children)

    def data_type(self):
        et = self.children[0].data_type() if self.children else t.NULL
        return t.ArrayType(et)

    def sql(self):
        return f"array({', '.join(c.sql() for c in self.children)})"


@evaluator(CreateArray)
def _eval_create_array(e: CreateArray, ctx: EvalContext):
    xp = ctx.xp
    n = len(e.children)
    cap = ctx.capacity
    if n == 0:
        # F.array() -> empty array<null> per row
        child = DeviceColumn(t.NULL, data=xp.zeros((1,), np.int8),
                             validity=xp.zeros((1,), dtype=bool))
        return ColumnValue(DeviceColumn(
            t.ArrayType(t.NULL), validity=xp.ones((cap,), dtype=bool),
            offsets=xp.zeros((cap + 1,), np.int32), children=(child,)))
    vals = []
    for c in e.children:
        v = c.eval(ctx)
        if isinstance(v, ScalarValue):
            v = scalar_to_column(ctx, v)
        vals.append(v.col)
    et = e.children[0].data_type()
    # interleave: element j of row r sits at child index r*n + j
    from ..ops.gather import gather_column
    child_cap = cap * n
    src = xp.arange(child_cap, dtype=np.int32) // n       # source row
    which = xp.arange(child_cap, dtype=np.int32) % n      # source column
    parts = []
    for j, col in enumerate(vals):
        g = gather_column(xp, col, src,
                          xp.ones((child_cap,), dtype=bool))
        parts.append(g)
    # select lane j where which == j
    data = parts[0].data
    validity = parts[0].validity
    for j in range(1, n):
        pick = which == j
        data = xp.where(pick, parts[j].data, data)
        validity = xp.where(pick, parts[j].validity, validity)
    child = DeviceColumn(et, data=data, validity=validity)
    offsets = (xp.arange(cap + 1, dtype=np.int32) * n).astype(np.int32)
    return ColumnValue(DeviceColumn(
        t.ArrayType(et), validity=xp.ones((cap,), dtype=bool),
        offsets=offsets, children=(child,)))


class CreateNamedStruct(Expression):
    def __init__(self, names: List[str], values: List[Expression]):
        self.names = list(names)
        self.children = tuple(values)

    def data_type(self):
        return t.StructType([t.StructField(n, c.data_type())
                             for n, c in zip(self.names, self.children)])

    def sql(self):
        inner = ", ".join(f"{n}, {c.sql()}"
                          for n, c in zip(self.names, self.children))
        return f"named_struct({inner})"


@evaluator(CreateNamedStruct)
def _eval_create_named_struct(e: CreateNamedStruct, ctx: EvalContext):
    xp = ctx.xp
    cols = []
    for c in e.children:
        v = c.eval(ctx)
        if isinstance(v, ScalarValue):
            v = scalar_to_column(ctx, v)
        cols.append(v.col)
    return ColumnValue(DeviceColumn(
        e.data_type(), validity=xp.ones((ctx.capacity,), dtype=bool),
        children=tuple(cols)))
