"""Math expressions.

Ref: org/apache/spark/sql/rapids/mathExpressions.scala and GpuOverrides
rules (Sqrt, Exp, Log*, trig family, Pow, Floor, Ceil, Round, Signum, ...).

Spark corner semantics: log of a non-positive number is NULL (not NaN);
floor/ceil of double return LONG; round is HALF_UP for decimals/integrals
and HALF_EVEN-free (Spark uses HALF_UP for Round, BRound is HALF_EVEN).
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from .arithmetic import cast_data
from .core import (EvalContext, Expression, and_validity, data_of, evaluator,
                   make_column, validity_of)


class UnaryMath(Expression):
    out_type: t.DataType = t.DOUBLE

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.out_type


def _unary_double(e, ctx: EvalContext):
    v = e.children[0].eval(ctx)
    d = cast_data(ctx, data_of(v, ctx), e.children[0].data_type(), t.DOUBLE)
    return d, validity_of(v, ctx)


def _simple(cls_name: str, fn_name: str):
    cls = type(cls_name, (UnaryMath,), {})

    @evaluator(cls)
    def _e(e, ctx: EvalContext, _fn=fn_name):
        d, val = _unary_double(e, ctx)
        return make_column(ctx, t.DOUBLE, getattr(ctx.xp, _fn)(d), val)
    return cls


Sqrt = _simple("Sqrt", "sqrt")
Exp = _simple("Exp", "exp")
Expm1 = _simple("Expm1", "expm1")
Sin = _simple("Sin", "sin")
Cos = _simple("Cos", "cos")
Tan = _simple("Tan", "tan")
Asin = _simple("Asin", "arcsin")
Acos = _simple("Acos", "arccos")
Atan = _simple("Atan", "arctan")
Sinh = _simple("Sinh", "sinh")
Cosh = _simple("Cosh", "cosh")
Tanh = _simple("Tanh", "tanh")
Cbrt = _simple("Cbrt", "cbrt")
Rint = _simple("Rint", "rint")
ToDegrees = _simple("ToDegrees", "degrees")
ToRadians = _simple("ToRadians", "radians")
Asinh = _simple("Asinh", "arcsinh")
Acosh = _simple("Acosh", "arccosh")
Atanh = _simple("Atanh", "arctanh")


class Cot(UnaryMath):
    """cot(x) = 1/tan(x) (Spark returns inf at 0 like 1/tan)."""


@evaluator(Cot)
def _eval_cot(e: Cot, ctx: EvalContext):
    d, val = _unary_double(e, ctx)
    with np.errstate(divide="ignore"):   # cot(0) = inf, like Spark
        out = 1.0 / ctx.xp.tan(d)
    return make_column(ctx, t.DOUBLE, out, val)


class Logarithm(Expression):
    """log(base, x); NULL for x <= 0 or base <= 0 (Spark)."""

    def __init__(self, base: Expression, child: Expression):
        self.children = (base, child)

    def data_type(self):
        return t.DOUBLE

    def sql(self):
        return (f"log({self.children[0].sql()}, "
                f"{self.children[1].sql()})")


@evaluator(Logarithm)
def _eval_logarithm(e: Logarithm, ctx: EvalContext):
    xp = ctx.xp
    bv = e.children[0].eval(ctx)
    xv = e.children[1].eval(ctx)
    b = cast_data(ctx, data_of(bv, ctx), e.children[0].data_type(),
                  t.DOUBLE)
    x = cast_data(ctx, data_of(xv, ctx), e.children[1].data_type(),
                  t.DOUBLE)
    ok = (x > 0) & (b > 0)
    sb = xp.where(ok, b, xp.full_like(b, 2.0))
    sx = xp.where(ok, x, xp.ones_like(x))
    out = xp.log(sx) / xp.log(sb)
    val = and_validity(ctx, and_validity(ctx, validity_of(bv, ctx),
                                         validity_of(xv, ctx)), ok)
    return make_column(ctx, t.DOUBLE, out, val)


class Log(UnaryMath):
    """Natural log; Spark returns NULL for input <= 0."""


@evaluator(Log)
def _eval_log(e: Log, ctx: EvalContext):
    xp = ctx.xp
    d, val = _unary_double(e, ctx)
    ok = d > 0
    safe = xp.where(ok, d, xp.ones_like(d))
    return make_column(ctx, t.DOUBLE, xp.log(safe),
                       and_validity(ctx, val, ok))


class Log2(Log):
    pass


class Log10(Log):
    pass


class Log1p(Log):
    pass


@evaluator(Log2)
def _eval_log2(e, ctx):
    xp = ctx.xp
    d, val = _unary_double(e, ctx)
    ok = d > 0
    safe = xp.where(ok, d, xp.ones_like(d))
    return make_column(ctx, t.DOUBLE, xp.log2(safe), and_validity(ctx, val, ok))


@evaluator(Log10)
def _eval_log10(e, ctx):
    xp = ctx.xp
    d, val = _unary_double(e, ctx)
    ok = d > 0
    safe = xp.where(ok, d, xp.ones_like(d))
    return make_column(ctx, t.DOUBLE, xp.log10(safe), and_validity(ctx, val, ok))


@evaluator(Log1p)
def _eval_log1p(e, ctx):
    xp = ctx.xp
    d, val = _unary_double(e, ctx)
    ok = d > -1
    safe = xp.where(ok, d, xp.zeros_like(d))
    return make_column(ctx, t.DOUBLE, xp.log1p(safe), and_validity(ctx, val, ok))


class Pow(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self):
        return t.DOUBLE


@evaluator(Pow)
def _eval_pow(e: Pow, ctx: EvalContext):
    lv, rv = e.children[0].eval(ctx), e.children[1].eval(ctx)
    ld = cast_data(ctx, data_of(lv, ctx), e.children[0].data_type(), t.DOUBLE)
    rd = cast_data(ctx, data_of(rv, ctx), e.children[1].data_type(), t.DOUBLE)
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return make_column(ctx, t.DOUBLE, ctx.xp.power(ld, rd), v)


class Atan2(Expression):
    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self):
        return t.DOUBLE


@evaluator(Atan2)
def _eval_atan2(e: Atan2, ctx: EvalContext):
    lv, rv = e.children[0].eval(ctx), e.children[1].eval(ctx)
    ld = cast_data(ctx, data_of(lv, ctx), e.children[0].data_type(), t.DOUBLE)
    rd = cast_data(ctx, data_of(rv, ctx), e.children[1].data_type(), t.DOUBLE)
    v = and_validity(ctx, validity_of(lv, ctx), validity_of(rv, ctx))
    return make_column(ctx, t.DOUBLE, ctx.xp.arctan2(ld, rd), v)


class Floor(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self):
        dt = self.children[0].data_type()
        if isinstance(dt, t.DecimalType):
            return t.DecimalType(dt.precision - dt.scale + 1, 0)
        if t.is_integral(dt):
            return dt
        return t.LONG


class Ceil(Floor):
    pass


@evaluator(Floor)
def _eval_floor(e: Floor, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    src = e.children[0].data_type()
    out = e.data_type()
    d = data_of(v, ctx)
    val = validity_of(v, ctx)
    is_ceil = type(e) is Ceil
    if isinstance(src, t.DecimalType):
        scale_f = np.int64(10 ** src.scale)
        q = d // scale_f if not is_ceil else -((-d) // scale_f)
        return make_column(ctx, out, q, val)
    if t.is_integral(src):
        return make_column(ctx, out, d, val)
    r = xp.ceil(d) if is_ceil else xp.floor(d)
    # Java d.toLong semantics: NaN -> 0, out-of-range saturates exactly
    r = xp.where(xp.isnan(r), 0.0, r)
    too_hi = r >= 9.223372036854776e18
    too_lo = r <= -9.223372036854776e18
    safe = xp.clip(r, -9.2e18, 9.2e18).astype(np.int64)
    data = xp.where(too_hi, np.int64(2**63 - 1),
                    xp.where(too_lo, np.int64(-(2**63)), safe))
    return make_column(ctx, out, data, val)


_EVAL_CEIL = _eval_floor
from .core import _EVALUATORS  # noqa: E402
_EVALUATORS[Ceil] = _eval_floor


class Signum(UnaryMath):
    pass


@evaluator(Signum)
def _eval_signum(e, ctx):
    d, val = _unary_double(e, ctx)
    return make_column(ctx, t.DOUBLE, ctx.xp.sign(d), val)


class Round(Expression):
    """HALF_UP rounding to `scale` digits (Spark Round)."""

    half_even = False

    def __init__(self, child, scale: int = 0):
        self.children = (child,)
        self.scale = scale

    def data_type(self):
        dt = self.children[0].data_type()
        if isinstance(dt, t.DecimalType):
            new_scale = min(max(self.scale, 0), dt.scale)
            p = dt.precision - dt.scale + new_scale + (1 if new_scale < dt.scale else 0)
            return t.DecimalType(min(p, 38), new_scale)
        return dt


class BRound(Round):
    half_even = True


def _round_impl(e: Round, ctx: EvalContext):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    src = e.children[0].data_type()
    d = data_of(v, ctx)
    val = validity_of(v, ctx)
    s = e.scale
    if isinstance(src, t.DecimalType):
        out = e.data_type()
        if out.scale >= src.scale:
            return make_column(ctx, out, d, val)
        f = np.int64(10 ** (src.scale - out.scale))
        if e.half_even:
            # floor-division puts r in [0, f); tie picks the even quotient
            q = d // f
            r = d - q * f
            up = (2 * r > f) | ((2 * r == f) & (q % 2 != 0))
            return make_column(ctx, out, (q + up.astype(np.int64)), val)
        from .arithmetic import _div_round_half_up
        q = _div_round_half_up(xp, d, f)
        return make_column(ctx, out, q, val)
    if t.is_integral(src):
        if s >= 0:
            return make_column(ctx, src, d, val)
        f = np.int64(10 ** (-s))
        from .arithmetic import _div_round_half_up
        q = _div_round_half_up(xp, d, f)
        return make_column(ctx, src, q * f, val)
    # floating: Spark rounds via BigDecimal HALF_UP; approximate with
    # scaled rounding (documented float corner)
    f = 10.0 ** s
    if e.half_even:
        data = xp.round(d * f) / f
    else:
        data = xp.where(d >= 0, xp.floor(d * f + 0.5),
                        xp.ceil(d * f - 0.5)) / f
    return make_column(ctx, src, data.astype(t.to_np_dtype(src)), val)


@evaluator(Round)
def _eval_round(e, ctx):
    return _round_impl(e, ctx)


_EVALUATORS[BRound] = _round_impl


class NormalizeNaNAndZero(Expression):
    """Canonicalize floats for grouping/join keys: every NaN becomes THE
    NaN and -0.0 becomes +0.0 (ref NormalizeFloatingNumbers.scala /
    GpuNormalizeNaNAndZero).  The engine's key-word encoding already
    normalizes inside group/sort kernels; this expression is the
    user-facing/plan-inserted form."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self):
        return self.children[0].data_type()

    def sql(self):
        return f"normalize_nan_and_zero({self.children[0].sql()})"


@evaluator(NormalizeNaNAndZero)
def _eval_normalize_nan_zero(e: NormalizeNaNAndZero, ctx):
    xp = ctx.xp
    v = e.children[0].eval(ctx)
    d = data_of(v, ctx)
    d = xp.where(xp.isnan(d), xp.full_like(d, np.nan), d)
    d = xp.where(d == 0, xp.zeros_like(d), d)   # -0.0 -> +0.0
    return make_column(ctx, e.data_type(), d, validity_of(v, ctx))
