"""Scalar subqueries (ref GpuScalarSubquery.scala: the reference wraps
Spark's ExecSubqueryExpression — the subquery runs first on the driver
and its single value is substituted into the outer plan's expressions).

Engine realization: `ScalarSubquery` holds the subquery's LOGICAL plan;
`resolve_scalar_subqueries` runs each subquery through the session
ahead of outer-plan planning (driver-side, exactly Spark's sequencing)
and replaces the node with a typed Literal, so the outer query compiles
with a constant — the most XLA-friendly form a runtime scalar can take.
"""

from __future__ import annotations

from .. import types as t
from .core import Expression, Literal

# every logical-plan attribute that can carry expressions (Window keeps
# them under window_exprs; Expand.projections is a list of lists)
_EXPR_ATTRS = ("condition", "exprs", "grouping", "aggregates",
               "projections", "orders", "keys", "window_exprs")


def _map_expr_container(v, fn):
    """Apply fn to every Expression inside a (possibly nested) container,
    preserving its shape."""
    if isinstance(v, Expression):
        return fn(v)
    if isinstance(v, (list, tuple)):
        out = [_map_expr_container(item, fn) if
               isinstance(item, (Expression, list, tuple)) else item
               for item in v]
        return type(v)(out) if isinstance(v, list) else tuple(out)
    return v


class ScalarSubquery(Expression):
    """A subquery that must yield exactly one row and one column."""

    def __init__(self, lp):
        self.children = ()
        self.lp = lp

    def data_type(self):
        return self.lp.schema()[1][0]

    def sql(self):
        return "scalar_subquery(...)"


def resolve_scalar_subqueries(lp, session, execute: bool = True):
    """Replace every ScalarSubquery in the plan's expression trees with
    the executed literal value (execute=False substitutes typed null
    placeholders — the explain path must not run device work).  Raises
    if a subquery yields != 1 row (Spark's runtime error)."""

    def resolve_expr(e: Expression) -> Expression:
        def fn(x):
            if isinstance(x, ScalarSubquery):
                if not execute:
                    return Literal(None, x.data_type())
                out = session.execute(x.lp)
                if out.num_columns < 1 or out.num_rows != 1:
                    raise ValueError(
                        f"scalar subquery must return one row, got "
                        f"{out.num_rows}")
                val = out.column(0).to_pylist()[0]
                return Literal(val, x.data_type())
            from .window import WindowExpression
            if isinstance(x, WindowExpression):
                # the window spec's keys live outside the children tuple
                import copy
                new_pb = [resolve_expr(p) for p in x.spec.partition_by]
                new_ob = [(resolve_expr(o[0]),) + tuple(o[1:])
                          if isinstance(o, tuple) else resolve_expr(o)
                          for o in x.spec.order_by]
                changed = any(a is not b for a, b in
                              zip(new_pb, x.spec.partition_by)) or \
                    any((a[0] if isinstance(a, tuple) else a) is not
                        (b[0] if isinstance(b, tuple) else b)
                        for a, b in zip(new_ob, x.spec.order_by))
                if changed:
                    spec = copy.copy(x.spec)
                    spec.partition_by = new_pb
                    spec.order_by = new_ob
                    x = copy.copy(x)
                    x.spec = spec
            return x
        return e.transform_up(fn)

    def walk(node):
        """Copy-on-write: the caller's logical plan must stay intact —
        explain substitutes placeholders, and a re-collect must re-run
        subqueries against current data, not a frozen literal."""
        import copy
        new_children = tuple(walk(c) for c in node.children)
        new_attrs = {}
        for attr in _EXPR_ATTRS:
            v = getattr(node, attr, None)
            if v is None:
                continue
            nv = _map_expr_container(v, resolve_expr)
            if not _same_exprs(v, nv):
                new_attrs[attr] = nv
        changed = new_attrs or any(
            a is not b for a, b in zip(new_children, node.children))
        if not changed:
            return node
        node = copy.copy(node)
        node.children = new_children
        for attr, nv in new_attrs.items():
            setattr(node, attr, nv)
        return node

    return walk(lp)


def _same_exprs(a, b) -> bool:
    """Identity comparison through nested containers (resolve rebuilds
    containers even when nothing changed inside)."""
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_same_exprs(x, y)
                                        for x, y in zip(a, b))
    return a is b


def has_scalar_subquery(lp) -> bool:
    found = []

    def check_expr(e):
        if not isinstance(e, Expression):
            return
        if e.collect(lambda x: isinstance(x, ScalarSubquery)):
            found.append(True)
        # window specs keep their keys outside the children tuple
        from .window import WindowExpression
        for w in [e] + e.collect(
                lambda x: isinstance(x, WindowExpression)):
            spec = getattr(w, "spec", None)
            if spec is None:
                continue
            for p in spec.partition_by:
                check_expr(p)
            for o in spec.order_by:
                check_expr(o[0] if isinstance(o, tuple) else o)

    def scan(v):
        if isinstance(v, Expression):
            check_expr(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                scan(item)

    def walk(node):
        for attr in _EXPR_ATTRS:
            scan(getattr(node, attr, None))
        for c in node.children:
            walk(c)

    walk(lp)
    return bool(found)
