"""Scalar subqueries (ref GpuScalarSubquery.scala: the reference wraps
Spark's ExecSubqueryExpression — the subquery runs first on the driver
and its single value is substituted into the outer plan's expressions).

Engine realization: `ScalarSubquery` holds the subquery's LOGICAL plan;
`resolve_scalar_subqueries` runs each subquery through the session
ahead of outer-plan planning (driver-side, exactly Spark's sequencing)
and replaces the node with a typed Literal, so the outer query compiles
with a constant — the most XLA-friendly form a runtime scalar can take.
"""

from __future__ import annotations

from .. import types as t
from .core import Expression, Literal


class ScalarSubquery(Expression):
    """A subquery that must yield exactly one row and one column."""

    def __init__(self, lp):
        self.children = ()
        self.lp = lp

    def data_type(self):
        return self.lp.schema()[1][0]

    def sql(self):
        return "scalar_subquery(...)"


def resolve_scalar_subqueries(lp, session):
    """Replace every ScalarSubquery in the plan's expression trees with
    the executed literal value.  Raises if a subquery yields != 1 row
    (Spark's runtime error for scalar subqueries)."""

    def resolve_expr(e: Expression) -> Expression:
        def fn(x):
            if isinstance(x, ScalarSubquery):
                out = session.execute(x.lp)
                if out.num_columns < 1 or out.num_rows != 1:
                    raise ValueError(
                        f"scalar subquery must return one row, got "
                        f"{out.num_rows}")
                val = out.column(0).to_pylist()[0]
                return Literal(val, x.data_type())
            return x
        return e.transform_up(fn)

    def walk(node):
        node.children = tuple(walk(c) for c in node.children)
        for attr in ("condition", "exprs", "grouping", "aggregates",
                     "projections", "orders", "keys"):
            v = getattr(node, attr, None)
            if v is None:
                continue
            if isinstance(v, Expression):
                setattr(node, attr, resolve_expr(v))
            elif isinstance(v, (list, tuple)):
                out = []
                changed = False
                for item in v:
                    if isinstance(item, Expression):
                        r = resolve_expr(item)
                        changed |= r is not item
                        out.append(r)
                    elif (isinstance(item, tuple) and item and
                          isinstance(item[0], Expression)):
                        r = (resolve_expr(item[0]),) + item[1:]
                        changed = True
                        out.append(r)
                    else:
                        out.append(item)
                if changed:
                    setattr(node, attr, type(v)(out) if
                            isinstance(v, list) else tuple(out))
        return node

    return walk(lp)


def has_scalar_subquery(lp) -> bool:
    found = []

    def check_expr(e):
        if isinstance(e, Expression):
            if e.collect(lambda x: isinstance(x, ScalarSubquery)):
                found.append(True)

    def walk(node):
        for attr in ("condition", "exprs", "grouping", "aggregates",
                     "projections", "orders", "keys"):
            v = getattr(node, attr, None)
            if isinstance(v, Expression):
                check_expr(v)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if isinstance(item, Expression):
                        check_expr(item)
                    elif (isinstance(item, tuple) and item and
                          isinstance(item[0], Expression)):
                        check_expr(item[0])
        for c in node.children:
            walk(c)

    walk(lp)
    return bool(found)
