"""Scalar subqueries (ref GpuScalarSubquery.scala: the reference wraps
Spark's ExecSubqueryExpression — the subquery runs first on the driver
and its single value is substituted into the outer plan's expressions).

Engine realization: `ScalarSubquery` holds the subquery's LOGICAL plan;
`resolve_scalar_subqueries` runs each subquery through the session
ahead of outer-plan planning (driver-side, exactly Spark's sequencing)
and replaces the node with a typed Literal, so the outer query compiles
with a constant — the most XLA-friendly form a runtime scalar can take.
"""

from __future__ import annotations

from .. import types as t
from .core import Expression, Literal

# every logical-plan attribute that can carry expressions (Window keeps
# them under window_exprs; Expand.projections is a list of lists)
_EXPR_ATTRS = ("condition", "exprs", "grouping", "aggregates",
               "projections", "orders", "keys", "window_exprs")


def _map_expr_container(v, fn):
    """Apply fn to every Expression inside a (possibly nested) container,
    preserving its shape."""
    if isinstance(v, Expression):
        return fn(v)
    if isinstance(v, (list, tuple)):
        out = [_map_expr_container(item, fn) if
               isinstance(item, (Expression, list, tuple)) else item
               for item in v]
        return type(v)(out) if isinstance(v, list) else tuple(out)
    return v


class ScalarSubquery(Expression):
    """A subquery that must yield exactly one row and one column."""

    def __init__(self, lp):
        self.children = ()
        self.lp = lp

    def data_type(self):
        return self.lp.schema()[1][0]

    def sql(self):
        return "scalar_subquery(...)"


def resolve_scalar_subqueries(lp, session):
    """Replace every ScalarSubquery in the plan's expression trees with
    the executed literal value.  Raises if a subquery yields != 1 row
    (Spark's runtime error for scalar subqueries)."""

    def resolve_expr(e: Expression) -> Expression:
        def fn(x):
            if isinstance(x, ScalarSubquery):
                out = session.execute(x.lp)
                if out.num_columns < 1 or out.num_rows != 1:
                    raise ValueError(
                        f"scalar subquery must return one row, got "
                        f"{out.num_rows}")
                val = out.column(0).to_pylist()[0]
                return Literal(val, x.data_type())
            from .window import WindowExpression
            if isinstance(x, WindowExpression):
                # the window spec's keys live outside the children tuple
                import copy
                spec = copy.copy(x.spec)
                spec.partition_by = [resolve_expr(p)
                                     for p in spec.partition_by]
                spec.order_by = [
                    (resolve_expr(o[0]),) + tuple(o[1:])
                    if isinstance(o, tuple) else resolve_expr(o)
                    for o in spec.order_by]
                x = copy.copy(x)
                x.spec = spec
            return x
        return e.transform_up(fn)

    def walk(node):
        node.children = tuple(walk(c) for c in node.children)
        for attr in _EXPR_ATTRS:
            v = getattr(node, attr, None)
            if v is None:
                continue
            setattr(node, attr, _map_expr_container(v, resolve_expr))
        return node

    return walk(lp)


def has_scalar_subquery(lp) -> bool:
    found = []

    def check_expr(e):
        if isinstance(e, Expression):
            if e.collect(lambda x: isinstance(x, ScalarSubquery)):
                found.append(True)

    def scan(v):
        if isinstance(v, Expression):
            check_expr(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                scan(item)

    def walk(node):
        for attr in _EXPR_ATTRS:
            scan(getattr(node, attr, None))
        for c in node.children:
            walk(c)

    walk(lp)
    return bool(found)
