"""Higher-order functions over arrays: transform / filter / exists /
forall.

Ref: sql-plugin/.../higherOrderFunctions.scala — the reference evaluates
lambda bodies columnar over the array's flattened child column; the same
shape maps perfectly to this build's element-space evaluation: a lambda
body is an ordinary expression evaluated in a context whose capacity is
the child column's, with the lambda variable bound to the child column
itself.  Offsets are then reused (transform), recomputed by segmented
counts (filter), or reduced per row (exists/forall).

Lambda bodies may reference the lambda variable(s) and literals; outer
column references inside a body are not supported (tagged off, both
engines) — the reference has the same restriction for its AST-style
lambda evaluation.
"""

from __future__ import annotations

from typing import List

import numpy as np
from ..ops.scan import cumsum_fast

from .. import types as t
from ..columnar.device import DeviceColumn
from .core import (ColumnValue, EvalContext, Expression, evaluator,
                   make_column)


class NamedLambdaVariable(Expression):
    def __init__(self, name: str, dtype: t.DataType = None):
        self.children = ()
        self.name = name
        self.dtype = dtype

    def data_type(self):
        if self.dtype is None:
            raise TypeError(f"unbound lambda variable {self.name}")
        return self.dtype

    def sql(self):
        return self.name


@evaluator(NamedLambdaVariable)
def _eval_lambda_var(e: NamedLambdaVariable, ctx: EvalContext):
    v = ctx.lambda_bindings.get(e.name)
    if v is None:
        from .core import EvalError
        raise EvalError(f"lambda variable {e.name} not in scope")
    return v


class LambdaFunction(Expression):
    def __init__(self, body: Expression, args: List[NamedLambdaVariable]):
        self.children = (body,)
        self.args = list(args)

    @property
    def body(self):
        return self.children[0]

    def data_type(self):
        return self.body.data_type()

    def sql(self):
        names = ", ".join(a.name for a in self.args)
        return f"lambdafunction({self.body.sql()}, {names})"


def references_outer_columns(body: Expression, arg_names) -> bool:
    from .core import AttributeReference, BoundReference
    found = []

    def visit(e):
        if isinstance(e, (AttributeReference, BoundReference)):
            found.append(e)
        return e
    body.transform_up(visit)
    return bool(found)


class ArrayHigherOrder(Expression):
    def __init__(self, arr: Expression, fn: LambdaFunction):
        self.children = (arr, fn)

    @property
    def arr(self):
        return self.children[0]

    @property
    def fn(self) -> LambdaFunction:
        return self.children[1]

    def _bind_lambda(self) -> LambdaFunction:
        """Type the lambda variable(s) with the array's element type."""
        at = self.arr.data_type()
        assert isinstance(at, t.ArrayType), at
        fn = self.fn
        typed = {fn.args[0].name: at.element_type}
        if len(fn.args) > 1:
            typed[fn.args[1].name] = t.INT  # element index

        def retype(e):
            if isinstance(e, NamedLambdaVariable) and e.name in typed:
                return NamedLambdaVariable(e.name, typed[e.name])
            return e
        body = fn.body.transform_up(retype)
        return LambdaFunction(body, [retype(a) for a in fn.args])

    def _element_eval(self, ctx: EvalContext, arr_col: DeviceColumn):
        """Evaluate the lambda body in element space; returns the body's
        ColumnValue over the child capacity."""
        from ..columnar.device import DeviceBatch
        xp = ctx.xp
        child = arr_col.children[0]
        fn = self._bind_lambda()
        n_elem = arr_col.offsets[-1]
        ectx = EvalContext(xp, DeviceBatch([child], n_elem))
        ectx.ansi = ctx.ansi
        ectx.lambda_bindings[fn.args[0].name] = ColumnValue(child)
        if len(fn.args) > 1:
            # element index within its row
            pos = xp.arange(child.capacity, dtype=np.int32)
            row = xp.clip(
                xp.searchsorted(arr_col.offsets, pos, side="right") - 1,
                0, arr_col.capacity - 1).astype(np.int32)
            idx = (pos - arr_col.offsets[row]).astype(np.int32)
            ectx.lambda_bindings[fn.args[1].name] = make_column(
                ectx, t.INT, idx, None)
        v = fn.body.eval(ectx)
        if not isinstance(v, ColumnValue):
            from .core import scalar_to_column
            v = scalar_to_column(ectx, v)
        return v


class ArrayTransform(ArrayHigherOrder):
    def data_type(self):
        return t.ArrayType(self._bind_lambda().body.data_type())

    def sql(self):
        return f"transform({self.arr.sql()}, {self.fn.sql()})"


@evaluator(ArrayTransform)
def _eval_array_transform(e: ArrayTransform, ctx: EvalContext):
    v = e.arr.eval(ctx)
    col = v.col
    out_elem = e._element_eval(ctx, col)
    return ColumnValue(DeviceColumn(
        e.data_type(), validity=col.validity, offsets=col.offsets,
        children=(out_elem.col,)))


class ArrayFilter(ArrayHigherOrder):
    def data_type(self):
        return self.arr.data_type()

    def sql(self):
        return f"filter({self.arr.sql()}, {self.fn.sql()})"


@evaluator(ArrayFilter)
def _eval_array_filter(e: ArrayFilter, ctx: EvalContext):
    from ..ops.gather import gather_column
    xp = ctx.xp
    v = e.arr.eval(ctx)
    col = v.col
    child = col.children[0]
    pred = e._element_eval(ctx, col)
    keep = pred.col.data.astype(bool)
    if pred.col.validity is not None:
        keep = keep & pred.col.validity  # null predicate drops the element
    n_elem = col.offsets[-1]
    in_bounds = xp.arange(child.capacity, dtype=np.int32) < n_elem
    keep = keep & in_bounds
    # new offsets: per-row kept counts
    kept_cum = xp.concatenate([
        xp.zeros((1,), np.int64),
        cumsum_fast(xp, keep.astype(np.int64))])
    new_offsets = kept_cum[col.offsets.astype(np.int64)].astype(np.int32)
    # stable-compact kept elements to the front
    order = xp.argsort(~keep, stable=True).astype(np.int32)
    total_kept = new_offsets[-1]
    live = xp.arange(child.capacity, dtype=np.int32) < total_kept
    new_child = gather_column(xp, child, order, live)
    return ColumnValue(DeviceColumn(
        col.dtype, validity=col.validity, offsets=new_offsets,
        children=(new_child,)))


class ArrayExists(ArrayHigherOrder):
    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"exists({self.arr.sql()}, {self.fn.sql()})"


class ArrayForAll(ArrayHigherOrder):
    def data_type(self):
        return t.BOOLEAN

    def sql(self):
        return f"forall({self.arr.sql()}, {self.fn.sql()})"


def _segmented_bool(e: ArrayHigherOrder, ctx: EvalContext, want_all: bool):
    """Spark three-valued logic: exists = true if any true, else NULL if
    any null predicate, else false; forall dually."""
    xp = ctx.xp
    v = e.arr.eval(ctx)
    col = v.col
    child = col.children[0]
    pred = e._element_eval(ctx, col)
    p = pred.col.data.astype(bool)
    pvalid = pred.col.validity if pred.col.validity is not None else \
        xp.ones((child.capacity,), dtype=bool)
    n_elem = col.offsets[-1]
    in_bounds = xp.arange(child.capacity, dtype=np.int32) < n_elem

    def per_row_count(mask):
        cum = xp.concatenate([
            xp.zeros((1,), np.int64), cumsum_fast(xp, mask.astype(np.int64))])
        return (cum[col.offsets[1:].astype(np.int64)] -
                cum[col.offsets[:-1].astype(np.int64)])

    n_true = per_row_count(p & pvalid & in_bounds)
    n_false = per_row_count(~p & pvalid & in_bounds)
    n_null = per_row_count(~pvalid & in_bounds)
    if want_all:
        data = n_false == 0
        known = (n_false > 0) | (n_null == 0)
    else:
        data = n_true > 0
        known = (n_true > 0) | (n_null == 0)
    validity = known
    if col.validity is not None:
        validity = validity & col.validity
    data = xp.where(validity, data, xp.zeros_like(data))
    return make_column(ctx, t.BOOLEAN, data, validity)


@evaluator(ArrayExists)
def _eval_array_exists(e: ArrayExists, ctx: EvalContext):
    return _segmented_bool(e, ctx, want_all=False)


@evaluator(ArrayForAll)
def _eval_array_forall(e: ArrayForAll, ctx: EvalContext):
    return _segmented_bool(e, ctx, want_all=True)


class MapHigherOrder(Expression):
    """transform_keys / transform_values: lambda (k, v) over each map
    entry, rebuilding one side (ref GpuTransformKeys/GpuTransformValues,
    higherOrderFunctions.scala)."""

    def __init__(self, m: Expression, fn: LambdaFunction):
        self.children = (m, fn)

    @property
    def fn(self) -> LambdaFunction:
        return self.children[1]

    def _bind_lambda(self) -> LambdaFunction:
        mt = self.children[0].data_type()
        assert isinstance(mt, t.MapType), mt
        fn = self.fn
        typed = {fn.args[0].name: mt.key_type}
        if len(fn.args) > 1:
            typed[fn.args[1].name] = mt.value_type

        def retype(e):
            if isinstance(e, NamedLambdaVariable) and e.name in typed:
                return NamedLambdaVariable(e.name, typed[e.name])
            return e
        body = fn.body.transform_up(retype)
        return LambdaFunction(body, [retype(a) for a in fn.args])

    def _entry_eval(self, ctx: EvalContext, mcol: DeviceColumn):
        from ..columnar.device import DeviceBatch
        xp = ctx.xp
        kcol, vcol = mcol.children
        fn = self._bind_lambda()
        n_elem = mcol.offsets[-1]
        ectx = EvalContext(xp, DeviceBatch([kcol, vcol], n_elem))
        ectx.ansi = ctx.ansi
        ectx.lambda_bindings[fn.args[0].name] = ColumnValue(kcol)
        if len(fn.args) > 1:
            ectx.lambda_bindings[fn.args[1].name] = ColumnValue(vcol)
        v = fn.body.eval(ectx)
        if not isinstance(v, ColumnValue):
            from .core import scalar_to_column
            v = scalar_to_column(ectx, v)
        return v


class TransformValues(MapHigherOrder):
    def data_type(self):
        mt = self.children[0].data_type()
        return t.MapType(mt.key_type, self._bind_lambda().body.data_type())

    def sql(self):
        return f"transform_values({self.children[0].sql()}, {self.fn.sql()})"


class TransformKeys(MapHigherOrder):
    def data_type(self):
        mt = self.children[0].data_type()
        return t.MapType(self._bind_lambda().body.data_type(),
                         mt.value_type)

    def sql(self):
        return f"transform_keys({self.children[0].sql()}, {self.fn.sql()})"


@evaluator(TransformValues)
def _eval_transform_values(e: TransformValues, ctx: EvalContext):
    m = e.children[0].eval(ctx).col
    out = e._entry_eval(ctx, m)
    return ColumnValue(DeviceColumn(
        e.data_type(), validity=m.validity, offsets=m.offsets,
        children=(m.children[0], out.col)))


@evaluator(TransformKeys)
def _eval_transform_keys(e: TransformKeys, ctx: EvalContext):
    # Spark raises on null or duplicate transformed keys in ANSI mode;
    # like the reference we keep the entry layout (keys map 1:1)
    m = e.children[0].eval(ctx).col
    out = e._entry_eval(ctx, m)
    return ColumnValue(DeviceColumn(
        e.data_type(), validity=m.validity, offsets=m.offsets,
        children=(out.col, m.children[1])))
