"""Zero-copy ML export: query results as device-resident JAX arrays.

The reference's ML integration story (ref ColumnarRdd.scala,
InternalColumnarRddConverter.scala, docs/ml-integration.md) hands GPU
columnar batches straight to XGBoost without a host round trip.  The
TPU-native equivalent hands the final device batches of a query to JAX
ML code with NO device->host transfer at all: the training step consumes
the same HBM buffers the SQL pipeline produced — a tighter integration
than the reference's, since consumer and producer share one runtime.

    from spark_rapids_tpu import ml
    arrays = ml.columnar_arrays(df)       # [{col: (data, validity)}, ...]
    X = jnp.stack([arrays[0]["f1"][0], arrays[0]["f2"][0]], axis=1)
    ... jax training loop ...
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def device_batches(df) -> List:
    """Run the DataFrame's plan and return the raw device batches per
    partition WITHOUT the DeviceToHost transition — the ColumnarRdd
    analog.  Falls back to numpy-backed batches for CPU-placed plans
    (the reference likewise degrades to host rows when the plan ended on
    CPU, InternalColumnarRddConverter's row path)."""
    from .exec.base import DeviceToHostExec, ExecContext
    from .exec.basic import CoalesceBatchesExec
    from .exec.gatherpart import GatherPartitionsExec

    session = df.session
    final_plan = session.prepare_plan(df._lp)
    # strip the whole collect boundary (DeviceToHost plus the gather/
    # coalesce inserted for fetch efficiency): ML consumers want the
    # plan's own partitioning and zero-copy device batches, not a
    # concatenated fetch-shaped result
    if isinstance(final_plan, DeviceToHostExec):
        final_plan = final_plan.children[0]
        while isinstance(final_plan, (CoalesceBatchesExec,
                                      GatherPartitionsExec)):
            final_plan = final_plan.children[0]
    session.last_plan = final_plan
    ctx = ExecContext(session.conf)
    out = []
    try:
        for pid in range(final_plan.num_partitions):
            out.append(list(final_plan.execute_partition(pid, ctx)))
    finally:
        session.release_plan_shuffles(final_plan)
    return out


def columnar_arrays(df) -> List[Dict[str, Tuple]]:
    """Per-partition dicts of column name -> (data, validity) JAX
    arrays, still on device.  Variable-width columns additionally carry
    their offsets: (data, validity, offsets)."""
    parts = device_batches(df)
    names = df.columns
    result = []
    for batches in parts:
        for b in batches:
            d: Dict[str, Tuple] = {}
            for name, col in zip(names, b.columns):
                if col.offsets is not None:
                    d[name] = (col.data, col.validity, col.offsets)
                else:
                    d[name] = (col.data, col.validity)
            d["__num_rows__"] = b.num_rows
            result.append(d)
    return result
