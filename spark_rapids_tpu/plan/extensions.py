"""External override providers — the GpuHiveOverrides pattern.

The reference wires Hive-specific rules through a provider hook so the
core engine never hard-depends on Hive classes (ref GpuOverrides.scala:53
`GpuHiveOverrides`, ExternalSource): if the provider's prerequisites are
present it contributes extra ExprRules/ExecRules, otherwise the engine
runs without them.

This module is that hook for the TPU engine: libraries register a
provider; each provider's `register()` runs once, lazily, the first time
the overrides engine is entered, and may add expression rules
(plan.overrides.expr_rule) or exec handling.  `spark_rapids_tpu.hive`
registers itself through this hook exactly the way GpuHiveOverrides
self-registers.
"""

from __future__ import annotations

from typing import Callable, List

_PROVIDERS: List[Callable[[], None]] = []
_loaded = False


def register_override_provider(fn: Callable[[], None]) -> None:
    """Add a provider; it runs once before the next plan rewrite."""
    global _loaded
    _PROVIDERS.append(fn)
    _loaded = False


def load_extension_rules() -> None:
    """Run all pending providers (idempotent)."""
    global _loaded
    if _loaded:
        return
    _loaded = True
    for fn in list(_PROVIDERS):
        fn()
