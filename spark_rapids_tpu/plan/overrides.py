"""Plan-rewrite engine: meta wrapping, tagging, TPU conversion, transitions.

TPU-native analog of the reference's core
(ref: GpuOverrides.scala:3476 apply / :3495 applyOverrides,
RapidsMeta.scala:70/543/911 meta hierarchy,
GpuTransitionOverrides.scala:44 transition insertion).

Flow:
  1. wrap the CPU physical plan into a Meta tree,
  2. tag every node: per-op enable confs, TypeSig checks on output schema,
     expression-level checks (each expression class has a rule + TypeSig,
     ref GpuOverrides.scala:727-3048 registry),
  3. convert untagged subtrees to TPU placement (aggregates become a
     Partial/Final TPU pair, ref aggregate.scala modes),
  4. insert HostToDevice/DeviceToHost transitions at placement boundaries,
  5. produce reference-style explain output (spark.rapids.sql.explain).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from .. import config as cfg
from .. import types as t
from ..exec import base as eb
from ..exec.aggregate import (CpuHashAggregateExec, TpuHashAggregateExec)
from ..exec.basic import (CoalesceBatchesExec, FilterExec, GlobalLimitExec,
                          LocalLimitExec, LocalScanExec, ProjectExec,
                          RangeExec, UnionExec)
from ..exec.gatherpart import GatherPartitionsExec
from ..expr import aggregates as agg
from ..expr import arithmetic as ar
from ..expr import conditional as cond
from ..expr import mathexpr as mx
from ..expr import predicates as pred
from ..expr.cast import Cast, cast_supported_on_tpu
from ..expr.core import (Alias, AttributeReference, BoundReference,
                         Expression, Literal, bind_expression)
from ..types import T, TypeSig


# ---------------------------------------------------------------------------
# Expression rules (ref ExprRule, GpuOverrides.scala:206)
# ---------------------------------------------------------------------------

class ExprRule:
    def __init__(self, sig: TypeSig, desc: str = "",
                 tag_fn: Optional[Callable] = None):
        self.sig = sig
        self.desc = desc
        self.tag_fn = tag_fn


EXPR_RULES: Dict[Type[Expression], ExprRule] = {}


def expr_rule(cls, sig: TypeSig, desc: str = "", tag_fn=None):
    EXPR_RULES[cls] = ExprRule(sig, desc, tag_fn)


_num = T.numeric64
_common = T.common_scalar
_cmp = (T.numeric64 + T.BOOLEAN + T.DATE + T.TIMESTAMP + T.STRING + T.NULL)

def _tag_literal(meta: "ExprMeta"):
    e = meta.expr
    if isinstance(e.data_type(), t.DecimalType) and e.value is not None \
            and not (-(2**63) <= int(e.value) < 2**63):
        meta.will_not_work(
            "decimal literal beyond 64-bit unscaled range stays on CPU")


expr_rule(Literal, T.all_types, "literal values", _tag_literal)

from ..expr.params import ParamLiteral  # noqa: E402 (needs Literal)

expr_rule(ParamLiteral, _num + T.DATE + T.TIMESTAMP + T.STRING,
          "parameterized literal (hoisted out of the jit key so "
          "literal-only query twins share compiled programs)")
expr_rule(Alias, T.all_types.nested(), "named expression")
expr_rule(AttributeReference,
          (_common + T.ARRAY + T.STRUCT + T.MAP + T.BINARY).nested(),
          "column reference")
expr_rule(BoundReference,
          (_common + T.ARRAY + T.STRUCT + T.MAP + T.BINARY).nested(),
          "bound column reference")
for c in (ar.Add, ar.Subtract, ar.Multiply, ar.Divide, ar.IntegralDivide,
          ar.Remainder, ar.Pmod, ar.UnaryMinus, ar.UnaryPositive, ar.Abs,
          ar.Greatest, ar.Least):
    expr_rule(c, _num)
for c in (pred.EqualTo, pred.EqualNullSafe, pred.LessThan,
          pred.LessThanOrEqual, pred.GreaterThan, pred.GreaterThanOrEqual,
          pred.In):
    expr_rule(c, _cmp)
for c in (pred.And, pred.Or, pred.Not):
    expr_rule(c, T.BOOLEAN)
for c in (pred.IsNull, pred.IsNotNull, pred.IsNaN):
    expr_rule(c, _common)
for c in (cond.If, cond.CaseWhen, cond.Coalesce, cond.NullIf, cond.Nvl):
    expr_rule(c, _cmp)  # branch-select kernels move the low word only
for c in (mx.Sqrt, mx.Exp, mx.Expm1, mx.Sin, mx.Cos, mx.Tan, mx.Asin,
          mx.Acos, mx.Atan, mx.Sinh, mx.Cosh, mx.Tanh, mx.Cbrt, mx.Rint,
          mx.ToDegrees, mx.ToRadians, mx.Log, mx.Log2, mx.Log10, mx.Log1p,
          mx.Pow, mx.Atan2, mx.Signum, mx.Round, mx.BRound, mx.Floor,
          mx.Ceil, mx.Asinh, mx.Acosh, mx.Atanh, mx.Cot, mx.Logarithm):
    expr_rule(c, _num)

from ..expr import bitwise as bw

for c in (bw.BitwiseAnd, bw.BitwiseOr, bw.BitwiseXor, bw.BitwiseNot,
          bw.ShiftLeft, bw.ShiftRight, bw.ShiftRightUnsigned):
    expr_rule(c, T.integral)


from ..expr import datetime_expr as dte
from ..expr import hashfns as hf
from ..expr import strings as se

for c in (se.Upper, se.Lower, se.Substring, se.Concat, se.Trim, se.TrimLeft,
          se.TrimRight, se.StringReplace, se.StringRepeat, se.Reverse,
          se.StringLPad, se.StringRPad, se.InitCap):
    expr_rule(c, T.STRING)
for c in (se.Length, se.BitLength, se.StringLocate):
    expr_rule(c, T.INT)
for c in (se.Contains, se.StartsWith, se.EndsWith, se.Like):
    expr_rule(c, T.BOOLEAN)
expr_rule(se.Ascii, T.INT)


# host-evaluated string families run inside a CPU-placed operator
# (SURVEY hard-part #3: no regex engine on TPU) — registered with
# per-family reasons so generated docs and explain output state WHY,
# the way the reference documents its incompat/disabled ops
# (ref GpuOverrides.scala:97-100)
def _tag_host_only(reason: str):
    def tag(meta: "ExprMeta", _r=reason):
        meta.will_not_work(_r)
    return tag


from ..expr import json_expr as je
from ..expr import regex as rx

_regex_reason = ("regex evaluation runs on the host engine "
                 "(no TPU regex kernel; ref SURVEY hard-part #3)")
for c in (rx.RLike, rx.RegExpExtract, rx.RegExpReplace, rx.StringSplit):
    expr_rule(c, T.STRING, "host-evaluated regex",
              _tag_host_only(_regex_reason))
expr_rule(se.ConcatWs, T.STRING, "host-evaluated concat_ws",
          _tag_host_only("concat_ws's variadic null/separator semantics "
                         "evaluate on the host engine"))
expr_rule(je.GetJsonObject, T.STRING, "host-evaluated JSON path",
          _tag_host_only("JSON-path evaluation runs on the host engine "
                         "(no TPU JSON parser)"))
expr_rule(hf.Md5, T.STRING, "md5 hex digest (host digest loop)",
          _tag_host_only("md5 digests run on the host engine "
                         "(byte-serial digest)"))
for c in (dte.Year, dte.Month, dte.DayOfMonth, dte.Quarter, dte.DayOfWeek,
          dte.WeekDay, dte.DayOfYear, dte.Hour, dte.Minute, dte.Second,
          dte.DateDiff):
    expr_rule(c, T.INT)
for c in (dte.LastDay, dte.DateAdd, dte.DateSub, dte.AddMonths,
          dte.TruncDate):
    expr_rule(c, T.DATE)
expr_rule(dte.ToUnixTimestamp, T.LONG)
expr_rule(dte.FromUnixTime, T.TIMESTAMP)
expr_rule(dte.TimeAdd, T.TIMESTAMP)
expr_rule(hf.Murmur3Hash, T.INT)
expr_rule(hf.MonotonicallyIncreasingID, T.LONG,
          "(partition << 33) + row position, ref "
          "GpuMonotonicallyIncreasingID.scala")
expr_rule(hf.SparkPartitionID, T.INT, "ref GpuSparkPartitionID.scala")
expr_rule(hf.Rand, T.DOUBLE,
          "uniform [0,1); engine-deterministic but not bit-compatible "
          "with Spark's XORShift sequence (incompat, like the reference)")

from ..expr import collection as coll

# --- registry tail: the remaining reference rules -------------------------
# (ref GpuOverrides.scala:727-3048; each entry either lowers on TPU or is
# registered with an explicit host-fallback reason so explain/docs tell
# the truth about where it runs)
from ..expr import misc_tail as mt
from ..expr import higher_order as ho
from ..expr import window as win
from ..expr.subquery import ScalarSubquery
from ..udf.python_udf import PythonUDF

expr_rule(mt.NaNvl, T.DOUBLE + T.FLOAT)
expr_rule(mt.InSet, T.BOOLEAN)
expr_rule(mt.AtLeastNNonNulls, T.BOOLEAN)
expr_rule(mt.KnownNotNull, T.all_types.nested(), "optimizer marker")
expr_rule(mt.KnownFloatingPointNormalized, T.all_types.nested(),
          "optimizer marker")
expr_rule(mt.PromotePrecision, T.DECIMAL_64 + T.DECIMAL_128,
          "decimal precision marker")
expr_rule(mt.UnscaledValue, T.LONG,
          tag_fn=lambda m: m.will_not_work(
              "unscaledvalue of decimal128 needs both lanes")
          if isinstance(m.expr.children[0].data_type(), t.DecimalType)
          and not m.expr.children[0].data_type().is64 else None)
expr_rule(mt.MakeDecimal, T.DECIMAL_64 + T.DECIMAL_128)
expr_rule(mt.CheckOverflow, T.DECIMAL_64 + T.DECIMAL_128)
expr_rule(mt.PreciseTimestampConversion, T.TIMESTAMP + T.LONG)
expr_rule(hf.InputFileName, T.STRING,
          "current scan file path (forces the PERFILE reader, ref "
          "InputFileBlockRule.scala)",
          _tag_host_only("file-path strings materialize on the host "
                         "engine (task-context metadata, not device "
                         "data)"))
expr_rule(mt.InputFileBlockStart, T.LONG,
          "0 for whole-file PERFILE reads, ref GpuInputFileBlockStart")
expr_rule(mt.InputFileBlockLength, T.LONG,
          "file size for whole-file PERFILE reads")

# window machinery registered as expressions, like the reference
# (GpuOverrides windowing rules); evaluation lives in WindowExec
for c in (win.WindowExpression, win.RowNumber, win.Rank, win.DenseRank,
          win.PercentRank, win.CumeDist, win.NTile):
    expr_rule(c, T.common_scalar.nested())
for c in (win.Lead, win.Lag):
    expr_rule(c, (T.common_scalar + T.STRING).nested())
expr_rule(win.WindowSpec, T.common_scalar.nested(),
          "window spec definition (partition/order/frame; the analog of "
          "WindowSpecDefinition + SpecifiedWindowFrame + SortOrder)")

expr_rule(ScalarSubquery, T.common_scalar,
          "resolved driver-side to a literal before execution")
expr_rule(PythonUDF, T.all_types.nested(),
          "compiled to engine expressions when possible; otherwise "
          "evaluated out-of-process (ArrowEvalPython worker pool)")

expr_rule(coll.MapKeys, T.ARRAY.nested(T.common_scalar))
expr_rule(coll.MapValues, T.ARRAY.nested(T.common_scalar))
expr_rule(coll.MapEntries, T.ARRAY.nested(T.common_scalar + T.STRUCT))
expr_rule(coll.GetMapValue, T.common_scalar,
          tag_fn=lambda m: m.will_not_work(
              "string-keyed map element access needs a literal key "
              "(column-key byte comparison not lowered)")
          if isinstance(m.expr.children[0].data_type().key_type,
                        (t.StringType, t.BinaryType))
          and not isinstance(m.expr.children[1], Literal) else None)
def _tag_create_map(m):
    if any(isinstance(c.data_type(),
                      (t.StringType, t.BinaryType, t.ArrayType,
                       t.StructType, t.MapType))
           for c in m.expr.children):
        m.will_not_work("map() over variable-width children not supported")
        return
    # Spark RAISES on null map keys (and on duplicates under the default
    # EXCEPTION dedup policy); a jitted kernel cannot raise data-dependent
    # errors, so nullable keys stay on the host engine
    for kc in m.expr.children[0::2]:
        if getattr(kc, "nullable", True):
            m.will_not_work(
                "map() with nullable keys stays on CPU (Spark raises on "
                "null keys; device kernels cannot raise data-dependently)")
            return


expr_rule(coll.CreateMap, T.MAP.nested(T.common_scalar),
          "duplicate-key detection follows the host engine",
          _tag_create_map)
expr_rule(coll.ArrayMax, T.common_scalar,
          tag_fn=lambda m: m.will_not_work(
              "array_max/min over nested/string elements not supported")
          if isinstance(m.expr.children[0].data_type().element_type,
                        (t.StringType, t.BinaryType, t.ArrayType,
                         t.StructType, t.MapType)) else None)
expr_rule(coll.ArrayMin, T.common_scalar,
          tag_fn=EXPR_RULES[coll.ArrayMax].tag_fn)
expr_rule(ho.TransformKeys, T.MAP.nested(T.common_scalar))
expr_rule(ho.TransformValues, T.MAP.nested(T.common_scalar))

expr_rule(dte.UnixTimestamp, T.LONG)
expr_rule(dte.DateFormatClass, T.STRING, "host-evaluated date_format",
          _tag_host_only("strftime-style formatting runs on the host "
                         "engine (byte-serial pattern rendering)"))
expr_rule(dte.DateAddInterval, T.DATE, "host-evaluated interval add",
          _tag_host_only("the calendar-interval type is not modeled on "
                         "device; interval arithmetic runs on the host "
                         "engine"))
expr_rule(se.SubstringIndex, T.STRING,
          "single-byte delimiters lower on device",
          tag_fn=lambda m: m.will_not_work(
              "substring_index with a multi-byte or empty delimiter "
              "needs sequential non-overlapping search; host engine")
          if len(m.expr.delim_bytes()) != 1 else None)

expr_rule(coll.Size, T.INT)
expr_rule(coll.ArrayContains, T.BOOLEAN,
          tag_fn=lambda m: m.will_not_work(
              "array_contains over nested/string elements not supported")
          if isinstance(m.expr.children[0].data_type().element_type,
                        (t.StringType, t.BinaryType, t.ArrayType,
                         t.StructType, t.MapType)) else None)
expr_rule(coll.SortArray, T.ARRAY.nested(T.common_scalar),
          tag_fn=lambda m: m.will_not_work(
              "sort_array over nested/string elements not supported")
          if isinstance(m.expr.children[0].data_type().element_type,
                        (t.StringType, t.BinaryType, t.ArrayType,
                         t.StructType, t.MapType)) else None)
from ..expr import complextype as cx
from ..expr import higher_order as ho

_nested_common = (T.common_scalar + T.ARRAY + T.STRUCT + T.MAP +
                  T.BINARY).nested()
expr_rule(cx.GetStructField, _nested_common, "struct field extract")
expr_rule(cx.GetArrayItem, _nested_common, "array index extract")
expr_rule(cx.ElementAt, _nested_common, "element_at")
expr_rule(cx.CreateNamedStruct, T.STRUCT.nested(T.common_scalar),
          "named_struct")


def _tag_create_array(meta: "ExprMeta"):
    et = meta.expr.children[0].data_type() if meta.expr.children else None
    if isinstance(et, (t.StringType, t.BinaryType, t.ArrayType,
                       t.StructType, t.MapType)):
        meta.will_not_work(
            "array() over string/nested elements is not supported on TPU")


expr_rule(cx.CreateArray, T.ARRAY.nested(T.common_scalar), "array()",
          _tag_create_array)


def _tag_higher_order(meta: "ExprMeta"):
    e = meta.expr
    fn = e.fn
    if ho.references_outer_columns(fn.body,
                                   {a.name for a in fn.args}):
        meta.will_not_work(
            "lambda bodies may only reference lambda variables")


expr_rule(ho.LambdaFunction, T.all_types.nested(), "lambda function")
expr_rule(ho.NamedLambdaVariable, T.all_types.nested(), "lambda variable")
expr_rule(ho.ArrayTransform, T.ARRAY.nested(T.common_scalar), "transform",
          _tag_higher_order)
expr_rule(ho.ArrayFilter, T.ARRAY.nested(T.common_scalar), "filter",
          _tag_higher_order)
expr_rule(ho.ArrayExists, T.BOOLEAN, "exists", _tag_higher_order)
expr_rule(ho.ArrayForAll, T.BOOLEAN, "forall", _tag_higher_order)
# regex expressions intentionally have NO rule: no TPU regex engine, the
# operator stays on the CPU engine whose numpy path evaluates them via
# `re` (ref marks regex-dependent ops incompat the same way)

expr_rule(coll.Explode, (T.common_scalar + T.ARRAY + T.STRUCT).nested(),
          "explode generator")
expr_rule(coll.PosExplode, (T.common_scalar + T.ARRAY + T.STRUCT).nested(),
          "posexplode generator")


def _tag_string_literal_needle(meta: "ExprMeta"):
    from ..expr.strings import _literal_bytes
    e = meta.expr
    needle_child = e.children[1] if len(e.children) > 1 else None
    if needle_child is not None and \
            _literal_bytes(needle_child) is None and \
            not isinstance(needle_child, Literal):
        meta.will_not_work(
            f"{type(e).__name__} requires a literal search argument on TPU")


for c in (se.Contains, se.StartsWith, se.EndsWith, se.Like,
          se.StringReplace):
    EXPR_RULES[c].tag_fn = _tag_string_literal_needle


def _tag_cast(meta: "ExprMeta"):
    e = meta.expr
    src = e.child.data_type()
    if not cast_supported_on_tpu(src, e.to):
        meta.will_not_work(
            f"cast from {src.name} to {e.to.name} is not supported on TPU")


expr_rule(Cast, T.all_types, "type cast", _tag_cast)

# aggregate functions.  Sum accepts decimal64 inputs and produces exact
# 128-bit buffers (segment_sum128); Average's final divide is 64-bit so
# decimal averages stay on CPU; Min/Max carry both decimal words through
# the ordered gather so full decimal128 is fine.
expr_rule(agg.Sum, T.numeric)
expr_rule(agg.Average, T.integral + T.FLOAT + T.DOUBLE)
expr_rule(agg.Count, T.all_types)
expr_rule(agg.Min, T.numeric + T.DATE + T.TIMESTAMP + T.BOOLEAN + T.STRING)
expr_rule(agg.Max, T.numeric + T.DATE + T.TIMESTAMP + T.BOOLEAN + T.STRING)
expr_rule(agg.First, _common)
expr_rule(agg.Last, _common)
# collect over flat types: element ordering inside the collected array is
# sorted-row order (list) / value order (set), ref GpuCollectList/Set
_collect_elem = T.numeric + T.BOOLEAN + T.DATE + T.TIMESTAMP + T.STRING
expr_rule(agg.CollectList, (_collect_elem + T.ARRAY).nested(_collect_elem))
expr_rule(agg.CollectSet, (_collect_elem + T.ARRAY).nested(_collect_elem))
for c in (agg.StddevPop, agg.StddevSamp, agg.VariancePop, agg.VarianceSamp):
    expr_rule(c, _num)
# pivot_first: first value where the pivot column matches; the mask fuses
# into the update expression (ref GpuPivotFirst, GpuOverrides.scala:2034)
expr_rule(agg.PivotFirst, _common,
          "pivot aggregate (one instance per pivot value)")
expr_rule(agg.ApproximatePercentile, T.numeric64,
          "exact inverted-CDF percentile over collected groups "
          "(decimal128 would drop the high word in the rank gather)")
expr_rule(agg.AggregateExpression, T.all_types.nested())


def _tag_time_window(meta: "ExprMeta"):
    if not meta.expr.is_tumbling and meta.expr.copy_index is None:
        # lowered per-slide copies (copy_index set) are plain elementwise
        # math and run on TPU; only a bare un-lowered sliding window is
        # unsupported
        meta.will_not_work(
            "bare sliding time windows need the Expand lowering "
            "(window() through select/groupBy lowers automatically)")


from ..expr.datetime_expr import TimeWindow as _TimeWindow
from ..expr.mathexpr import NormalizeNaNAndZero as _NormNaN

expr_rule(_TimeWindow, T.STRUCT.nested(T.TIMESTAMP),
          "tumbling time window bucketing", _tag_time_window)
expr_rule(_NormNaN, T.FLOAT + T.DOUBLE,
          "canonicalize NaN/-0.0 for grouping and join keys")

# columnar native UDFs trace straight into the operator's XLA computation
# (ref GpuUserDefinedFunction + RapidsUDF.evaluateColumnar)
from ..udf.native import NativeUDFExpression

expr_rule(NativeUDFExpression, T.common_scalar + T.BINARY,
          "user-supplied columnar UDF")
# opaque PythonUDF has no rule: it keeps its operator on the CPU unless the
# planner extracted it into ArrowEvalPythonExec (ref GpuOverrides fallback)


# ---------------------------------------------------------------------------
# Meta hierarchy (ref RapidsMeta.scala)
# ---------------------------------------------------------------------------

class BaseMeta:
    def __init__(self, conf: cfg.RapidsConf):
        self.conf = conf
        self.reasons: List[str] = []

    def will_not_work(self, reason: str):
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_replace(self) -> bool:
        return not self.reasons


class ExprMeta(BaseMeta):
    """Wraps one expression node (ref BaseExprMeta, RapidsMeta.scala:911)."""

    def __init__(self, expr: Expression, conf, input_names, input_types):
        super().__init__(conf)
        self.expr = expr
        self.input_names = input_names
        self.input_types = input_types
        self.children = [ExprMeta(c, conf, input_names, input_types)
                         for c in expr.children]
        if isinstance(expr, agg.AggregateExpression):
            self.children = [ExprMeta(expr.func, conf, input_names,
                                      input_types)]
        if isinstance(expr, ho.ArrayHigherOrder):
            # retype the lambda variables from the (bound) array element
            # type so the body's meta tree type-checks
            try:
                bound = bind_expression(expr, input_names, input_types)
                self.children = [
                    ExprMeta(bound.arr, conf, input_names, input_types),
                    ExprMeta(bound._bind_lambda(), conf, input_names,
                             input_types)]
            except Exception:  # tpulint: allow[TPU-R011] tag() on the
                # unbound tree reports the bind failure as a
                # will-not-work reason — the sanctioned sink, one
                # phase later
                pass

    def tag(self):
        rule = EXPR_RULES.get(type(self.expr))
        if rule is None:
            self.will_not_work(
                f"expression {type(self.expr).__name__} is not supported on TPU")
        else:
            if not self.conf.is_op_enabled("expression",
                                           type(self.expr).__name__):
                self.will_not_work(
                    f"expression {type(self.expr).__name__} has been disabled")
            try:
                bound = bind_expression(self.expr, self.input_names,
                                        self.input_types)
                dt = bound.data_type()
                if not isinstance(dt, t.NullType) and \
                        not rule.sig.is_supported(dt):
                    for r in rule.sig.reasons_not_supported(dt):
                        self.will_not_work(
                            f"{type(self.expr).__name__} produces "
                            f"unsupported type: {r}")
            except Exception as ex:  # unresolvable -> cannot place
                self.will_not_work(
                    f"{type(self.expr).__name__}: {ex}")
            if rule.tag_fn is not None and not self.reasons:
                try:
                    bound = bind_expression(self.expr, self.input_names,
                                            self.input_types)
                    m2 = ExprMeta.__new__(ExprMeta)
                    m2.__dict__.update(self.__dict__)
                    m2.expr = bound
                    m2.reasons = self.reasons
                    rule.tag_fn(m2)
                except Exception as ex:
                    self.will_not_work(str(ex))
        for c in self.children:
            c.tag()

    @property
    def can_replace_tree(self) -> bool:
        return self.can_replace and all(c.can_replace_tree
                                        for c in self.children)

    def all_reasons(self) -> List[str]:
        out = list(self.reasons)
        for c in self.children:
            out += c.all_reasons()
        return out


class ExecMeta(BaseMeta):
    """Wraps one physical operator (ref SparkPlanMeta, RapidsMeta.scala:543)."""

    def __init__(self, exec_node: eb.Exec, conf):
        super().__init__(conf)
        self.exec = exec_node
        self.children = [ExecMeta(c, conf) for c in exec_node.children]

    # schema feeding this node's expressions
    def _input_schema(self):
        if self.exec.children:
            c = self.exec.children[0]
            return c.output_names, c.output_types
        return [], []

    def expressions(self) -> List[Expression]:
        e = self.exec
        if isinstance(e, ProjectExec):
            return list(e.exprs)
        if isinstance(e, FilterExec):
            return [e.condition]
        if isinstance(e, (CpuHashAggregateExec,)):
            return list(e.grouping) + list(e.aggregates)
        from ..exec.sort import SortExec as _SE
        if isinstance(e, _SE):
            return [o[0] for o in e.orders]
        from ..exec.expand import ExpandExec as _XE
        from ..exec.expand import GenerateExec as _GE
        if isinstance(e, _XE):
            return [x for proj in e.projections for x in proj]
        if isinstance(e, _GE):
            return [e.generator]
        return []

    def tag(self):
        e = self.exec
        name = type(e).__name__
        if getattr(e, "deliberate_cpu", False):
            # python-exchange operators run on CPU by design (the data
            # crosses into Python either way) — not an acceleration gap
            self.will_not_work(
                f"{name} runs on CPU by design (python data exchange)")
            for c in self.children:
                c.tag()
            self.expr_metas = []
            return
        if not self.conf.is_op_enabled("exec", name):
            self.will_not_work(f"{name} has been disabled by config")
        rule_sig = EXEC_SIGS.get(type(e))
        if rule_sig is None:
            self.will_not_work(f"{name} has no TPU implementation")
        else:
            for n, dt in zip(e.output_names, e.output_types):
                if isinstance(dt, t.NullType):
                    continue
                if not rule_sig.is_supported(dt):
                    for r in rule_sig.reasons_not_supported(dt):
                        self.will_not_work(f"output column {n}: {r}")
        names, dtypes = self._input_schema()
        self.expr_metas = [ExprMeta(x, self.conf, names, dtypes)
                           for x in self.expressions()]
        for em in self.expr_metas:
            em.tag()
            if not em.can_replace_tree:
                for r in em.all_reasons():
                    self.will_not_work(r)
        custom = EXEC_TAGS.get(type(e))
        if custom:
            custom(self)
        for c in self.children:
            c.tag()

    # ---- conversion -------------------------------------------------------
    def convert(self) -> eb.Exec:
        new_children = [c.convert() for c in self.children]
        e = self.exec.with_new_children(new_children)
        if not self.can_replace or not self.conf.sql_enabled:
            return e
        conv = EXEC_CONVERTS.get(type(e))
        if conv is not None:
            return conv(e, self.conf)
        import copy
        e.placement = eb.TPU
        return e

    # ---- explain ----------------------------------------------------------
    def explain_lines(self, level=0) -> List[str]:
        pad = "  " * level
        name = type(self.exec).__name__
        if self.can_replace:
            lines = [f"{pad}*Exec <{name}> will run on TPU"]
        else:
            lines = [f"{pad}!Exec <{name}> cannot run on TPU because "
                     + "; ".join(self.reasons[:4])]
        for c in self.children:
            lines += c.explain_lines(level + 1)
        return lines


# exec output-type signatures (ref ExecChecks, TypeChecks.scala:886)
_exec_common = (T.common_scalar + T.ARRAY + T.STRUCT + T.MAP + T.BINARY).nested()
EXEC_SIGS: Dict[Type[eb.Exec], TypeSig] = {
    LocalScanExec: _exec_common,
    RangeExec: T.LONG,
    ProjectExec: _exec_common,
    FilterExec: _exec_common,
    UnionExec: _exec_common,
    LocalLimitExec: _exec_common,
    GlobalLimitExec: _exec_common,
    CoalesceBatchesExec: _exec_common,
    GatherPartitionsExec: _exec_common,
    # struct keys group fine: key_words_for_column recurses children
    # (time-window bucketing groups by struct<start,end>)
    CpuHashAggregateExec: (T.common_scalar + T.ARRAY + T.STRUCT).nested(
        T.common_scalar),
}

from ..exec.broadcast import (BroadcastExchangeExec, BroadcastHashJoinExec,
                              BroadcastNestedLoopJoinExec)
from ..exec.join import (CpuJoinExec, HashJoinExec, NestedLoopJoinExec,
                         ShuffledHashJoinExec)
from ..exec.sort import SortExec

EXEC_SIGS[SortExec] = T.common_scalar.nested()
EXEC_SIGS[CpuJoinExec] = _exec_common
EXEC_SIGS[NestedLoopJoinExec] = _exec_common
EXEC_SIGS[HashJoinExec] = _exec_common
EXEC_SIGS[ShuffledHashJoinExec] = _exec_common
EXEC_SIGS[BroadcastExchangeExec] = _exec_common
EXEC_SIGS[BroadcastHashJoinExec] = _exec_common
EXEC_SIGS[BroadcastNestedLoopJoinExec] = _exec_common

EXEC_TAGS: Dict[Type[eb.Exec], Callable] = {}
EXEC_CONVERTS: Dict[Type[eb.Exec], Callable] = {}


def _fuse_single_chip(conf: cfg.RapidsConf) -> bool:
    """Collapse exchanges when this process drives exactly one chip.

    An N-partition exchange on a single device runs N per-partition
    programs SERIALLY — N dispatch/sync floors buying parallelism that
    does not exist (the multi-chip mesh path, parallel/ici_exec.py, is
    where partitions buy real concurrency).  Absorbing the exchange into
    its consumer turns the stage into ONE fused program, the single-chip
    mirror of the ICI stage fusion."""
    mode = conf.get(cfg.SINGLE_CHIP_FUSE)
    if mode == "off":
        return False
    if mode == "on":
        return True
    import jax
    return len(jax.devices()) == 1


def _strip_exchange(exchange: eb.Exec, coalesce: bool = False) -> eb.Exec:
    """Replace an exchange with a partition gather (+ optional device-side
    batch coalesce so streaming consumers see ONE batch instead of one
    per source partition — each probe batch costs its own sync)."""
    src = exchange.children[0]
    node = src
    if src.num_partitions > 1:
        node = GatherPartitionsExec(src)
        node.placement = src.placement
    if coalesce:
        node = CoalesceBatchesExec(node)
        node.placement = src.placement
    return node


def _convert_join(e: "CpuJoinExec", conf) -> eb.Exec:
    left, right = e.children
    colocated = getattr(e, "colocated", False)
    if _fuse_single_chip(conf):
        if colocated and \
                all(isinstance(c, ShuffleExchangeExec) for c in e.children):
            # shuffled hash join on one chip: the exchanges exist only to
            # co-locate keys, which a single chip already is — drop both
            # and run ONE count/sync/expand instead of one per partition
            left = _strip_exchange(left, coalesce=True)   # probe streams
            right = _strip_exchange(right)                # build concats
            colocated = False
        elif left.num_partitions > 1 and not colocated:
            # broadcast/plain join with a multi-partition probe: each
            # probe batch pays its own count->sync->expand round; one
            # chip gains nothing from the split, so funnel the probe
            # into a single device batch first
            g = GatherPartitionsExec(left)
            g.placement = left.placement
            left = CoalesceBatchesExec(g)
            left.placement = g.placement
    if isinstance(right, BroadcastExchangeExec):
        cls = BroadcastHashJoinExec
    elif colocated:
        # both sides hash-exchanged on the keys: the co-partitioned
        # spill-backed path (build = one catalog shard, not the table)
        cls = ShuffledHashJoinExec
    else:
        cls = HashJoinExec
    j = cls(e.left_keys, e.right_keys, e.how, e.condition,
            left, right, colocated=colocated)
    j.placement = eb.TPU
    return j


def _tag_join(meta: "ExecMeta"):
    e: CpuJoinExec = meta.exec
    if e.condition is not None and e.how not in ("inner", "left"):
        # inner post-filters; left repairs unmatched probe rows in the
        # expand kernel (right arrives pre-flipped to left)
        meta.will_not_work(
            f"conditional {e.how} join is not supported on TPU")
    # key types must be hash/equality-capable
    l, r = e.children
    for k in e.left_keys + e.right_keys:
        names = l.output_names + r.output_names
        dtypes = l.output_types + r.output_types
        try:
            b = bind_expression(k, l.output_names, l.output_types)
        except Exception:
            try:
                b = bind_expression(k, r.output_names, r.output_types)
            except Exception as ex:
                meta.will_not_work(str(ex))
                continue
        dt = b.data_type()
        if not (T.comparable + T.STRUCT).is_supported(dt):
            meta.will_not_work(f"join key type {dt.name} not supported")
    # payload sizing: the join size pass computes top-level child-row /
    # char totals for span columns, but a varlen type nested INSIDE
    # another type (array<string>, map<_, string>, struct<string> — the
    # struct gather branch forwards no char cap either) still defaults
    # its inner buffer to the source capacity — a duplicating gather
    # would silently truncate it, so those payloads stay on CPU until
    # the size pass learns to walk nested spans
    def nested_varlen(dt: t.DataType) -> bool:
        if isinstance(dt, t.ArrayType):
            return _has_varlen(dt.element_type)
        if isinstance(dt, t.MapType):
            return _has_varlen(dt.key_type) or _has_varlen(dt.value_type)
        if isinstance(dt, t.StructType):
            return any(_has_varlen(f.data_type) for f in dt.fields)
        return False

    def _has_varlen(dt: t.DataType) -> bool:
        if isinstance(dt, (t.StringType, t.BinaryType,
                           t.ArrayType, t.MapType)):
            return True
        if isinstance(dt, t.StructType):
            return any(_has_varlen(f.data_type) for f in dt.fields)
        return False

    for side in e.children:
        for dt in side.output_types:
            if nested_varlen(dt):
                meta.will_not_work(
                    f"join payload type {dt.name} (varlen nested in "
                    f"varlen) not sized for duplicating gathers")


def _convert_aggregate(e: CpuHashAggregateExec, conf) -> eb.Exec:
    """Replace the complete-mode CPU aggregate with a TPU Partial/Final
    pair (ref aggregate.scala partial/final mode pipeline).  When the
    planner put an exchange below the aggregate, the partial half moves
    BELOW the exchange (Spark's partial-aggregation pushdown) so only
    pre-aggregated groups cross the wire."""
    child = e.children[0]
    if isinstance(child, ShuffleExchangeExec):
        if _fuse_single_chip(conf):
            # one chip: partial-agg pushdown shrinks a wire that does not
            # exist; a single fused Complete program over the gathered
            # input replaces partial x N -> exchange -> final x N
            return TpuHashAggregateExec(
                e.grouping, e.aggregates, agg.COMPLETE,
                _strip_exchange(child, coalesce=True))
        from ..shuffle.partitioning import HashPartitioning
        source = child.children[0]
        partial = TpuHashAggregateExec(e.grouping, e.aggregates,
                                       agg.PARTIAL, source)
        part = HashPartitioning(
            [AttributeReference(n) for n in partial.output_names[
                :len(e.grouping)]],
            child.partitioning.num_partitions)
        exchange = ShuffleExchangeExec(part, partial)
        exchange.placement = eb.TPU
        final = TpuHashAggregateExec(e.grouping, partial.aggregates,
                                     agg.FINAL, exchange)
        return final
    # no exchange below: groups are already co-located, so a single
    # Complete-mode aggregate (update+evaluate, merge only for multi-batch
    # inputs) replaces the Partial/Final pair — one compiled program and
    # one device pass instead of two (Spark collapses the same way when
    # partial aggregation cannot help)
    return TpuHashAggregateExec(e.grouping, e.aggregates, agg.COMPLETE,
                                child)


EXEC_CONVERTS[CpuHashAggregateExec] = _convert_aggregate
EXEC_CONVERTS[CpuJoinExec] = _convert_join
EXEC_TAGS[CpuJoinExec] = _tag_join

from ..exec.window import WindowExec  # noqa: E402
from ..shuffle.exchange import ShuffleExchangeExec  # noqa: E402

EXEC_SIGS[WindowExec] = T.common_scalar.nested()
EXEC_SIGS[ShuffleExchangeExec] = _exec_common


def _convert_window(e: WindowExec, conf) -> eb.Exec:
    child = e.children[0]
    if _fuse_single_chip(conf) and isinstance(child, ShuffleExchangeExec):
        # window partitions need co-location only; one chip has it —
        # WindowExec concats its input and carry-sorts by (pkeys, okeys)
        e = WindowExec(e.window_exprs, _strip_exchange(child))
    e.placement = eb.TPU
    return e


def _convert_sort(e: SortExec, conf) -> eb.Exec:
    child = e.children[0]
    if e.is_global and _fuse_single_chip(conf) and \
            isinstance(child, ShuffleExchangeExec):
        # range exchange orders ranges ACROSS partitions; a single chip
        # sorts the gathered whole in one program instead
        e = SortExec(e.orders, _strip_exchange(child), is_global=True)
    e.placement = eb.TPU
    return e


EXEC_CONVERTS[WindowExec] = _convert_window
EXEC_CONVERTS[SortExec] = _convert_sort

from ..io.scan import FileScanExec  # noqa: E402

EXEC_SIGS[FileScanExec] = _exec_common

from ..exec.basic import SampleExec  # noqa: E402
from ..exec.expand import ExpandExec, GenerateExec  # noqa: E402

EXEC_SIGS[SampleExec] = _exec_common
EXEC_SIGS[ExpandExec] = _exec_common
EXEC_SIGS[GenerateExec] = _exec_common

from ..io.cached_batch import CachedScanExec, CacheWriteExec  # noqa: E402

EXEC_SIGS[CachedScanExec] = _exec_common
EXEC_SIGS[CacheWriteExec] = _exec_common


def _tag_file_scan(meta: "ExecMeta"):
    from .. import config as cfg
    e: FileScanExec = meta.exec
    key = {"parquet": cfg.PARQUET_ENABLED, "orc": cfg.ORC_ENABLED,
           "csv": cfg.CSV_ENABLED}.get(e.fmt)
    if key is not None and not meta.conf.get(key):
        meta.will_not_work(f"{e.fmt} scan disabled by config")


EXEC_TAGS[FileScanExec] = _tag_file_scan


def _tag_window(meta: ExecMeta):
    from ..expr import window as W
    from ..expr.aggregates import (AggregateFunction, Average, Count, First,
                                   Last, Max, Min, Sum)
    e: WindowExec = meta.exec
    cn = e.children[0].output_names
    ct = e.children[0].output_types
    for w in e.window_exprs:
        f = w.func
        if isinstance(f, AggregateFunction):
            if not isinstance(f, (Sum, Count, Average, Min, Max, First,
                                  Last)):
                meta.will_not_work(
                    f"window aggregate {type(f).__name__} not supported")
            kind, lo, hi = w.spec.effective_frame(False)
            bounded = not (lo == W.UNBOUNDED_PRECEDING and
                           hi in (W.CURRENT_ROW, W.UNBOUNDED_FOLLOWING))
            if kind == "range" and bounded:
                # bounded range frames need exactly one ascending flat
                # numeric/date/timestamp order key (binary-search bounds)
                orders = w.spec.order_by
                ok = len(orders) == 1 and orders[0][1]
                if ok:
                    try:
                        dt = bind_expression(orders[0][0], cn,
                                             ct).data_type()
                        ok = (t.is_numeric(dt) and not
                              isinstance(dt, t.DecimalType)) or \
                            isinstance(dt, (t.DateType, t.TimestampType))
                    except Exception:  # tpulint: allow[TPU-R011] the
                        # ok=False flag routes into the will_not_work
                        # call right below — reported, not swallowed
                        ok = False
                if not ok:
                    meta.will_not_work(
                        "bounded range frames need a single ascending "
                        "numeric/date/timestamp order key")
        elif not isinstance(f, (W.RowNumber, W.Rank, W.DenseRank, W.Lead,
                                W.Lag, W.NTile)):
            meta.will_not_work(
                f"window function {type(f).__name__} not supported")


EXEC_TAGS[WindowExec] = _tag_window


def _tag_aggregate(meta: ExecMeta):
    e: CpuHashAggregateExec = meta.exec
    cn, ct = e.children[0].output_names, e.children[0].output_types
    for ae in e.aggregates:
        fn = ae.func
        rule = EXPR_RULES.get(type(fn))
        if rule is None:
            meta.will_not_work(
                f"aggregate {type(fn).__name__} is not supported on TPU")
            continue
        if fn.children:
            try:
                b = bind_expression(fn.child, cn, ct)
                dt = b.data_type()
                if not rule.sig.is_supported(dt):
                    for r in rule.sig.reasons_not_supported(dt):
                        meta.will_not_work(
                            f"{type(fn).__name__} over unsupported input: {r}")
                if isinstance(fn, agg.Sum) and \
                        isinstance(dt, t.DecimalType) and not dt.is64:
                    # the update-stage cast reads the decimal low word; a
                    # >18-digit input would lose its high word before the
                    # exact 128-bit buffer accumulation starts
                    meta.will_not_work(
                        "sum over decimal(>18) inputs runs on CPU")
            except Exception as ex:
                meta.will_not_work(str(ex))


EXEC_TAGS[CpuHashAggregateExec] = _tag_aggregate


# ---------------------------------------------------------------------------
# Transitions (ref GpuTransitionOverrides)
# ---------------------------------------------------------------------------

def insert_transitions(root: eb.Exec) -> eb.Exec:
    def fix(node: eb.Exec) -> eb.Exec:
        new_children = []
        for c in node.children:
            c = fix(c)
            if node.placement == eb.TPU and c.placement == eb.CPU and \
                    not isinstance(c, eb.DeviceToHostExec):
                c = eb.HostToDeviceExec(c)
            elif node.placement == eb.CPU and c.placement == eb.TPU:
                c = eb.DeviceToHostExec(c)
            new_children.append(c)
        if new_children or node.children:
            node = node.with_new_children(new_children)
        return node

    root = fix(root)
    # fix() clones every node, and the num_partitions probe below can
    # EXECUTE the plan (an AQE reader materializes its map stage to size
    # its specs) — so replicated build readers must be re-pointed at the
    # cloned probe partner HERE, not only after insert_transitions
    # returns, or the stale partner shuffles the probe side a second
    # time and leaks every block it writes.
    from ..shuffle.aqe import relink_replicated_readers
    root = relink_replicated_readers(root)
    if root.placement == eb.TPU:
        # collect boundary: funnel every partition's device batches into
        # ONE device-side concat before crossing to host — each fetch
        # costs two tunnel round trips, so a 4-partition result fetched
        # per-batch pays 8 syncs where one coalesced batch pays 2 (the
        # coalesce-before-transition role of GpuCoalesceBatches)
        if root.num_partitions > 1:
            root = GatherPartitionsExec(root)
            root.placement = eb.TPU
        # NOT require_single_batch: a result bigger than the coalesce
        # target streams in bounded chunks instead of materializing one
        # giant device batch (device-OOM guard for huge collects)
        coal = CoalesceBatchesExec(root)
        coal.placement = eb.TPU
        root = eb.DeviceToHostExec(coal)
    # fuse DeviceToHost(HostToDevice(x)) -> x
    def fuse(node: eb.Exec) -> eb.Exec:
        if isinstance(node, eb.HostToDeviceExec) and \
                isinstance(node.children[0], eb.DeviceToHostExec):
            return node.children[0].children[0]
        if isinstance(node, eb.DeviceToHostExec) and \
                isinstance(node.children[0], eb.HostToDeviceExec):
            return node.children[0].children[0]
        return node
    return root.transform_up(fuse)


class TpuOverrides:
    """Entry point (ref GpuOverrides.apply, ColumnarOverrideRules)."""

    def __init__(self, conf: cfg.RapidsConf):
        self.conf = conf
        self.last_explain = ""
        self.last_lint = []

    def apply(self, plan: eb.Exec) -> eb.Exec:
        # external override providers contribute rules lazily (the
        # GpuHiveOverrides hook, ref GpuOverrides.scala:53)
        from .extensions import load_extension_rules
        load_extension_rules()
        if not self.conf.sql_enabled:
            self.last_explain = "(TPU acceleration disabled)"
            return plan
        meta = ExecMeta(plan, self.conf)
        meta.tag()
        if self.conf.get(cfg.OPTIMIZER_ENABLED):
            from .cost import CostBasedOptimizer
            CostBasedOptimizer(self.conf).optimize(meta)
        explain_mode = self.conf.explain
        lines = meta.explain_lines()
        self.last_explain = "\n".join(lines)
        if explain_mode == "ALL":
            print(self.last_explain)
        elif explain_mode == "NOT_ON_GPU":
            bad = [l for l in lines if l.lstrip().startswith("!")]
            if bad:
                print("\n".join(bad))
        converted = meta.convert()
        from ..parallel.ici_exec import install_ici_stages
        converted = install_ici_stages(converted, self.conf)
        if self.conf.get(cfg.LINT_ENABLED):
            # opt-in pre-flight: hazards the rewrite engine admitted but
            # the runtime would crash on (or quietly serve wrong/slow)
            # become structured diagnostics, and the subtrees with a
            # sound host fallback are downgraded instead of executed.
            # The lint runs flow-sensitively (spark.rapids.tpu.lint.infer,
            # on by default): the abstract interpreter's per-subtree
            # states decide the contract rules, so the downgrade set
            # includes violations only dataflow can see (TPU-L011 —
            # a contract broken BETWEEN its exchange and its consumer).
            from ..analysis.plan_lint import downgrade_hazards, lint_plan
            self.last_lint = lint_plan(converted, self.conf)
            if self.last_lint:
                converted = downgrade_hazards(converted, self.last_lint,
                                              self.conf)
                from ..analysis.diagnostics import format_diagnostics
                lint_text = "tpulint:\n" + \
                    format_diagnostics(self.last_lint)
                self.last_explain += "\n" + lint_text
                if explain_mode != "NONE":
                    print(lint_text, end="")
        from ..shuffle.aqe import (install_aqe_readers,
                                   relink_replicated_readers)
        converted = install_aqe_readers(converted, self.conf)
        # transition insertion clones nodes, so this must run LAST or a
        # replicated build reader keeps a stale pre-clone partner (which
        # re-shuffles the probe side and leaks the blocks)
        return relink_replicated_readers(insert_transitions(converted))
