"""Collect-side transfer elision for order-preserving plans.

A global sort of a host-resident source computes a PERMUTATION: the
result's bytes already exist on the host, only the row order is new.
Fetching the full sorted payload re-moves every byte over the
bandwidth-bound interconnect; fetching just the device-computed row
index (one integer lane, range-narrowed by the fetch plan) and applying
`take` on the host copy moves ~4 bytes/row instead of the whole row —
the collect-side sibling of the write path's keep-mask elision
(io/writer.py), playing the role GDS plays for the reference: bytes
that already sit in the right memory never cross the wire.

Scope: Sort (global) over optional Filter / attribute-only Project
chains over an in-memory LocalRelation.  Small results skip the rewrite
(below _MIN_ROWS the fetch fits one transfer anyway, and the device
path keeps full end-to-end coverage in tests).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

# below this, payload < latency: the rewrite cannot win and small-data
# tests keep exercising the real device fetch path
_MIN_ROWS = 1 << 16

_RID = "__rid__"


def try_host_assisted_collect(session, lp) -> Optional[pa.Table]:
    """Return the collect result via host take, or None when the plan is
    not a pure row-permutation of a host-resident source."""
    from .. import config as cfg
    from ..plan import logical as L

    if not (session.conf.sql_enabled and
            session.conf.get(cfg.HOST_ASSISTED_COLLECT)):
        return None
    if not isinstance(lp, L.Sort) or not lp.is_global:
        return None
    from ..expr.core import Alias, AttributeReference

    filters = []
    node = lp.children[0]
    while True:
        if isinstance(node, L.Project):
            if not all(isinstance(e, AttributeReference)
                       for e in node.exprs):
                return None
            node = node.children[0]
        elif isinstance(node, L.Filter):
            filters.append(node.condition)
            node = node.children[0]
        elif isinstance(node, L.LocalRelation):
            break
        else:
            return None
    host = node.table
    if host.num_rows < _MIN_ROWS:
        return None

    # device plan: carry a row id through the filters and the sort, and
    # fetch ONLY it (the fetch plan narrows its value range).  Only the
    # columns the filters/sort keys read ride along — payload columns
    # would bloat the sort's carry lanes (and its compile) for nothing.
    from ..expr.hashfns import MonotonicallyIncreasingID
    needed = []
    for e in [c for c in filters] + [o[0] for o in lp.orders]:
        for a in e.collect(lambda x: isinstance(x, AttributeReference)):
            if a.name not in needed:
                needed.append(a.name)
    rid_plan: L.LogicalPlan = L.Project(
        [AttributeReference(n) for n in host.schema.names
         if n in needed]
        + [Alias(MonotonicallyIncreasingID(), _RID)], node)
    for cond in reversed(filters):
        rid_plan = L.Filter(cond, rid_plan)
    rid_plan = L.Sort(lp.orders, True, rid_plan)
    rid_plan = L.Project([AttributeReference(_RID)], rid_plan)
    # the rid plan needs only the PERMUTATION — the compile-lean sort
    # (iterated 2-operand passes, ops/carry.py) computes exactly that
    # without lowering a many-operand carry-sort (minutes of compile for
    # a shape used by nothing else)
    from ..ops.carry import compile_lean_enabled, set_compile_lean
    prev = compile_lean_enabled()
    set_compile_lean(True)
    try:
        rid = session.execute(rid_plan).column(_RID).to_numpy()
    finally:
        set_compile_lean(prev)

    # (partition << 33) + offset -> global row index; LocalScanExec
    # slices the table into ceil(n/p)-row partitions in order
    n_parts = max(1, node.num_partitions)
    per = -(-host.num_rows // n_parts)
    idx = (rid >> 33) * per + (rid & ((np.int64(1) << 33) - 1))
    out = host.combine_chunks().take(idx)
    names = lp.schema()[0]
    if list(out.schema.names) != names:
        out = out.select(names)
    return out
