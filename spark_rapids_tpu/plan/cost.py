"""Cost-based optimizer: the optional second pass that can move subtrees
back to the CPU when acceleration would not pay for its transitions.

Ref: CostBasedOptimizer.scala:1-528 (invoked from GpuOverrides.scala:
3512-3524).  The reference walks plan "sections" comparing per-row
GPU/CPU operator costs plus row<->columnar transition costs.  Here the
same inputs feed an exact two-state dynamic program over the meta tree:

  best_tpu(n) = tpu_cost(n) + sum_c min(best_tpu(c), best_cpu(c) + h2d(c))
  best_cpu(n) = cpu_cost(n) + sum_c min(best_cpu(c), best_tpu(c) + d2h(c))

(best_tpu = inf where tagging already rejected the node).  Backtracking
marks every CPU-chosen node with "removed by cost-based optimizer",
exactly the reason string consumers of the reference see.

Per-operator costs are tunable the same way as the reference's
(`spark.rapids.sql.optimizer.{cpu,tpu}.exec.<ExecName>` keys override the
defaults), and row counts flow from scan statistics through per-operator
cardinality factors.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

from .. import config as cfg
from ..exec import base as eb

# default per-row operator costs (arbitrary units; only ratios matter).
# TPU ops are cheaper per row but transitions cost extra — the same shape
# as the reference's defaults (CostBasedOptimizer.scala DEFAULT_*).
DEFAULT_CPU_OP_COST = 1.0
DEFAULT_TPU_OP_COST = 0.25
# host<->device transition per-row costs (ref
# spark.rapids.sql.optimizer.cpu.exec.ColumnarToRowExec analog)
DEFAULT_H2D_COST = 0.4
DEFAULT_D2H_COST = 0.4
# rows assumed when no statistics are available
DEFAULT_ROW_COUNT = 1_000_000


_CARDINALITY = {
    # output rows as a factor of input rows (first child)
    "FilterExec": 0.5,
    "CpuHashAggregateExec": 0.2,
    "TpuHashAggregateExec": 0.2,
    "ExpandExec": 2.0,
    "GenerateExec": 4.0,
    "SampleExec": 0.1,
}


def estimate_rows(node: eb.Exec, child_rows: List[float]) -> float:
    """Output-row estimate for one operator given its children's
    estimates — the single row model shared by the cost-based optimizer
    and the flow-sensitive plan typechecker (analysis/interp.py), so
    admission decisions and CBO placement reason from the same numbers.

    With ``spark.rapids.tpu.feedback.enabled`` the estimator ledger
    (obs/estimator.py) confidence-weight-blends the recorded mean
    actual for this node's (exec kind, input signature) into the
    static estimate — every consumer of this function (CBO, the
    L010/L012 byte estimates, the L014 bound, admission tickets)
    sharpens from the same feedback."""
    static = _static_rows(node, child_rows)
    try:
        from ..obs.estimator import EstimatorLedger
        blended = EstimatorLedger.get().blend_rows(node, static)
    except Exception:
        blended = None
    return static if blended is None else max(blended, 0.0)


def _static_rows(node: eb.Exec, child_rows: List[float]) -> float:
    """The pure static row model (no feedback) — what a cold planner
    uses, and what the blend anchors its (1-w) share to."""
    name = type(node).__name__
    from ..exec.basic import GlobalLimitExec, LocalLimitExec, LocalScanExec, RangeExec
    if isinstance(node, LocalScanExec):
        return float(node.table.num_rows)
    if isinstance(node, RangeExec):
        return max(1.0, abs(node.end - node.start) / abs(node.step))
    from ..io.scan import FileScanExec
    if isinstance(node, FileScanExec):
        try:
            import os
            size = sum(os.path.getsize(p) for p in node.paths)
            return max(size / 100.0, 1.0)  # ~100 compressed bytes/row
        except OSError:
            return float(DEFAULT_ROW_COUNT)
    if isinstance(node, (LocalLimitExec, GlobalLimitExec)):
        n = float(node.limit)
        return min(n, child_rows[0]) if child_rows else n
    if not child_rows:
        return float(DEFAULT_ROW_COUNT)
    if name in ("UnionExec",):
        return sum(child_rows)
    if name in ("HashJoinExec", "ShuffledHashJoinExec", "CpuJoinExec",
                "BroadcastHashJoinExec", "NestedLoopJoinExec",
                "BroadcastNestedLoopJoinExec"):
        return max(child_rows)
    return child_rows[0] * _CARDINALITY.get(name, 1.0)


class CostBasedOptimizer:
    def __init__(self, conf: cfg.RapidsConf):
        self.conf = conf
        self.explain_lines: List[str] = []

    # -- inputs -------------------------------------------------------------
    def _op_cost(self, side: str, name: str, default: float) -> float:
        raw = self.conf.raw(f"spark.rapids.sql.optimizer.{side}.exec.{name}")
        return float(raw) if raw is not None else default

    def _rows(self, node: eb.Exec, child_rows: List[float]) -> float:
        return estimate_rows(node, child_rows)

    # -- the DP -------------------------------------------------------------
    def optimize(self, meta) -> int:
        """Tags CPU-cheaper nodes on the meta tree; returns #nodes moved."""
        plans: Dict[int, Tuple] = {}

        def walk(m) -> Tuple[float, float, float]:
            """returns (rows, best_cpu, best_tpu) for the subtree."""
            child_states = [walk(c) for c in m.children]
            rows = self._rows(m.exec, [s[0] for s in child_states])
            name = type(m.exec).__name__
            cpu_op = self._op_cost("cpu", name, DEFAULT_CPU_OP_COST) * rows
            tpu_op = self._op_cost("tpu", name, DEFAULT_TPU_OP_COST) * rows

            cpu_total, tpu_total = cpu_op, tpu_op
            child_choice_cpu, child_choice_tpu = [], []
            for (crows, ccpu, ctpu) in child_states:
                h2d = DEFAULT_H2D_COST * crows
                d2h = DEFAULT_D2H_COST * crows
                # parent on CPU
                if ccpu <= ctpu + d2h:
                    cpu_total += ccpu
                    child_choice_cpu.append("cpu")
                else:
                    cpu_total += ctpu + d2h
                    child_choice_cpu.append("tpu")
                # parent on TPU
                if ctpu <= ccpu + h2d:
                    tpu_total += ctpu
                    child_choice_tpu.append("tpu")
                else:
                    tpu_total += ccpu + h2d
                    child_choice_tpu.append("cpu")
            if not m.can_replace:
                tpu_total = math.inf
            plans[id(m)] = (child_choice_cpu, child_choice_tpu)
            return rows, cpu_total, tpu_total

        def mark(m, placement: str):
            if placement == "cpu" and m.can_replace:
                m.will_not_work("removed by cost-based optimizer")
                self.explain_lines.append(
                    f"CBO: {type(m.exec).__name__} -> CPU")
            choices = plans[id(m)][0 if placement == "cpu" else 1]
            for c, choice in zip(m.children, choices):
                mark(c, choice)

        rows, best_cpu, best_tpu = walk(meta)
        # the plan root hands rows back to the host either way
        root_tpu = best_tpu + DEFAULT_D2H_COST * rows
        root = "cpu" if best_cpu <= root_tpu else "tpu"
        before = _count_replaceable(meta)
        mark(meta, root)
        moved = before - _count_replaceable(meta)
        if self.conf.get(cfg.OPTIMIZER_EXPLAIN) == "ALL" and \
                self.explain_lines:
            print("\n".join(self.explain_lines))
        return moved


def _count_replaceable(meta) -> int:
    n = 1 if meta.can_replace else 0
    return n + sum(_count_replaceable(c) for c in meta.children)
