"""Logical plan nodes + analyzer.

The reference receives analyzed physical plans from Spark's Catalyst; as a
standalone framework we carry a small logical layer (built by the DataFrame
API) whose only jobs are name resolution, type propagation, and implicit
casts.  Shapes mirror Catalyst so the rewrite engine downstream sees
familiar structure.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import pyarrow as pa

from .. import types as t
from ..expr.core import (Alias, AttributeReference, BoundReference,
                         Expression, Literal, output_name)


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    def schema(self) -> Tuple[List[str], List[t.DataType]]:
        raise NotImplementedError

    @property
    def names(self):
        return self.schema()[0]

    @property
    def dtypes(self):
        return self.schema()[1]


class LocalRelation(LogicalPlan):
    def __init__(self, table: pa.Table, num_partitions: int = 1):
        self.table = table
        self.num_partitions = num_partitions
        # device-batch pin cache shared by every scan planned from this
        # node; lifetime == the user's DataFrame (see LocalScanExec)
        self.device_cache: dict = {}

    def schema(self):
        from ..columnar.interop import from_arrow_type
        return (list(self.table.schema.names),
                [from_arrow_type(f.type) for f in self.table.schema])


class Range(LogicalPlan):
    def __init__(self, start, end, step=1, num_partitions=1):
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions

    def schema(self):
        return ["id"], [t.LONG]


class FileRelation(LogicalPlan):
    """Scan of parquet/orc/csv files (resolved by io layer)."""

    def __init__(self, fmt: str, paths: List[str], schema_names,
                 schema_types, options=None):
        self.fmt = fmt
        self.paths = paths
        self._names = schema_names
        self._types = schema_types
        self.options = options or {}
        self.pushed_filters: List[Expression] = []

    def schema(self):
        return list(self._names), list(self._types)


class Project(LogicalPlan):
    def __init__(self, exprs: Sequence[Expression], child: LogicalPlan):
        self.exprs = list(exprs)
        self.children = (child,)

    def schema(self):
        names, dtypes = [], []
        cn, ct = self.children[0].schema()
        from ..expr.core import bind_expression
        for e in self.exprs:
            b = bind_expression(e, cn, ct)
            names.append(output_name(e))
            dtypes.append(b.data_type())
        return names, dtypes


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.children = (child,)

    def schema(self):
        return self.children[0].schema()


class Aggregate(LogicalPlan):
    def __init__(self, grouping: Sequence[Expression],
                 aggregates, child: LogicalPlan):
        from ..expr.aggregates import AggregateExpression
        self.grouping = list(grouping)
        self.aggregates: List[AggregateExpression] = list(aggregates)
        self.children = (child,)

    def schema(self):
        cn, ct = self.children[0].schema()
        from ..expr.core import bind_expression
        names, dtypes = [], []
        for g in self.grouping:
            b = bind_expression(g, cn, ct)
            names.append(output_name(g))
            dtypes.append(b.data_type())
        for a in self.aggregates:
            names.append(a.name)
            fn = a.func
            if fn.children:
                bound_child = bind_expression(fn.child, cn, ct)
                fb = type(fn).__new__(type(fn))
                fb.__dict__.update(fn.__dict__)
                fb.children = (bound_child,)
                dtypes.append(fb.data_type())
            else:
                dtypes.append(fn.data_type())
        return names, dtypes


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 how: str, condition: Optional[Expression] = None,
                 using: Optional[List[str]] = None,
                 force_shuffled: bool = False):
        self.children = (left, right)
        self.how = how  # inner, left, right, full, left_semi, left_anti, cross
        self.condition = condition
        self.using = using
        # planner pin from the bridge: a build side past the broadcast/
        # collect threshold must take the spill-backed shuffled path,
        # never broadcast (ref the retired maxBuildSideBytes gate)
        self.force_shuffled = force_shuffled

    def schema(self):
        ln, lt = self.children[0].schema()
        rn, rt = self.children[1].schema()
        if self.how in ("left_semi", "left_anti"):
            return ln, lt
        if self.using:
            # USING semantics (mirrors plan_join's output projection):
            # coalesced key columns first, then each side's remainder
            names, types = [], []
            for k in self.using:
                names.append(k)
                types.append(rt[rn.index(k)] if self.how == "right"
                             else lt[ln.index(k)])
            for n, t_ in zip(ln, lt):
                if n not in self.using:
                    names.append(n)
                    types.append(t_)
            for n, t_ in zip(rn, rt):
                if n not in self.using:
                    names.append(n)
                    types.append(t_)
            return names, types
        return ln + rn, lt + rt


class Sort(LogicalPlan):
    def __init__(self, orders, is_global: bool, child: LogicalPlan):
        # orders: list of (expr, ascending, nulls_first)
        self.orders = orders
        self.is_global = is_global
        self.children = (child,)

    def schema(self):
        return self.children[0].schema()


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.children = (child,)

    def schema(self):
        return self.children[0].schema()


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        self.children = tuple(children)

    def schema(self):
        return self.children[0].schema()


class Window(LogicalPlan):
    """Window function application; window_exprs are WindowExpression."""

    def __init__(self, window_exprs, child: LogicalPlan):
        self.window_exprs = list(window_exprs)
        self.children = (child,)

    def schema(self):
        cn, ct = self.children[0].schema()
        from ..expr.core import bind_expression
        names = list(cn)
        dtypes = list(ct)
        for we in self.window_exprs:
            names.append(we.name)
            dtypes.append(we.resolved_type(cn, ct))
        return names, dtypes


class Expand(LogicalPlan):
    """Multiple projections per input row (ref GpuExpandExec)."""

    def __init__(self, projections: List[List[Expression]],
                 names: List[str], child: LogicalPlan):
        self.projections = projections
        self._names = names
        self.children = (child,)

    def schema(self):
        cn, ct = self.children[0].schema()
        from ..expr.core import bind_expression
        dtypes = [bind_expression(e, cn, ct).data_type()
                  for e in self.projections[0]]
        return list(self._names), dtypes


class Distinct(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        self.children = (child,)

    def schema(self):
        return self.children[0].schema()


class Sample(LogicalPlan):
    def __init__(self, fraction: float, seed: int, child: LogicalPlan):
        self.fraction = fraction
        self.seed = seed
        self.children = (child,)

    def schema(self):
        return self.children[0].schema()


class Repartition(LogicalPlan):
    def __init__(self, num_partitions: int, keys: Optional[List[Expression]],
                 child: LogicalPlan):
        self.num_partitions = num_partitions
        self.keys = keys
        self.children = (child,)

    def schema(self):
        return self.children[0].schema()


class Generate(LogicalPlan):
    """explode/posexplode over an array column (ref GpuGenerateExec)."""

    def __init__(self, generator: Expression, outer: bool,
                 output_names: List[str], child: LogicalPlan):
        self.generator = generator
        self.outer = outer
        self._out_names = output_names
        self.children = (child,)

    def schema(self):
        cn, ct = self.children[0].schema()
        from ..expr.core import bind_expression
        g = bind_expression(self.generator, cn, ct)
        gnames, gtypes = g.generator_output()
        names = self._out_names if self._out_names else gnames
        return cn + list(names), ct + list(gtypes)


class MapInPandas(LogicalPlan):
    """df.mapInPandas(fn, schema) (ref GpuMapInPandasExec)."""

    def __init__(self, fn, out_names, out_types, child: LogicalPlan):
        self.fn = fn
        self.out_names = list(out_names)
        self.out_types = list(out_types)
        self.children = (child,)

    def schema(self):
        return list(self.out_names), list(self.out_types)


class FlatMapGroupsInPandas(LogicalPlan):
    """groupBy(k).applyInPandas(fn, schema)
    (ref GpuFlatMapGroupsInPandasExec)."""

    def __init__(self, grouping, fn, out_names, out_types,
                 child: LogicalPlan):
        self.grouping = list(grouping)
        self.fn = fn
        self.out_names = list(out_names)
        self.out_types = list(out_types)
        self.children = (child,)

    def schema(self):
        return list(self.out_names), list(self.out_types)


class AggregateInPandas(LogicalPlan):
    """groupBy(k).agg(<grouped-agg pandas UDF>)
    (ref GpuAggregateInPandasExec)."""

    def __init__(self, grouping, udfs, child: LogicalPlan):
        # udfs: list of (out_name, fn, ret_type, input_col_names)
        self.grouping = list(grouping)
        self.udfs = list(udfs)
        self.children = (child,)

    def schema(self):
        cn, ct = self.children[0].schema()
        by_name = dict(zip(cn, ct))
        names = [k.name for k in self.grouping] + \
            [n for n, *_ in self.udfs]
        dtypes = [by_name[k.name] for k in self.grouping] + \
            [rt for _, _, rt, _ in self.udfs]
        return names, dtypes


class CoGroupMapInPandas(LogicalPlan):
    """cogroup(...).applyInPandas(fn, schema)
    (ref GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left_grouping, right_grouping, fn, out_names,
                 out_types, left: LogicalPlan, right: LogicalPlan):
        self.left_grouping = list(left_grouping)
        self.right_grouping = list(right_grouping)
        self.fn = fn
        self.out_names = list(out_names)
        self.out_types = list(out_types)
        self.children = (left, right)

    def schema(self):
        return list(self.out_names), list(self.out_types)
