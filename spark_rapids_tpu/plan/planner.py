"""Logical -> physical planning.

Produces a CPU-placed physical plan — the same starting point the
reference gets from Spark's query planner — which plan/overrides.py then
rewrites onto the TPU (tagging unsupported pieces to stay on CPU).
"""

from __future__ import annotations

from typing import List

from .. import types as t
from ..exec import base as eb
from ..exec.aggregate import CpuHashAggregateExec
from ..exec.basic import (CoalesceBatchesExec, FilterExec, GlobalLimitExec,
                          LocalLimitExec, LocalScanExec, ProjectExec,
                          RangeExec, UnionExec)
from ..expr.aggregates import AggregateExpression, First
from ..expr.core import AttributeReference, Expression
from . import logical as L


def _rewrite_python_udfs(exprs: List[Expression], conf,
                         schema=None):
    """Compile-or-extract PythonUDF calls (ref udf-compiler
    LogicalPlanRules.scala:29 for the compile attempt; GpuArrowEvalPythonExec
    extraction for the opaque remainder)."""
    from ..udf.python_udf import PythonUDF
    udfs: List = []
    types_by_name = dict(zip(*schema)) if schema else {}

    def typed(e: Expression) -> Expression:
        """Resolve attr dtypes so the compiled tree type-checks."""
        def fn(x):
            if isinstance(x, AttributeReference) and x.dtype is None and \
                    x.name in types_by_name:
                return AttributeReference(x.name, types_by_name[x.name])
            return x
        return e.transform_up(fn)

    def walk(e: Expression) -> Expression:
        if isinstance(e, PythonUDF):
            if conf.udf_compiler_enabled and not e.vectorized:
                from ..udf.compiler import try_compile_udf
                compiled = try_compile_udf(e.fn, [typed(c)
                                                  for c in e.children])
                if compiled is not None:
                    # keep the declared return type stable across the
                    # compiled/opaque paths (schema must not depend on the
                    # compiler flag)
                    if compiled.data_type() != e.return_type:
                        from ..expr.cast import Cast
                        compiled = Cast(compiled, e.return_type)
                    # the compiled tree may still hold nested opaque UDFs
                    # in its leaves — extract those normally
                    return walk_children(compiled)
            # extract the whole subtree; nested UDFs inside evaluate
            # recursively during host evaluation, so children stay intact
            for n, u in udfs:
                if u is e:
                    return AttributeReference(n)
            name = f"pythonUDF{len(udfs)}"
            udfs.append((name, e))
            return AttributeReference(name)
        return walk_children(e)

    def walk_children(e: Expression) -> Expression:
        if not e.children:
            return e
        return e.with_children([walk(c) for c in e.children])

    return [walk(e) for e in exprs], udfs


def _plan_with_udfs(exprs: List[Expression], child_lp: L.LogicalPlan, conf):
    """Plan `child_lp` and, if any expr holds an opaque PythonUDF, interpose
    ArrowEvalPythonExec producing the UDF outputs as extra columns."""
    new_exprs, udfs = _rewrite_python_udfs(exprs, conf, child_lp.schema())
    child = plan(child_lp, conf)
    if udfs:
        from ..exec.python_udf import ArrowEvalPythonExec
        child = ArrowEvalPythonExec(udfs, child)
    return new_exprs, udfs, child


def plan(lp: L.LogicalPlan, conf) -> eb.Exec:
    from ..io.cached_batch import (CacheManager, CacheWriteExec,
                                   CachedScanExec)
    entry = CacheManager.lookup(lp)
    if entry is not None:
        names, dtypes = lp.schema()
        if entry.materialized:
            return CachedScanExec(entry, names, dtypes)
        inner = _plan_uncached(lp, conf)
        return CacheWriteExec(entry, inner)
    return _plan_uncached(lp, conf)


def _plan_uncached(lp: L.LogicalPlan, conf) -> eb.Exec:
    if isinstance(lp, L.LocalRelation):
        return LocalScanExec(lp.table, lp.num_partitions,
                             pin_cache=lp.device_cache)
    if isinstance(lp, L.Range):
        return RangeExec(lp.start, lp.end, lp.step, lp.num_partitions)
    if isinstance(lp, L.FileRelation):
        from ..io.scan import make_scan_exec
        return make_scan_exec(lp, conf)
    if isinstance(lp, L.Project):
        child_lp = lp.children[0]
        if isinstance(child_lp, L.FileRelation) and all(
                isinstance(e, AttributeReference) for e in lp.exprs):
            # column pruning pushdown (ref GpuFileSourceScanExec pruning)
            from ..io.scan import make_scan_exec
            scan = make_scan_exec(child_lp, conf)
            scan.required_columns = [e.name for e in lp.exprs]
            return scan
        exprs, _udfs, child = _plan_with_udfs(lp.exprs, child_lp, conf)
        return ProjectExec(exprs, child)
    if isinstance(lp, L.Filter):
        child_lp = lp.children[0]
        if isinstance(child_lp, L.FileRelation):
            # predicate pushdown for row-group pruning; the exact Filter
            # stays above (ref parquet footer filters + GpuFilterExec).
            # The pushed filter lives only in this query's scan exec — the
            # shared FileRelation node is never mutated.
            from ..io.scan import make_scan_exec
            scan = make_scan_exec(child_lp, conf,
                                  extra_filters=[lp.condition])
            return FilterExec(lp.condition, scan)
        conds, udfs, child = _plan_with_udfs([lp.condition], child_lp, conf)
        if udfs:
            # UDF outputs were appended below; filter on them, then project
            # the original columns back out
            names, _ = lp.children[0].schema()
            keep = [AttributeReference(n) for n in names]
            return ProjectExec(keep, FilterExec(conds[0], child))
        return FilterExec(conds[0], child)
    if isinstance(lp, L.Aggregate):
        child = plan(lp.children[0], conf)
        if child.num_partitions > 1:
            # co-locate groups: hash exchange on the grouping keys (the
            # conversion pass rewrites this into partial->exchange->final)
            if lp.grouping:
                from ..shuffle.exchange import ShuffleExchangeExec
                from ..shuffle.partitioning import HashPartitioning
                child = ShuffleExchangeExec(
                    HashPartitioning(lp.grouping, child.num_partitions),
                    child)
            else:
                from ..exec.gatherpart import GatherPartitionsExec
                child = GatherPartitionsExec(child)
        return CpuHashAggregateExec(lp.grouping, lp.aggregates, child)
    if isinstance(lp, L.Join):
        from ..exec.join import plan_join
        return plan_join(lp, plan(lp.children[0], conf),
                         plan(lp.children[1], conf), conf)
    if isinstance(lp, L.Sort):
        from ..exec.sort import SortExec
        child = plan(lp.children[0], conf)
        if lp.is_global and child.num_partitions > 1:
            # total-order sort: range-partition then sort within partitions
            from ..shuffle.exchange import ShuffleExchangeExec
            from ..shuffle.partitioning import RangePartitioning
            child = ShuffleExchangeExec(
                RangePartitioning(lp.orders, child.num_partitions), child)
        return SortExec(lp.orders, child, is_global=lp.is_global)
    if isinstance(lp, L.Limit):
        child_lp = lp.children[0]
        if isinstance(child_lp, L.Sort) and child_lp.is_global:
            # TopN: per-partition sort+limit, then one final merge sort+limit
            # (ref limit.scala GpuTopN / TakeOrderedAndProjectExec) — avoids
            # the range-partition exchange a full global sort would need
            from ..exec.gatherpart import GatherPartitionsExec
            from ..exec.sort import SortExec
            inner = plan(child_lp.children[0], conf)
            local = LocalLimitExec(
                lp.n, SortExec(child_lp.orders, inner, is_global=False))
            merged = GatherPartitionsExec(local) \
                if inner.num_partitions > 1 else local
            return GlobalLimitExec(
                lp.n, SortExec(child_lp.orders, merged, is_global=False))
        child = plan(child_lp, conf)
        if child.num_partitions > 1:
            from ..exec.gatherpart import GatherPartitionsExec
            child = GatherPartitionsExec(LocalLimitExec(lp.n, child))
        return GlobalLimitExec(lp.n, child)
    if isinstance(lp, L.Union):
        return UnionExec([plan(c, conf) for c in lp.children])
    if isinstance(lp, L.Distinct):
        # plan as GROUP BY all columns so the multi-partition path gets
        # the same co-locating hash exchange an aggregate gets (per-
        # partition-only dedup would leak cross-partition duplicates)
        names, dtypes = lp.schema()
        grouping = [AttributeReference(n) for n in names]
        return _plan_uncached(L.Aggregate(grouping, [], lp.children[0]),
                              conf)
    if isinstance(lp, L.Window):
        from ..exec.window import WindowExec
        child = plan(lp.children[0], conf)
        if child.num_partitions > 1:
            specs = [w.spec for w in lp.window_exprs]
            pkeys = specs[0].partition_by if specs else []
            same_keys = all(
                [k.sql() for k in s.partition_by] ==
                [k.sql() for k in pkeys] for s in specs)
            if pkeys and same_keys:
                from ..shuffle.exchange import ShuffleExchangeExec
                from ..shuffle.partitioning import HashPartitioning
                child = ShuffleExchangeExec(
                    HashPartitioning(list(pkeys), child.num_partitions),
                    child)
            else:
                from ..exec.gatherpart import GatherPartitionsExec
                child = GatherPartitionsExec(child)
        return WindowExec(lp.window_exprs, child)
    if isinstance(lp, L.Expand):
        from ..exec.expand import ExpandExec
        return ExpandExec(lp.projections, lp._names,
                          plan(lp.children[0], conf))
    if isinstance(lp, L.Generate):
        from ..exec.expand import GenerateExec
        return GenerateExec(lp.generator, lp.outer, lp._out_names,
                            plan(lp.children[0], conf))
    if isinstance(lp, L.Sample):
        from ..exec.basic import SampleExec
        return SampleExec(lp.fraction, lp.seed, plan(lp.children[0], conf))
    if isinstance(lp, L.Repartition):
        from ..shuffle.exchange import ShuffleExchangeExec
        from ..shuffle.partitioning import (HashPartitioning,
                                            RoundRobinPartitioning)
        child = plan(lp.children[0], conf)
        part = HashPartitioning(lp.keys, lp.num_partitions) if lp.keys \
            else RoundRobinPartitioning(lp.num_partitions)
        return ShuffleExchangeExec(part, child)
    if isinstance(lp, L.MapInPandas):
        from ..exec.pandas_udf import MapInPandasExec
        return MapInPandasExec(lp.fn, lp.out_names, lp.out_types,
                               plan(lp.children[0], conf))
    if isinstance(lp, L.FlatMapGroupsInPandas):
        from ..exec.pandas_udf import FlatMapGroupsInPandasExec
        child = _colocate_groups(lp.grouping, plan(lp.children[0], conf))
        return FlatMapGroupsInPandasExec(
            [k.name for k in lp.grouping], lp.fn, lp.out_names,
            lp.out_types, child)
    if isinstance(lp, L.AggregateInPandas):
        from ..exec.pandas_udf import AggregateInPandasExec
        child = _colocate_groups(lp.grouping, plan(lp.children[0], conf))
        return AggregateInPandasExec([k.name for k in lp.grouping],
                                     lp.udfs, child)
    if isinstance(lp, L.CoGroupMapInPandas):
        from ..exec.pandas_udf import FlatMapCoGroupsInPandasExec
        lplan = plan(lp.children[0], conf)
        rplan = plan(lp.children[1], conf)
        # both sides must route equal keys to the same partition id:
        # murmur3 routing is value-based, so hashing each side on its own
        # keys with a COMMON partition count co-locates matching groups
        n = max(lplan.num_partitions, rplan.num_partitions)
        left = _colocate_groups(lp.left_grouping, lplan, n_parts=n)
        right = _colocate_groups(lp.right_grouping, rplan, n_parts=n)
        return FlatMapCoGroupsInPandasExec(
            [k.name for k in lp.left_grouping],
            [k.name for k in lp.right_grouping],
            lp.fn, lp.out_names, lp.out_types, left, right)
    raise NotImplementedError(f"no physical plan for {type(lp).__name__}")


def _colocate_groups(grouping, child, n_parts=None):
    """Hash-exchange so every group lands in one partition (the pandas
    grouped execs need whole groups, like the aggregate path)."""
    target = n_parts if n_parts is not None else child.num_partitions
    if child.num_partitions <= 1 and (n_parts is None or n_parts <= 1):
        return child
    if not grouping:
        from ..exec.gatherpart import GatherPartitionsExec
        return GatherPartitionsExec(child)
    from ..shuffle.exchange import ShuffleExchangeExec
    from ..shuffle.partitioning import HashPartitioning
    return ShuffleExchangeExec(
        HashPartitioning(list(grouping), target), child)


def force_perfile_if_input_file(root: eb.Exec) -> None:
    """When the plan evaluates input_file_name(), multi-file coalescing /
    multithreaded readers would make the value ambiguous — force the
    PERFILE reader (the reference's InputFileBlockRule.scala +
    queryUsesInputFile checks in GpuMultiFileReader.scala do the same)."""
    from ..expr.hashfns import InputFileName
    from ..io.scan import FileScanExec

    found = []

    def check(node):
        for attr in ("_bound", "exprs"):
            v = getattr(node, attr, None)
            if v is None:
                continue
            for e in (v if isinstance(v, (list, tuple)) else [v]):
                if hasattr(e, "collect") and \
                        e.collect(lambda x: isinstance(x, InputFileName)):
                    found.append(node)
                    return

    root.foreach(check)
    if found:
        root.foreach(lambda n: isinstance(n, FileScanExec) and
                     setattr(n, "reader_type", "PERFILE"))
