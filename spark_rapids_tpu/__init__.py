"""spark_rapids_tpu: a TPU-native Spark SQL acceleration framework.

Brand-new design with the capabilities of the RAPIDS Accelerator for Apache
Spark (reference surveyed in SURVEY.md), executing columnar SQL operators as
fused XLA computations on TPU via JAX/Pallas instead of cuDF/JNI kernels.
"""

import jax

# SQL semantics require int64/float64 end to end; bf16/f32 remain available
# where ops opt in (e.g. MXU paths).
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
