"""TpuSession: the user entry point.

Plays the combined role of SparkSession + the reference's plugin bootstrap
(ref Plugin.scala RapidsDriverPlugin/RapidsExecutorPlugin): holds config,
initializes the device manager/semaphore/spill catalog, and drives
logical -> physical -> overrides -> execution for DataFrame queries.

With `spark.rapids.sql.enabled=false` queries run entirely on the CPU
engine — the differential-test harness toggles exactly this key, the same
way the reference's integration tests do.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import pyarrow as pa

from .. import config as cfg
from ..config import RapidsConf
from ..exec.base import ExecContext, SpeculativeSizingMiss
from ..plan import logical as L
from ..plan.overrides import TpuOverrides
from ..plan.planner import plan as plan_physical
from .dataframe import DataFrame


def _replay_class(plan, conf) -> str:
    """The final plan's effective replay class (tpudsan lattice root),
    stamped on the phase:overrides span so run fingerprints and the
    failure black box can see recompute guarantees weaken across runs.
    Best-effort: classification must never fail a query."""
    try:
        if not conf.get(cfg.DSAN_ENABLED):
            return "unclassified"
        from ..analysis.determinism import classify_plan
        return classify_plan(plan, conf).effective(plan)
    except Exception:
        return "unclassified"


class TpuSession:
    _active: Optional["TpuSession"] = None
    _lock = threading.Lock()
    _create_lock = threading.Lock()
    _tls = threading.local()

    def __init__(self, conf: Optional[Dict] = None):
        self._conf_map = dict(conf or {})
        self.last_plan = None
        self.last_explain = ""
        # flight recorder (obs/): per-query trace + self-emitted event log
        self._last_trace = None
        self._obs_plan = None
        self._obs_writer = None
        self._sql_counter = 0
        # pool sessions (api/pool.py) bind tracer + memsan ledger
        # thread-locally so co-running queries never share either
        self._obs_isolation = False
        self.last_peak_device_bytes = None
        self._init_runtime()
        with TpuSession._lock:
            TpuSession._active = self

    def _init_runtime(self):
        conf = self.conf
        # continuous metrics: the registry collects by default (cheap);
        # the HTTP exposition endpoint is opt-in via metrics.port
        from ..obs import metrics as obs_metrics
        obs_metrics.set_enabled(conf.get(cfg.METRICS_ENABLED))
        port = conf.get(cfg.METRICS_PORT)
        if port is not None and conf.get(cfg.METRICS_ENABLED):
            from ..obs.health import ensure_server
            self.metrics_server = ensure_server(port)
        else:
            self.metrics_server = None
        # background-thread failures (heartbeat loop, metrics endpoint)
        # bundle into the same black box as query failures when one is
        # configured — the router is process-global because those
        # threads outlive any single session
        from ..obs import bgerrors
        if conf.get(cfg.HBM_POSTMORTEM_ENABLED):
            bg_dir = conf.get(cfg.HBM_POSTMORTEM_DIR) or \
                conf.get(cfg.REGRESS_HISTORY_DIR)
            if bg_dir:
                bgerrors.set_postmortem_dir(bg_dir)
        # fleet observatory bounds: size the producer-side serve-span
        # buffer the /spans endpoint drains
        from ..obs.fleet import RemoteSpanStore
        RemoteSpanStore.get().configure(
            conf.get(cfg.FLEET_SPANS_MAX_TRACES),
            conf.get(cfg.FLEET_SPANS_MAX_PER_TRACE))
        # compile observatory: every XLA build at the process_jit seam
        # gets split timing, a classified cause and (with a ledger dir)
        # cross-session persistence (obs/compileprof.py)
        from ..obs.compileprof import CompileObservatory
        ledger_dir = conf.get(cfg.COMPILE_LEDGER_DIR) or \
            conf.get(cfg.REGRESS_HISTORY_DIR)
        ledger_path = None
        if ledger_dir:
            from ..obs.history import HistoryDir
            ledger_path = HistoryDir(ledger_dir).compile_ledger_path()
        hlo_dir = conf.get(cfg.XSAN_HLO_DIR)
        if not hlo_dir and ledger_dir:
            from ..obs.compileprof import HLO_SUBDIR
            hlo_dir = os.path.join(ledger_dir, HLO_SUBDIR)
        CompileObservatory.get().configure(
            enabled=conf.get(cfg.COMPILE_OBSERVATORY_ENABLED),
            ledger_path=ledger_path,
            buckets=conf.capacity_buckets + conf.string_data_buckets,
            thrash_warn_ratio=conf.get(cfg.JIT_THRASH_WARN_RATIO),
            hlo_dir=hlo_dir or None)
        # estimator observatory: predicted-vs-actual per operator
        # signature, persisted next to the compile ledger; recording is
        # always on, feedback.enabled additionally blends it back into
        # planning and arms the exchange-boundary re-planner
        from ..obs.estimator import EstimatorLedger
        est_path = None
        if ledger_dir:
            from ..obs.history import HistoryDir
            est_path = HistoryDir(ledger_dir).estimator_ledger_path()
        EstimatorLedger.get().configure(
            ledger_path=est_path,
            feedback_enabled=conf.get(cfg.FEEDBACK_ENABLED),
            blend_floor=conf.get(cfg.FEEDBACK_BLEND_FLOOR),
            blend_cap=conf.get(cfg.FEEDBACK_BLEND_CAP),
            min_observations=conf.get(cfg.FEEDBACK_MIN_OBSERVATIONS),
            replan_factor=conf.get(cfg.FEEDBACK_REPLAN_FACTOR))
        # latency observatory: per-tenant SLO windows + tail reservoir
        # fed by critical-path extraction on every traced query; the
        # per-query ledger lands in the regress HistoryDir
        from ..obs.slo import LatencyObservatory
        slo_ledger = None
        hist_dir = conf.get(cfg.REGRESS_HISTORY_DIR)
        if hist_dir:
            from ..obs.history import HistoryDir
            slo_ledger = HistoryDir(hist_dir).latency_ledger_path()
        LatencyObservatory.get().configure(
            target_ms=conf.get(cfg.SLO_TARGET_MS),
            objective=conf.get(cfg.SLO_OBJECTIVE),
            ledger_path=slo_ledger)
        # progress observatory: the live in-flight view + cooperative
        # cancel tokens + stuck-query watchdog thresholds
        from ..obs.progress import ProgressTracker
        ProgressTracker.get().configure(
            enabled=conf.get(cfg.PROGRESS_ENABLED),
            max_queries=conf.get(cfg.PROGRESS_MAX_QUERIES),
            stall_seconds=conf.get(cfg.WATCHDOG_STALL_SECONDS),
            auto_cancel_seconds=conf.get(
                cfg.WATCHDOG_AUTO_CANCEL_SECONDS))
        from ..memory.meta import set_default_codec
        set_default_codec(conf.get(cfg.SHUFFLE_COMPRESSION_CODEC))
        from ..shims import ShimLoader, set_active_shim
        self.shim = ShimLoader.get_shim(
            conf.raw("spark.rapids.tpu.sparkVersion", "3.2.0"))
        set_active_shim(self.shim)
        from ..exec.base import set_device_timing, set_trace_annotations
        set_trace_annotations(conf.get(cfg.PROFILE_TRACE_ANNOTATIONS))
        # DEBUG metrics level: block per-op so opTime is real device time
        # (ref NvtxWithMetrics; round-2 verdict: async dispatch made every
        # operator report ~0 and booked all kernel time to the D2H sync)
        set_device_timing(conf.get(cfg.METRICS_LEVEL) == "DEBUG")
        if conf.get(cfg.BACKEND) == "tpu" and conf.sql_enabled:
            # in-process both-sides bootstrap (ref Plugin.scala: driver +
            # executor plugins; one process hosts both roles here)
            from ..plugin import TpuDriverPlugin, TpuExecutorPlugin
            self.driver_plugin = TpuDriverPlugin(self._conf_map)
            self.driver_plugin.init()
            self.executor_plugin = TpuExecutorPlugin(
                self._conf_map, driver=self.driver_plugin)
            self.executor_plugin.init()
            self.shim = self.executor_plugin.shim  # one source of truth
            self.device_manager = self.executor_plugin.device_manager
            self.semaphore = self.executor_plugin.semaphore
            self.spill_catalog = self.executor_plugin.spill_catalog
        else:
            self.driver_plugin = None
            self.executor_plugin = None
            self.device_manager = None
            self.semaphore = None
            self.spill_catalog = None
        # HBM observatory: the process-wide occupancy timeline every
        # spill/arena/broadcast/admission hook feeds (obs/memprof.py).
        # Configured after plugin init so the device budget is known.
        from ..obs.memprof import MemoryTimeline
        MemoryTimeline.configure(
            enabled=conf.get(cfg.HBM_TIMELINE_ENABLED),
            max_samples=conf.get(cfg.HBM_TIMELINE_MAX_SAMPLES),
            budget_bytes=self.spill_catalog.device_budget
            if self.spill_catalog is not None else 0)
        # after plugin init: the cold-cache probe reads the persistent
        # compile cache dir the plugin just configured
        self._init_sort_mode(conf)
        # warm-start tier: replay the costliest ledger recipes so first
        # queries dispatch to ready programs.  Ordered after plugin and
        # sort-mode init — the replay compiles through the persistent
        # disk cache and must not flip the cold-cache probe's verdict.
        self._prewarm_thread = None
        if ledger_path and conf.get(cfg.JIT_PREWARM_ENABLED) and \
                conf.get(cfg.COMPILE_OBSERVATORY_ENABLED):
            from ..obs.prewarm import prewarm_session
            self._prewarm_thread = prewarm_session(
                ledger_path,
                top_k=conf.get(cfg.JIT_PREWARM_TOP_K),
                background=conf.get(cfg.JIT_PREWARM_BACKGROUND))

    _auto_sort_mode_decided = False

    def _init_sort_mode(self, conf: RapidsConf) -> None:
        """Pick the sort kernel structure (ops/carry.py module doc):
        'auto' = compile-lean exactly while the persistent XLA compile
        cache is cold, throughput carry-sorts once it is warm.  The
        auto probe decides ONCE per process — this process's own cache
        writes must not flip kernel structure between sessions."""
        import os
        from ..ops.carry import set_compile_lean
        mode = conf.get(cfg.SORT_COMPILE_LEAN)
        if mode in ("on", "off"):
            set_compile_lean(mode == "on")
            TpuSession._auto_sort_mode_decided = True
            return
        if TpuSession._auto_sort_mode_decided:
            return
        try:
            import jax
            d = jax.config.jax_compilation_cache_dir
            if not d:
                # no persistent cache configured yet (plugin runs only
                # for device sessions) — leave the decision to a later
                # session that actually compiles device kernels
                return
            cold = not os.path.isdir(d) or not any(os.scandir(d))
        except Exception:
            cold = False
        set_compile_lean(cold)
        TpuSession._auto_sort_mode_decided = True

    # -- conf ---------------------------------------------------------------
    @property
    def conf(self) -> RapidsConf:
        return RapidsConf(self._conf_map)

    def set_conf(self, key: str, value) -> "TpuSession":
        self._conf_map[key] = value
        return self

    @classmethod
    def builder(cls):
        return _Builder()

    @classmethod
    def active(cls) -> "TpuSession":
        """The session for THIS thread: the pool-bound one when the
        calling thread borrowed from a SessionPool (api/pool.py), else
        the process-wide last-created session, built on demand.
        Thread-safe — concurrent first calls no longer race to build
        two default sessions."""
        bound = getattr(cls._tls, "session", None)
        if bound is not None:
            return bound
        if cls._active is None:
            with cls._create_lock:
                if cls._active is None:
                    TpuSession()  # registers itself as _active
        return cls._active

    @classmethod
    def bind_to_thread(cls,
                       session: Optional["TpuSession"]) -> None:
        """Bind (or with None, unbind) the calling thread's active()
        session — the SessionPool's borrow/return hook."""
        cls._tls.session = session

    # -- data sources -------------------------------------------------------
    def create_dataframe(self, data, num_partitions: int = 1) -> DataFrame:
        if isinstance(data, pa.Table):
            table = data
        elif isinstance(data, pa.RecordBatch):
            table = pa.Table.from_batches([data])
        elif isinstance(data, dict):
            table = pa.table(data)
        else:
            import pandas as pd
            if isinstance(data, pd.DataFrame):
                table = pa.Table.from_pandas(data, preserve_index=False)
            else:
                raise TypeError(f"cannot create DataFrame from {type(data)}")
        return DataFrame(L.LocalRelation(table, num_partitions), self)

    def range(self, start, end=None, step=1, num_partitions=1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(L.Range(start, end, step, num_partitions), self)

    @property
    def read(self):
        from ..io.reader import DataFrameReader
        return DataFrameReader(self)

    # -- execution ----------------------------------------------------------
    def prepare_plan(self, lp: L.LogicalPlan, run_subqueries: bool = True):
        """Logical plan -> final physical plan: dialect install, scalar
        subqueries, planning, overrides — the shared front half of
        execute()/explain()/ml.device_batches.

        run_subqueries=False (explain) substitutes subqueries with typed
        null placeholders instead of EXECUTING them: printing a plan must
        never run device work (ref explain stays driver-side)."""
        from ..expr.subquery import (has_scalar_subquery,
                                     resolve_scalar_subqueries)
        from ..obs.tracer import trace_span
        from ..shims import set_active_shim
        # queries are evaluated sequentially per process; installing the
        # dialect per execution keeps interleaved sessions with different
        # sparkVersions correct (concurrent multi-dialect sessions are
        # out of scope, like one ShimLoader per JVM in the reference)
        set_active_shim(self.shim)
        if has_scalar_subquery(lp):
            # subqueries run first, driver-side, and substitute as typed
            # literals (ref GpuScalarSubquery / ExecSubqueryExpression)
            with trace_span("phase:subqueries", kind="phase"):
                lp = resolve_scalar_subqueries(lp, self,
                                               execute=run_subqueries)
        with trace_span("phase:planning", kind="phase"):
            physical = plan_physical(lp, self.conf)
            from ..plan.planner import force_perfile_if_input_file
            force_perfile_if_input_file(physical)
        with trace_span("phase:overrides", kind="phase") as sp:
            overrides = TpuOverrides(self.conf)
            final_plan = overrides.apply(physical)
            lint = getattr(overrides, "last_lint", [])
            sp.set(lint_diags=len(lint),
                   lint_rules=sorted({d.code for d in lint}),
                   replay_class=_replay_class(final_plan, self.conf))
        self.last_plan = final_plan
        self.last_explain = overrides.last_explain
        self._count_fallbacks(final_plan)
        return final_plan

    def _count_fallbacks(self, final_plan) -> None:
        """Feed tpu_fallback_ops_total: operators the overrides engine
        left on the host engine, by exec name (a growing fallback set
        is the regression watchdog's loudest deterministic signal)."""
        from ..exec.base import CPU
        from ..obs import metrics as m
        if not m.enabled():
            return
        fam = m.counter("tpu_fallback_ops_total",
                        "plan operators left on the host engine",
                        ("op",))
        final_plan.foreach(
            lambda e: fam.labels(op=type(e).__name__).inc()
            if e.placement == CPU else None)

    def release_plan_shuffles(self, final_plan) -> None:
        """Release shuffle blocks a plan registered in the global spill
        catalog (ref remove-shuffle on stage cleanup) — each collect
        re-plans, so dropping them cannot be observed."""
        from ..shuffle.manager import TpuShuffleManager
        ids = []
        final_plan.foreach(
            lambda e: ids.append(e._shuffle_id)
            if getattr(e, "_shuffle_id", None) is not None else None)
        if ids:
            mgr = TpuShuffleManager.get()
            for sid in ids:
                mgr.unregister(sid)
        # device-resident exchange memos (IciExchangeExec) hold whole
        # shuffled datasets in HBM — same cleanup point as shuffle blocks
        final_plan.foreach(
            lambda e: e.release_shuffle()
            if hasattr(e, "release_shuffle") else None)

    def execute(self, lp: L.LogicalPlan,
                deadline_ms: Optional[int] = None) -> pa.Table:
        """Execute + collect, under the continuous query-lifecycle
        metrics (active/completed/failed) every health probe reads.

        ``deadline_ms`` bounds the query's wall time: past it the next
        cooperative checkpoint (partition boundary, admission queue
        wait, shuffle fetch loop) raises the typed
        TpuQueryDeadlineExceeded, unwinding through the same release
        obligations as any other failure.  Unset, the session-level
        ``spark.rapids.tpu.progress.deadlineMs`` default applies."""
        from ..obs import metrics as m
        m.gauge("tpu_queries_active",
                "queries currently executing").gauge_inc()
        try:
            result = self._execute(lp, deadline_ms=deadline_ms)
        except BaseException:
            m.counter("tpu_queries_failed_total",
                      "queries that raised").inc()
            raise
        finally:
            m.gauge("tpu_queries_active",
                    "queries currently executing").dec()
        m.counter("tpu_queries_completed_total",
                  "queries that returned a result").inc()
        return result

    def cancel(self, query_id: str) -> bool:
        """Request cooperative cancellation of an in-flight query on
        this session.  Returns True if a live query matched; the query
        itself raises TpuQueryCancelled at its next checkpoint
        (partition boundary, admission wait, or shuffle fetch loop)."""
        from ..obs.progress import ProgressTracker
        return ProgressTracker.get().cancel(
            query_id, tenant=getattr(self, "_tenant", "") or "default")

    def _execute(self, lp: L.LogicalPlan,
                 deadline_ms: Optional[int] = None) -> pa.Table:
        from ..obs import memprof
        from ..obs import progress as prog
        from ..obs import tracer as obs
        conf = self.conf
        if conf.get(cfg.CSAN_ENABLED):
            # lock witness: wrap registered locks before any of them is
            # taken on this query's path; refresh() also picks up locks
            # whose owners were constructed since the last query
            from ..obs import lockwitness
            lockwitness.ensure_installed()
        eventlog_dir = conf.get(cfg.EVENT_LOG_DIR)
        tracing = conf.get(cfg.TRACE_ENABLED) or eventlog_dir is not None
        # HBM observatory attribution scope: every spill/arena event on
        # this thread books under (tenant, query) until the query ends
        memprof.push_context(getattr(self, "_tenant", "") or "default",
                             f"q{self._sql_counter}")
        # progress observatory: register the live-view record + cancel
        # token, bound thread-local so the cooperative checkpoints in
        # exec/admission/shuffle find it without signature plumbing
        if deadline_ms is None:
            deadline_ms = conf.get(cfg.PROGRESS_DEADLINE_MS)
        handle = prog.ProgressTracker.get().begin_query(
            f"q{self._sql_counter}",
            tenant=getattr(self, "_tenant", "") or "default",
            deadline_ms=deadline_ms)
        prog.bind_to_thread(handle)
        try:
            if not tracing:
                try:
                    result = self._execute_query(lp, None, None)
                    prog.ProgressTracker.get().end_query(handle)
                    return result
                except BaseException as ex:
                    prog.ProgressTracker.get().end_query(handle, ex)
                    self._maybe_postmortem(ex, None)
                    raise
            # flight recorder: one QueryTrace per execute(); the
            # installed tracer is what every instrumented layer
            # (operator spans, spill/shuffle/ICI/bridge events) records
            tracer = obs.QueryTrace(
                max_spans=conf.get(cfg.TRACE_MAX_SPANS))
            if self._obs_isolation:
                obs.install_local(tracer)
            else:
                obs.install(tracer)
            self._last_trace = tracer
            self._obs_plan = None
            try:
                result = self._execute_query(lp, tracer, eventlog_dir)
                prog.ProgressTracker.get().end_query(handle)
                return result
            except BaseException as ex:
                # failed queries flush too: spans close with the
                # exception recorded, the event log gets a JobFailed
                # group; the black box dumps AFTER the flush so the
                # bundle sees the sealed trace
                prog.ProgressTracker.get().end_query(handle, ex)
                self._flush_query_obs(tracer, ex, eventlog_dir)
                self._maybe_postmortem(ex, tracer)
                raise
            finally:
                if self._obs_isolation:
                    obs.uninstall_local()
                else:
                    obs.uninstall()
        finally:
            prog.bind_to_thread(None)
            memprof.pop_context()

    def _execute_query(self, lp: L.LogicalPlan, tracer,
                       eventlog_dir) -> pa.Table:
        from ..obs.tracer import trace_span
        from ..plan.host_assist import try_host_assisted_collect
        with trace_span("phase:host_assist", kind="phase"):
            assisted = try_host_assisted_collect(self, lp)
        if assisted is not None:
            if tracer is not None:
                tracer.finalize()
                tracer._flush_done = True  # no plan ran: nothing to log
            return assisted
        with trace_span("phase:plan", kind="phase"):
            final_plan = self.prepare_plan(lp)
        # byte-weighted admission (serve.hbmAdmissionBudgetBytes): the
        # plan's tmsan static peak bound is its ticket — acquired once,
        # held across the speculation retry (re-entrancy), released in
        # the finally (release-on-failure)
        try:
            ticket, controller = self._admit_plan(final_plan)
        except BaseException:
            # a cancel / deadline / AdmissionTimeout raised while
            # queued must not strand the shuffle blocks that exchange
            # map stages already materialized during planning
            self.release_plan_shuffles(final_plan)
            raise
        try:
            return self._execute_admitted(lp, final_plan, tracer,
                                          eventlog_dir, ticket)
        finally:
            if controller is not None:
                controller.release(ticket)

    def _execute_admitted(self, lp: L.LogicalPlan, final_plan, tracer,
                          eventlog_dir, ticket) -> pa.Table:
        from ..obs.tracer import trace_span
        self._obs_plan = final_plan
        self._install_predictions(tracer, final_plan)
        from ..plugin import ExecutionPlanCaptureCallback
        ExecutionPlanCaptureCallback.on_plan(final_plan)
        ctx = ExecContext(self.conf)
        # exchange-boundary re-planner: armed for the whole execution
        # (feedback.enabled gates inside); it needs the live ticket to
        # re-price and the exec context to pin strategy switches on
        from ..analysis import replan as replan_mod
        from ..memory.admission import AdmissionController
        rctx = replan_mod.ReplanContext(
            plan_root=final_plan, conf=self.conf, ticket=ticket,
            controller=AdmissionController.get()
            if ticket is not None else None,
            tracer=tracer, exec_ctx=ctx)
        replan_mod.install(rctx)
        # boundaries whose map stage ran during planning replay now —
        # still before the first reduce partition launches
        replan_mod.scan_materialized(rctx)
        from ..memory.spill import SpillCatalog
        debug = self.conf.get(cfg.MEMORY_DEBUG)
        cat = SpillCatalog.get()
        # tmsan runtime sanitizer: record + assert the buffer lifecycle
        # state machine on every catalog/arena event while the query
        # runs, then require a clean ledger (no leaks) afterwards.
        # Pool sessions install thread-locally: a per-query clean check
        # must not flag co-running queries' live buffers as leaks.
        from ..memory import memsan
        memsan_on = self.conf.get(cfg.MEMSAN_ENABLED)
        if memsan_on:
            ledger = memsan.install_local() if self._obs_isolation \
                else memsan.install()
        if debug:
            cat.debug = True
            before = {b_id for b_id, *_ in cat.leak_report()}
        try:
            try:
                with trace_span("phase:execute", kind="phase"):
                    result = final_plan.execute_collect(ctx)
            except SpeculativeSizingMiss:
                # a capacity guess undershot (guard came back false):
                # nothing was surfaced — but any cache materialization
                # this run streamed is built on truncated batches and
                # must be discarded before the exact re-execution
                from ..obs import metrics as m
                m.counter("tpu_queries_retried_total",
                          "speculation-miss exact re-executions").inc()
                from ..io.cached_batch import CacheWriteExec

                def _reset_cache(node):
                    if isinstance(node, CacheWriteExec):
                        node.entry.materialized = False
                        node.entry.partitions = []
                        node.entry.schema = None
                final_plan.foreach(_reset_cache)
                if tracer is not None:
                    # abandoned generators never see the exception:
                    # close their spans now so the re-execution starts
                    # from a consistent trace
                    tracer.interrupt("speculation-miss")
                self.release_plan_shuffles(final_plan)
                with trace_span("phase:plan-retry", kind="phase"):
                    final_plan = self.prepare_plan(lp)
                if ticket is not None and ticket.repaired:
                    # the retry re-planned from scratch: re-shrink the
                    # fresh plan so it still fits the admitted ticket
                    from ..memory.admission import AdmissionController
                    ctrl = AdmissionController.get()
                    if ctrl is not None:
                        self._repair_for_admission(final_plan,
                                                   ctrl.budget_bytes)
                self._obs_plan = final_plan
                self._install_predictions(tracer, final_plan)
                ctx = ExecContext(self.conf)
                ctx.task_context["no_speculation"] = True
                # the retry re-planned: point the re-planner at the
                # fresh plan/context (its ticket carries over)
                rctx.plan_root = final_plan
                rctx.exec_ctx = ctx
                replan_mod.scan_materialized(rctx)
                with trace_span("phase:execute-retry", kind="phase"):
                    result = final_plan.execute_collect(ctx)
        except BaseException:
            # an aborted query routinely strands buffers; the original
            # error must surface, not a misleading leak report
            self.release_plan_shuffles(final_plan)
            if debug:
                cat.debug = False
            if memsan_on:
                self.last_peak_device_bytes = ledger.peak_device_bytes
                if tracer is not None:
                    tracer.measured_peak_device_bytes = \
                        ledger.peak_device_bytes
                self._memsan_uninstall(memsan)
            raise
        finally:
            replan_mod.uninstall()
        self.release_plan_shuffles(final_plan)
        if memsan_on:
            try:
                # everything the query registered must have reached
                # CLOSED (pinned scan caches are sanctioned residents);
                # leaks surface with owning-exec provenance
                try:
                    ledger.assert_clean()
                except BaseException:
                    from ..obs import metrics as m
                    m.counter("tpu_memsan_dirty_ledgers_total",
                              "queries whose shadow ledger was dirty "
                              "(leak or lifecycle violation)").inc()
                    raise
            finally:
                self.last_peak_device_bytes = ledger.peak_device_bytes
                if tracer is not None:
                    tracer.measured_peak_device_bytes = \
                        ledger.peak_device_bytes
                self._memsan_uninstall(memsan)
        if debug:
            leaks = [l for l in cat.leak_report() if l[0] not in before]
            cat.debug = False
            if leaks:
                detail = "\n---\n".join(
                    f"{i} tier={t_} bytes={b}\n{st}"
                    for i, t_, b, st in leaks)
                from ..memory.memsan import LifecycleViolation
                raise LifecycleViolation(
                    f"query leaked {len(leaks)} spillable "
                    f"buffer(s) (memory.tpu.debug):\n{detail}")
        if tracer is not None:
            self._flush_query_obs(tracer, None, eventlog_dir)
        return result

    def _memsan_uninstall(self, memsan) -> None:
        if self._obs_isolation:
            memsan.uninstall_local()
        else:
            memsan.uninstall()

    # -- byte-weighted admission (multi-tenant serving) ---------------------

    def _admit_plan(self, final_plan):
        """Admission for one prepared plan: its tmsan static peak-
        device-bytes bound (TPU-L014) is the ticket.  A bound past the
        whole budget first re-plans through the out-of-core repair so
        the re-analyzed bound fits; then the ticket queues FIFO in the
        controller.  Returns (ticket, controller), (None, None) when
        admission is unconfigured — the single-tenant fast path."""
        from ..memory.admission import AdmissionController
        controller = AdmissionController.get()
        if controller is None:
            return None, None
        conf = self.conf
        bound = self._static_peak_bound(final_plan, conf)
        repaired = False
        if bound is not None and bound > controller.budget_bytes:
            repaired = self._repair_for_admission(
                final_plan, controller.budget_bytes)
            if repaired:
                bound = self._static_peak_bound(
                    final_plan, conf,
                    budget=controller.budget_bytes) or bound
        ticket = controller.admit(
            0 if bound is None else int(bound),
            label=type(final_plan).__name__,
            timeout_s=conf.get(cfg.SERVE_ADMISSION_TIMEOUT_MS) / 1000.0,
            repaired=repaired,
            # pool sessions carry their slot id (api/pool.py); a
            # standalone session books under the default tenant
            tenant=getattr(self, "_tenant", ""))
        return ticket, controller

    def _static_peak_bound(self, final_plan, conf,
                           budget=None) -> Optional[int]:
        """The plan's conservative peak-HBM bound from the lifetime
        pass; None when the analyzer cannot produce one (the query then
        rides an unweighted 0-byte ticket — admission stays advisory,
        never wrong-side-blocking)."""
        try:
            from ..analysis.lifetime import analyze_memory
            c = conf if budget is None else \
                conf.set(cfg.MEMSAN_HBM_BUDGET.key, int(budget))
            b = analyze_memory(final_plan, c).bound(final_plan)
            return None if b is None else int(b)
        except Exception:
            return None

    def _repair_for_admission(self, final_plan, budget) -> bool:
        """Re-plan an oversized ticket through the existing TPU-L014
        repair: run the lifetime pass against the ADMISSION budget and
        force oc_budget on each repairable frontier node (sort /
        aggregate merge), so the query co-runs out-of-core instead of
        hogging the whole budget."""
        try:
            from ..analysis.lifetime import (analyze_memory,
                                             try_outofcore_repair)
            conf2 = self.conf.set(cfg.MEMSAN_HBM_BUDGET.key,
                                  int(budget))
            res = analyze_memory(final_plan, conf2)
            done = False
            for d in res.diags:
                if d.code == "TPU-L014" and d.node is not None:
                    try:
                        done = try_outofcore_repair(
                            final_plan, d.node, conf2) or done
                    except Exception:
                        pass  # unrepairable node: queue at full size
            return done
        except Exception:
            return False

    # -- continuous metrics -------------------------------------------------
    _health_monitor = None

    def metrics_snapshot(self) -> Dict:
        """The JSON health document the /healthz endpoint serves —
        status derived from arena exhaustion, memsan ledger state,
        heartbeat misses and device-probe liveness — plus the full
        Prometheus exposition text under ``prometheus`` (the same
        surface without running an HTTP server)."""
        from ..obs.health import HealthMonitor, render_prometheus
        if TpuSession._health_monitor is None:
            TpuSession._health_monitor = HealthMonitor()
        snap = TpuSession._health_monitor.snapshot()
        snap["prometheus"] = render_prometheus()
        return snap

    def hbm_report(self) -> Dict:
        """The HBM observatory's occupancy-attribution answer: each
        tenant's resident device bytes split into pinned vs demotable
        (spillable-now) vs closed-pending, plus staging-arena fill and
        admission reservations (obs/memprof.py).  Returns a
        disabled-shaped report when hbm.timeline.enabled is off."""
        from ..obs.memprof import MemoryTimeline
        return MemoryTimeline.get().report()

    # -- flight recorder ----------------------------------------------------
    def last_query_trace(self):
        """The obs.QueryTrace of the last traced query (None when both
        spark.rapids.tpu.trace.enabled and eventLog.dir were unset)."""
        return self._last_trace

    def _install_predictions(self, tracer, final_plan) -> None:
        """Attach the CBO/interp row+byte model and tmsan's static
        peak-HBM bound to the trace, keyed by plan node — actuals are
        recorded at span close and the pair feeds `tools profile
        --accuracy` (the feedback signal for CBO tuning)."""
        if tracer is None:
            return
        try:
            from ..analysis.interp import infer_plan
            from ..analysis.lifetime import analyze_memory, total_bytes
            from ..obs.estimator import signature_of
            interp = infer_plan(final_plan, self.conf)
            mem = analyze_memory(final_plan, self.conf, interp)

            def visit(n):
                st = interp.state(n)
                if st is None:
                    return
                bound = mem.bound(n)
                tracer.predictions[id(n)] = {
                    "node": type(n).__name__,
                    "sig": signature_of(n),
                    "rows": None if st.rows is None else int(st.rows),
                    "bytes": int(total_bytes(st)),
                    "peakHbmBound": None if bound is None
                    else int(bound),
                }
            final_plan.foreach(visit)
            bound = mem.bound(final_plan)
            tracer.static_peak_bound = bound
            # the progress observatory blends the same per-node row
            # model into its ETA — feed it the ledger we just built
            from ..obs import progress as prog
            handle = prog.current_handle()
            if handle is not None:
                handle.set_predictions(tracer.predictions)
        except Exception:
            # the model is advisory: an analyzer crash must degrade the
            # accuracy report, never the query
            pass

    def _flush_query_obs(self, tracer, error, eventlog_dir) -> None:
        """Seal the trace and append the query to the event log — the
        single exit point for success, speculation-retry and failure
        paths alike (idempotent: re-entry on a writer error is a no-op).
        """
        if tracer is None or getattr(tracer, "_flush_done", False):
            return
        tracer._flush_done = True
        final_plan = self._obs_plan
        if final_plan is not None:
            try:
                from ..exec.base import drain_plan_metrics
                drain_plan_metrics(final_plan)  # ONE device crossing
            except Exception:
                pass  # a dead device must not mask the query's error
        tracer.finalize(error=error)
        try:
            # distill predicted-vs-actual into the estimator ledger —
            # the signal the feedback blend and `bench --accuracy` read
            from ..obs.estimator import EstimatorLedger
            EstimatorLedger.get().record_query(
                tracer.predictions, tracer.actuals,
                static_bound=getattr(tracer, "static_peak_bound", None),
                measured_peak=getattr(
                    tracer, "measured_peak_device_bytes", None))
        except Exception:
            pass  # grading is advisory; never mask the query's outcome
        try:
            # critical-path extraction + SLO accounting: annotates the
            # root span (so the event-log write below carries it into
            # Perfetto), bumps the per-segment counters and feeds the
            # latency observatory's burn window / tail reservoir
            from ..obs.critpath import record_query_latency
            record_query_latency(
                tracer, tenant=getattr(self, "_tenant", "") or "default",
                error=error,
                label=type(final_plan).__name__ if final_plan is not None
                else "")
        except Exception:
            pass  # attribution is advisory; never mask the query's outcome
        if eventlog_dir is None or final_plan is None:
            return
        sql_id = self._sql_counter
        self._sql_counter += 1
        try:
            writer = self._event_log_writer(eventlog_dir)
            writer.write_query(
                sql_id, final_plan, tracer,
                error=repr(error) if error is not None else None,
                description=f"{type(final_plan).__name__} "
                            f"(query {sql_id})")
        except Exception:
            if error is None:
                raise  # an unwritable event log must surface somewhere
            # ...but never by masking the query's own failure

    def _maybe_postmortem(self, error, tracer) -> None:
        """Failure black box: dump a bounded post-mortem bundle for a
        failed query (operator error, dirty memsan ledger, admission
        timeout — they all unwind through here).  Strictly best-effort:
        a black-box crash must never mask the query's own error."""
        try:
            conf = self.conf
            if not conf.get(cfg.HBM_POSTMORTEM_ENABLED):
                return
            out_dir = conf.get(cfg.HBM_POSTMORTEM_DIR) or \
                conf.get(cfg.REGRESS_HISTORY_DIR)
            if not out_dir:
                return
            from ..obs.postmortem import dump_postmortem
            path = dump_postmortem(
                out_dir, error, session=self, tracer=tracer,
                plan=self._obs_plan,
                tenant=getattr(self, "_tenant", "") or "default",
                max_bundles=conf.get(cfg.HBM_POSTMORTEM_MAX_BUNDLES))
            if path and tracer is not None:
                # point the self-emitted event log at the bundle: the
                # writer records the sealed trace's spans, so a late
                # instant span is visible in the JobFailed group
                eventlog_dir = conf.get(cfg.EVENT_LOG_DIR)
                if eventlog_dir:
                    try:
                        writer = self._event_log_writer(eventlog_dir)
                        writer.write_postmortem_pointer(path)
                    except Exception:
                        pass
        except Exception:
            pass

    def _event_log_writer(self, directory: str):
        w = self._obs_writer
        if w is None or w.directory != directory:
            import uuid
            from ..obs.eventlog_writer import EventLogWriter
            w = EventLogWriter(
                directory, app_id=f"tpu-{uuid.uuid4().hex[:12]}",
                spark_version=getattr(self.shim, "version", ""),
                conf_map=self._conf_map)
            self._obs_writer = w
        return w

    def explain(self, lp: L.LogicalPlan) -> str:
        final_plan = self.prepare_plan(lp, run_subqueries=False)
        return final_plan.tree_string() + "\n--\n" + self.last_explain


class _Builder:
    def __init__(self):
        self._conf: Dict = {}

    def config(self, key, value):
        self._conf[key] = value
        return self

    def get_or_create(self) -> TpuSession:
        return TpuSession(self._conf)


def last_query_metrics(session: TpuSession, level: str = None):
    """(operator, metric, value) rows from the last executed plan at the
    configured verbosity (ref GpuMetric levels feeding the SQL UI)."""
    from ..exec.base import metrics_report
    lvl = level or session.conf.get(cfg.METRICS_LEVEL)
    if session.last_plan is None:
        return []
    return metrics_report(session.last_plan, lvl)
