"""SessionPool: N logical TpuSessions multiplexed over the ONE
process-wide runtime for multi-tenant serving.

The heavyweight state — device manager, spill catalog, shuffle manager,
staging arena, MetricsRegistry, CompileObservatory, persistent compile
cache — is process-wide by construction (each is a singleton the plugin
bootstrap initializes idempotently), so pooling sessions costs the
per-session bookkeeping only: last-plan/explain slots, the event-log
writer (one app id per session, so concurrent queries never interleave
in one log) and the per-query flight-recorder trace.

Borrowing binds the session to the calling thread
(``TpuSession.bind_to_thread``), so library code resolving
``TpuSession.active()`` mid-query sees the borrower's session; pool
sessions run with ``_obs_isolation`` on, which installs the tracer and
the memsan shadow ledger THREAD-LOCALLY — a per-query clean check never
flags a co-running query's live buffers as leaks, and spans never
interleave across traces.

Byte-weighted co-running is the admission controller's job
(memory/admission.py, ``spark.rapids.tpu.serve.*``): the pool bounds
how many queries are in flight, the controller bounds how many BYTES.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, Optional

from .. import config as cfg
from ..config import RapidsConf
from .session import TpuSession


class PoolClosedError(RuntimeError):
    """Borrow refused because the pool is closed — typed (tpufsan
    TPU-R013) so serving callers can tell shutdown from capacity;
    subclasses RuntimeError so pre-taxonomy callers keep working."""


class PoolTimeout(TimeoutError):
    """No idle session (borrow) or still-busy sessions (drain) within
    the deadline; subclasses TimeoutError for pre-taxonomy callers."""


class SessionPool:
    """Fixed-size pool of TpuSessions sharing one process runtime."""

    def __init__(self, size: Optional[int] = None,
                 conf: Optional[Dict] = None):
        conf_map = dict(conf or {})
        rc = RapidsConf(conf_map)
        self.size = int(size) if size is not None else \
            rc.get(cfg.SERVE_POOL_SIZE)
        if self.size < 1:
            raise ValueError(f"pool size must be >= 1, got {self.size}")
        self._cv = threading.Condition()
        # csan lock witness: deferred no-op unless the witness is
        # installed (spark.rapids.tpu.csan.enabled)
        from ..obs import lockwitness
        lockwitness.maybe_register("api.pool.SessionPool._cv", self,
                                   "_cv")
        self._closed = False
        self._sessions = []
        for i in range(self.size):
            s = TpuSession(conf_map)
            s._obs_isolation = True
            # the tenant label its admission tickets book under — the
            # pool-session id by default (ISSUE: per-tenant accounting
            # on tpu_admission_* counters and queue gauges)
            s._tenant = f"pool-{i}"
            self._sessions.append(s)
        self._idle = deque(self._sessions)

    # -- borrow / return ------------------------------------------------------
    def _borrow(self, timeout: Optional[float]) -> TpuSession:
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        from ..obs import metrics as m
        with self._cv:
            while not self._idle:
                if self._closed:
                    raise PoolClosedError("SessionPool is closed")
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise PoolTimeout(
                        f"no idle session within {timeout:g}s "
                        f"(pool size {self.size})")
                self._cv.wait(remaining)
            if self._closed:
                raise PoolClosedError("SessionPool is closed")
            s = self._idle.popleft()
            m.gauge("tpu_session_pool_in_use",
                    "pool sessions currently borrowed") \
                .set(self.size - len(self._idle))
            return s

    def _return(self, s: TpuSession) -> None:
        from ..obs import metrics as m
        with self._cv:
            self._idle.append(s)
            m.gauge("tpu_session_pool_in_use",
                    "pool sessions currently borrowed") \
                .set(self.size - len(self._idle))
            self._cv.notify_all()

    @contextlib.contextmanager
    def session(self, timeout: Optional[float] = None):
        """Borrow a session, bound to the calling thread for the
        duration (``TpuSession.active()`` resolves to it)."""
        s = self._borrow(timeout)
        TpuSession.bind_to_thread(s)
        try:
            yield s
        finally:
            TpuSession.bind_to_thread(None)
            self._return(s)

    def run(self, fn, timeout: Optional[float] = None):
        """``fn(session)`` on a borrowed session (the one-liner most
        serving threads want)."""
        with self.session(timeout) as s:
            return fn(s)

    def cancel(self, tenant: str, query_id: str) -> bool:
        """Request cooperative cancellation of an in-flight query by
        (tenant, query_id) — the pair every live-view row carries
        (``GET /queries`` / ``tools top``).  Returns True if a live
        query matched."""
        from ..obs.progress import ProgressTracker
        return ProgressTracker.get().cancel(query_id, tenant=tenant)

    # -- observability --------------------------------------------------------
    def hbm_report(self) -> Dict:
        """Pool-level HBM occupancy rollup: the process-wide observatory
        report (the timeline is a singleton — every pool session's
        queries book into it under their ``pool-<i>`` tenant) plus a
        whale line: which tenant holds the most resident bytes right
        now, and each tenant's share of the pool total."""
        from ..obs.memprof import MemoryTimeline
        rep = MemoryTimeline.get().report()
        total = rep.get("total_bytes") or 0
        whale, whale_bytes = None, 0
        for tenant, row in rep.get("tenants", {}).items():
            resident = row.get("resident_bytes", 0)
            row["share"] = round(resident / total, 4) if total else 0.0
            if resident > whale_bytes:
                whale, whale_bytes = tenant, resident
        rep["pool_size"] = self.size
        rep["whale_tenant"] = whale
        rep["whale_bytes"] = whale_bytes
        return rep

    def slo_report(self) -> Dict:
        """Pool-level SLO rollup from the latency observatory (a
        singleton — every pool session's traced queries record into it
        under their ``pool-<i>`` tenant): per-tenant good/total counts,
        windowed burn rate, p50/p99 and the dominant tail segment,
        plus a worst-burn line mirroring hbm_report's whale line."""
        from ..obs.slo import LatencyObservatory
        rep = LatencyObservatory.get().slo_report()
        worst, worst_burn = None, 0.0
        for tenant, row in rep.get("tenants", {}).items():
            if row.get("burn_rate", 0.0) > worst_burn:
                worst, worst_burn = tenant, row["burn_rate"]
        rep["pool_size"] = self.size
        rep["worst_burn_tenant"] = worst
        rep["worst_burn_rate"] = worst_burn
        return rep

    # -- lifecycle ------------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every session is idle (all in-flight queries
        done) — the quiesce point the serve gate checks orphaned
        shuffles after."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while len(self._idle) < self.size:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise PoolTimeout(
                        f"pool did not drain within {timeout:g}s "
                        f"({self.size - len(self._idle)} busy)")
                self._cv.wait(remaining)

    def close(self) -> None:
        """Refuse further borrows; idle sessions stay usable directly
        (the process-wide runtime they share outlives the pool)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def idle(self) -> int:
        with self._cv:
            return len(self._idle)
