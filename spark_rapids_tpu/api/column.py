"""Column wrapper: operator overloading over the expression IR
(mirrors pyspark.sql.Column)."""

from __future__ import annotations

from typing import Optional, Tuple

from ..expr import arithmetic as ar
from ..expr import predicates as pred
from ..expr.cast import Cast
from ..expr.core import (Alias, AttributeReference, Expression, Literal,
                         output_name)
from .. import types as t


def _expr(v) -> Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, Expression):
        return v
    return Literal(v)


class Column:
    def __init__(self, expr: Expression, alias: Optional[str] = None,
                 sort_order: Optional[Tuple[bool, bool]] = None):
        self.expr = expr
        self._alias = alias
        self._sort_order = sort_order

    # arithmetic
    def __add__(self, o):
        return Column(ar.Add(self.expr, _expr(o)))

    def __radd__(self, o):
        return Column(ar.Add(_expr(o), self.expr))

    def __sub__(self, o):
        return Column(ar.Subtract(self.expr, _expr(o)))

    def __rsub__(self, o):
        return Column(ar.Subtract(_expr(o), self.expr))

    def __mul__(self, o):
        return Column(ar.Multiply(self.expr, _expr(o)))

    def __rmul__(self, o):
        return Column(ar.Multiply(_expr(o), self.expr))

    def __truediv__(self, o):
        return Column(ar.Divide(self.expr, _expr(o)))

    def __rtruediv__(self, o):
        return Column(ar.Divide(_expr(o), self.expr))

    def __mod__(self, o):
        return Column(ar.Remainder(self.expr, _expr(o)))

    def __neg__(self):
        return Column(ar.UnaryMinus(self.expr))

    # comparisons
    def __eq__(self, o):  # type: ignore[override]
        return Column(pred.EqualTo(self.expr, _expr(o)))

    def __ne__(self, o):  # type: ignore[override]
        return Column(pred.Not(pred.EqualTo(self.expr, _expr(o))))

    def __lt__(self, o):
        return Column(pred.LessThan(self.expr, _expr(o)))

    def __le__(self, o):
        return Column(pred.LessThanOrEqual(self.expr, _expr(o)))

    def __gt__(self, o):
        return Column(pred.GreaterThan(self.expr, _expr(o)))

    def __ge__(self, o):
        return Column(pred.GreaterThanOrEqual(self.expr, _expr(o)))

    # boolean
    def __and__(self, o):
        return Column(pred.And(self.expr, _expr(o)))

    def __or__(self, o):
        return Column(pred.Or(self.expr, _expr(o)))

    def __invert__(self):
        return Column(pred.Not(self.expr))

    # null / membership
    def is_null(self):
        return Column(pred.IsNull(self.expr))

    isNull = is_null

    def is_not_null(self):
        return Column(pred.IsNotNull(self.expr))

    isNotNull = is_not_null

    def isin(self, *vals):
        if len(vals) == 1 and isinstance(vals[0], (list, tuple)):
            vals = tuple(vals[0])
        return Column(pred.In(self.expr, [Literal(v) for v in vals]))

    def eq_null_safe(self, o):
        return Column(pred.EqualNullSafe(self.expr, _expr(o)))

    eqNullSafe = eq_null_safe

    # misc
    def alias(self, name: str):
        return Column(Alias(self.expr, name), alias=name)

    def cast(self, to):
        if isinstance(to, str):
            to = _parse_type(to)
        elif not isinstance(to, t.DataType):
            import pyarrow as pa
            if isinstance(to, pa.DataType):
                from ..columnar.interop import from_arrow_type
                to = from_arrow_type(to)
        return Column(Cast(self.expr, to))

    def asc(self):
        return Column(self.expr, self._alias, sort_order=(True, True))

    def desc(self):
        return Column(self.expr, self._alias, sort_order=(False, False))

    def asc_nulls_last(self):
        return Column(self.expr, self._alias, sort_order=(True, False))

    def desc_nulls_first(self):
        return Column(self.expr, self._alias, sort_order=(False, True))

    def substr(self, start, length):
        from ..expr.strings import Substring
        return Column(Substring(self.expr, Literal(start), Literal(length)))

    def contains(self, s):
        from ..expr.strings import Contains
        return Column(Contains(self.expr, _expr(s)))

    def rlike(self, pattern: str):
        from ..expr.regex import RLike
        return Column(RLike(self.expr, Literal(pattern)))

    def getItem(self, key):
        from ..expr.complextype import GetArrayItem, GetStructField
        if isinstance(key, str):
            return Column(GetStructField(self.expr, key))
        return Column(GetArrayItem(self.expr, _expr(key)))

    def getField(self, name: str):
        from ..expr.complextype import GetStructField
        return Column(GetStructField(self.expr, name))

    def __getitem__(self, key):
        return self.getItem(key)

    def startswith(self, s):
        from ..expr.strings import StartsWith
        return Column(StartsWith(self.expr, _expr(s)))

    def endswith(self, s):
        from ..expr.strings import EndsWith
        return Column(EndsWith(self.expr, _expr(s)))

    def over(self, window) -> "Column":
        from ..expr.aggregates import AggregateExpression
        from ..expr.window import WindowBuilder, WindowExpression
        spec = window.spec if isinstance(window, WindowBuilder) else window
        e = self.expr
        if isinstance(e, Alias):
            name = e.name
            e = e.child
        else:
            name = self._alias
        if isinstance(e, AggregateExpression):
            e = e.func
        return Column(WindowExpression(e, spec, name))

    def __repr__(self):
        return f"Column<{self.expr.sql()}>"


def _parse_type(s: str) -> t.DataType:
    s = s.strip().lower()
    simple = {
        "boolean": t.BOOLEAN, "bool": t.BOOLEAN,
        "byte": t.BYTE, "tinyint": t.BYTE,
        "short": t.SHORT, "smallint": t.SHORT,
        "int": t.INT, "integer": t.INT,
        "long": t.LONG, "bigint": t.LONG,
        "float": t.FLOAT, "double": t.DOUBLE,
        "string": t.STRING, "binary": t.BINARY,
        "date": t.DATE, "timestamp": t.TIMESTAMP,
    }
    if s in simple:
        return simple[s]
    if s.startswith("decimal"):
        import re
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", s)
        if m:
            return t.DecimalType(int(m.group(1)), int(m.group(2)))
        return t.DecimalType(10, 0)
    raise ValueError(f"cannot parse type {s!r}")


def col(name: str) -> Column:
    return Column(AttributeReference(name))


def lit(v) -> Column:
    return Column(Literal(v))
