"""DataFrame API mirroring Spark's (the surface the reference accelerates).

Builds logical plans; execution happens in TpuSession.execute via the
planner + overrides engine.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import pyarrow as pa

from ..expr.core import (Alias, AttributeReference, Expression, Literal,
                         output_name)
from ..plan import logical as L
from .column import Column, col, lit


def _to_expr(c) -> Expression:
    if isinstance(c, Column):
        return c.expr
    if isinstance(c, Expression):
        return c
    if isinstance(c, str):
        return AttributeReference(c)
    return Literal(c)


def _lower_sliding_windows(lp, exprs):
    """Spark's TimeWindowing rule: a sliding window(ts, w, s) expands
    each row into ceil(w/s) per-slide copies, filtered to the windows
    that actually contain ts, and downstream expressions reference the
    materialized window column (ref
    org/apache/spark/sql/rapids/TimeWindow.scala + Spark's analysis
    lowering through Expand).  Returns (new_lp, new_exprs)."""
    import math

    from ..expr.complextype import GetStructField
    from ..expr.core import Alias as _Alias
    from ..expr.core import AttributeReference as _Attr
    from ..expr.datetime_expr import TimeWindow
    from ..expr.predicates import And, GreaterThan, LessThanOrEqual

    all_sliding = []
    for e in exprs:
        all_sliding += e.collect(
            lambda x: isinstance(x, TimeWindow) and
            not x.is_tumbling and x.copy_index is None)
    if not all_sliding:
        return lp, exprs
    keys = {(w.window, w.slide, w.start, w.children[0].sql())
            for w in all_sliding}
    if len(keys) > 1:
        # Spark raises AnalysisException for multiple time windows in
        # one projection; substituting one Expand for both would
        # silently return the wrong windows
        raise ValueError(
            "only one sliding time window is allowed per "
            "select/groupBy (Spark's TimeWindowing restriction)")
    sliding = all_sliding[0]
    wname = None
    for e in exprs:
        if isinstance(e, _Alias) and e.child in all_sliding:
            wname = e.name
            break
    names, _ = lp.schema()
    if wname is None:
        wname = "window"
        while wname in names:
            wname = "_" + wname
    elif wname in names:
        raise ValueError(
            f"window alias {wname!r} collides with an input column")
    n_copies = math.ceil(sliding.window / sliding.slide)
    projections = []
    for i in range(n_copies):
        proj = [_Attr(n) for n in names]
        proj.append(TimeWindow(sliding.children[0], sliding.window,
                               sliding.slide, sliding.start,
                               copy_index=i))
        projections.append(proj)
    out_names = list(names) + [wname]
    expanded = L.Expand(projections, out_names, lp)
    wref = _Attr(wname)
    ts = sliding.children[0]
    keep = And(GreaterThan(GetStructField(wref, "end"), ts),
               LessThanOrEqual(GetStructField(wref, "start"), ts))
    filtered = L.Filter(keep, expanded)

    def substitute(e):
        def fn(x):
            # only the single lowered window shape substitutes (the
            # multi-window case raised above)
            if (isinstance(x, TimeWindow) and not x.is_tumbling and
                    x.copy_index is None):
                return _Attr(wname)
            return x
        if isinstance(e, _Alias) and e.child in all_sliding:
            return _Attr(wname) if e.name == wname else \
                _Alias(_Attr(wname), e.name)
        return e.transform_up(fn)

    return filtered, [substitute(e) for e in exprs]


class DataFrame:
    def __init__(self, lp: L.LogicalPlan, session):
        self._lp = lp
        self.session = session

    # -- schema -------------------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return self._lp.schema()[0]

    @property
    def dtypes(self):
        names, types = self._lp.schema()
        return list(zip(names, [t.name for t in types]))

    def __getitem__(self, name: str) -> Column:
        return col(name)

    # -- transformations ----------------------------------------------------
    def select(self, *cols) -> "DataFrame":
        from ..expr.window import WindowExpression
        exprs = []
        for c in cols:
            if isinstance(c, str) and c == "*":
                exprs += [AttributeReference(n) for n in self.columns]
            else:
                e = _to_expr(c)
                if isinstance(e, Alias) and isinstance(e.child,
                                                       WindowExpression):
                    e.child.name = e.name
                    e = e.child
                if isinstance(c, Column) and c._alias and \
                        isinstance(e, WindowExpression):
                    e.name = c._alias
                exprs.append(e)
        # route generators (explode/posexplode) through a Generate node
        from ..expr.collection import Generator
        gens = [e for e in exprs
                if isinstance(e, Generator) or
                (isinstance(e, Alias) and isinstance(e.child, Generator))]
        if gens:
            if len(gens) > 1:
                raise ValueError("only one generator per select")
            g = gens[0]
            out_names = []
            if isinstance(g, Alias):
                out_names = [g.name]
                g = g.child
            gen_names = list(g._out_names)
            if not out_names:
                out_names = gen_names
            elif len(gen_names) == 2:  # posexplode with single alias
                out_names = ["pos", out_names[0]]
            base = L.Generate(g, getattr(g, "outer", False), out_names,
                              self._lp)
            child_names = self.columns
            proj = []
            for e in exprs:
                if isinstance(e, Generator) or \
                        (isinstance(e, Alias) and
                         isinstance(e.child, Generator)):
                    proj += [AttributeReference(n) for n in out_names]
                else:
                    proj.append(e)
            return DataFrame(L.Project(proj, base), self.session)
        # sliding time windows lower through Expand + Filter first;
        # re-entering select lets the remaining routing (generators,
        # window expressions) see the substituted expressions
        base_lp, exprs = _lower_sliding_windows(self._lp, exprs)
        if base_lp is not self._lp:
            from .column import Column as _Col
            return DataFrame(base_lp, self.session).select(
                *[_Col(e) for e in exprs])
        # route window expressions through a Window node, then project
        windows = [e for e in exprs if isinstance(e, WindowExpression)]
        if windows:
            base = L.Window(windows, self._lp)
            proj = []
            for e in exprs:
                if isinstance(e, WindowExpression):
                    proj.append(AttributeReference(e.name))
                else:
                    proj.append(e)
            return DataFrame(L.Project(proj, base), self.session)
        return DataFrame(L.Project(exprs, self._lp), self.session)

    def with_column(self, name: str, c) -> "DataFrame":
        cols = [col(n) for n in self.columns if n != name]
        cc = c if isinstance(c, Column) else Column(_to_expr(c))
        return self.select(*cols, cc.alias(name))

    withColumn = with_column

    def filter(self, condition) -> "DataFrame":
        return DataFrame(L.Filter(_to_expr(condition), self._lp),
                         self.session)

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        exprs = [_to_expr(c) for c in cols]
        base_lp, exprs = _lower_sliding_windows(self._lp, exprs)
        df = self if base_lp is self._lp else \
            DataFrame(base_lp, self.session)
        return GroupedData(exprs, df)

    groupBy = group_by

    def rollup(self, *cols) -> "GroupedData":
        """GROUP BY ROLLUP — grouping sets [(k1..kn), (k1..kn-1), ..., ()]
        via an Expand below the aggregate (ref GpuExpandExec)."""
        return GroupedData([_to_expr(c) for c in cols], self, mode="rollup")

    def cube(self, *cols) -> "GroupedData":
        """GROUP BY CUBE — all subsets of the grouping keys."""
        return GroupedData([_to_expr(c) for c in cols], self, mode="cube")

    def sample(self, fraction: float, seed: Optional[int] = None
               ) -> "DataFrame":
        return DataFrame(L.Sample(fraction,
                                  seed if seed is not None else 42,
                                  self._lp), self.session)

    def agg(self, *aggs) -> "DataFrame":
        return self.group_by().agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"leftsemi": "left_semi", "semi": "left_semi",
               "leftanti": "left_anti", "anti": "left_anti",
               "outer": "full", "fullouter": "full",
               "left_outer": "left", "right_outer": "right"}.get(
                   how.lower().replace("_", ""), how.lower())
        cond = None
        using = None
        if on is not None:
            if isinstance(on, str):
                using = [on]
            elif isinstance(on, (list, tuple)) and on and \
                    isinstance(on[0], str):
                using = list(on)
            else:
                cond = _to_expr(on)
        return DataFrame(L.Join(self._lp, other._lp, how, cond, using),
                         self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(L.Union([self._lp, other._lp]), self.session)

    unionAll = union

    def order_by(self, *cols, ascending=True) -> "DataFrame":
        orders = []
        for i, c in enumerate(cols):
            if isinstance(c, Column) and c._sort_order is not None:
                asc, nf = c._sort_order
                orders.append((c.expr, asc, nf))
            else:
                asc = ascending if isinstance(ascending, bool) \
                    else ascending[i]
                orders.append((_to_expr(c), asc, asc))
        return DataFrame(L.Sort(orders, True, self._lp), self.session)

    orderBy = order_by
    sort = order_by

    def sort_within_partitions(self, *cols, ascending=True) -> "DataFrame":
        orders = [(_to_expr(c), ascending, ascending) for c in cols]
        return DataFrame(L.Sort(orders, False, self._lp), self.session)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self._lp), self.session)

    def distinct(self) -> "DataFrame":
        return DataFrame(L.Distinct(self._lp), self.session)

    def drop(self, *names) -> "DataFrame":
        keep = [AttributeReference(n) for n in self.columns
                if n not in names]
        return DataFrame(L.Project(keep, self._lp), self.session)

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        exprs = [Alias(AttributeReference(n), new) if n == old
                 else AttributeReference(n) for n in self.columns]
        return DataFrame(L.Project(exprs, self._lp), self.session)

    withColumnRenamed = with_column_renamed

    def repartition(self, num_partitions: int, *cols) -> "DataFrame":
        keys = [_to_expr(c) for c in cols] or None
        return DataFrame(L.Repartition(num_partitions, keys, self._lp),
                         self.session)

    def select_expr_window(self, *window_exprs) -> "DataFrame":
        return DataFrame(L.Window(list(window_exprs), self._lp), self.session)

    def mapInPandas(self, fn, schema) -> "DataFrame":
        """Map partitions through fn(iterator[pd.DataFrame]) ->
        iterator[pd.DataFrame] (ref GpuMapInPandasExec)."""
        names, dtypes = _parse_schema(schema)
        return DataFrame(L.MapInPandas(fn, names, dtypes, self._lp),
                         self.session)

    map_in_pandas = mapInPandas

    # -- caching ------------------------------------------------------------
    def cache(self) -> "DataFrame":
        """Mark for parquet-cached-batch materialization on the next
        action (ref ParquetCachedBatchSerializer; gated by shim like the
        reference's 3.1.1+ support)."""
        shim = getattr(self.session, "shim", None)
        if shim is not None and not shim.cached_batch_serializer_supported():
            return self  # dialect too old: cache() is a no-op recompute
        from ..io.cached_batch import CacheManager
        CacheManager.cache(self._lp)
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        from ..io.cached_batch import CacheManager
        CacheManager.uncache(self._lp)
        return self

    @property
    def is_cached(self) -> bool:
        from ..io.cached_batch import CacheManager
        return CacheManager.lookup(self._lp) is not None

    # -- actions ------------------------------------------------------------
    def collect(self) -> pa.Table:
        return self.session.execute(self._lp)

    def to_pandas(self):
        return self.collect().to_pandas()

    toPandas = to_pandas

    def count(self) -> int:
        from .functions import count
        res = self.agg(count(lit(1)).alias("count")).collect()
        return res.column("count").to_pylist()[0]

    def show(self, n: int = 20):
        print(self.limit(n).collect().to_pandas().to_string())

    def explain(self) -> str:
        s = self.session.explain(self._lp)
        print(s)
        return s

    # -- writers ------------------------------------------------------------
    @property
    def write(self):
        from ..io.writer import DataFrameWriter
        return DataFrameWriter(self)


class GroupedData:
    def __init__(self, grouping: List[Expression], df: DataFrame,
                 mode: str = "groupby"):
        self.grouping = grouping
        self.df = df
        self.mode = mode

    def _grouping_sets_plan(self) -> "tuple":
        """Build the Expand feeding a rollup/cube aggregate.  Returns
        (expand_lp, grouping_exprs, rewrite) — grouping is the nulled key
        copies plus the synthetic spark_grouping_id (distinguishing
        natural-null keys from keys absent in a grouping set, Spark's
        grouping__id); `rewrite` maps aggregate inputs onto untouched
        copies of every input column, so aggregating a grouping key sees
        the original values (Spark keeps both copies in its Expand too)."""
        import itertools
        keys = self.grouping
        if not all(isinstance(k, AttributeReference) for k in keys):
            raise TypeError("rollup/cube keys must be plain columns")
        names, dtypes = self.df._lp.schema()
        idx = {n: i for i, n in enumerate(names)}
        key_names = [k.name for k in keys]
        nk = len(keys)
        if self.mode == "rollup":
            sets = [tuple(range(nk - i)) for i in range(nk + 1)]
        else:  # cube
            sets = []
            for r in range(nk, -1, -1):
                sets += list(itertools.combinations(range(nk), r))
        orig = {n: f"__orig_{n}" for n in names}
        projections = []
        for s in sets:
            gid = 0
            proj = [AttributeReference(n) for n in names]  # agg inputs
            for i, k in enumerate(keys):
                if i in s:
                    proj.append(AttributeReference(k.name))
                else:
                    gid |= 1 << (nk - 1 - i)
                    proj.append(Literal(None, dtypes[idx[k.name]]))
            proj.append(Literal(gid))
            projections.append(proj)
        out_names = [orig[n] for n in names] + key_names + \
            ["spark_grouping_id"]
        expand = L.Expand(projections, out_names, self.df._lp)
        grouping = [AttributeReference(n) for n in key_names] + \
            [AttributeReference("spark_grouping_id")]

        def rewrite(e: Expression) -> Expression:
            def fn(x):
                if isinstance(x, AttributeReference) and x.name in orig:
                    return AttributeReference(orig[x.name], x.dtype)
                return x
            return e.transform_up(fn)
        return expand, grouping, rewrite

    def applyInPandas(self, fn, schema) -> DataFrame:
        """Grouped-map pandas UDF (ref GpuFlatMapGroupsInPandasExec)."""
        names, dtypes = _parse_schema(schema)
        return DataFrame(L.FlatMapGroupsInPandas(
            self.grouping, fn, names, dtypes, self.df._lp),
            self.df.session)

    apply_in_pandas = applyInPandas

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)

    def agg(self, *aggs) -> DataFrame:
        from ..expr.aggregates import AggregateExpression
        from .functions import PandasAggUDF
        # grouped-aggregate pandas UDFs route the whole aggregate through
        # AggregateInPandasExec (ref GpuAggregateInPandasExec); mixing
        # with regular aggregates is unsupported, like pyspark
        pandas_specs = []
        plain = []
        for a in aggs:
            e = a.expr if isinstance(a, Column) else a
            name = a._alias if isinstance(a, Column) else None
            if isinstance(e, Alias) and isinstance(e.child, PandasAggUDF):
                name, e = e.name, e.child
            if isinstance(e, PandasAggUDF):
                in_cols = [c.name if isinstance(c, AttributeReference)
                           else None for c in e.children]
                if any(c is None for c in in_cols):
                    raise TypeError(
                        "grouped-agg pandas UDF arguments must be plain "
                        "columns")
                pandas_specs.append(
                    (name or e.sql(), e.fn, e.rt, in_cols))
            else:
                plain.append(a)
        if pandas_specs:
            if plain:
                raise TypeError("cannot mix pandas grouped-agg UDFs with "
                                "built-in aggregates")
            if not all(isinstance(k, AttributeReference)
                       for k in self.grouping):
                raise TypeError("pandas grouped-agg needs plain column "
                                "grouping keys")
            return DataFrame(L.AggregateInPandas(
                self.grouping, pandas_specs, self.df._lp), self.df.session)
        out = []
        gid_aliases = []  # grouping_id() projections (rollup/cube only)
        for a in aggs:
            if isinstance(a, Column):
                e = a.expr
                name = a._alias
            else:
                e = a
                name = None
            from ..expr.core import Alias as _Alias
            if isinstance(e, _Alias) and isinstance(e.child,
                                                    AggregateExpression):
                name = e.name
                e = e.child
            if isinstance(e, _Alias) and \
                    isinstance(e.child, AttributeReference) and \
                    e.child.name == "spark_grouping_id":
                name = e.name
                e = e.child
            if isinstance(e, AttributeReference) and \
                    e.name == "spark_grouping_id":
                if self.mode not in ("rollup", "cube"):
                    raise TypeError(
                        "grouping_id() only valid with rollup/cube")
                gid_aliases.append(name or "grouping_id()")
                continue
            if isinstance(e, AggregateExpression):
                ae = e
                if name:
                    ae.name = name
            else:
                from ..expr.aggregates import AggregateFunction
                if isinstance(e, AggregateFunction):
                    ae = AggregateExpression(e, name)
                else:
                    raise TypeError(f"not an aggregate: {e}")
            out.append(ae)
        if self.mode in ("rollup", "cube"):
            from ..expr.aggregates import AggregateExpression as _AE
            expand, grouping, rewrite = self._grouping_sets_plan()
            out = [_AE(rewrite(ae.func), ae.name) for ae in out]
            agg_lp = L.Aggregate(grouping, out, expand)
            agg_names = agg_lp.schema()[0]
            keep = [AttributeReference(n) for n in agg_names
                    if n != "spark_grouping_id"]
            keep += [Alias(AttributeReference("spark_grouping_id"), n)
                     for n in gid_aliases]
            return DataFrame(L.Project(keep, agg_lp), self.df.session)
        if gid_aliases:
            raise TypeError("grouping_id() only valid with rollup/cube")
        if getattr(self, "_pivot", None) is not None:
            out = self._expand_pivot_aggs(out)
        return DataFrame(L.Aggregate(self.grouping, out, self.df._lp),
                         self.df.session)

    def pivot(self, pivot_col, values=None) -> "GroupedData":
        """df.groupBy(k).pivot(p, [v1, v2]).agg(...) — one output column
        per (pivot value, aggregate).

        TPU-first realization of the reference's pivot support
        (ref AggregateFunctions.scala GpuPivotFirst): each pivot value
        becomes a conditionally-masked aggregate
        `agg(IF(p == v, x, NULL))`, so the whole pivot is ONE pass
        through the existing sort+segment kernel and XLA fuses the N
        masks — no imperative per-value buffers.  When `values` is
        omitted they are collected from the data first, like Spark."""
        p = _to_expr(pivot_col)
        if values is None:
            vt = self.df.select(Column(p)).distinct().collect()
            values = sorted(vt.column(0).to_pylist(),
                            key=lambda v: (v is None, str(v)))
        g = GroupedData(self.grouping, self.df, self.mode)
        g._pivot = (p, list(values))
        return g

    def _expand_pivot_aggs(self, aggs):
        from ..expr.aggregates import (AggregateExpression, First,
                                       PivotFirst)
        from ..expr.conditional import If
        from ..expr.core import Literal
        from ..expr.predicates import EqualNullSafe
        p, values = self._pivot
        out = []
        for v in values:
            for ae in aggs:
                fn = ae.func
                if not fn.children:
                    raise TypeError(
                        "pivot aggregates need an input column "
                        "(count(*) unsupported, use count(col))")
                name = str(v) if len(aggs) == 1 else f"{v}_{ae.name}"
                if type(fn) is First:
                    # the canonical pivot lowering unit
                    # (ref GpuPivotFirst, GpuOverrides.scala:2034-2060)
                    out.append(AggregateExpression(
                        PivotFirst(p, fn.child, v), name))
                    continue
                from .. import types as _t
                masked = fn.with_children(
                    [If(EqualNullSafe(p, Literal(v)), fn.child,
                        Literal(None, _t.NULL))] +
                    list(fn.children[1:]))
                out.append(AggregateExpression(masked, name))
        return out

    def count(self) -> DataFrame:
        from .functions import count
        return self.agg(count(lit(1)).alias("count"))

    def _simple(self, fn, cols):
        from . import functions as F
        names = cols or [n for n, tn in self.df.dtypes
                         if tn in ("tinyint", "smallint", "int", "bigint",
                                   "float", "double") or
                         tn.startswith("decimal")]
        return self.agg(*[getattr(F, fn)(col(n)).alias(f"{fn}({n})")
                          for n in names])

    def sum(self, *cols) -> DataFrame:
        return self._simple("sum", list(cols))

    def avg(self, *cols) -> DataFrame:
        return self._simple("avg", list(cols))

    def min(self, *cols) -> DataFrame:
        return self._simple("min", list(cols))

    def max(self, *cols) -> DataFrame:
        return self._simple("max", list(cols))


class CoGroupedData:
    """Pair of grouped frames for cogrouped-map pandas UDFs
    (ref GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        self.left = left
        self.right = right

    def applyInPandas(self, fn, schema) -> DataFrame:
        names, dtypes = _parse_schema(schema)
        return DataFrame(L.CoGroupMapInPandas(
            self.left.grouping, self.right.grouping, fn, names, dtypes,
            self.left.df._lp, self.right.df._lp), self.left.df.session)

    apply_in_pandas = applyInPandas


def _parse_schema(schema):
    """'a int, b double' | pa.Schema | [(name, DataType)] -> names, types."""
    from ..columnar.interop import from_arrow_type
    if isinstance(schema, pa.Schema):
        return list(schema.names), [from_arrow_type(f.type) for f in schema]
    if isinstance(schema, str):
        from .column import _parse_type
        names, dtypes = [], []
        # split on commas at paren depth 0 so decimal(p,s) survives
        parts, depth, cur = [], 0, []
        for ch in schema:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                parts.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
        if cur:
            parts.append("".join(cur))
        for part in parts:
            toks = part.strip().split(None, 1)
            if len(toks) != 2:
                raise ValueError(f"cannot parse schema field {part!r}")
            names.append(toks[0])
            dtypes.append(_parse_type(toks[1].strip()))
        return names, dtypes
    names, dtypes = [], []
    for name, dt in schema:
        names.append(name)
        dtypes.append(dt)
    return names, dtypes
