"""pyspark.sql.functions-style function surface."""

from __future__ import annotations

from ..expr import aggregates as agg
from ..expr import arithmetic as ar
from ..expr import conditional as cond
from ..expr import mathexpr as mx
from ..expr import predicates as pred
from ..expr.cast import Cast
from ..expr.core import Alias, AttributeReference, Expression, Literal
from .column import Column, col, lit, _expr


def _c(e: Expression) -> Column:
    return Column(e)


# -- aggregates --------------------------------------------------------------

def sum(c) -> Column:  # noqa: A001
    return _c(agg.AggregateExpression(agg.Sum(_expr(c))))


def count(c="*") -> Column:
    child = None if (isinstance(c, str) and c == "*") else _expr(c)
    return _c(agg.AggregateExpression(agg.Count(child)))


def avg(c) -> Column:
    return _c(agg.AggregateExpression(agg.Average(_expr(c))))


mean = avg


def min(c) -> Column:  # noqa: A001
    return _c(agg.AggregateExpression(agg.Min(_expr(c))))


def max(c) -> Column:  # noqa: A001
    return _c(agg.AggregateExpression(agg.Max(_expr(c))))


def first(c, ignorenulls: bool = False) -> Column:
    return _c(agg.AggregateExpression(agg.First(_expr(c), ignorenulls)))


def last(c, ignorenulls: bool = False) -> Column:
    return _c(agg.AggregateExpression(agg.Last(_expr(c), ignorenulls)))


def stddev(c) -> Column:
    return _c(agg.AggregateExpression(agg.StddevSamp(_expr(c))))


stddev_samp = stddev


def stddev_pop(c) -> Column:
    return _c(agg.AggregateExpression(agg.StddevPop(_expr(c))))


def variance(c) -> Column:
    return _c(agg.AggregateExpression(agg.VarianceSamp(_expr(c))))


var_samp = variance


def var_pop(c) -> Column:
    return _c(agg.AggregateExpression(agg.VariancePop(_expr(c))))


def count_distinct(*cols) -> Column:
    from ..expr.aggregates import CountDistinct
    return _c(agg.AggregateExpression(CountDistinct([_expr(c) for c in cols])))


# -- scalar ------------------------------------------------------------------

def abs(c) -> Column:  # noqa: A001
    return _c(ar.Abs(_expr(c)))


def sqrt(c) -> Column:
    return _c(mx.Sqrt(_expr(c)))


def exp(c) -> Column:
    return _c(mx.Exp(_expr(c)))


def log(c) -> Column:
    return _c(mx.Log(_expr(c)))


def log2(c) -> Column:
    return _c(mx.Log2(_expr(c)))


def log10(c) -> Column:
    return _c(mx.Log10(_expr(c)))


def pow(l, r) -> Column:  # noqa: A001
    return _c(mx.Pow(_expr(l), _expr(r)))


def floor(c) -> Column:
    return _c(mx.Floor(_expr(c)))


def ceil(c) -> Column:
    return _c(mx.Ceil(_expr(c)))


def round(c, scale: int = 0) -> Column:  # noqa: A001
    return _c(mx.Round(_expr(c), scale))


def bround(c, scale: int = 0) -> Column:
    return _c(mx.BRound(_expr(c), scale))


def signum(c) -> Column:
    return _c(mx.Signum(_expr(c)))


def greatest(*cols) -> Column:
    return _c(ar.Greatest(*[_expr(c) for c in cols]))


def least(*cols) -> Column:
    return _c(ar.Least(*[_expr(c) for c in cols]))


def when(condition, value) -> "CaseBuilder":
    return CaseBuilder([(_expr(condition), _expr(value))])


class CaseBuilder(Column):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(cond.CaseWhen(branches))

    def when(self, condition, value) -> "CaseBuilder":
        return CaseBuilder(self._branches + [(_expr(condition), _expr(value))])

    def otherwise(self, value) -> Column:
        return Column(cond.CaseWhen(self._branches, _expr(value)))


def coalesce(*cols) -> Column:
    return _c(cond.Coalesce(*[_expr(c) for c in cols]))


def isnull(c) -> Column:
    return _c(pred.IsNull(_expr(c)))


def isnan(c) -> Column:
    return _c(pred.IsNaN(_expr(c)))


def expr_if(c, a, b) -> Column:
    return _c(cond.If(_expr(c), _expr(a), _expr(b)))


# -- window ------------------------------------------------------------------

def row_number() -> Column:
    from ..expr.window import RowNumber
    return _c(RowNumber())


def rank() -> Column:
    from ..expr.window import Rank
    return _c(Rank())


def dense_rank() -> Column:
    from ..expr.window import DenseRank
    return _c(DenseRank())


def lead(c, offset: int = 1) -> Column:
    from ..expr.window import Lead
    return _c(Lead(_expr(c), offset))


def lag(c, offset: int = 1) -> Column:
    from ..expr.window import Lag
    return _c(Lag(_expr(c), offset))


def ntile(n: int) -> Column:
    from ..expr.window import NTile
    return _c(NTile(n))


# strings / datetime / hash re-exported once those modules land
def upper(c) -> Column:
    from ..expr.strings import Upper
    return _c(Upper(_expr(c)))


def lower(c) -> Column:
    from ..expr.strings import Lower
    return _c(Lower(_expr(c)))


def length(c) -> Column:
    from ..expr.strings import Length
    return _c(Length(_expr(c)))


def substring(c, pos, length) -> Column:
    from ..expr.strings import Substring
    return _c(Substring(_expr(c), Literal(pos), Literal(length)))


def concat(*cols) -> Column:
    from ..expr.strings import Concat
    return _c(Concat(*[_expr(c) for c in cols]))


def year(c) -> Column:
    from ..expr.datetime_expr import Year
    return _c(Year(_expr(c)))


def month(c) -> Column:
    from ..expr.datetime_expr import Month
    return _c(Month(_expr(c)))


def dayofmonth(c) -> Column:
    from ..expr.datetime_expr import DayOfMonth
    return _c(DayOfMonth(_expr(c)))


def hash(*cols) -> Column:  # noqa: A001
    from ..expr.hashfns import Murmur3Hash
    return _c(Murmur3Hash([_expr(c) for c in cols]))


def explode(c) -> Column:
    from ..expr.collection import Explode
    return _c(Explode(_expr(c)))


def explode_outer(c) -> Column:
    from ..expr.collection import Explode
    return _c(Explode(_expr(c), outer=True))


def posexplode(c) -> Column:
    from ..expr.collection import PosExplode
    return _c(PosExplode(_expr(c)))


def posexplode_outer(c) -> Column:
    from ..expr.collection import PosExplode
    return _c(PosExplode(_expr(c), outer=True))


def size(c) -> Column:
    from ..expr.collection import Size
    return _c(Size(_expr(c)))


def array_contains(c, value) -> Column:
    from ..expr.collection import ArrayContains
    v = value if isinstance(value, (Column, Expression)) else Literal(value)
    return _c(ArrayContains(_expr(c), _expr(v)))


def sort_array(c, asc: bool = True) -> Column:
    from ..expr.collection import SortArray
    return _c(SortArray(_expr(c), asc))


def grouping_id() -> Column:
    return _c(AttributeReference("spark_grouping_id"))


# -- UDFs --------------------------------------------------------------------

def udf(f=None, returnType=None):
    """Create a scalar Python UDF (pyspark.sql.functions.udf parity).

    When `spark.rapids.sql.udfCompiler.enabled` is on, the planner tries to
    compile the function's bytecode into the expression IR so it fuses into
    the TPU computation (ref udf-compiler); otherwise it runs as opaque
    Python through ArrowEvalPythonExec.
    """
    from .. import types as t
    from ..udf.python_udf import PythonUDF

    if isinstance(f, t.DataType):  # @udf(IntegerType()) form (pyspark parity)
        f, returnType = None, f
    rt = returnType or t.STRING

    def wrap(fn):
        def call(*cols) -> Column:
            return _c(PythonUDF(fn, rt, [_expr(c) for c in cols],
                                vectorized=False))
        call.__name__ = getattr(fn, "__name__", "udf")
        call.func = fn
        call.returnType = rt
        return call

    return wrap if f is None else wrap(f)


def pandas_udf(f=None, returnType=None, functionType=None):
    """Vectorized (pandas Series -> Series) UDF
    (ref GpuArrowEvalPythonExec pandas path).

    functionType="grouped_agg" creates a Series -> scalar aggregate for
    use in groupBy().agg() (ref GpuAggregateInPandasExec)."""
    from .. import types as t
    from ..udf.python_udf import PythonUDF

    if isinstance(f, t.DataType):  # @pandas_udf(DoubleType()) form
        f, returnType = None, f
    rt = returnType or t.DOUBLE

    if functionType not in (None, "scalar", "grouped_agg"):
        raise ValueError(
            f"unsupported pandas_udf functionType {functionType!r}; use "
            f"'scalar', 'grouped_agg', or the dedicated APIs "
            f"(mapInPandas / applyInPandas) for map-style UDFs")

    def wrap(fn):
        if functionType == "grouped_agg":
            def call(*cols) -> Column:
                return _c(PandasAggUDF(fn, rt, [_expr(c) for c in cols]))
        else:
            def call(*cols) -> Column:
                return _c(PythonUDF(fn, rt, [_expr(c) for c in cols],
                                    vectorized=True))
        call.__name__ = getattr(fn, "__name__", "pandas_udf")
        call.func = fn
        call.returnType = rt
        return call

    return wrap if f is None else wrap(f)


class PandasAggUDF(Expression):
    """Marker expression: a grouped-aggregate pandas UDF call.  Consumed
    by GroupedData.agg, which routes the whole aggregate through
    AggregateInPandasExec (never evaluated directly)."""

    def __init__(self, fn, rt, args):
        self.fn = fn
        self.rt = rt
        self.children = tuple(args)

    def data_type(self):
        return self.rt

    def sql(self):
        name = getattr(self.fn, "__name__", "pandas_agg")
        return f"{name}({', '.join(c.sql() for c in self.children)})"


def native_udf(impl, *cols) -> Column:
    """Apply a TpuUDF (columnar native UDF, ref RapidsUDF.java) to columns."""
    from ..udf.native import NativeUDFExpression
    return _c(NativeUDFExpression(impl, [_expr(c) for c in cols]))


# -- complex types / higher-order functions ---------------------------------

_LAMBDA_COUNTER = [0]


def _make_lambda(fn) -> "Expression":
    """Python callable -> LambdaFunction (pyspark-style F.transform API)."""
    import inspect
    from ..expr.higher_order import LambdaFunction, NamedLambdaVariable
    n_args = len(inspect.signature(fn).parameters)
    _LAMBDA_COUNTER[0] += 1
    names = [f"x_{_LAMBDA_COUNTER[0]}", f"i_{_LAMBDA_COUNTER[0]}"][:n_args]
    vars_ = [NamedLambdaVariable(n) for n in names]
    body = fn(*[Column(v) for v in vars_])
    return LambdaFunction(body.expr, vars_)


def transform(c, fn) -> Column:
    from ..expr.higher_order import ArrayTransform
    return _c(ArrayTransform(_expr(c), _make_lambda(fn)))


def filter(c, fn) -> Column:  # noqa: A001
    from ..expr.higher_order import ArrayFilter
    return _c(ArrayFilter(_expr(c), _make_lambda(fn)))


def exists(c, fn) -> Column:
    from ..expr.higher_order import ArrayExists
    return _c(ArrayExists(_expr(c), _make_lambda(fn)))


def forall(c, fn) -> Column:
    from ..expr.higher_order import ArrayForAll
    return _c(ArrayForAll(_expr(c), _make_lambda(fn)))


def element_at(c, index) -> Column:
    from ..expr.complextype import ElementAt
    from ..expr.core import Literal
    return _c(ElementAt(_expr(c), _expr(index)))


def array(*cols) -> Column:
    from ..expr.complextype import CreateArray
    return _c(CreateArray([_expr(c) for c in cols]))


def struct(*cols) -> Column:
    from ..expr.complextype import CreateNamedStruct
    from ..expr.core import output_name
    exprs = [_expr(c) for c in cols]
    names = [output_name(e) for e in exprs]
    return _c(CreateNamedStruct(names, exprs))


# -- regex ------------------------------------------------------------------

def regexp_extract(c, pattern: str, idx: int = 1) -> Column:
    from ..expr.core import Literal
    from ..expr.regex import RegExpExtract
    return _c(RegExpExtract(_expr(c), Literal(pattern), Literal(idx)))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    from ..expr.core import Literal
    from ..expr.regex import RegExpReplace
    return _c(RegExpReplace(_expr(c), Literal(pattern),
                            Literal(replacement)))


def split(c, pattern: str, limit: int = -1) -> Column:
    from ..expr.core import Literal
    from ..expr.regex import StringSplit
    return _c(StringSplit(_expr(c), Literal(pattern), Literal(limit)))


def concat_ws(sep: str, *cols) -> Column:
    """concat_ws(sep, c1, c2, ...): join non-null args with sep."""
    from ..expr.strings import ConcatWs
    return _c(ConcatWs(Literal(sep), *[_expr(c) for c in cols]))


def md5(c) -> Column:
    from ..expr.hashfns import Md5
    return _c(Md5(_expr(c)))


def get_json_object(c, path: str) -> Column:
    from ..expr.json_expr import GetJsonObject
    return _c(GetJsonObject(_expr(c), Literal(path)))


def monotonically_increasing_id() -> Column:
    from ..expr.hashfns import MonotonicallyIncreasingID
    return _c(MonotonicallyIncreasingID())


def spark_partition_id() -> Column:
    from ..expr.hashfns import SparkPartitionID
    return _c(SparkPartitionID())


def input_file_name() -> Column:
    from ..expr.hashfns import InputFileName
    return _c(InputFileName())


def rand(seed: int = 0) -> Column:
    from ..expr.hashfns import Rand
    return _c(Rand(seed))


def collect_list(c) -> Column:
    return _c(agg.AggregateExpression(agg.CollectList(_expr(c))))


def collect_set(c) -> Column:
    return _c(agg.AggregateExpression(agg.CollectSet(_expr(c))))


def approx_percentile(c, percentage: float, accuracy: int = 10000
                      ) -> Column:
    """Exact inverted-CDF percentile per group (ref percentile_approx /
    GPU ApproximatePercentile; accuracy accepted for API parity — the
    sort-based kernel is always exact)."""
    return _c(agg.AggregateExpression(
        agg.ApproximatePercentile(_expr(c), percentage, accuracy)))


percentile_approx = approx_percentile


def pivot_first(pivot_col, value_col, pivot_value) -> Column:
    """The first value where pivot_col equals pivot_value — the unit a
    pivot aggregate lowers to (ref GpuPivotFirst)."""
    return _c(agg.AggregateExpression(
        agg.PivotFirst(_expr(pivot_col), _expr(value_col), pivot_value)))


def window(time_col, window_duration: str, slide_duration: str = None,
           start_time: str = "0 seconds") -> Column:
    """Tumbling time-window bucketing: window(ts, '10 minutes') yields a
    struct<start,end> grouping key (ref
    org/apache/spark/sql/rapids/TimeWindow.scala)."""
    from ..expr.datetime_expr import TimeWindow, parse_duration_micros
    w = parse_duration_micros(window_duration)
    s = parse_duration_micros(slide_duration) if slide_duration else None
    st = parse_duration_micros(start_time, allow_nonpositive=True) \
        if start_time else 0
    return _c(TimeWindow(_expr(time_col), w, s, st))


def scalar_subquery(df) -> Column:
    """A one-row one-column DataFrame used as a scalar in expressions
    (ref GpuScalarSubquery.scala; the subquery executes first and its
    value substitutes as a typed literal)."""
    from ..expr.subquery import ScalarSubquery
    return _c(ScalarSubquery(df._lp))


def bitwise_not(c) -> Column:
    from ..expr.bitwise import BitwiseNot
    return _c(BitwiseNot(_expr(c)))


def shiftleft(c, n) -> Column:
    from ..expr.bitwise import ShiftLeft
    return _c(ShiftLeft(_expr(c), _expr(n)))


def shiftright(c, n) -> Column:
    from ..expr.bitwise import ShiftRight
    return _c(ShiftRight(_expr(c), _expr(n)))


def shiftrightunsigned(c, n) -> Column:
    from ..expr.bitwise import ShiftRightUnsigned
    return _c(ShiftRightUnsigned(_expr(c), _expr(n)))


def cot(c) -> Column:
    from ..expr.mathexpr import Cot
    return _c(Cot(_expr(c)))


def asinh(c) -> Column:
    from ..expr.mathexpr import Asinh
    return _c(Asinh(_expr(c)))


def acosh(c) -> Column:
    from ..expr.mathexpr import Acosh
    return _c(Acosh(_expr(c)))


def atanh(c) -> Column:
    from ..expr.mathexpr import Atanh
    return _c(Atanh(_expr(c)))


def log_base(base, c) -> Column:
    """log(base, x) (Spark's two-argument log)."""
    from ..expr.mathexpr import Logarithm
    return _c(Logarithm(_expr(base), _expr(c)))


def ascii(c) -> Column:
    from ..expr.strings import Ascii
    return _c(Ascii(_expr(c)))


def bitwise_and(a, b) -> Column:
    from ..expr.bitwise import BitwiseAnd
    return _c(BitwiseAnd(_expr(a), _expr(b)))


def bitwise_or(a, b) -> Column:
    from ..expr.bitwise import BitwiseOr
    return _c(BitwiseOr(_expr(a), _expr(b)))


def bitwise_xor(a, b) -> Column:
    from ..expr.bitwise import BitwiseXor
    return _c(BitwiseXor(_expr(a), _expr(b)))


def percent_rank() -> Column:
    from ..expr.window import PercentRank
    return _c(PercentRank())


def cume_dist() -> Column:
    from ..expr.window import CumeDist
    return _c(CumeDist())
