"""Spark-facing bridge: ship physical-plan stages from a Spark executor
into this engine over Arrow IPC.

The reference integrates with Spark from INSIDE the JVM: Plugin.scala
forces itself into spark.sql.extensions (Plugin.scala:77-112) and its
ColumnarRule (Plugin.scala:44-51) swaps physical subtrees for Gpu execs
that call cuDF through JNI.  A JAX/XLA engine cannot live inside the JVM,
so the bridge is a per-executor SIDECAR process (SURVEY hard-part #2's
recommended shape): the JVM side replaces a supported subtree
(scan -> filter -> project -> aggregate) with a stage that

  1. serializes the subtree as a JSON plan spec (bridge/spec.py — the
     language-neutral contract a Scala ColumnarRule emits),
  2. streams its input ColumnarBatches as Arrow IPC to the sidecar
     (bridge/sidecar.py) over a localhost socket, the same transport the
     reference already uses between the JVM and pandas workers
     (GpuArrowEvalPythonExec), and
  3. reads the stage's result back as Arrow.

The sidecar advertises its port on stdout at startup (the analog of the
UCX port riding MapStatus's BlockManagerId topology field,
RapidsShuffleInternalManagerBase.scala:175-185).

No JVM exists in this build environment, so tests/test_bridge.py plays
the JVM's role faithfully: a separate OS process builds plan specs +
Arrow streams exactly as the Scala rule would and validates results
against an independent oracle.
"""

from .client import BridgeClient
from .sidecar import SidecarServer
from .spec import plan_spec_to_logical

__all__ = ["BridgeClient", "SidecarServer", "plan_spec_to_logical"]
