"""JSON plan-spec: the language-neutral stage contract the JVM side emits.

A spec describes one pushed-down stage over one or more Arrow input
streams (the subtree a ColumnarRule replaced, ref GpuOverrides' convert
of scan/filter/project/aggregate/join/window subtrees).  Shape:

    {"input": {"schema": [["k", "bigint"], ["v", "bigint"]]},
     "inputs": [{"schema": [...]}, ...],   # optional extra streams (joins)
     "ops": [
       {"op": "filter", "condition": <expr>},
       {"op": "project", "exprs": [{"expr": <expr>, "name": "x"}]},
       {"op": "aggregate",
        "groupBy": [<expr>...],
        "aggs": [{"fn": "sum", "expr": <expr>, "name": "s"}]},
       {"op": "join", "right": 1,          # index into the input streams
        "how": "inner", "on": ["k"],       # or "condition": <expr>
       },
       {"op": "window",
        "partitionBy": [<expr>...],
        "orderBy": [{"expr": <expr>, "ascending": true,
                     "nullsFirst": true}],
        "funcs": [{"fn": "row_number", "name": "rn"},
                  {"fn": "ntile", "n": 4, "name": "q"},
                  {"fn": "lag", "expr": <expr>, "offset": 1, "name": "p"},
                  {"fn": "sum", "expr": <expr>, "name": "rs"}],
        "frame": {"type": "rows", "start": -2, "end": "currentRow"}},
                                       # frame optional; bounds are ints
                                       # or "unboundedPreceding" /
                                       # "unboundedFollowing"/"currentRow"
       {"op": "sort", "orders": [{"expr": <expr>, "ascending": true,
                                  "nullsFirst": true}]},
       {"op": "limit", "n": 10}
     ]}

The main stream is input 0; `join` ops reference later streams by index.
Expressions are JSON trees:

    {"col": "v"} | {"lit": 5, "type": "bigint"} |
    {"op": "gt", "children": [<expr>, <expr>]} |
    {"op": "cast", "type": "double", "children": [<expr>]} |
    {"op": "in", "children": [<expr>], "values": [<lit>...]}

Operator tiers: comparisons/boolean (eq/ne/lt/le/gt/ge/and/or/not,
isnull/isnotnull/isnan), arithmetic (add/sub/mul/div/mod/abs), strings
(upper/lower/length/substr/concat/trim/ltrim/rtrim/contains/startswith/
endswith), datetime (year/month/dayofmonth/hour/minute/second/datediff/
date_add/date_sub), conditionals (if/coalesce), cast, in.

Types use Spark SQL DDL names (the same strings the DataFrame API's
schema parser accepts), so the Scala side can emit
`DataType.catalogString` verbatim.
"""

from __future__ import annotations

from typing import Dict, List

from ..api.column import _parse_type
from ..plan import logical as L


_AGG_FNS = ("sum", "count", "avg", "min", "max", "first", "last")


def expr_from_spec(spec: Dict):
    """JSON expression tree -> engine expression."""
    from ..expr import arithmetic as ar
    from ..expr import conditional as cond
    from ..expr import datetime_expr as dte
    from ..expr import predicates as pr
    from ..expr import strings as se
    from ..expr.cast import Cast
    from ..expr.core import AttributeReference, Literal
    if "col" in spec:
        return AttributeReference(spec["col"])
    if "lit" in spec:
        dt = _parse_type(spec["type"]) if "type" in spec else None
        return Literal(spec["lit"], dt) if dt is not None \
            else Literal(spec["lit"])
    op = spec["op"]
    kids = [expr_from_spec(c) for c in spec.get("children", [])]
    table = {
        "eq": pr.EqualTo, "lt": pr.LessThan, "le": pr.LessThanOrEqual,
        "gt": pr.GreaterThan, "ge": pr.GreaterThanOrEqual,
        "and": pr.And, "or": pr.Or,
        "add": ar.Add, "sub": ar.Subtract, "mul": ar.Multiply,
        "div": ar.Divide, "mod": ar.Remainder,
        # string tier (Scala SpecBuilder's string cases)
        "upper": se.Upper, "lower": se.Lower, "length": se.Length,
        "substr": se.Substring, "concat": se.Concat, "trim": se.Trim,
        "ltrim": se.TrimLeft, "rtrim": se.TrimRight,
        "contains": se.Contains, "startswith": se.StartsWith,
        "endswith": se.EndsWith,
        # datetime tier
        "year": dte.Year, "month": dte.Month,
        "dayofmonth": dte.DayOfMonth, "hour": dte.Hour,
        "minute": dte.Minute, "second": dte.Second,
        "datediff": dte.DateDiff, "date_add": dte.DateAdd,
        "date_sub": dte.DateSub,
        # misc
        "abs": ar.Abs, "coalesce": cond.Coalesce, "if": cond.If,
        "isnan": pr.IsNaN,
    }
    if op in table:
        return table[op](*kids)
    if op == "cast":
        return Cast(kids[0], _parse_type(spec["type"]))
    if op == "in":
        # children[0] is the value; the literal list rides "values"
        items = [expr_from_spec(v) for v in spec.get("values", [])]
        return pr.In(kids[0], items)
    if op == "ne":
        return pr.Not(pr.EqualTo(*kids))
    if op == "not":
        return pr.Not(kids[0])
    if op == "isnull":
        return pr.IsNull(kids[0])
    if op == "isnotnull":
        return pr.IsNotNull(kids[0])
    raise ValueError(f"unsupported bridge expression op {op!r}")


def _agg_from_spec(a: Dict):
    from ..expr.aggregates import (AggregateExpression, Average, Count,
                                   First, Last, Max, Min, Sum)
    fn = a["fn"]
    if fn not in _AGG_FNS:
        raise ValueError(f"unsupported bridge aggregate {fn!r}")
    child = expr_from_spec(a["expr"]) if a.get("expr") is not None else None
    cls = {"sum": Sum, "avg": Average, "min": Min, "max": Max,
           "first": First, "last": Last}.get(fn)
    if fn == "count":
        agg = Count(child)
    else:
        agg = cls(child)
    return AggregateExpression(agg, a.get("name") or fn)


_WINDOW_FNS = ("row_number", "rank", "dense_rank", "percent_rank",
               "cume_dist", "ntile", "sum", "count", "avg",
               "min", "max", "lead", "lag")


def _frame_from_spec(f: Dict):
    """{"type": "rows"|"range", "start": N|"unboundedPreceding"|
    "currentRow", "end": ...} -> the engine's (kind, lo, hi) triple."""
    from ..expr.window import (CURRENT_ROW, UNBOUNDED_FOLLOWING,
                               UNBOUNDED_PRECEDING)

    def bound(v):
        if v == "unboundedPreceding":
            return UNBOUNDED_PRECEDING
        if v == "unboundedFollowing":
            return UNBOUNDED_FOLLOWING
        if v == "currentRow":
            return CURRENT_ROW
        return int(v)

    kind = f.get("type", "rows")
    if kind not in ("rows", "range"):
        raise ValueError(f"unsupported bridge window frame {kind!r}")
    return (kind, bound(f.get("start", "unboundedPreceding")),
            bound(f.get("end", "currentRow")))


def _window_from_spec(op: Dict) -> List:
    """Window op spec -> WindowExpression list."""
    from ..expr.aggregates import Average, Count, Max, Min, Sum
    from ..expr.window import (DenseRank, Lag, Lead, Rank, RowNumber,
                               WindowExpression, WindowSpec)
    spec = WindowSpec(
        partition_by=[expr_from_spec(p) for p in op.get("partitionBy", [])],
        order_by=[(expr_from_spec(o["expr"]),
                   bool(o.get("ascending", True)),
                   bool(o.get("nullsFirst", o.get("ascending", True))))
                  for o in op.get("orderBy", [])],
        frame=_frame_from_spec(op["frame"]) if op.get("frame") else None)
    out = []
    for f in op["funcs"]:
        fn = f["fn"]
        if fn not in _WINDOW_FNS:
            raise ValueError(f"unsupported bridge window fn {fn!r}")
        child = expr_from_spec(f["expr"]) if f.get("expr") is not None \
            else None
        if fn == "row_number":
            func = RowNumber()
        elif fn == "rank":
            func = Rank()
        elif fn == "dense_rank":
            func = DenseRank()
        elif fn == "percent_rank":
            from ..expr.window import PercentRank
            func = PercentRank()
        elif fn == "cume_dist":
            from ..expr.window import CumeDist
            func = CumeDist()
        elif fn == "ntile":
            from ..expr.window import NTile
            func = NTile(int(f.get("n", 1)))
        elif fn == "lead":
            func = Lead(child, int(f.get("offset", 1)))
        elif fn == "lag":
            func = Lag(child, int(f.get("offset", 1)))
        else:
            cls = {"sum": Sum, "count": Count, "avg": Average,
                   "min": Min, "max": Max}[fn]
            func = cls(child)
        out.append(WindowExpression(func, spec, f.get("name") or fn))
    return out


def plan_spec_to_logical(spec: Dict, table, extra_tables=()) -> L.LogicalPlan:
    """Spec + the stage's Arrow input stream(s) -> engine logical plan.
    `table` is input 0; `extra_tables[i-1]` backs input i (joins)."""
    from ..expr.core import Alias
    lp: L.LogicalPlan = L.LocalRelation(table,
                                        spec.get("numPartitions", 1))
    for op in spec.get("ops", []):
        kind = op["op"]
        if kind == "filter":
            lp = L.Filter(expr_from_spec(op["condition"]), lp)
        elif kind == "project":
            exprs = []
            for e in op["exprs"]:
                ex = expr_from_spec(e["expr"])
                exprs.append(Alias(ex, e["name"]) if e.get("name") else ex)
            lp = L.Project(exprs, lp)
        elif kind == "aggregate":
            grouping = [expr_from_spec(g) for g in op.get("groupBy", [])]
            aggs = [_agg_from_spec(a) for a in op.get("aggs", [])]
            lp = L.Aggregate(grouping, aggs, lp)
        elif kind == "join":
            ridx = int(op["right"])
            if not (1 <= ridx <= len(extra_tables)):
                raise ValueError(
                    f"join input index {ridx} out of range "
                    f"({len(extra_tables)} extra streams)")
            right = L.LocalRelation(extra_tables[ridx - 1],
                                    spec.get("numPartitions", 1))
            how = op.get("how", "inner")
            cond = expr_from_spec(op["condition"]) \
                if op.get("condition") is not None else None
            lp = L.Join(lp, right, how, cond,
                        using=list(op.get("on") or []) or None,
                        force_shuffled=op.get("strategy") == "shuffled")
        elif kind == "window":
            lp = L.Window(_window_from_spec(op), lp)
        elif kind == "sort":
            orders = [(expr_from_spec(o["expr"]),
                       bool(o.get("ascending", True)),
                       bool(o.get("nullsFirst", o.get("ascending", True))))
                      for o in op["orders"]]
            lp = L.Sort(orders, True, lp)
        elif kind == "limit":
            lp = L.Limit(int(op["n"]), lp)
        else:
            raise ValueError(f"unsupported bridge operator {kind!r}")
    return lp
