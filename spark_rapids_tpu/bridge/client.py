"""Bridge client: the executor-side driver of the sidecar protocol.

The Scala ColumnarRule's replacement exec holds one of these per task
(connection pooling is the JVM side's concern, like the reference's
transport client cache, RapidsShuffleTransport.makeClient).  This Python
implementation is both the reference client for the protocol and what
the fake-JVM test harness uses."""

from __future__ import annotations

import io
import json
import socket
import struct

import pyarrow as pa

from .sidecar import MAGIC, _read_exact


class BridgeError(RuntimeError):
    """The sidecar rejected or failed the stage (sidecar stays alive)."""


class BridgeClient:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 600.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)

    def ping(self) -> bool:
        self._sock.sendall(MAGIC + b"P")
        tag = _read_exact(self._sock, 1)
        _read_exact(self._sock, 8)
        return tag == b"O"

    @staticmethod
    def _ipc(table: pa.Table) -> bytes:
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, table.schema) as w:
            w.write_table(table)
        return sink.getvalue()

    def execute_stage(self, spec: dict, table: pa.Table,
                      extra_tables=()) -> pa.Table:
        import time

        from ..obs import metrics as m
        from ..obs.tracer import trace_span
        t0 = time.perf_counter()
        with trace_span("bridge.execute_stage",
                        op=str(spec.get("op", ""))) as obs_sp:
            blob = json.dumps(spec).encode()
            sent = 0
            if extra_tables:
                parts = [MAGIC, b"M", struct.pack("<I", len(blob)), blob,
                         struct.pack("<I", 1 + len(extra_tables))]
                for tb in (table, *extra_tables):
                    ipc = self._ipc(tb)
                    parts += [struct.pack("<Q", len(ipc)), ipc]
                    sent += len(ipc)
                self._sock.sendall(b"".join(parts))
            else:
                ipc = self._ipc(table)
                sent = len(ipc)
                self._sock.sendall(
                    MAGIC + b"E" + struct.pack("<I", len(blob)) + blob +
                    struct.pack("<Q", len(ipc)) + ipc)
            tag = _read_exact(self._sock, 1)
            if tag == b"E":
                (n,) = struct.unpack("<I", _read_exact(self._sock, 4))
                raise BridgeError(_read_exact(self._sock, n).decode())
            (n,) = struct.unpack("<Q", _read_exact(self._sock, 8))
            with pa.ipc.open_stream(
                    io.BytesIO(_read_exact(self._sock, n))) as r:
                out = r.read_all()
            obs_sp.set(request_bytes=sent, response_bytes=n,
                       rows=out.num_rows)
            m.counter("tpu_bridge_round_trips_total",
                      "sidecar execute_stage round trips").inc()
            m.counter("tpu_bridge_request_bytes_total",
                      "Arrow IPC bytes sent to the sidecar").inc(sent)
            m.counter("tpu_bridge_response_bytes_total",
                      "Arrow IPC bytes received from the sidecar") \
                .inc(n)
            m.histogram("tpu_bridge_latency_seconds",
                        "execute_stage round-trip latency") \
                .observe(time.perf_counter() - t0)
            return out

    def shutdown_sidecar(self):
        try:
            self._sock.sendall(MAGIC + b"Q")
        except OSError:
            pass

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
