"""Sidecar server: hosts the engine next to a Spark executor.

One sidecar per executor (ref the reference's one-GPU-per-executor
assumption, Plugin.scala:180-181).  The JVM connects over localhost TCP
and drives the framed protocol:

    request : MAGIC 'E' | u32 spec_len | spec JSON | u64 ipc_len | Arrow IPC
    request : MAGIC 'M' | u32 spec_len | spec JSON | u32 n_inputs |
              (u64 ipc_len | Arrow IPC) * n_inputs   (multi-input stages:
                                                      input 0 is the main
                                                      stream, later ones
                                                      back join ops)
    response: 'O' | u64 ipc_len | Arrow IPC        (stage result)
              'E' | u32 msg_len | utf-8 error      (stage failed; sidecar
                                                    stays up)
    request : MAGIC 'P'  -> response 'O' u64=0     (ping)
    request : MAGIC 'Q'  -> sidecar exits          (shutdown)

Startup prints `TPU_SIDECAR_PORT=<port>` on stdout — the discovery
handshake (the reference advertises its fast-path port through
MapStatus's BlockManagerId topology field,
RapidsShuffleInternalManagerBase.scala:175-185)."""

from __future__ import annotations

import io
import json
import socket
import struct
import sys
import threading
from typing import Optional

import pyarrow as pa

MAGIC = b"TPUB"


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("bridge peer closed")
        buf += chunk
    return buf


class SidecarServer:
    def __init__(self, conf: Optional[dict] = None, port: int = 0):
        self.conf = dict(conf or {})
        self.conf.setdefault("spark.rapids.sql.enabled", True)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._session = None
        self._stop = threading.Event()

    def _get_session(self):
        if self._session is None:
            from ..api.session import TpuSession
            b = TpuSession.builder()
            for k, v in self.conf.items():
                b = b.config(k, v)
            self._session = b.get_or_create()
        return self._session

    def execute_stage(self, spec: dict, table: pa.Table,
                      extra_tables=()) -> pa.Table:
        from .spec import plan_spec_to_logical
        session = self._get_session()
        lp = plan_spec_to_logical(spec, table, extra_tables)
        return session.execute(lp)

    # -- server loop --------------------------------------------------------
    def serve_forever(self, announce=True):
        if announce:
            print(f"TPU_SIDECAR_PORT={self.port}", flush=True)
        # accept with a timeout so shutdown() (called from a connection
        # thread) reliably wakes this loop — closing a socket does not
        # interrupt a blocked accept on all platforms
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while True:
                head = _read_exact(conn, 5)
                if head[:4] != MAGIC:
                    return
                op = head[4:5]
                if op == b"P":
                    conn.sendall(b"O" + struct.pack("<Q", 0))
                    continue
                if op == b"Q":
                    self.shutdown()
                    return
                if op not in (b"E", b"M"):
                    return
                (spec_len,) = struct.unpack("<I", _read_exact(conn, 4))
                spec_bytes = _read_exact(conn, spec_len)
                if op == b"M":
                    (n_in,) = struct.unpack("<I", _read_exact(conn, 4))
                else:
                    n_in = 1
                ipcs = []
                for _ in range(max(n_in, 1)):
                    (ipc_len,) = struct.unpack("<Q", _read_exact(conn, 8))
                    ipcs.append(_read_exact(conn, ipc_len))
                try:
                    spec = json.loads(spec_bytes)
                    tables = []
                    for ipc in ipcs:
                        with pa.ipc.open_stream(io.BytesIO(ipc)) as r:
                            tables.append(r.read_all())
                    out = self.execute_stage(spec, tables[0], tables[1:])
                    sink = io.BytesIO()
                    with pa.ipc.new_stream(sink, out.schema) as w:
                        w.write_table(out)
                    body = sink.getvalue()
                    conn.sendall(b"O" + struct.pack("<Q", len(body)) + body)
                except Exception as ex:  # noqa: BLE001 — survive bad stages
                    msg = f"{type(ex).__name__}: {ex}".encode()
                    conn.sendall(b"E" + struct.pack("<I", len(msg)) + msg)
        except (EOFError, OSError):
            return
        finally:
            conn.close()

    def shutdown(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def main():
    conf = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    SidecarServer(conf).serve_forever()


if __name__ == "__main__":
    main()
