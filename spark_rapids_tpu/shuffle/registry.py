"""Block location registry: which endpoint owns which shuffle blocks.

Ref: RapidsShuffleHeartbeatManager's peer registry + the shuffle
manager's block-to-executor mapping — the reference resolves a reduce
task's block locations through Spark's MapOutputTracker and then fetches
over UCX from the owning executor.

Here map stages register their blocks' owning endpoint (executor id,
host, block-server port) per shuffle; reduce-side reads consult the
registry to split a partition's blocks into

* local  — owned by THIS process: served zero-copy from the in-process
  ``ShuffleBufferCatalog``, never crossing the wire;
* remote — owned by a peer: streamed through ``AsyncBlockFetcher`` from
  a live replica of the owning group.

Endpoints register in *groups*: one ``register`` call names the replica
set that can all serve the same block set (one entry in the common
case).  Liveness rides the attached ``HeartbeatManager`` — the registry
never invents its own failure detector."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence


@dataclass(frozen=True)
class BlockEndpoint:
    """One block-server endpoint (executor identity + dial address)."""

    executor_id: str
    host: str
    port: int


class BlockLocationRegistry:
    """Process-wide map: shuffle_id -> ordered owner groups.

    Each owner group is a replica set (endpoints able to serve the SAME
    blocks); distinct groups own DISJOINT block sets, so a reduce read
    takes every group exactly once and retries only inside a group."""

    _instance: Optional["BlockLocationRegistry"] = None
    _class_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._owners: Dict[int, List[List[BlockEndpoint]]] = {}
        self._local: Optional[BlockEndpoint] = None
        self._heartbeat = None
        # content digests published by map stages alongside their
        # endpoints: shuffle_id -> {((sid,mid,rid), index): u64}.  The
        # reduce side can cross-check a replica's advertised digest
        # against the writer's published one (content addressing
        # survives the writer's death; a replica can't vouch for
        # itself)
        self._digests: Dict[int, Dict] = {}

    @classmethod
    def get(cls) -> "BlockLocationRegistry":
        with cls._class_lock:
            if cls._instance is None:
                cls._instance = BlockLocationRegistry()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._class_lock:
            cls._instance = None

    # -- wiring -------------------------------------------------------------
    def set_local(self, executor_id: str, host: str = "127.0.0.1",
                  port: int = 0) -> None:
        """Identify THIS process's endpoint so reads can tell their own
        registrations from remote ones."""
        with self._lock:
            self._local = BlockEndpoint(executor_id, host, port)

    @property
    def local(self) -> Optional[BlockEndpoint]:
        with self._lock:
            return self._local

    def attach_heartbeat(self, heartbeat) -> None:
        """Wire the HeartbeatManager whose expiry decides liveness."""
        with self._lock:
            self._heartbeat = heartbeat

    @property
    def heartbeat(self):
        with self._lock:
            return self._heartbeat

    # -- registration -------------------------------------------------------
    def register(self, shuffle_id: int,
                 endpoints: Sequence[BlockEndpoint]) -> None:
        """Record one owner group (a replica set) for ``shuffle_id``.
        Map stages call this once per owning executor; re-registering an
        identical group is a no-op so idempotent map-stage retries don't
        duplicate fetches."""
        group = list(endpoints)
        if not group:
            return
        with self._lock:
            groups = self._owners.setdefault(int(shuffle_id), [])
            if group not in groups:
                groups.append(group)

    def note_block_digests(self, shuffle_id: int, digests: Dict) -> None:
        """Publish map-write content digests for ``shuffle_id`` (keys
        are ((shuffle,map,reduce), index) like the catalog's).  Merges:
        each map stage publishes only its own blocks."""
        if not digests:
            return
        with self._lock:
            self._digests.setdefault(int(shuffle_id), {}).update(digests)

    def block_digests(self, shuffle_id: int) -> Dict:
        with self._lock:
            return dict(self._digests.get(int(shuffle_id), {}))

    def forget_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._owners.pop(int(shuffle_id), None)
            self._digests.pop(int(shuffle_id), None)

    # -- lookup -------------------------------------------------------------
    def owner_groups(self, shuffle_id: int) -> List[List[BlockEndpoint]]:
        with self._lock:
            return [list(g) for g in self._owners.get(int(shuffle_id), [])]

    def is_local_group(self, group: Sequence[BlockEndpoint]) -> bool:
        """A group containing this process's endpoint is served from the
        in-process catalog — those blocks must never cross the wire."""
        with self._lock:
            local = self._local
        if local is None:
            return False
        return any(e.executor_id == local.executor_id for e in group)

    def remote_groups(self, shuffle_id: int) -> List[List[BlockEndpoint]]:
        return [g for g in self.owner_groups(shuffle_id)
                if not self.is_local_group(g)]

    def live_endpoints(self, group: Sequence[BlockEndpoint]
                       ) -> List[BlockEndpoint]:
        """Replicas of ``group`` the heartbeat still considers alive
        (all of them when no heartbeat is attached)."""
        hb = self.heartbeat
        if hb is None:
            return list(group)
        hb.expire_dead()
        live = {p.executor_id for p in hb.live_peers()}
        return [e for e in group if e.executor_id in live]

    def num_shuffles(self) -> int:
        with self._lock:
            return len(self._owners)
