"""Shuffle exchange operator.

Ref: execution/GpuShuffleExchangeExec.scala:223 + GpuShuffleCoalesceExec.
Map side: compute partition ids on device (Spark-compatible murmur3 so
CPU/TPU route identically), one stable sort groups rows by target
partition, host slices by the counts vector, slices register in the
caching shuffle manager (batches stay on device — no row serialization,
the reference's core shuffle win).  Reduce side: concatenate this
partition's slices from every map task."""

from __future__ import annotations

import functools
import threading
from typing import Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.device import DeviceBatch
from ..expr.core import EvalContext
from ..exec.base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU,
                         Batch, Exec, ExecContext, MetricTimer, process_jit,
                         schema_sig, semantic_sig)
from ..exec.concat import concat_batches
from .manager import TpuShuffleManager, materialize_block, slice_rows
from .partitioning import Partitioning, slice_batch_by_partition


class ShuffleExchangeExec(Exec):
    def __init__(self, partitioning: Partitioning, child: Exec):
        super().__init__([child])
        self.partitioning = partitioning.bind(child.output_names,
                                              child.output_types)
        self._write_lock = threading.Lock()
        self._shuffle_id: Optional[int] = None

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    @property
    def num_partitions(self):
        return self.partitioning.num_partitions

    def describe(self):
        return f"ShuffleExchange {self.partitioning.describe()}"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "hash routing is content-determined; block "
            "arrival order on the reduce side follows scheduling, the "
            "per-partition row multiset is invariant")

    def memory_effects(self, child_states, conf):
        """The accelerated shuffle caches every map-output block in the
        catalog (SHUFFLE priority, spill-managed) until the session
        releases the shuffle at query end: the whole exchanged dataset
        is retained, but bounded by the spill budget.  Blocks pad
        per (map, reduce) pair — maps x reduces capacity buckets, not
        one — so the model sizes a padded BLOCK and multiplies."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         spill_budget)
        if not child_states:
            return None
        st = child_states[0]
        blocks = (st.num_partitions or 1) * max(self.num_partitions, 1)
        whole = min(
            padded_partition_bytes(st.replace(num_partitions=blocks))
            * blocks, float(spill_budget(conf)))
        return MemoryEffects(hold=whole, retained=whole,
                             note="spill-managed shuffle blocks")

    def _map_batch(self, xp, batch: Batch, row_offset: int):
        ctx = EvalContext(xp, batch)
        pids = self.partitioning.partition_ids(xp, ctx, batch, row_offset)
        return slice_batch_by_partition(xp, batch, pids,
                                        self.num_partitions)

    @functools.cached_property
    def _jit_key(self):
        return ("ShuffleExchangeExec", schema_sig(self.children[0]),
                semantic_sig(self.partitioning))

    @property
    def _jit_map(self):
        return process_jit(self._jit_key,
                           lambda: lambda b, off: self._map_batch(jnp, b,
                                                                  off))

    def _ensure_written(self, ctx: ExecContext):
        with self._write_lock:
            if self._shuffle_id is not None:
                return
            from ..obs.tracer import trace_span
            with trace_span("shuffle.map_write",
                            partitions=self.num_partitions) as obs_sp:
                self._write_all(ctx, obs_sp)

    def _write_all(self, ctx: ExecContext, obs_sp):
        """Map side under one flight-recorder span: obs_sp collects the
        staged block count and device bytes for the timeline and the
        event log's shuffle-write task metric."""
        mgr = TpuShuffleManager.get()
        shuffle_id = mgr.new_shuffle_id()
        xp = self.xp
        child = self.children[0]
        # content addressing rides the session conf: the catalog digests
        # every block this write registers (tpudsan's replay oracle and
        # the fetch-side verification both key off these)
        from .. import config as cfg_dsan
        from .digest import set_digest_enabled
        set_digest_enabled(ctx.conf.get(cfg_dsan.DSAN_DIGEST_ENABLED))
        # phase 1: dispatch every map batch's partition-sort (async);
        # phase 2: ONE host sync brings back ALL count vectors (a
        # per-batch sync costs a full tunnel round trip each)
        staged: List[tuple] = []  # (map_id, sorted_batch, counts)
        for map_id in range(child.num_partitions):
            row_offset = 0
            for b in child.execute_partition(map_id, ctx):
                with MetricTimer(self.metrics[OP_TIME]):
                    if self.placement == TPU:
                        sorted_b, counts = self._jit_map(
                            b, np.int32(row_offset))
                    else:
                        sorted_b, counts = self._map_batch(
                            np, b, row_offset)
                staged.append((map_id, sorted_b, counts))
                row_offset += int(b.num_rows)
        if staged and self.placement == TPU:
            all_counts = np.asarray(
                jnp.stack([c for _, _, c in staged]))   # one sync
        else:
            all_counts = np.stack([np.asarray(c)
                                   for _, _, c in staged]) \
                if staged else np.zeros((0, self.num_partitions))
        from .. import config as cfg
        from ..memory.spill import batch_device_bytes
        slice_views = ctx.conf.get(cfg.SHUFFLE_SLICE_VIEWS)
        saved_bytes = 0
        if slice_views:
            # one pass per batch: the sorted batch registers ONCE as a
            # shared spillable block; each reduce partition gets a lazy
            # (start, n) view instead of an eager padded gather copy
            from ..columnar.device import DEFAULT_ROW_BUCKETS, bucket_for
            with MetricTimer(self.metrics[OP_TIME]):
                for (map_id, sorted_b, _), counts_host in zip(staged,
                                                              all_counts):
                    layout = []
                    start = 0
                    for pid_out in range(self.num_partitions):
                        n = int(counts_host[pid_out])
                        if n:
                            layout.append((pid_out, start, n))
                        start += n
                    mgr.write_map_output_sorted(shuffle_id, map_id,
                                                sorted_b, layout)
                    whole = batch_device_bytes(sorted_b)
                    bpr = whole / max(int(sorted_b.capacity), 1)
                    eager = sum(
                        bpr * bucket_for(max(n, 1), DEFAULT_ROW_BUCKETS)
                        for _, _, n in layout)
                    saved_bytes += max(0, int(eager - whole))
        else:
            per_map: Dict[int, Dict[int, List[Batch]]] = {}
            with MetricTimer(self.metrics[OP_TIME]):
                for (map_id, sorted_b, _), counts_host in zip(staged,
                                                              all_counts):
                    slices = per_map.setdefault(map_id, {})
                    start = 0
                    for pid_out in range(self.num_partitions):
                        n = int(counts_host[pid_out])
                        if n == 0:
                            continue
                        piece = _slice_rows(xp, sorted_b, start, n)
                        slices.setdefault(pid_out, []).append(piece)
                        start += n
            for map_id in range(child.num_partitions):
                slices = per_map.get(map_id, {})
                merged = {}
                for pid_out, parts in slices.items():
                    merged[pid_out] = parts[0] if len(parts) == 1 else \
                        concat_batches(xp, parts, self.output_names,
                                       self.output_types)
                mgr.write_map_output(shuffle_id, map_id, merged)
        from ..obs import metrics as m
        if obs_sp or m.enabled():
            total = sum(batch_device_bytes(b) for _, b, _ in staged)
            if obs_sp:
                obs_sp.set(shuffle_id=shuffle_id, blocks=len(staged),
                           bytes=total)
            m.counter("tpu_shuffle_write_bytes_total",
                      "device bytes staged by shuffle map writes") \
                .inc(total)
            m.counter("tpu_shuffle_write_blocks_total",
                      "map-output blocks written").inc(len(staged))
            if slice_views:
                m.counter(
                    "tpu_shuffle_write_saved_bytes_total",
                    "device bytes NOT re-staged by the one-pass "
                    "slice-view map write (vs eager per-partition "
                    "gather copies)").inc(saved_bytes)
        from .digest import digest_enabled
        if digest_enabled():
            # publish write-time digests next to the endpoint record:
            # content addressing must survive this writer's death, so
            # the registry (not just the serving catalog) carries them
            from .registry import BlockLocationRegistry
            BlockLocationRegistry.get().note_block_digests(
                shuffle_id, mgr.catalog.digests_for_shuffle(shuffle_id))
        self._shuffle_id = shuffle_id

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from ..io.scan import set_current_input_file
        self._ensure_written(ctx)
        # past an exchange there is no "current file" (Spark's
        # input_file_name() returns "" there; ref InputFileBlockRule.scala)
        set_current_input_file("")
        xp = self.xp
        from ..obs import metrics as m
        from .locality import read_reduce_blocks
        read_batches = m.counter("tpu_shuffle_read_batches_total",
                                 "reduce-side blocks read back")
        # locality-aware read: catalog blocks zero-copy, remote owner
        # groups streamed through the async fetcher (registry-driven)
        for b in read_reduce_blocks(self._shuffle_id, pid,
                                    conf=ctx.conf, xp=xp):
            b = materialize_block(b, xp)
            self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            read_batches.inc()
            yield b


# row-range slicing now lives next to the catalog's slice views
_slice_rows = slice_rows
