"""Adaptive query execution over the caching shuffle.

Ref: GpuCustomShuffleReaderExec.scala (the AQE shuffle reader the
reference substitutes into adaptive plans) + the AQE surgery in
GpuTransitionOverrides.optimizeAdaptiveTransitions.  Spark's AQE
re-plans between query stages using materialized map-output statistics;
this engine materializes a shuffle the first time any reduce partition
is requested, so the same statistics exist at exactly the same point —
the reader below consumes them to:

  * coalesce adjacent small reduce partitions up to an advisory target
    size (fewer, fuller batches downstream), and
  * split skewed partitions for shuffled hash joins: the probe side's
    blocks divide into chunks while the build side replicates, the same
    split-and-replicate shape as Spark's OptimizeSkewedJoin.

Coalesced groups keep reduce ids adjacent, so hash co-location and
range order are both preserved.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from .. import config as cfg
from ..exec.base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, Batch, Exec,
                         ExecContext)
from .exchange import ShuffleExchangeExec
from .manager import TpuShuffleManager


class PartitionSpec:
    """What one post-AQE partition reads from the underlying shuffle."""

    __slots__ = ("reduce_ids", "block_slice")

    def __init__(self, reduce_ids: Sequence[int],
                 block_slice: Optional[Tuple[int, int]] = None):
        self.reduce_ids = list(reduce_ids)
        self.block_slice = block_slice  # (start, end) over the blocks of a
        #                                 single skew-split reduce partition

    def describe(self) -> str:
        if self.block_slice:
            return (f"skew({self.reduce_ids[0]}:"
                    f"{self.block_slice[0]}-{self.block_slice[1]})")
        if len(self.reduce_ids) == 1:
            return str(self.reduce_ids[0])
        return f"coalesced({self.reduce_ids[0]}-{self.reduce_ids[-1]})"


def partition_stats(shuffle_id: int, n_parts: int) -> List[int]:
    """Bytes per reduce partition from the caching shuffle's catalog
    (the MapStatus sizes AQE consumes in Spark)."""
    mgr = TpuShuffleManager.get()
    sizes = []
    for rid in range(n_parts):
        total = 0
        for blk in mgr.catalog.blocks_for_reduce(shuffle_id, rid):
            for b in mgr.catalog.get(blk):
                total += getattr(b, "device_bytes", None) or \
                    getattr(b, "host_size", lambda: 0)() or 0
        sizes.append(total)
    return sizes


def coalesce_specs(sizes: Sequence[int], target: int) -> List[PartitionSpec]:
    """Greedy adjacent grouping up to the advisory size (Spark's
    ShufflePartitionsUtil.coalescePartitions)."""
    specs: List[PartitionSpec] = []
    group: List[int] = []
    acc = 0
    for rid, sz in enumerate(sizes):
        if group and acc + sz > target:
            specs.append(PartitionSpec(group))
            group, acc = [], 0
        group.append(rid)
        acc += sz
    if group:
        specs.append(PartitionSpec(group))
    return specs


def skew_split_specs(sizes: Sequence[int], n_blocks: Sequence[int],
                     factor: float, threshold: int,
                     target: int) -> Optional[List[PartitionSpec]]:
    """Split partitions larger than max(factor*median, threshold) into
    per-block-range chunks (Spark's OptimizeSkewedJoin detection rule).
    Returns None when nothing is skewed."""
    live = sorted(s for s in sizes if s > 0) or [0]
    median = live[len(live) // 2]
    cut = max(factor * median, threshold)
    out: List[PartitionSpec] = []
    any_skew = False
    for rid, sz in enumerate(sizes):
        blocks = n_blocks[rid]
        if sz > cut and blocks > 1:
            any_skew = True
            n_chunks = min(blocks, max(2, round(sz / max(target, 1))))
            per = blocks / n_chunks
            for c in range(n_chunks):
                lo, hi = round(c * per), round((c + 1) * per)
                if hi > lo:
                    out.append(PartitionSpec([rid], (lo, hi)))
        else:
            out.append(PartitionSpec([rid]))
    return out if any_skew else None


class AQEShuffleReadExec(Exec):
    """Adaptive reader over a materialized exchange
    (ref GpuCustomShuffleReaderExec.scala)."""

    def __init__(self, exchange: ShuffleExchangeExec, conf: cfg.RapidsConf,
                 replicate_for: Optional["AQEShuffleReadExec"] = None):
        super().__init__([exchange])
        self.placement = exchange.placement
        self.conf = conf
        self._specs: Optional[List[PartitionSpec]] = None
        self._lock = threading.Lock()
        # when set, this reader mirrors the partner's specs with every
        # block_slice widened to "all blocks" — the replicated build side
        # of a skew-split join
        self.replicate_for = replicate_for

    @property
    def exchange(self) -> ShuffleExchangeExec:
        return self.children[0]

    @property
    def output_names(self):
        return self.exchange.output_names

    @property
    def output_types(self):
        return self.exchange.output_types

    def describe(self):
        # the display name changed across Spark versions
        # (CustomShuffleReader in 3.0/3.1, AQEShuffleRead in 3.2 — ref
        # per-shim AQE exec naming); mirror the session's dialect
        from ..shims import active_shim
        n = len(self._specs) if self._specs is not None else "?"
        return f"{active_shim().aqe_shuffle_read_name()}({n} specs)"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "coalesced/split reduce reads concatenate "
            "blocks in registry order; the combined row multiset per "
            "output partition is stats-determined, not arrival-"
            "determined")

    # -- spec computation ---------------------------------------------------
    def _materialize(self):
        from ..exec.base import SpeculativeSizingMiss
        ctx = ExecContext(self.conf)
        self.exchange._ensure_written(ctx)
        try:
            ctx.verify_spec_guards()
        except SpeculativeSizingMiss:
            # The map stage ran under this PRIVATE context, so its
            # guards never reach the session's speculation-retry: a
            # speculative join feeding this exchange undershot and the
            # catalog now holds TRUNCATED blocks.  Heal locally — drop
            # the bad shuffle and rewrite it exactly, no speculation.
            from ..obs import metrics as m
            m.counter("tpu_shuffle_map_rewrites_total",
                      "map stages rewritten after a speculation guard "
                      "failed under the exchange's private context").inc()
            with self.exchange._write_lock:
                sid = self.exchange._shuffle_id
                self.exchange._shuffle_id = None
            if sid is not None:
                TpuShuffleManager.get().unregister(sid)
            ctx = ExecContext(self.conf)
            ctx.task_context["no_speculation"] = True
            self.exchange._ensure_written(ctx)
            ctx.verify_spec_guards()

    def specs(self) -> List[PartitionSpec]:
        with self._lock:
            if self._specs is not None:
                return self._specs
            if self.replicate_for is not None:
                partner = self.replicate_for.specs()
                self._specs = [PartitionSpec(s.reduce_ids, None)
                               for s in partner]
                return self._specs
            self._materialize()
            sid = self.exchange._shuffle_id
            n = self.exchange.num_partitions
            sizes = partition_stats(sid, n)
            # exchange boundary: the map output is measured and the
            # reduce side has not launched — the one moment a
            # misestimate can still be acted on (analysis/replan.py)
            from ..analysis.replan import on_map_stage_materialized
            on_map_stage_materialized(self, sid, sizes)
            target = self.conf.get(cfg.ADVISORY_PARTITION_SIZE)
            self._specs = coalesce_specs(sizes, target)
            return self._specs

    def set_specs(self, specs: List[PartitionSpec]):
        with self._lock:
            self._specs = list(specs)

    @property
    def num_partitions(self):
        return len(self.specs())

    # -- read ---------------------------------------------------------------
    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from ..io.scan import set_current_input_file
        from .manager import materialize_block
        spec = self.specs()[pid]
        self.exchange._ensure_written(ctx)
        # no "current file" past an exchange (ref InputFileBlockRule.scala)
        set_current_input_file("")
        mgr = TpuShuffleManager.get()
        sid = self.exchange._shuffle_id
        xp = self.xp
        from ..obs import metrics as m
        from .locality import read_reduce_blocks
        read_batches = m.counter("tpu_shuffle_read_batches_total",
                                 "reduce-side blocks read back")
        for rid in spec.reduce_ids:
            if spec.block_slice is not None:
                # skew-split chunks index the LOCAL catalog's block list
                # (skew detection never fires for remote owner groups —
                # see _SkewAwareRead.specs), so the slice path stays a
                # direct catalog read
                lo, hi = spec.block_slice
                blocks = mgr.catalog.blocks_for_reduce(sid, rid)[lo:hi]
                src = (b for blk in blocks for b in mgr.catalog.get(blk))
            else:
                # locality-aware: local blocks zero-copy, remote owner
                # groups streamed through the async fetcher
                src = read_reduce_blocks(sid, rid, conf=self.conf, xp=xp)
            for b in src:
                b = materialize_block(b, xp)
                self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                read_batches.inc()
                yield b


def install_aqe_readers(root: Exec, conf: cfg.RapidsConf) -> Exec:
    """Post-conversion pass wrapping exchanges with adaptive readers
    (the plan surgery GpuTransitionOverrides does for adaptive plans)."""
    if not conf.get(cfg.ADAPTIVE_ENABLED):
        return root
    from ..exec.join import HashJoinExec

    def rewrite(node: Exec) -> Exec:
        new_children = [rewrite(c) for c in node.children]
        node = node.with_new_children(new_children)
        if isinstance(node, HashJoinExec):
            l, r = node.children
            if isinstance(l, ShuffleExchangeExec) and \
                    isinstance(r, ShuffleExchangeExec):
                lread = AQEShuffleReadExec(l, conf)
                if conf.get(cfg.SKEW_JOIN_ENABLED) and \
                        node.how in ("inner", "left_semi", "left_anti",
                                     "left"):
                    lread = _SkewAwareRead(l, conf)
                    rread = AQEShuffleReadExec(r, conf,
                                               replicate_for=lread)
                else:
                    rread = AQEShuffleReadExec(r, conf,
                                               replicate_for=lread)
                return node.with_new_children([lread, rread])
            return node
        new_kids = []
        changed = False
        for c in node.children:
            if isinstance(c, ShuffleExchangeExec) and \
                    _coalescable_consumer(node):
                new_kids.append(AQEShuffleReadExec(c, conf))
                changed = True
            else:
                new_kids.append(c)
        return node.with_new_children(new_kids) if changed else node

    return rewrite(root)


def relink_replicated_readers(root: Exec) -> Exec:
    """Repair ``replicate_for`` after plan surgery.  Passes downstream of
    install_aqe_readers (transition insertion, any with_new_children
    rewrite) clone nodes, so a build-side reader's ``replicate_for`` can
    end up pointing at the PRE-clone probe reader — whose exchange is an
    orphan that would shuffle the probe side a second time at execution
    and leak every block it writes (nothing in the final plan owns its
    shuffle id).  Re-point it at the probe reader actually in the tree."""
    from ..exec.base import DeviceToHostExec, HostToDeviceExec
    from ..exec.join import HashJoinExec

    def unwrap(node: Exec) -> Exec:
        while isinstance(node, (DeviceToHostExec, HostToDeviceExec)) \
                and node.children:
            node = node.children[0]
        return node

    def fix(node: Exec) -> None:
        if isinstance(node, HashJoinExec) and len(node.children) == 2:
            l, r = (unwrap(c) for c in node.children)
            if isinstance(l, AQEShuffleReadExec) and \
                    isinstance(r, AQEShuffleReadExec) and \
                    r.replicate_for is not None and r.replicate_for is not l:
                r.replicate_for = l
        for c in node.children:
            fix(c)

    fix(root)
    return root


class _SkewAwareRead(AQEShuffleReadExec):
    """Probe-side reader that also splits skewed partitions."""

    def specs(self) -> List[PartitionSpec]:
        with self._lock:
            if self._specs is not None:
                return self._specs
            self._materialize()
            sid = self.exchange._shuffle_id
            n = self.exchange.num_partitions
            mgr = TpuShuffleManager.get()
            sizes = partition_stats(sid, n)
            from ..analysis.replan import on_map_stage_materialized
            on_map_stage_materialized(self, sid, sizes)
            n_blocks = [len(mgr.catalog.blocks_for_reduce(sid, rid))
                        for rid in range(n)]
            target = self.conf.get(cfg.ADVISORY_PARTITION_SIZE)
            # skew chunks slice the local catalog's block list; sizes
            # and n_blocks are local-only stats, so with remote owner
            # groups a split would drop (or double-read) remote blocks —
            # fall back to plain coalescing there
            from .registry import BlockLocationRegistry
            remote = BlockLocationRegistry.get().remote_groups(sid)
            split = None if remote else skew_split_specs(
                sizes, n_blocks,
                self.conf.get(cfg.SKEW_JOIN_FACTOR),
                self.conf.get(cfg.SKEW_JOIN_THRESHOLD), target)
            self._specs = split if split is not None else \
                coalesce_specs(sizes, target)
            return self._specs


def _coalescable_consumer(node: Exec) -> bool:
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.sort import SortExec
    from ..exec.window import WindowExec
    from ..exec.aggregate import CpuHashAggregateExec
    return isinstance(node, (TpuHashAggregateExec, CpuHashAggregateExec,
                             SortExec, WindowExec))
