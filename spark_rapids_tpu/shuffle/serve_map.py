"""Runnable map-side block server: the OTHER process of a distributed
shuffle.

``python -m spark_rapids_tpu.shuffle.serve_map --rows N --parts P
--codec lz4 --seed 7`` builds deterministic fact/dim tables, hash-
partitions them with the engine's Spark-compatible murmur3 routing,
registers every partition slice in this process's ShuffleBufferCatalog,
and serves them from a ShuffleServer on an ephemeral port.

Used by ``bench.py --dist`` and the cross-process shuffle test: the
parent process plays the reduce side — it registers this process as the
remote owner of both shuffles and fetches/joins over loopback.

stdout protocol (one line each, flushed):

    PORT <port>          after the server is up
    STATS <json>         after the parent signals done (any stdin line
                         or EOF): codec byte counters, served request
                         counts, and the leak report

The same table-building helpers are imported by the parent for its
in-process reference run, so bit-exactness compares identical inputs."""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Tuple

import numpy as np
import pyarrow as pa

FACT_SID = 1
DIM_SID = 2
_KEYS = 1000  # key cardinality: every dim key appears in the fact side


def build_side_tables(rows: int, seed: int) -> Tuple[pa.RecordBatch,
                                                     pa.RecordBatch]:
    """Deterministic fact(k, v) + dim(k, d) record batches.  Sequential
    v/d lanes keep the payload compressible (the bench measures codec
    ratios on them); the key lane cycles so joins fan out evenly."""
    rng = np.random.RandomState(seed)
    k = (np.arange(rows, dtype=np.int64) * 2654435761 % _KEYS)
    v = np.arange(rows, dtype=np.int64) + int(rng.randint(0, 1000))
    fact = pa.record_batch({"k": pa.array(k), "v": pa.array(v)})
    dk = np.arange(_KEYS, dtype=np.int64)
    dd = dk * 3 + 1
    dim = pa.record_batch({"k": pa.array(dk), "d": pa.array(dd)})
    return fact, dim


def partition_record_batch(rb: pa.RecordBatch, key: str, n_parts: int
                           ) -> Dict[int, pa.RecordBatch]:
    """Split rows by the engine's hash routing (pmod(murmur3(key), n)) —
    the same partitioner the exchange uses, so both processes of the
    distributed join route rows identically."""
    from ..columnar.device import batch_to_device
    from ..expr.core import AttributeReference, EvalContext
    from ..shuffle.partitioning import HashPartitioning
    from .. import types as t
    part = HashPartitioning([AttributeReference(key)], n_parts).bind(
        rb.schema.names, [t.LONG] * len(rb.schema.names))
    b = batch_to_device(rb, xp=np)
    pids = np.asarray(part.partition_ids(np, EvalContext(np, b), b))
    pids = pids[:rb.num_rows]
    out = {}
    tbl = pa.table(rb)
    for pid in range(n_parts):
        idx = np.nonzero(pids == pid)[0]
        if len(idx):
            out[pid] = tbl.take(pa.array(idx)).combine_chunks().to_batches()[0]
    return out


def register_map_outputs(mgr, shuffle_id: int, rb: pa.RecordBatch,
                         key: str, n_parts: int, n_maps: int = 2) -> None:
    """Split the table into ``n_maps`` map tasks and register each map's
    partition slices — several blocks per reduce partition, like a real
    multi-batch map stage."""
    from ..columnar.device import batch_to_device
    rows = rb.num_rows
    per = max(1, (rows + n_maps - 1) // n_maps)
    for mid in range(n_maps):
        piece = rb.slice(mid * per, per)
        if piece.num_rows == 0:
            continue
        parts = partition_record_batch(piece, key, n_parts)
        mgr.write_map_output(shuffle_id, mid, {
            pid: batch_to_device(p, xp=np) for pid, p in parts.items()})


def _arg(flag: str, default: str) -> str:
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return default


def main() -> int:
    rows = int(_arg("--rows", "20000"))
    parts = int(_arg("--parts", "4"))
    codec = _arg("--codec", "none")
    seed = int(_arg("--seed", "7"))
    executor_id = _arg("--executor-id", "serve-map-0")
    from ..memory.meta import set_default_codec
    from ..memory.spill import SpillCatalog
    from ..obs import metrics as m
    from ..obs.health import MetricsServer
    from .manager import TpuShuffleManager
    from .transport import ShuffleServer
    set_default_codec(codec)
    mgr = TpuShuffleManager.get()
    fact, dim = build_side_tables(rows, seed)
    register_map_outputs(mgr, FACT_SID, fact, "k", parts)
    register_map_outputs(mgr, DIM_SID, dim, "k", parts)
    # the fleet endpoint: /metrics + /healthz + /spans on an ephemeral
    # port, advertised so the parent's aggregator scrapes this process
    # and its tracer pulls our serve spans back
    obs = MetricsServer(0)
    server = ShuffleServer(mgr, executor_id=executor_id,
                           obs_port=obs.port).start()
    # "PORT <port> OBS <obs_port>": the parent splits on whitespace and
    # reads field [1], so pre-fleet parents still parse this line
    print(f"PORT {server.port} OBS {obs.port}", flush=True)
    sys.stdin.readline()  # parent signals done (or closes the pipe)
    fact_comp = mgr.compression_stats(FACT_SID)
    dim_comp = mgr.compression_stats(DIM_SID)
    serve_steps = mgr.serve_stats()
    mgr.unregister(FACT_SID)
    mgr.unregister(DIM_SID)
    leaked = mgr.catalog.num_blocks()
    leaks = SpillCatalog.get().leak_report()
    raw_c = m.counter("tpu_shuffle_raw_bytes_total",
                      labelnames=("codec",))
    comp_c = m.counter("tpu_shuffle_compressed_bytes_total",
                       labelnames=("codec",))
    from ..obs.fleet import RemoteSpanStore
    from .transport import _server_requests_counter
    req_c = _server_requests_counter()
    stats = {
        "codec": codec,
        "raw_bytes": raw_c.value(codec=codec),
        "compressed_bytes": comp_c.value(codec=codec),
        "server_metadata_requests": req_c.value(kind="metadata"),
        "server_transfer_requests": req_c.value(kind="transfer"),
        "leaked_blocks": leaked,
        "leaks": len(leaks),
        "fact_compression": fact_comp,
        "dim_compression": dim_comp,
        "serve_seconds_by_step": serve_steps,
        "unpulled_spans": RemoteSpanStore.get().span_count(),
    }
    server.stop()
    obs.close()
    print("STATS " + json.dumps(stats), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
