"""Shuffle peer heartbeats.

Ref: RapidsShuffleHeartbeatManager.scala:50-187 — the driver keeps a
registry of shuffle-capable executors; executors register at startup and
heartbeat periodically; registration responses carry the current peer list
so executors eagerly connect to new peers."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


def _missed_counter():
    from ..obs import metrics as m
    return m.counter("tpu_shuffle_heartbeat_missed_total",
                     "peers expired after missing their heartbeat "
                     "window")


def _peers_gauge():
    from ..obs import metrics as m
    return m.gauge("tpu_shuffle_peers_live",
                   "shuffle-capable peers inside the heartbeat window")


@dataclass
class PeerInfo:
    executor_id: str
    host: str
    port: int
    last_heartbeat: float = field(default_factory=time.monotonic)
    # the peer's /metrics//spans endpoint (0 = none advertised); the
    # FleetAggregator scrapes through this, not the shuffle port
    obs_port: int = 0


class HeartbeatManager:
    """Driver side (ref registerExecutor:97 / executorHeartbeat:118)."""

    def __init__(self, timeout_s: float = 30.0):
        self._peers: Dict[str, PeerInfo] = {}
        self._lock = threading.Lock()
        self.timeout_s = timeout_s

    def register_executor(self, executor_id: str, host: str, port: int,
                          obs_port: int = 0) -> List[PeerInfo]:
        with self._lock:
            self._peers[executor_id] = PeerInfo(executor_id, host, port,
                                                obs_port=int(obs_port))
            out = [p for p in self._peers.values()
                   if p.executor_id != executor_id]
            _peers_gauge().set(len(self._peers))
            return out

    def executor_heartbeat(self, executor_id: str) -> List[PeerInfo]:
        with self._lock:
            now = time.monotonic()
            p = self._peers.get(executor_id)
            if p is not None:
                p.last_heartbeat = now
            return [q for q in self._peers.values()
                    if q.executor_id != executor_id
                    and now - q.last_heartbeat <= self.timeout_s]

    def live_peers(self) -> List[PeerInfo]:
        with self._lock:
            now = time.monotonic()
            return [p for p in self._peers.values()
                    if now - p.last_heartbeat <= self.timeout_s]

    def expire_dead(self) -> List[str]:
        with self._lock:
            now = time.monotonic()
            dead = [k for k, p in self._peers.items()
                    if now - p.last_heartbeat > self.timeout_s]
            for k in dead:
                del self._peers[k]
            if dead:
                _missed_counter().inc(len(dead))
            _peers_gauge().set(len(self._peers))
            return dead


class HeartbeatEndpoint:
    """Executor side: periodic heartbeats on a daemon thread (ref
    RapidsShuffleHeartbeatEndpoint)."""

    def __init__(self, manager: HeartbeatManager, executor_id: str,
                 host: str, port: int, interval_s: float = 5.0,
                 on_peers: Optional[Callable[[List[PeerInfo]], None]] = None,
                 obs_port: int = 0):
        self.manager = manager
        self.executor_id = executor_id
        self.interval_s = interval_s
        self.on_peers = on_peers
        self._stop = threading.Event()
        peers = manager.register_executor(executor_id, host, port,
                                          obs_port=obs_port)
        if on_peers:
            on_peers(peers)
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                peers = self.manager.executor_heartbeat(self.executor_id)
                if self.on_peers:
                    self.on_peers(peers)
            except Exception as ex:
                # a bad beat must not kill the loop (a dead loop means
                # this executor silently expires from every peer list),
                # but it must not vanish either: route through the
                # typed background-error path — counter + health
                # degradation + black-box bundle (tpufsan TPU-R011)
                from ..obs.bgerrors import note_background_error
                note_background_error("heartbeat-loop", ex)

    def stop(self):
        self._stop.set()
