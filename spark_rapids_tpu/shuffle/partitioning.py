"""On-device partitioning: hash / round-robin / range / single.

Ref: GpuHashPartitioning.scala, GpuRoundRobinPartitioning.scala,
GpuRangePartitioner.scala, GpuSinglePartitioning.scala and the slicing
machinery in GpuPartitioning.scala:50-130.

Partition ids compute on device (Spark-compatible: pmod(murmur3(keys), n)
for hash partitioning, so CPU and TPU engines route rows identically);
slicing reuses the stable-compaction kernel — one sort by partition id,
then per-partition span extraction."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch
from ..expr.core import EvalContext, Expression, bind_expression
from ..expr.hashfns import Murmur3Hash
from ..ops.gather import gather_batch


class Partitioning:
    num_partitions: int = 1

    def bind(self, names, dtypes):
        return self

    def partition_ids(self, xp, ctx: EvalContext, batch: DeviceBatch,
                      row_offset: int = 0):
        """int32[cap] partition id per row."""
        raise NotImplementedError

    def describe(self) -> str:
        return f"{type(self).__name__}({self.num_partitions})"


class SinglePartitioning(Partitioning):
    num_partitions = 1

    def partition_ids(self, xp, ctx, batch, row_offset=0):
        return xp.zeros((batch.capacity,), dtype=np.int32)


class HashPartitioning(Partitioning):
    def __init__(self, keys: Sequence[Expression], num_partitions: int):
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self._bound: Optional[Murmur3Hash] = None

    def bind(self, names, dtypes):
        out = HashPartitioning(self.keys, self.num_partitions)
        out._bound = Murmur3Hash(
            [bind_expression(k, names, dtypes) for k in self.keys])
        return out

    def partition_ids(self, xp, ctx, batch, row_offset=0):
        h = self._bound.eval(ctx).col.data.astype(xp.int32)
        n = np.int32(self.num_partitions)
        # Spark: pmod(hash, n)
        r = xp.mod(h, n)
        return xp.where(r < 0, r + n, r).astype(np.int32)


class RoundRobinPartitioning(Partitioning):
    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def partition_ids(self, xp, ctx, batch, row_offset=0):
        idx = xp.arange(batch.capacity, dtype=np.int32) + np.int32(row_offset)
        return xp.mod(idx, np.int32(self.num_partitions))


class RangePartitioning(Partitioning):
    """Range partitioning by sampled bounds (ref GpuRangePartitioner:
    sample rows, pick n-1 boundary rows, bucket by binary search)."""

    def __init__(self, orders, num_partitions: int):
        # orders: [(expr, ascending, nulls_first)]
        self.orders = list(orders)
        self.num_partitions = num_partitions
        self._bound_orders = None
        self.bounds_words: Optional[List] = None  # per-word boundary arrays

    def bind(self, names, dtypes):
        out = RangePartitioning(self.orders, self.num_partitions)
        out._bound_orders = [(bind_expression(e, names, dtypes), asc, nf)
                             for e, asc, nf in self.orders]
        out.bounds_words = self.bounds_words
        return out

    def _row_words(self, xp, ctx, batch):
        from ..ops import segmented as seg
        live = ctx.row_mask()
        words = []
        for e, asc, nf in self._bound_orders:
            v = e.eval(ctx)
            from ..expr.core import ColumnValue, make_column
            if not isinstance(v, ColumnValue):
                v = make_column(ctx, e.data_type(),
                                v.value if v.value is not None else 0,
                                None if v.value is not None else False)
            words += seg.key_words_for_column(xp, v.col, live,
                                              for_grouping=False,
                                              nulls_first=nf, ascending=asc)
        return words

    def compute_bounds(self, xp, ctx, batch):
        """Pick n-1 equally spaced boundary key-words from a sorted batch
        sample."""
        from ..ops import segmented as seg
        words = self._row_words(xp, ctx, batch)
        order = seg.lexsort(xp, words, batch.capacity)
        n = self.num_partitions
        live_n = xp.maximum(batch.num_rows, 1)
        picks = ((xp.arange(n - 1, dtype=np.int64) + 1) * live_n) // n
        picks = xp.clip(picks, 0, batch.capacity - 1).astype(np.int32)
        self.bounds_words = [w[order][picks] for w in words]

    def partition_ids(self, xp, ctx, batch, row_offset=0):
        if self.bounds_words is None:
            self.compute_bounds(xp, ctx, batch)
        words = self._row_words(xp, ctx, batch)
        cap = batch.capacity
        pid = xp.zeros((cap,), dtype=np.int32)
        # row > bound_b (lexicographically) for each of the n-1 bounds
        for b in range(self.num_partitions - 1):
            gt = xp.zeros((cap,), dtype=bool)
            eq = xp.ones((cap,), dtype=bool)
            for w, bw in zip(words, self.bounds_words):
                bv = bw[b]
                gt = gt | (eq & (w > bv))
                eq = eq & (w == bv)
            pid = pid + (gt | eq).astype(np.int32)
        return pid


def slice_batch_by_partition(xp, batch: DeviceBatch, pids,
                             num_partitions: int):
    """Sort rows by partition id (stable) and return (sorted_batch,
    partition_row_counts[int64 np array]).  The caller slices host-side by
    counts — the analog of GpuPartitioning's contiguous split."""
    from ..ops import carry
    live = xp.arange(batch.capacity, dtype=np.int32) < batch.num_rows
    key = xp.where(live, pids, np.int32(num_partitions))  # padding last
    # rows ride the sort as payload lanes (no post-sort gathers)
    _, cols, ex = carry.sort_rows(xp, [key.astype(xp.uint32)],
                                  batch.columns, batch.capacity,
                                  extras=[key])
    sorted_pids = ex[0]
    counts = xp.zeros((num_partitions,), dtype=np.int64)
    if xp is np:
        u, c = np.unique(sorted_pids[sorted_pids < num_partitions],
                         return_counts=True)
        counts[u] = c
    else:
        import jax
        counts = jax.ops.segment_sum(
            (sorted_pids < num_partitions).astype(xp.int32),
            xp.clip(sorted_pids, 0, num_partitions).astype(xp.int32),
            num_segments=num_partitions + 1)[:num_partitions].astype(
                xp.int64)
    return DeviceBatch(cols, batch.num_rows, batch.names), counts
