"""Content-addressed shuffle blocks (tpudsan's dynamic oracle substrate).

Every map-output block gets a 64-bit content digest recorded in the
``ShuffleBufferCatalog`` at write time and advertised in ``TableMeta``
(``content_digest``).  Reduce-side fetches re-digest the deserialized
payload and compare — a mismatch means the bytes decoded fine but are
not the bytes the map task registered (stale replica, bit rot past the
codec's own framing, or a nondeterministic recompute), and fails typed
as ``TpuShuffleDigestError`` so the replica-retry loop prefers another
owner.

The digest is *content*-addressed, not byte-addressed: it hashes the
Arrow-canonical form of the live rows.

* capacity padding never contributes (``batch_to_arrow`` trims to
  ``num_rows``);
* value slots under a null mask are canonicalized to the Arrow
  builder's zero-fill — two batches with equal live values and equal
  null positions digest identically even when the masked garbage
  differs (it does differ between independent recomputes);
* sliced arrays (non-zero offsets, unaligned validity bitmaps) are
  rebased before hashing, so a slice-view block and its gathered
  materialization agree.

That canonical form is exactly what the permuted-replay oracle
(devtools/run_lint.py --dsan) compares across recomputes: a subtree
that declares ``order_stable`` or better must reproduce every block
digest under permuted batch arrival, and every per-reduce multiset
digest under a changed partition count."""

from __future__ import annotations

import hashlib
import io
import os
import struct
from typing import Iterable

import pyarrow as pa

_DIGEST_BYTES = 8  # u64 — rides TableMeta's fixed little-endian struct

# process-wide switch, set from spark.rapids.tpu.dsan.digest.enabled at
# the shuffle write path (ref set_default_codec's session-init pattern);
# the catalog and the fetch verifier both consult it.  The env seed
# lets session-less subprocesses (serve_map, the --dist bench's map
# child) flip it without a conf object.
_enabled = os.environ.get("SPARK_RAPIDS_TPU_DSAN_DIGEST", "1") != "0"


def set_digest_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def digest_enabled() -> bool:
    return _enabled


def _canonical_batch(rb: pa.RecordBatch) -> pa.RecordBatch:
    """Rebuild any column whose raw buffers are not canonical.

    Null-bearing columns carry arbitrary bytes under the mask and
    rebuild through the Arrow builder (zero-filled null slots).  Sliced
    columns carry offsets OR oversized parent buffers — a zero-offset
    head slice keeps the parent's full data buffer and IPC serializes
    it whole, so offset alone is NOT a sufficient test; any column
    whose referenced buffers exceed its logical bytes compacts through
    a C++ take (exact-length buffers, rebased to offset 0)."""
    cols = []
    dirty = False
    for col in rb.columns:
        if col.null_count:
            col = pa.array(col.to_pylist(), type=col.type)
            dirty = True
        elif col.offset or col.get_total_buffer_size() != col.nbytes:
            col = col.take(pa.array(range(len(col)), type=pa.int64()))
            dirty = True
        cols.append(col)
    if not dirty:
        return rb
    return pa.RecordBatch.from_arrays(cols, names=list(rb.schema.names))


def block_digest(batch) -> int:
    """u64 content digest of a batch's live rows (blake2b-8 over the
    canonical Arrow IPC bytes).  Accepts a DeviceBatch (materialized
    through the same ``batch_to_arrow`` path serialization uses) or a
    ``pa.RecordBatch`` directly."""
    if not isinstance(batch, pa.RecordBatch):
        from ..columnar.device import batch_to_arrow
        batch = batch_to_arrow(batch)
    rb = _canonical_batch(batch)
    h = hashlib.blake2b(digest_size=_DIGEST_BYTES)
    h.update(struct.pack("<q", rb.num_rows))
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    h.update(sink.getvalue())
    return int.from_bytes(h.digest(), "little")


def fold_multiset(digests: Iterable[int]) -> int:
    """Order-insensitive fold of block digests: u64 sum of a re-hash of
    each element.  The permuted-replay oracle's changed-partition-count
    leg compares this per reduce partition — the block *set* reshapes
    when the input split changes, but the row multiset feeding each
    reduce partition must not (hash routing is content-determined)."""
    acc = 0
    for d in digests:
        h = hashlib.blake2b(struct.pack("<Q", d & 0xFFFFFFFFFFFFFFFF),
                            digest_size=_DIGEST_BYTES)
        acc = (acc + int.from_bytes(h.digest(), "little")) \
            & 0xFFFFFFFFFFFFFFFF
    return acc


def row_multiset_digest(batch) -> int:
    """Order-insensitive digest of a batch's row multiset: fold of
    per-row digests.  Used by the oracle's changed-split leg where even
    intra-block row order may legitimately differ between runs."""
    if not isinstance(batch, pa.RecordBatch):
        from ..columnar.device import batch_to_arrow
        batch = batch_to_arrow(batch)
    rb = _canonical_batch(batch)
    return fold_multiset(
        block_digest(rb.slice(i, 1)) for i in range(rb.num_rows))
