"""Locality-aware reduce-side reads.

Ref: RapidsCachingReader.scala — the reference's reader splits a reduce
task's blocks into catalog-local ones (served zero-copy from the caching
writer's device buffers) and remote ones (fetched through the UCX
transport), then hands the iterator to the join.

This module is the single read path ``exchange.py`` and the AQE readers
call.  It consults the ``BlockLocationRegistry``:

* blocks in the in-process catalog are yielded as-is (lazy spill
  handles — zero-copy until the consumer materializes), counted in
  ``tpu_shuffle_local_blocks_total`` — the proof they never crossed the
  wire;
* each *remote* owner group streams through ``AsyncBlockFetcher`` so
  decompression (producer thread) overlaps the consumer's join compute,
  with a bounded retry over the group's live replicas: an attempt that
  dies mid-stream resumes from the next replica at the first block not
  yet delivered (block order is the catalog's deterministic sort), so
  every block is delivered exactly once or the stage fails typed with
  provenance — never a hang, never a duplicate."""

from __future__ import annotations

import threading
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .errors import TpuShufflePeerDeadError
from .manager import TpuShuffleManager
from .registry import BlockEndpoint, BlockLocationRegistry

# one connection per peer endpoint, shared across reduce partitions
# (ref RapidsShuffleTransport caching client connections per peer)
_pool: Dict[Tuple[str, int], "object"] = {}
_pool_lock = threading.Lock()


def client_for(host: str, port: int, timeout: float = 30.0):
    from .transport import ShuffleClient
    key = (host, int(port))
    with _pool_lock:
        c = _pool.get(key)
        if c is None:
            c = ShuffleClient(host, int(port), timeout=timeout)
            _pool[key] = c
        return c


def reset_pool() -> None:
    with _pool_lock:
        clients = list(_pool.values())
        _pool.clear()
    for c in clients:
        try:
            c.close()
        except OSError:
            pass


def _read_conf(conf):
    from .. import config as cfg
    if conf is None:
        dflt = cfg.RapidsConf({})
        conf = dflt
    return (conf.get(cfg.SHUFFLE_LOCALITY_ENABLED),
            conf.get(cfg.SHUFFLE_FETCH_MAX_IN_FLIGHT),
            conf.get(cfg.SHUFFLE_FETCH_TIMEOUT_MS) / 1000.0,
            conf.get(cfg.SHUFFLE_FETCH_MAX_RETRIES),
            conf.get(cfg.FLEET_PROPAGATION_ENABLED),
            conf.get(cfg.FLEET_SCRAPE_TIMEOUT_MS) / 1000.0)


def read_reduce_blocks(shuffle_id: int, reduce_id: int, conf=None,
                       xp=np) -> Iterator:
    """Yield every block of one reduce partition: local catalog entries
    first (lazy — the caller materializes), then each remote owner
    group's batches streamed from a live replica."""
    from ..obs import metrics as m
    mgr = TpuShuffleManager.get()
    reg = BlockLocationRegistry.get()
    local_c = m.counter(
        "tpu_shuffle_local_blocks_total",
        "reduce-side blocks served zero-copy from the in-process "
        "catalog — the locality split's proof they never crossed "
        "the wire")
    for block in mgr.catalog.blocks_for_reduce(shuffle_id, reduce_id):
        for b in mgr.catalog.get(block):
            local_c.inc()
            yield b
    (locality_on, window, timeout, max_retries, prop_on,
     pull_timeout) = _read_conf(conf)
    if not locality_on:
        return
    remote = reg.remote_groups(shuffle_id)
    if not remote:
        return
    for group in remote:
        yield from _fetch_group(group, shuffle_id, reduce_id, reg, xp,
                                window, timeout, max_retries, m,
                                prop_on, pull_timeout)


def _fetch_group(group, shuffle_id: int, reduce_id: int, reg, xp,
                 window: int, timeout: float, max_retries: int, m,
                 prop_on: bool = True, pull_timeout: float = 2.0
                 ) -> Iterator:
    """Stream one owner group's blocks, retrying across live replicas.

    ``delivered`` counts blocks already handed to the consumer; a retry
    resumes the replica's deterministic block order past that point, so
    the group completes exactly once.

    Fleet propagation: each attempt opens a ``shuffle.fetch`` span and
    threads its (trace_id, span_id, tenant) down the wire; when the
    attempt finishes the producer's serve spans are pulled back over
    its /spans endpoint and grafted under the fetch span, skew-
    corrected.  Orphan hygiene: a peer that negotiated v2 but whose
    spans cannot be recovered (died mid-fetch, pull failed) closes the
    fetch span with ``spans_lost`` and counts
    tpu_trace_remote_spans_lost_total — never an unclosed span."""
    from ..obs.tracer import SPAN, active_tracer, trace_event
    from .transport import AsyncBlockFetcher
    retries_c = m.counter(
        "tpu_shuffle_fetch_retries_total",
        "remote fetch attempts re-driven against another live replica "
        "after a typed failure")
    tracer = active_tracer() if prop_on else None
    delivered = 0
    attempts = 0
    tried = []
    last_exc: Optional[BaseException] = None
    while attempts <= max_retries:
        live = reg.live_endpoints(group)
        # rotate so a retry prefers a replica not just tried
        if tried and len(live) > 1:
            live = [e for e in live if e.executor_id != tried[-1]] + \
                [e for e in live if e.executor_id == tried[-1]]
        if not live:
            break
        ep = live[0]
        attempts += 1
        if attempts > 1:
            retries_c.inc()
        tried.append(ep.executor_id)
        client = client_for(ep.host, ep.port, timeout)
        ctx = None
        sid = None
        if tracer is not None:
            from ..obs.fleet import TraceContext, current_tenant
            sid = tracer.start("shuffle.fetch", SPAN,
                               shuffle_id=shuffle_id,
                               reduce_id=reduce_id,
                               peer=ep.executor_id, attempt=attempts)
            if sid is not None:
                ctx = TraceContext(tracer.trace_id, sid,
                                   current_tenant())
        fetcher = AsyncBlockFetcher(
            client, shuffle_id, reduce_id, xp=xp, window=window,
            timeout=timeout, heartbeat=reg.heartbeat,
            peer_id=ep.executor_id, ctx=ctx)
        already = delivered  # handed over by previous attempts
        skipped = 0
        fetched_here = 0
        try:
            for b in fetcher.blocks():
                if skipped < already:
                    skipped += 1
                    continue
                delivered += 1
                fetched_here += 1
                yield b
            if tracer is not None and sid is not None:
                tracer.add_attrs(sid, blocks=fetched_here)
                tracer.end(sid, "ok")
                _merge_serve_spans(tracer, sid, client, ep, ctx,
                                   pull_timeout)
            if fetched_here or delivered or attempts:
                trace_event("shuffle.remote_fetch",
                            shuffle_id=shuffle_id, reduce_id=reduce_id,
                            peer=ep.executor_id, blocks=delivered,
                            attempts=attempts)
            return
        except TpuShufflePeerDeadError as ex:
            last_exc = ex
        except Exception as ex:  # typed + counted by the fetcher
            last_exc = ex
        if tracer is not None and sid is not None:
            # the attempt failed: the span closes typed NOW, and any
            # serve spans the peer may hold for it are declared lost —
            # a dead peer's /spans will never answer, and a live one's
            # partial record would mis-parent under a failed attempt
            _note_spans_lost(tracer, sid, client, ctx,
                             repr(last_exc))
    detail = (f"shuffle {shuffle_id} reduce {reduce_id}: owner group "
              f"{[e.executor_id for e in group]} exhausted after "
              f"{attempts} attempt(s) (tried {tried}, "
              f"{delivered} block(s) delivered)")
    if last_exc is not None:
        last_exc.fetch_provenance = detail
        raise last_exc
    # no replica was even attemptable: every endpoint heartbeat-dead.
    # Count it here — the fetcher's classifier never saw this failure
    m.counter("tpu_shuffle_fetch_errors_total",
              "async fetch failures by kind",
              labelnames=("kind",)).labels(kind="peer_dead").inc()
    raise TpuShufflePeerDeadError(
        ",".join(e.executor_id for e in group), detail=detail)


def _ctx_was_sendable(client, ctx) -> bool:
    """Did this attempt actually put a context on the wire?  Only then
    can the producer hold spans for it (pre-v2 peers never saw one)."""
    return ctx is not None and (client.last_peer_version or 0) >= 2


def _merge_serve_spans(tracer, sid, client, ep, ctx,
                       pull_timeout: float) -> None:
    """Post-attempt: drain the producer's serve spans for this trace
    and graft them under the (already closed) fetch span.  Every
    failure downgrades to spans_lost accounting — the read path has
    the data; observability loss must never fail it."""
    if not _ctx_was_sendable(client, ctx):
        return
    if not client.peer_obs_port:
        return
    from ..obs.fleet import pull_remote_spans
    try:
        spans = pull_remote_spans(ep.host, client.peer_obs_port,
                                  tracer.trace_id,
                                  timeout_s=pull_timeout)
        tracer.add_remote_spans(
            sid, spans, offset_ns=client.clock_offset_ns or 0,
            proc=client.peer_executor_id or ep.executor_id)
    except Exception as ex:
        _note_spans_lost(tracer, sid, client, ctx,
                         f"spans pull failed: {ex!r}", force=True)


def _note_spans_lost(tracer, sid, client, ctx, error: str,
                     force: bool = False) -> None:
    """Orphan hygiene: close the fetch span typed with a spans_lost
    annotation and count the loss.  No-op when no context ever crossed
    the wire (nothing remote exists to lose)."""
    tracer.end(sid, "error", error)  # no-op if already closed
    if not force and not _ctx_was_sendable(client, ctx):
        return
    tracer.add_attrs(sid, spans_lost=True)
    tracer.note_remote_spans_lost()
    from ..obs.fleet import remote_lost_counter
    remote_lost_counter().inc()
