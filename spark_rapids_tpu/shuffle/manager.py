"""Shuffle manager + spill-backed buffer catalog.

Ref: RapidsShuffleInternalManagerBase.scala:74-462 (caching writer keeps
batches in device memory, no row serialization; reader serves local blocks
from the catalog zero-copy) and ShuffleBufferCatalog.scala.

The TPU realization keeps each map task's partition slices registered in a
catalog keyed by (shuffle_id, map_id, reduce_id).  The catalog is the
single registration choke point into memory/spill.py: every stored block
is a spill-managed handle (SpillableBatch or a row-range view over one),
so the memory framework can demote shuffle retention DEVICE->HOST->DISK
under pressure and the memsan ledger sees every byte the shuffle holds.

Two block representations coexist:

* whole blocks — one SpillableBatch per (map, reduce) pair (the eager
  path, and everything arriving via ``write_map_output``);
* slice views — ``ShuffleBlockSlice``: the map batch is sorted by target
  partition ONCE and registered as ONE spillable buffer; each reduce
  partition's block is a (start, num_rows) view that gathers its rows
  lazily at first read.  The write path stages each batch's bytes once
  instead of once per reduce partition (see ``write_map_output_sorted``).
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..columnar.device import DeviceBatch


class ShuffleBlockId(tuple):
    """(shuffle_id, map_id, reduce_id)."""

    def __new__(cls, shuffle_id: int, map_id: int, reduce_id: int):
        return super().__new__(cls, (shuffle_id, map_id, reduce_id))


def slice_rows(xp, batch: DeviceBatch, start: int, n: int) -> DeviceBatch:
    """Host-driven row-range slice of a (sorted) batch; keeps buffers on
    device via gather."""
    from ..columnar.device import DEFAULT_ROW_BUCKETS, bucket_for
    from ..ops.gather import gather_batch
    cap = bucket_for(max(n, 1), DEFAULT_ROW_BUCKETS)
    idx = xp.arange(cap, dtype=np.int32) + np.int32(start)
    idx = xp.clip(idx, 0, batch.capacity - 1)
    valid = xp.arange(cap, dtype=np.int32) < n
    out = gather_batch(xp, batch, idx, valid, n)
    return DeviceBatch(out.columns, n, batch.names)


def materialize_block(b, xp):
    """Resolve any catalog block (SpillableBatch, ShuffleBlockSlice, or a
    raw batch) to a concrete batch on ``xp``."""
    get = getattr(b, "get_batch", None)
    return get(xp) if get is not None else b


class _SharedMapOutput:
    """One sorted map-output batch shared by every slice view cut from
    it; the spill registration closes when the last view releases."""

    __slots__ = ("sb", "_refs", "_lock")

    def __init__(self, sb, refs: int):
        self.sb = sb  # SpillableBatch
        self._refs = refs
        self._lock = threading.Lock()

    def get_batch(self, xp):
        return self.sb.get_batch(xp)

    @property
    def device_bytes(self):
        return getattr(self.sb, "device_bytes", 0)

    def release(self):
        with self._lock:
            self._refs -= 1
            last = self._refs <= 0
        if last:
            self.sb.close()


class ShuffleBlockSlice:
    """Row-range view over one shared sorted map-output batch.

    Duck-types the SpillableBatch surface the shuffle readers use
    (``get_batch``/``num_rows``/``device_bytes``/``close``) so it can sit
    in the catalog next to whole blocks."""

    __slots__ = ("_shared", "start", "num_rows", "_total_rows")

    def __init__(self, shared: _SharedMapOutput, start: int, num_rows: int,
                 total_rows: int):
        self._shared = shared
        self.start = start
        self.num_rows = num_rows
        self._total_rows = max(total_rows, 1)

    def get_batch(self, xp) -> DeviceBatch:
        base = self._shared.get_batch(xp)
        return slice_rows(xp, base, self.start, self.num_rows)

    @property
    def device_bytes(self) -> int:
        # proportional share of the shared buffer: exact enough for AQE
        # partition statistics, and it sums to the buffer's real bytes
        return int(self._shared.device_bytes
                   * (self.num_rows / self._total_rows))

    def close(self):
        self._shared.release()


class ShuffleBufferCatalog:
    """Registry of shuffle buffers (ref ShuffleBufferCatalog.scala).

    Registration choke point: ``add`` spill-registers raw device batches,
    so nothing reaches the catalog outside the memory framework's view."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buffers: Dict[ShuffleBlockId, List] = {}
        # per-shuffle schema fingerprint, recorded once at first add: all
        # blocks of one shuffle share the child plan's schema, so the
        # block server can answer metadata requests from these stats
        # without materializing (let alone serializing) any payload
        self._schema_fp: Dict[int, int] = {}
        # per-block content digests keyed ((shuffle,map,reduce), index),
        # computed at map-write time (spark.rapids.tpu.dsan.digest.
        # enabled) — the metadata handler only LOOKS THEM UP, so its
        # O(blocks) no-materialize contract holds
        self._digests: Dict[Tuple[ShuffleBlockId, int], int] = {}

    def _note_schema(self, shuffle_id: int, batch) -> None:
        if shuffle_id in self._schema_fp:
            return
        names = getattr(batch, "names", None)
        if names is None:
            return
        from ..memory.meta import schema_fingerprint
        self._schema_fp[shuffle_id] = schema_fingerprint(
            names, batch.dtypes)

    def schema_fp(self, shuffle_id: int) -> int:
        with self._lock:
            return self._schema_fp.get(shuffle_id, 0)

    def add(self, block: ShuffleBlockId, batch) -> None:
        from ..memory.spill import SpillCatalog, SpillPriority
        from .digest import block_digest, digest_enabled
        with self._lock:
            self._note_schema(block[0], batch)
        dg = 0
        if digest_enabled():
            dg = block_digest(materialize_block(batch, np))
        if isinstance(batch, DeviceBatch):
            batch = SpillCatalog.get().register(batch,
                                                SpillPriority.SHUFFLE)
        with self._lock:
            bufs = self._buffers.setdefault(block, [])
            if dg:
                self._digests[(block, len(bufs))] = dg
            bufs.append(batch)

    def add_sliced(self, shuffle_id: int, map_id: int,
                   sorted_batch: DeviceBatch,
                   layout: Iterable[Tuple[int, int, int]]) -> None:
        """Register ONE sorted map batch and cut per-reduce views from
        it.  ``layout`` is (reduce_id, start, num_rows) triples; the
        shared spill registration lives until every view closes."""
        from ..memory.spill import SpillCatalog, SpillPriority
        from .digest import block_digest, digest_enabled
        layout = [t for t in layout if t[2] > 0]
        if not layout:
            return
        with self._lock:
            self._note_schema(shuffle_id, sorted_batch)
        slice_digests = {}
        if digest_enabled():
            # ONE host conversion of the sorted batch; per-reduce digests
            # come from arrow row-range slices of it (block_digest
            # rebases sliced buffers, so these agree with the digest of
            # the gathered materialization the block server serves)
            from ..columnar.device import batch_to_arrow
            rb = batch_to_arrow(materialize_block(sorted_batch, np))
            for reduce_id, start, n in layout:
                slice_digests[reduce_id] = block_digest(rb.slice(start, n))
        sb = sorted_batch
        if isinstance(sb, DeviceBatch):
            sb = SpillCatalog.get().register(sb, SpillPriority.SHUFFLE)
        total = int(getattr(sorted_batch, "num_rows", 0)) or \
            sum(n for _, _, n in layout)
        shared = _SharedMapOutput(sb, refs=len(layout))
        with self._lock:
            for reduce_id, start, n in layout:
                blk = ShuffleBlockId(shuffle_id, map_id, reduce_id)
                bufs = self._buffers.setdefault(blk, [])
                dg = slice_digests.get(reduce_id, 0)
                if dg:
                    self._digests[(blk, len(bufs))] = dg
                bufs.append(ShuffleBlockSlice(shared, start, n, total))

    def get(self, block: ShuffleBlockId) -> List:
        with self._lock:
            return list(self._buffers.get(block, []))

    def digest(self, block: ShuffleBlockId, index: int = 0) -> int:
        """The content digest recorded for one block at map-write time
        (0 when digests were disabled then) — a pure lookup, so the
        metadata handler can carry it without materializing anything."""
        with self._lock:
            return self._digests.get((block, index), 0)

    def digests_for_shuffle(self, shuffle_id: int
                            ) -> Dict[Tuple[ShuffleBlockId, int], int]:
        """All recorded digests of one shuffle — what the map stage
        publishes to the BlockLocationRegistry alongside its endpoint."""
        with self._lock:
            return {k: v for k, v in self._digests.items()
                    if k[0][0] == shuffle_id}

    def blocks_for_reduce(self, shuffle_id: int, reduce_id: int
                          ) -> List[ShuffleBlockId]:
        with self._lock:
            return sorted(b for b in self._buffers
                          if b[0] == shuffle_id and b[2] == reduce_id)

    def remove_shuffle(self, shuffle_id: int) -> None:
        """Drop a shuffle's blocks AND release their spill registrations —
        otherwise the process-global SpillCatalog grows without bound and
        its device-budget accounting spills live buffers forever."""
        with self._lock:
            doomed = []
            for k in [b for b in self._buffers if b[0] == shuffle_id]:
                doomed.extend(self._buffers.pop(k))
            self._schema_fp.pop(shuffle_id, None)
            for k in [k for k in self._digests if k[0][0] == shuffle_id]:
                self._digests.pop(k)
        for sb in doomed:
            close = getattr(sb, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def num_blocks(self) -> int:
        with self._lock:
            return len(self._buffers)

    def device_bytes(self) -> int:
        """Bytes the catalog currently retains (spill tier included) —
        the number tmsan's shuffle-retention bound models."""
        with self._lock:
            blocks = [b for bs in self._buffers.values() for b in bs]
        return sum(int(getattr(b, "device_bytes", 0) or 0) for b in blocks)


class TpuShuffleManager:
    """Process-wide shuffle service (ref GpuShuffleEnv + the shuffle
    manager's writer/reader split)."""

    _instance: Optional["TpuShuffleManager"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.catalog = ShuffleBufferCatalog()
        self._ids = itertools.count()
        self._written: Dict[Tuple[int, int], bool] = {}
        self._written_lock = threading.Lock()
        # per-shuffle (raw, encoded) payload byte totals, fed by every
        # transfer/spill serialization of this shuffle's blocks — the
        # per-shuffle compression ratio for spans and SUITE_JSON
        self._comp: Dict[int, List[int]] = {}
        self._comp_lock = threading.Lock()
        # per-shuffle serve-side seconds by step (decode/catalog_read/
        # serialize/compress/send), fed by the block server — the
        # per-peer serve breakdown serve_map ships in its STATS line
        self._serve: Dict[int, Dict[str, float]] = {}

    @classmethod
    def get(cls) -> "TpuShuffleManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TpuShuffleManager()
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None

    def new_shuffle_id(self) -> int:
        return next(self._ids)

    # -- write side ---------------------------------------------------------
    def write_map_output(self, shuffle_id: int, map_id: int,
                         slices: Dict[int, DeviceBatch]) -> None:
        """Register one map task's partition slices (ref
        RapidsCachingWriter.write).  Batches stay live in device memory
        but the catalog registers them spillable, so memory pressure
        demotes them HOST->DISK exactly like the reference's
        shuffle-buffer spill."""
        for reduce_id, batch in slices.items():
            self.catalog.add(ShuffleBlockId(shuffle_id, map_id, reduce_id),
                             batch)
        with self._written_lock:
            self._written[(shuffle_id, map_id)] = True

    def write_map_output_sorted(self, shuffle_id: int, map_id: int,
                                sorted_batch: DeviceBatch,
                                layout: Iterable[Tuple[int, int, int]]
                                ) -> None:
        """One-pass map write: the partition-sorted batch registers once,
        reduce partitions become lazy row-range views (the slice-view
        write path, spark.rapids.tpu.shuffle.sliceViews)."""
        self.catalog.add_sliced(shuffle_id, map_id, sorted_batch, layout)
        with self._written_lock:
            self._written[(shuffle_id, map_id)] = True

    def map_done(self, shuffle_id: int, map_id: int) -> bool:
        # map-completion flags are read by remote reduce readers while
        # other map tasks are still publishing: the dict mutates under
        # a reader's feet without this lock (tpucsan audit, PR 13)
        with self._written_lock:
            return self._written.get((shuffle_id, map_id), False)

    # -- read side ----------------------------------------------------------
    def read_partition(self, shuffle_id: int, reduce_id: int
                       ) -> Iterator[DeviceBatch]:
        """Serve all blocks of one reduce partition (local zero-copy; the
        transport layer adds remote fetch, ref RapidsCachingReader)."""
        for block in self.catalog.blocks_for_reduce(shuffle_id, reduce_id):
            for b in self.catalog.get(block):
                yield b

    # -- compression accounting ---------------------------------------------
    def note_payload_sizes(self, shuffle_id: int, raw: int,
                           encoded: int) -> None:
        with self._comp_lock:
            tot = self._comp.setdefault(shuffle_id, [0, 0])
            tot[0] += int(raw)
            tot[1] += int(encoded)

    def note_serve_time(self, shuffle_id: int, step: str,
                        seconds: float) -> None:
        with self._comp_lock:
            steps = self._serve.setdefault(shuffle_id, {})
            steps[step] = steps.get(step, 0.0) + float(seconds)

    def serve_stats(self, shuffle_id: Optional[int] = None) -> Dict:
        """Serve-side seconds by step — one shuffle's, or all shuffles
        folded together (what serve_map reports at exit)."""
        with self._comp_lock:
            if shuffle_id is not None:
                return dict(self._serve.get(shuffle_id, {}))
            out: Dict[str, float] = {}
            for steps in self._serve.values():
                for step, secs in steps.items():
                    out[step] = out.get(step, 0.0) + secs
            return out

    def compression_stats(self, shuffle_id: int) -> Optional[Dict]:
        with self._comp_lock:
            tot = self._comp.get(shuffle_id)
            if tot is None or tot[0] <= 0:
                return None
            raw, enc = tot
        return {"raw_bytes": raw, "compressed_bytes": enc,
                "ratio": enc / raw}

    def unregister(self, shuffle_id: int):
        # sink the shuffle's lifetime compression ratio into the flight
        # recorder before the books close (metrics keep the codec-level
        # totals; this is the per-shuffle view)
        stats = self.compression_stats(shuffle_id)
        if stats is not None:
            from ..obs.tracer import trace_event
            trace_event("shuffle.compression", shuffle_id=shuffle_id,
                        raw_bytes=stats["raw_bytes"],
                        compressed_bytes=stats["compressed_bytes"],
                        ratio=stats["ratio"])
        self.catalog.remove_shuffle(shuffle_id)
        with self._comp_lock:
            self._comp.pop(shuffle_id, None)
            self._serve.pop(shuffle_id, None)
        from .registry import BlockLocationRegistry
        BlockLocationRegistry.get().forget_shuffle(shuffle_id)
