"""Shuffle manager + buffer catalog.

Ref: RapidsShuffleInternalManagerBase.scala:74-462 (caching writer keeps
batches in device memory, no row serialization; reader serves local blocks
from the catalog zero-copy) and ShuffleBufferCatalog.scala.

The TPU realization keeps each map task's partition slices as live device
(or host) batches registered in a catalog keyed by
(shuffle_id, map_id, reduce_id).  Spill integration: each stored batch is
wrapped SpillableShuffleBuffer so the memory framework can demote it
DEVICE->HOST->DISK under pressure (memory/spill.py)."""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..columnar.device import DeviceBatch


class ShuffleBlockId(tuple):
    """(shuffle_id, map_id, reduce_id)."""

    def __new__(cls, shuffle_id: int, map_id: int, reduce_id: int):
        return super().__new__(cls, (shuffle_id, map_id, reduce_id))


class ShuffleBufferCatalog:
    """Registry of shuffle buffers (ref ShuffleBufferCatalog.scala)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buffers: Dict[ShuffleBlockId, List] = {}
        self._bytes = 0

    def add(self, block: ShuffleBlockId, batch) -> None:
        with self._lock:
            self._buffers.setdefault(block, []).append(batch)

    def get(self, block: ShuffleBlockId) -> List:
        with self._lock:
            return list(self._buffers.get(block, []))

    def blocks_for_reduce(self, shuffle_id: int, reduce_id: int
                          ) -> List[ShuffleBlockId]:
        with self._lock:
            return sorted(b for b in self._buffers
                          if b[0] == shuffle_id and b[2] == reduce_id)

    def remove_shuffle(self, shuffle_id: int) -> None:
        """Drop a shuffle's blocks AND release their spill registrations —
        otherwise the process-global SpillCatalog grows without bound and
        its device-budget accounting spills live buffers forever."""
        with self._lock:
            for k in [b for b in self._buffers if b[0] == shuffle_id]:
                for sb in self._buffers[k]:
                    close = getattr(sb, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:
                            pass
                del self._buffers[k]

    def num_blocks(self) -> int:
        with self._lock:
            return len(self._buffers)


class TpuShuffleManager:
    """Process-wide shuffle service (ref GpuShuffleEnv + the shuffle
    manager's writer/reader split)."""

    _instance: Optional["TpuShuffleManager"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.catalog = ShuffleBufferCatalog()
        self._ids = itertools.count()
        self._written: Dict[Tuple[int, int], bool] = {}

    @classmethod
    def get(cls) -> "TpuShuffleManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TpuShuffleManager()
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None

    def new_shuffle_id(self) -> int:
        return next(self._ids)

    # -- write side ---------------------------------------------------------
    def write_map_output(self, shuffle_id: int, map_id: int,
                         slices: Dict[int, DeviceBatch]) -> None:
        """Register one map task's partition slices (ref
        RapidsCachingWriter.write).  Batches stay live in device memory but
        are registered spillable, so memory pressure demotes them
        HOST->DISK exactly like the reference's shuffle-buffer spill."""
        from ..memory.spill import SpillCatalog, SpillPriority
        spill = SpillCatalog.get()
        for reduce_id, batch in slices.items():
            sb = spill.register(batch, SpillPriority.SHUFFLE) \
                if isinstance(batch, DeviceBatch) else batch
            self.catalog.add(ShuffleBlockId(shuffle_id, map_id, reduce_id),
                             sb)
        self._written[(shuffle_id, map_id)] = True

    def map_done(self, shuffle_id: int, map_id: int) -> bool:
        return self._written.get((shuffle_id, map_id), False)

    # -- read side ----------------------------------------------------------
    def read_partition(self, shuffle_id: int, reduce_id: int
                       ) -> Iterator[DeviceBatch]:
        """Serve all blocks of one reduce partition (local zero-copy; the
        transport layer adds remote fetch, ref RapidsCachingReader)."""
        for block in self.catalog.blocks_for_reduce(shuffle_id, reduce_id):
            for b in self.catalog.get(block):
                yield b

    def unregister(self, shuffle_id: int):
        self.catalog.remove_shuffle(shuffle_id)
