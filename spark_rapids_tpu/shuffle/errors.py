"""Shuffle failure types (ref org/apache/spark/shuffle/rapids/
RapidsShuffleExceptions.scala): fetch failures surface as retryable errors
so the scheduler's stage-retry machinery provides recovery."""


class TpuShuffleError(Exception):
    pass


class TpuShuffleFetchFailedError(TpuShuffleError):
    """A remote block could not be fetched; the caller should retry the
    map stage (lineage recompute model, same as the reference)."""


class TpuShuffleTimeoutError(TpuShuffleFetchFailedError):
    pass
