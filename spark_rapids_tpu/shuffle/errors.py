"""Shuffle failure types (ref org/apache/spark/shuffle/rapids/
RapidsShuffleExceptions.scala): fetch failures surface as retryable errors
so the scheduler's stage-retry machinery provides recovery."""


class TpuShuffleError(Exception):
    pass


class TpuShuffleFetchFailedError(TpuShuffleError):
    """A remote block could not be fetched; the caller should retry the
    map stage (lineage recompute model, same as the reference)."""


class TpuShuffleTimeoutError(TpuShuffleFetchFailedError, TimeoutError):
    """A fetch exceeded its deadline while the peer still looked alive
    (heartbeat expiry covers the dead-peer case).  Also a builtin
    TimeoutError so pre-typed callers keep catching it."""


class TpuShufflePeerDeadError(TpuShuffleFetchFailedError):
    """The serving peer was declared dead by the heartbeat manager.

    Raised instead of letting the socket time out: liveness is decided
    by heartbeat expiry (shuffle/heartbeat.py), so the fetch fails fast
    and carries the peer identity for the retry scheduler."""

    def __init__(self, peer_id: str, detail: str = ""):
        self.peer_id = peer_id
        msg = f"shuffle peer {peer_id!r} declared dead by heartbeat"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class TpuShuffleTruncatedFrameError(TpuShuffleFetchFailedError):
    """The connection closed mid-frame: some bytes of a frame arrived
    but not all of them.  Distinct from a clean close so callers can
    tell a half-written transfer from an idle disconnect."""

    def __init__(self, expected: int, got: int, what: str = "frame"):
        self.expected = expected
        self.got = got
        super().__init__(
            f"truncated shuffle {what}: expected {expected} bytes, "
            f"got {got}")


class TpuShuffleStaleFrameError(TpuShuffleFetchFailedError):
    """A response frame carried a request id other than the in-flight
    request's — a stale answer from a prior timed-out request on the
    same connection.  Accepting it would hand the caller the WRONG
    partition's bytes, so correlation mismatches fail typed and drop
    the connection."""

    def __init__(self, expected: int, got: int):
        self.expected = expected
        self.got = got
        super().__init__(
            f"stale shuffle frame: expected request id {expected}, "
            f"got {got}")


class TpuShuffleBlockMissingError(TpuShuffleFetchFailedError):
    """The peer's catalog has no such block: the map output was never
    registered there, or the shuffle was already released.  Retryable
    against a replica; carries the block key for provenance."""

    def __init__(self, detail: str = ""):
        super().__init__(f"shuffle block missing on peer: {detail}"
                         if detail else "shuffle block missing on peer")


class TpuShuffleVersionError(TpuShuffleFetchFailedError):
    """A frame announced a wire version this build does not speak.
    Versioning fails TYPED on both sides: a server answers an unknown
    request version with a structured MSG_ERROR (never a guess at the
    body layout), and a client treats an unknown response version as
    this error and drops the connection — correlation state is
    unknowable past an unparsed frame."""

    def __init__(self, got: int, supported: str = "1-2"):
        self.got = got
        super().__init__(
            f"unsupported shuffle wire version {got} "
            f"(this build speaks {supported})")


class TpuShuffleDigestError(TpuShuffleFetchFailedError):
    """A fetched block decoded cleanly but its content digest does not
    match the digest the map writer registered (TableMeta.
    content_digest): the payload is internally consistent yet is NOT
    the registered block — a stale replica, bit rot below the codec's
    framing, or a nondeterministic recompute served in place of the
    original.  Carries the block key and both digests so the retry
    scheduler (and tpudsan's oracle) can attribute the divergence."""

    def __init__(self, block, index: int, expected: int, got: int):
        self.block = tuple(block)
        self.index = index
        self.expected = expected
        self.got = got
        sid, mid, rid = self.block
        super().__init__(
            f"shuffle block content digest mismatch: "
            f"({sid},{mid},{rid})[{index}] expected "
            f"{expected:#018x}, got {got:#018x}")


class TpuShuffleCorruptBlockError(TpuShuffleFetchFailedError):
    """A fetched payload failed header validation or codec
    decompression: the bytes arrived complete but do not decode.
    Distinct from truncation (the connection stayed healthy) so the
    retry policy can prefer a replica over the same corrupt source."""

    def __init__(self, detail: str = ""):
        super().__init__(f"corrupt shuffle block: {detail}"
                         if detail else "corrupt shuffle block")
