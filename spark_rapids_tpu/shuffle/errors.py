"""Shuffle failure types (ref org/apache/spark/shuffle/rapids/
RapidsShuffleExceptions.scala): fetch failures surface as retryable errors
so the scheduler's stage-retry machinery provides recovery."""


class TpuShuffleError(Exception):
    pass


class TpuShuffleFetchFailedError(TpuShuffleError):
    """A remote block could not be fetched; the caller should retry the
    map stage (lineage recompute model, same as the reference)."""


class TpuShuffleTimeoutError(TpuShuffleFetchFailedError, TimeoutError):
    """A fetch exceeded its deadline while the peer still looked alive
    (heartbeat expiry covers the dead-peer case).  Also a builtin
    TimeoutError so pre-typed callers keep catching it."""


class TpuShufflePeerDeadError(TpuShuffleFetchFailedError):
    """The serving peer was declared dead by the heartbeat manager.

    Raised instead of letting the socket time out: liveness is decided
    by heartbeat expiry (shuffle/heartbeat.py), so the fetch fails fast
    and carries the peer identity for the retry scheduler."""

    def __init__(self, peer_id: str, detail: str = ""):
        self.peer_id = peer_id
        msg = f"shuffle peer {peer_id!r} declared dead by heartbeat"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class TpuShuffleTruncatedFrameError(TpuShuffleFetchFailedError):
    """The connection closed mid-frame: some bytes of a frame arrived
    but not all of them.  Distinct from a clean close so callers can
    tell a half-written transfer from an idle disconnect."""

    def __init__(self, expected: int, got: int, what: str = "frame"):
        self.expected = expected
        self.got = got
        super().__init__(
            f"truncated shuffle {what}: expected {expected} bytes, "
            f"got {got}")
