"""Shuffle transport: client/server traits, async Transaction model, and a
TCP implementation for cross-process fetches.

Ref: RapidsShuffleTransport.scala:30-120 (transport/client/server traits,
Transaction completion model, MessageType {MetadataRequest, TransferRequest,
Buffer}), RapidsShuffleClient/Server, BufferSendState windows; the UCX
realization lives in shuffle-plugin/.../ucx/UCX.scala.

TPU-native mapping: intra-pod exchanges ride XLA collectives (parallel/
mesh executor — the ICI path); this module is the DCN/cross-process path:
a TCP server serving catalog blocks as (TableMeta, Arrow-IPC body) frames,
an async client with a completion-callback Transaction, and windowed
chunked sends mirroring the bounce-buffer flow control."""

from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..memory.meta import (TableMeta, TpuCorruptPayloadError,
                           deserialize_batch, serialize_batch_with_sizes)
from .errors import (TpuShuffleBlockMissingError, TpuShuffleCorruptBlockError,
                     TpuShuffleError, TpuShuffleFetchFailedError,
                     TpuShufflePeerDeadError, TpuShuffleStaleFrameError,
                     TpuShuffleTimeoutError, TpuShuffleTruncatedFrameError)
from .manager import ShuffleBlockId, TpuShuffleManager, materialize_block

# message types (ref RapidsShuffleTransport.scala:96-119)
MSG_METADATA_REQ = 1
MSG_METADATA_RESP = 2
MSG_TRANSFER_REQ = 3
MSG_BUFFER = 4
MSG_ERROR = 5

# request_id is a full u64: the client draws ids from range(1, 1<<62),
# so a narrower wire field would alias distinct requests once the
# counter passes its width (the 32-bit field wrapped after 4B requests
# and broke response correlation)
_FRAME = struct.Struct("<BQq")  # type, request_id, body_len
CHUNK = 1 << 20  # windowed send size (bounce-buffer analog)

# MSG_ERROR bodies are "code:detail"; codes map to the typed taxonomy
# client-side so a peer's failure reason survives the wire
ERR_BLOCK_MISSING = "block_missing"
ERR_BAD_MESSAGE = "bad_message"


def _server_requests_counter():
    from ..obs import metrics as m
    return m.counter("tpu_shuffle_server_requests_total",
                     "block-server requests served, by kind — metadata "
                     "answers come from catalog stats (O(1)), transfer "
                     "answers stream payload bytes", ("kind",))


class TransactionStatus:
    PENDING = "pending"
    SUCCESS = "success"
    ERROR = "error"


class Transaction:
    """Async completion handle (ref Transaction in the transport trait)."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.status = TransactionStatus.PENDING
        self.error: Optional[str] = None
        self.exc: Optional[BaseException] = None
        self.result = None
        self._done = threading.Event()

    def complete(self, result):
        self.result = result
        self.status = TransactionStatus.SUCCESS
        self._done.set()

    def fail(self, error: str, exc: Optional[BaseException] = None):
        """Record failure; ``exc`` preserves the typed shuffle error so
        ``wait`` re-raises it instead of a generic fetch failure."""
        self.error = error
        self.exc = exc
        self.status = TransactionStatus.ERROR
        self._done.set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TpuShuffleTimeoutError(
                f"shuffle transaction {self.request_id} timed out")
        if self.status == TransactionStatus.ERROR:
            if self.exc is not None:
                raise self.exc
            raise TpuShuffleFetchFailedError(self.error or "unknown")
        return self.result


class ShuffleServer:
    """Serves catalog blocks over TCP (ref RapidsShuffleServer.scala)."""

    def __init__(self, manager: Optional[TpuShuffleManager] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.manager = manager or TpuShuffleManager.get()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        head = _recv_exact(self.request, _FRAME.size)
                        if head is None:
                            return
                        mtype, req_id, blen = _FRAME.unpack(head)
                        body = _recv_exact(self.request, blen) if blen else b""
                        if mtype == MSG_METADATA_REQ:
                            outer._handle_metadata(self.request, req_id,
                                                   body)
                        elif mtype == MSG_TRANSFER_REQ:
                            outer._handle_transfer(self.request, req_id,
                                                   body)
                        else:
                            _send_frame(self.request, MSG_ERROR, req_id,
                                        f"{ERR_BAD_MESSAGE}:unknown "
                                        f"type {mtype}".encode())
                except (ConnectionError, OSError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        # sever in-flight connections too: a stopped server must look
        # DEAD to clients, not keep serving on old sockets forever
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _handle_metadata(self, sock, req_id, body):
        """Answer from catalog-tracked stats — O(blocks), NOT
        O(partition bytes).  Serializing (and compressing) every batch
        just to report row counts made a metadata request cost as much
        as the transfer itself; the catalog records num_rows /
        device_bytes / a per-shuffle schema fingerprint at registration,
        so nothing materializes here."""
        _server_requests_counter().labels(kind="metadata").inc()
        shuffle_id, reduce_id = struct.unpack("<qq", body)
        cat = self.manager.catalog
        fp = cat.schema_fp(shuffle_id)
        blocks = cat.blocks_for_reduce(shuffle_id, reduce_id)
        metas = []
        for blk in blocks:
            for i, b in enumerate(cat.get(blk)):
                nr = getattr(b, "num_rows", 0)
                if not isinstance(nr, int):
                    nr = int(np.asarray(nr))
                nbytes = int(getattr(b, "device_bytes", 0) or 0)
                metas.append((blk, i, TableMeta.of_stats(nr, nbytes, fp)))
        out = struct.pack("<i", len(metas))
        for (sid, mid, rid), i, meta in metas:
            out += struct.pack("<qqqq", sid, mid, rid, i) + meta.pack()
        _send_frame(sock, MSG_METADATA_RESP, req_id, out)

    def _handle_transfer(self, sock, req_id, body):
        _server_requests_counter().labels(kind="transfer").inc()
        sid, mid, rid, idx = struct.unpack("<qqqq", body)
        batches = self.manager.catalog.get(ShuffleBlockId(sid, mid, rid))
        if idx >= len(batches):
            _send_frame(sock, MSG_ERROR, req_id,
                        f"{ERR_BLOCK_MISSING}:({sid},{mid},{rid})[{idx}] "
                        f"not in catalog".encode())
            return
        payload, raw_len, enc_len = serialize_batch_with_sizes(
            _materialize(batches[idx]))
        # per-shuffle compressed/raw totals: the span + SUITE_JSON ratio
        self.manager.note_payload_sizes(sid, raw_len, enc_len)
        # windowed chunked send (bounce-buffer flow, BufferSendState analog)
        total = len(payload)
        _send_frame(sock, MSG_BUFFER, req_id,
                    struct.pack("<q", total))
        for off in range(0, total, CHUNK):
            sock.sendall(payload[off:off + CHUNK])


class ShuffleClient:
    """Fetches remote blocks (ref RapidsShuffleClient + doFetch flow)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._req_ids = iter(range(1, 1 << 62))
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr,
                                                  timeout=self.timeout)
        return self._sock

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def fetch_metadata(self, shuffle_id: int, reduce_id: int) -> Transaction:
        tx = Transaction(next(self._req_ids))
        try:
            with self._lock:
                sock = self._conn()
                _send_frame(sock, MSG_METADATA_REQ, tx.request_id,
                            struct.pack("<qq", shuffle_id, reduce_id))
                mtype, rid, body = _recv_frame(sock)
                _check_correlation(tx, rid)
            if mtype == MSG_ERROR:
                _raise_peer_error(body)
                tx.fail(body.decode())
                return tx
            (n,) = struct.unpack_from("<i", body, 0)
            off = 4
            metas = []
            for _ in range(n):
                sid, mid, red, idx = struct.unpack_from("<qqqq", body, off)
                off += 32
                meta = TableMeta.unpack(body[off:off + TableMeta._S.size])
                off += TableMeta._S.size
                metas.append(((sid, mid, red, idx), meta))
            tx.complete(metas)
        except TpuShuffleError as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=ex)
        except socket.timeout as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=TpuShuffleTimeoutError(str(ex)))
        except OSError as ex:
            self._drop_conn()
            tx.fail(str(ex))
        return tx

    def fetch_block(self, sid: int, mid: int, rid: int, idx: int, xp=np
                    ) -> Transaction:
        tx = Transaction(next(self._req_ids))
        try:
            with self._lock:
                sock = self._conn()
                _send_frame(sock, MSG_TRANSFER_REQ, tx.request_id,
                            struct.pack("<qqqq", sid, mid, rid, idx))
                mtype, req, body = _recv_frame(sock)
                _check_correlation(tx, req)
                if mtype == MSG_ERROR:
                    _raise_peer_error(body)
                    tx.fail(body.decode())
                    return tx
                (total,) = struct.unpack("<q", body)
                payload = _recv_exact(sock, total)
                if payload is None or len(payload) < total:
                    raise TpuShuffleTruncatedFrameError(
                        total, len(payload or b""), what="block body")
            try:
                batch = deserialize_batch(payload, xp=xp)
            except TpuCorruptPayloadError as ex:
                raise TpuShuffleCorruptBlockError(
                    f"({sid},{mid},{rid})[{idx}]: {ex}") from ex
            tx.complete(batch)
        except TpuShuffleError as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=ex)
        except socket.timeout as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=TpuShuffleTimeoutError(str(ex)))
        except OSError as ex:
            self._drop_conn()
            tx.fail(str(ex))
        return tx

    def _drop_conn(self):
        """Connection state after any failure is unknowable (half-read
        frames); reconnect on the next request."""
        try:
            self.close()
        except OSError:
            pass


class AsyncBlockFetcher:
    """Pipelined reduce-side fetch (ref RapidsShuffleClient's
    BufferReceiveState windows + doFetch flow).

    A background thread streams the partition's blocks from the peer
    while the consumer joins the previous block; at most ``window``
    fetched-but-unconsumed blocks buffer in between, so reduce-side host
    memory is bounded at window x block size while transfer overlaps
    per-partition join compute.

    Liveness rides shuffle/heartbeat.py: when a ``heartbeat`` manager
    and ``peer_id`` are wired in, a peer that heartbeat expiry declares
    dead fails the iteration with TpuShufflePeerDeadError immediately —
    before and between block fetches — instead of waiting out a socket
    timeout."""

    _DONE = object()

    def __init__(self, client: "ShuffleClient", shuffle_id: int,
                 reduce_id: int, xp=np, window: int = 4,
                 timeout: float = 30.0, heartbeat=None,
                 peer_id: Optional[str] = None):
        self.client = client
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.xp = xp
        self.window = max(int(window), 1)
        self.timeout = timeout
        self.heartbeat = heartbeat
        self.peer_id = peer_id
        self._stop = threading.Event()

    # -- liveness -----------------------------------------------------------
    def _check_peer(self):
        if self.heartbeat is None or self.peer_id is None:
            return
        self.heartbeat.expire_dead()
        live = {p.executor_id for p in self.heartbeat.live_peers()}
        if self.peer_id not in live:
            raise TpuShufflePeerDeadError(self.peer_id)

    # -- pipeline -----------------------------------------------------------
    def _producer(self, keys, q):
        try:
            for (sid, mid, rid, idx) in keys:
                if self._stop.is_set():
                    return
                self._check_peer()
                b = self.client.fetch_block(sid, mid, rid, idx,
                                            xp=self.xp).wait(self.timeout)
                if not self._put(q, b):
                    return
            self._put(q, self._DONE)
        except BaseException as ex:  # noqa: BLE001 — relayed to consumer
            self._put(q, ex)

    def _put(self, q, item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def blocks(self) -> Iterator:
        """Yield the partition's blocks in block order, prefetching up
        to the window ahead of the consumer."""
        from ..obs import metrics as m
        try:
            self._check_peer()
            metas = self.client.fetch_metadata(
                self.shuffle_id, self.reduce_id).wait(self.timeout)
        except TpuShuffleError as ex:
            raise self._classify(ex, m)
        keys = [k for k, _ in metas]
        if not keys:
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.window)
        t = threading.Thread(target=self._producer, args=(keys, q),
                             name="shuffle-fetcher", daemon=True)
        t.start()
        blocks_c = m.counter("tpu_shuffle_fetch_blocks_total",
                             "blocks fetched by the async fetcher")
        bytes_c = m.counter("tpu_shuffle_fetch_bytes_total",
                            "device bytes fetched by the async fetcher")
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    return
                if isinstance(item, BaseException):
                    raise self._classify(item, m)
                blocks_c.inc()
                if m.enabled():
                    from ..memory.spill import batch_device_bytes
                    bytes_c.inc(batch_device_bytes(item))
                yield item
        finally:
            self._stop.set()

    __iter__ = blocks

    def _classify(self, ex: BaseException, m) -> BaseException:
        """Fold transport failures into the typed error taxonomy and
        count them: a socket error from a heartbeat-dead peer IS a dead
        peer, whatever errno it surfaced as."""
        if isinstance(ex, TpuShufflePeerDeadError):
            kind = "peer_dead"
        elif isinstance(ex, TpuShuffleTruncatedFrameError):
            kind = "truncated"
        elif isinstance(ex, TpuShuffleStaleFrameError):
            kind = "stale"
        elif isinstance(ex, TpuShuffleCorruptBlockError):
            kind = "corrupt"
        elif isinstance(ex, TpuShuffleBlockMissingError):
            kind = "block_missing"
        elif isinstance(ex, TpuShuffleTimeoutError):
            kind = "timeout"
        else:
            try:
                self._check_peer()
            except TpuShufflePeerDeadError as dead:
                dead.__cause__ = ex
                ex, kind = dead, "peer_dead"
            else:
                kind = "fetch_failed"
                if not isinstance(ex, TpuShuffleError):
                    ex = TpuShuffleFetchFailedError(str(ex))
        m.counter("tpu_shuffle_fetch_errors_total",
                  "async fetch failures by kind",
                  labelnames=("kind",)).labels(kind=kind).inc()
        return ex


def _materialize(b):
    return materialize_block(b, np)


def _check_correlation(tx: Transaction, rid: int) -> None:
    """A response must answer THIS request: a mismatched id is a stale
    frame from a prior timed-out request still in the pipe — accepting
    it would return the wrong partition's bytes.  Fails typed; the
    caller drops the connection (its framing is now unknowable)."""
    if rid != tx.request_id:
        raise TpuShuffleStaleFrameError(tx.request_id, rid)


def _raise_peer_error(body: bytes) -> None:
    """Map a MSG_ERROR 'code:detail' body onto the typed taxonomy."""
    text = body.decode(errors="replace")
    code, _, detail = text.partition(":")
    if code == ERR_BLOCK_MISSING:
        raise TpuShuffleBlockMissingError(detail)


def _send_frame(sock, mtype: int, req_id: int, body: bytes):
    sock.sendall(_FRAME.pack(mtype, req_id, len(body)) + body)


def _recv_frame(sock) -> Tuple[int, int, bytes]:
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        raise ConnectionError("peer closed")
    if len(head) < _FRAME.size:
        raise TpuShuffleTruncatedFrameError(_FRAME.size, len(head),
                                            what="frame header")
    mtype, req_id, blen = _FRAME.unpack(head)
    body = _recv_exact(sock, blen) if blen else b""
    if blen and (body is None or len(body) < blen):
        raise TpuShuffleTruncatedFrameError(blen, len(body or b""),
                                            what="frame body")
    return mtype, req_id, body


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf
        buf += chunk
    return buf
