"""Shuffle transport: client/server traits, async Transaction model, and a
TCP implementation for cross-process fetches.

Ref: RapidsShuffleTransport.scala:30-120 (transport/client/server traits,
Transaction completion model, MessageType {MetadataRequest, TransferRequest,
Buffer}), RapidsShuffleClient/Server, BufferSendState windows; the UCX
realization lives in shuffle-plugin/.../ucx/UCX.scala.

TPU-native mapping: intra-pod exchanges ride XLA collectives (parallel/
mesh executor — the ICI path); this module is the DCN/cross-process path:
a TCP server serving catalog blocks as (TableMeta, Arrow-IPC body) frames,
an async client with a completion-callback Transaction, and windowed
chunked sends mirroring the bounce-buffer flow control."""

from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..memory.meta import (TableMeta, TpuCorruptPayloadError,
                           deserialize_batch, serialize_batch_with_sizes)
from .errors import (TpuShuffleBlockMissingError, TpuShuffleCorruptBlockError,
                     TpuShuffleDigestError, TpuShuffleError,
                     TpuShuffleFetchFailedError, TpuShufflePeerDeadError,
                     TpuShuffleStaleFrameError, TpuShuffleTimeoutError,
                     TpuShuffleTruncatedFrameError, TpuShuffleVersionError)
from .manager import ShuffleBlockId, TpuShuffleManager, materialize_block

# message types (ref RapidsShuffleTransport.scala:96-119)
MSG_METADATA_REQ = 1
MSG_METADATA_RESP = 2
MSG_TRANSFER_REQ = 3
MSG_BUFFER = 4
MSG_ERROR = 5
# v2 additions: version/clock handshake.  HELLO rides v1 framing on
# purpose — a pre-v2 server parses it fine (then answers bad_message
# with CORRECT correlation), so negotiation never corrupts the stream.
MSG_HELLO = 6
MSG_HELLO_RESP = 7

# request_id is a full u64: the client draws ids from range(1, 1<<62),
# so a narrower wire field would alias distinct requests once the
# counter passes its width (the 32-bit field wrapped after 4B requests
# and broke response correlation)
_FRAME = struct.Struct("<BQq")  # type, request_id, body_len
CHUNK = 1 << 20  # windowed send size (bounce-buffer analog)

# --- v2 framing: the trace-context header extension ------------------------
# A v2 frame leads with a magic byte that can never be a v1 message
# type, then: version, message type, request id, body length, context
# length; the packed TraceContext blob precedes the body.  The
# (magic, version, mtype, request_id) prefix is FROZEN across all
# future versions so an unknown-version frame can still be refused with
# correct correlation.  Only REQUESTS use v2 framing (the context flows
# consumer -> producer); responses stay v1 so an old client against a
# new server sees pure v1 traffic.
WIRE_V2_MAGIC = 0xE2
WIRE_VERSION = 2
_FRAME2 = struct.Struct("<BBBQqH")  # magic, ver, type, req_id, blen, ctxlen

# MSG_ERROR bodies are "code:detail"; codes map to the typed taxonomy
# client-side so a peer's failure reason survives the wire
ERR_BLOCK_MISSING = "block_missing"
ERR_BAD_MESSAGE = "bad_message"
ERR_BAD_VERSION = "bad_version"
ERR_INTERNAL = "internal"

# hello bodies: request is the client's send timestamp; the response
# echoes it and adds the server's receive/send timestamps (NTP-style
# four-timestamp clock estimate), wire version, /spans-capable obs
# port, and the serving executor's identity
_HELLO_REQ = struct.Struct("<q")
_HELLO_RESP = struct.Struct("<BqqqiH")


def _server_requests_counter():
    from ..obs import metrics as m
    return m.counter("tpu_shuffle_server_requests_total",
                     "block-server requests served, by kind — metadata "
                     "answers come from catalog stats (O(1)), transfer "
                     "answers stream payload bytes", ("kind",))


#: serve-side latency ladder: loopback serves sit in the 10us-10ms
#: decades, far below the fetch-path default buckets
_SERVE_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3,
                  2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.5)


def _serve_hist():
    from ..obs import metrics as m
    return m.histogram("tpu_shuffle_serve_seconds",
                       "block-server time per request step (request "
                       "decode, catalog read, arrow serialize, codec "
                       "compress, socket send)", ("step",),
                       buckets=_SERVE_BUCKETS)


class TransactionStatus:
    PENDING = "pending"
    SUCCESS = "success"
    ERROR = "error"


class Transaction:
    """Async completion handle (ref Transaction in the transport trait)."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.status = TransactionStatus.PENDING
        self.error: Optional[str] = None
        self.exc: Optional[BaseException] = None
        self.result = None
        self._done = threading.Event()

    def complete(self, result):
        self.result = result
        self.status = TransactionStatus.SUCCESS
        self._done.set()

    def fail(self, error: str, exc: Optional[BaseException] = None):
        """Record failure; ``exc`` preserves the typed shuffle error so
        ``wait`` re-raises it instead of a generic fetch failure."""
        self.error = error
        self.exc = exc
        self.status = TransactionStatus.ERROR
        self._done.set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TpuShuffleTimeoutError(
                f"shuffle transaction {self.request_id} timed out")
        if self.status == TransactionStatus.ERROR:
            if self.exc is not None:
                raise self.exc
            raise TpuShuffleFetchFailedError(self.error or "unknown")
        return self.result


# server-side per-connection deadline: generous (reused connections
# idle legitimately between fetch waves) but bounded — liveness, not
# latency
SERVER_IDLE_TIMEOUT_S = 120.0


class ShuffleServer:
    """Serves catalog blocks over TCP (ref RapidsShuffleServer.scala).

    Speaks both wire versions: v1 frames exactly as before (old peers
    keep working), v2 frames whose header extension carries the
    requesting query's TraceContext — those requests additionally
    record serve spans into the RemoteSpanStore for the consumer's
    ``/spans`` pull, parented under the consumer's fetch span."""

    def __init__(self, manager: Optional[TpuShuffleManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 executor_id: str = "", obs_port: int = 0):
        self.manager = manager or TpuShuffleManager.get()
        self.executor_id = executor_id
        self.obs_port = obs_port
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # a hung/silent peer must never pin this handler thread
                # forever (tpufsan TPU-R014); an idle-timeout close
                # surfaces client-side as a typed fetch failure and the
                # locality retry loop reconnects
                self.request.settimeout(SERVER_IDLE_TIMEOUT_S)
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        if not outer._serve_one(self.request):
                            return
                except (ConnectionError, OSError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        # sever in-flight connections too: a stopped server must look
        # DEAD to clients, not keep serving on old sockets forever
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- frame pump ----------------------------------------------------------
    def _serve_one(self, sock) -> bool:
        """Read and answer ONE frame; False on clean disconnect.  The
        first byte discriminates v1 (a message type, all < 0xE2) from
        v2 (the magic byte)."""
        first = _recv_exact(sock, 1)
        if first is None:
            return False
        ctx = None
        if first[0] == WIRE_V2_MAGIC:
            rest = _recv_exact(sock, _FRAME2.size - 1)
            if rest is None or len(rest) < _FRAME2.size - 1:
                return False
            _magic, version, mtype, req_id, blen, clen = \
                _FRAME2.unpack(first + rest)
            ctx_blob = _recv_exact(sock, clen) if clen else b""
            body = _recv_exact(sock, blen) if blen else b""
            if version != WIRE_VERSION:
                # typed refusal with CORRECT correlation: the frozen
                # v2 prefix guarantees req_id parsed right even for a
                # future version whose tail layout we cannot read
                _send_frame(sock, MSG_ERROR, req_id,
                            f"{ERR_BAD_VERSION}:{version}".encode())
                return True
            if ctx_blob:
                from ..obs.fleet import TraceContext
                try:
                    ctx = TraceContext.unpack(ctx_blob)
                except (struct.error, ValueError):
                    ctx = None  # a bad context degrades tracing only
        else:
            rest = _recv_exact(sock, _FRAME.size - 1)
            if rest is None or len(rest) < _FRAME.size - 1:
                return False
            mtype, req_id, blen = _FRAME.unpack(first + rest)
            body = _recv_exact(sock, blen) if blen else b""
        try:
            if mtype == MSG_METADATA_REQ:
                self._handle_metadata(sock, req_id, body, ctx=ctx)
            elif mtype == MSG_TRANSFER_REQ:
                self._handle_transfer(sock, req_id, body, ctx=ctx)
            elif mtype == MSG_HELLO:
                self._handle_hello(sock, req_id, body)
            else:
                _send_frame(sock, MSG_ERROR, req_id,
                            f"{ERR_BAD_MESSAGE}:unknown "
                            f"type {mtype}".encode())
        except (ConnectionError, OSError):
            raise  # the socket itself is gone — nothing to relay on
        except Exception as ex:
            # an engine failure while serving ONE request (corrupt
            # catalog entry, dirty ledger, serializer bug) must reach
            # the requesting peer as a typed refusal it can dispatch
            # on, not as a dropped connection it can only classify as
            # "fetch failed, maybe dead" (tpufsan typed-propagation
            # contract: the fault campaign injects here)
            _send_frame(sock, MSG_ERROR, req_id,
                        f"{ERR_INTERNAL}:{type(ex).__name__}: "
                        f"{ex}".encode())
        return True

    def _handle_hello(self, sock, req_id, body):
        """Version + clock handshake: echo the client's send timestamp
        with our receive/send timestamps (perf_counter_ns — arbitrary
        epoch per process, which is exactly why the client needs the
        four-timestamp offset estimate), plus wire version, the /spans
        obs port, and this executor's identity."""
        # tpulint: allow[TPU-R006] clock-sync protocol timestamps —
        # the raw reads ARE the payload, not engine timing
        t1 = time.perf_counter_ns()
        (t0,) = _HELLO_REQ.unpack_from(body, 0)
        eb = (self.executor_id or "").encode()
        # tpulint: allow[TPU-R006] clock-sync protocol timestamp
        t2 = time.perf_counter_ns()
        _send_frame(sock, MSG_HELLO_RESP, req_id,
                    _HELLO_RESP.pack(WIRE_VERSION, t0, t1, t2,
                                     int(self.obs_port or 0), len(eb))
                    + eb)

    def _recorder(self, ctx, name: str, **attrs):
        if ctx is None:
            return None
        from ..obs.fleet import ServeSpanRecorder
        return ServeSpanRecorder(
            ctx, name,
            proc=self.executor_id or f"server:{self.port}", **attrs)

    def _step(self, rec, shuffle_id: int, step: str, t0_ns: int,
              t1_ns: int) -> None:
        """One timed serve step: the per-kind breakdown histogram and
        the shuffle's serve-time ledger always see it; a span child is
        recorded only when the request carried a TraceContext."""
        secs = max(t1_ns - t0_ns, 0) / 1e9
        _serve_hist().labels(step=step).observe(secs)
        self.manager.note_serve_time(shuffle_id, step, secs)
        if rec is not None:
            rec.step(f"serve.{step}", t0_ns, t1_ns)

    def _handle_metadata(self, sock, req_id, body, ctx=None):
        """Answer from catalog-tracked stats — O(blocks), NOT
        O(partition bytes).  Serializing (and compressing) every batch
        just to report row counts made a metadata request cost as much
        as the transfer itself; the catalog records num_rows /
        device_bytes / a per-shuffle schema fingerprint at registration,
        so nothing materializes here."""
        _server_requests_counter().labels(kind="metadata").inc()
        rec = self._recorder(ctx, "shuffle.serve.metadata")
        # tpulint: allow[TPU-R006] serve-span step boundaries: the
        # producer has no installed tracer — ServeSpanRecorder builds
        # the remote spans the consumer's tracer will graft
        t_in = time.perf_counter_ns()
        shuffle_id, reduce_id = struct.unpack("<qq", body)
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_dec = time.perf_counter_ns()
        cat = self.manager.catalog
        fp = cat.schema_fp(shuffle_id)
        blocks = cat.blocks_for_reduce(shuffle_id, reduce_id)
        metas = []
        for blk in blocks:
            for i, b in enumerate(cat.get(blk)):
                nr = getattr(b, "num_rows", 0)
                if not isinstance(nr, int):
                    nr = int(np.asarray(nr))
                nbytes = int(getattr(b, "device_bytes", 0) or 0)
                # content digest: a cached write-time value — a pure
                # dict lookup, so the no-materialize contract holds
                metas.append((blk, i, TableMeta.of_stats(
                    nr, nbytes, fp, cat.digest(blk, i))))
        out = struct.pack("<i", len(metas))
        for (sid, mid, rid), i, meta in metas:
            out += struct.pack("<qqqq", sid, mid, rid, i) + meta.pack()
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_cat = time.perf_counter_ns()
        _send_frame(sock, MSG_METADATA_RESP, req_id, out)
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_sent = time.perf_counter_ns()
        self._step(rec, shuffle_id, "decode", t_in, t_dec)
        self._step(rec, shuffle_id, "catalog_read", t_dec, t_cat)
        self._step(rec, shuffle_id, "send", t_cat, t_sent)
        if rec is not None:
            rec.set_attrs(shuffle_id=shuffle_id, reduce_id=reduce_id,
                          blocks=len(metas))
            rec.close()

    def _handle_transfer(self, sock, req_id, body, ctx=None):
        _server_requests_counter().labels(kind="transfer").inc()
        rec = self._recorder(ctx, "shuffle.serve.transfer")
        # tpulint: allow[TPU-R006] serve-span step boundaries (see
        # _handle_metadata): producer-side spans for the fleet merge
        t_in = time.perf_counter_ns()
        sid, mid, rid, idx = struct.unpack("<qqqq", body)
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_dec = time.perf_counter_ns()
        self._step(rec, sid, "decode", t_in, t_dec)
        batches = self.manager.catalog.get(ShuffleBlockId(sid, mid, rid))
        if idx >= len(batches):
            _send_frame(sock, MSG_ERROR, req_id,
                        f"{ERR_BLOCK_MISSING}:({sid},{mid},{rid})[{idx}] "
                        f"not in catalog".encode())
            if rec is not None:
                rec.close("error", f"block_missing ({sid},{mid},{rid})"
                                   f"[{idx}]")
            return
        mat = _materialize(batches[idx])
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_cat = time.perf_counter_ns()
        self._step(rec, sid, "catalog_read", t_dec, t_cat)
        timings: Dict[str, int] = {}
        payload, raw_len, enc_len = serialize_batch_with_sizes(
            mat, timings=timings)
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_ser = time.perf_counter_ns()
        # split the serializer's wall between arrow IPC and the codec
        # using its own internal timings (compress is 0ns for codec=none
        # — the span is still recorded so the breakdown shape is stable)
        comp_ns = min(timings.get("compress_ns", 0), t_ser - t_cat)
        self._step(rec, sid, "serialize", t_cat, t_ser - comp_ns)
        self._step(rec, sid, "compress", t_ser - comp_ns, t_ser)
        # per-shuffle compressed/raw totals: the span + SUITE_JSON ratio
        self.manager.note_payload_sizes(sid, raw_len, enc_len)
        # windowed chunked send (bounce-buffer flow, BufferSendState analog)
        total = len(payload)
        _send_frame(sock, MSG_BUFFER, req_id,
                    struct.pack("<q", total))
        for off in range(0, total, CHUNK):
            sock.sendall(payload[off:off + CHUNK])
        # tpulint: allow[TPU-R006] serve-span step boundary
        t_sent = time.perf_counter_ns()
        self._step(rec, sid, "send", t_ser, t_sent)
        if rec is not None:
            rec.set_attrs(shuffle_id=sid, map_id=mid, reduce_id=rid,
                          index=idx, raw_bytes=raw_len,
                          encoded_bytes=enc_len)
            rec.close()


class ShuffleClient:
    """Fetches remote blocks (ref RapidsShuffleClient + doFetch flow).

    On the first request over a connection the client performs the
    MSG_HELLO version/clock handshake.  A pre-v2 peer answers it with a
    correlated ``bad_message`` error — the client then pins the peer to
    v1 and never emits a v2 frame at it, so mixed-version clusters
    degrade to uncorrelated-but-correct v1 traffic instead of framing
    corruption.  A v2 peer's reply carries the NTP-style timestamps
    (fed to ``obs.fleet.ClockSync``), its /spans obs port, and its
    executor identity."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._req_ids = iter(range(1, 1 << 62))
        self._lock = threading.Lock()
        # hello-negotiated peer facts (None version = not negotiated yet)
        self.peer_version: Optional[int] = None
        self.peer_obs_port = 0
        self.peer_executor_id = ""
        self.clock_offset_ns: Optional[int] = None
        self.clock_rtt_ns: Optional[int] = None
        # sticky across _drop_conn: whether any connection to this peer
        # ever negotiated v2 — the orphan-hygiene path needs to know a
        # context COULD have been sent even after the connection died
        self.last_peer_version: Optional[int] = None

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr,
                                                  timeout=self.timeout)
        return self._sock

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    # -- hello / version negotiation ----------------------------------------
    def _ensure_hello(self, sock) -> None:
        """Negotiate once per connection (caller holds the lock)."""
        if self.peer_version is not None:
            return
        req_id = next(self._req_ids)
        # tpulint: allow[TPU-R006] clock-sync protocol timestamps —
        # t0/t3 are the NTP-style handshake's local bracket
        t0 = time.perf_counter_ns()
        _send_frame(sock, MSG_HELLO, req_id, _HELLO_REQ.pack(t0))
        mtype, rid, body = _recv_frame(sock)
        # tpulint: allow[TPU-R006] clock-sync protocol timestamp
        t3 = time.perf_counter_ns()
        if rid != req_id:
            raise TpuShuffleStaleFrameError(req_id, rid)
        if mtype == MSG_ERROR:
            text = body.decode(errors="replace")
            if text.startswith(ERR_BAD_MESSAGE):
                # pre-v2 peer: HELLO is an unknown type to it, but the
                # v1-framed refusal correlated correctly — pin to v1
                self.peer_version = self.last_peer_version = 1
                return
            raise TpuShuffleFetchFailedError(f"hello failed: {text}")
        if mtype != MSG_HELLO_RESP:
            raise TpuShuffleFetchFailedError(
                f"hello answered with message type {mtype}")
        version, t0_echo, t1, t2, obs_port, elen = \
            _HELLO_RESP.unpack_from(body, 0)
        self.peer_executor_id = body[
            _HELLO_RESP.size:_HELLO_RESP.size + elen].decode(
            errors="replace")
        self.peer_version = min(int(version), WIRE_VERSION)
        self.last_peer_version = self.peer_version
        self.peer_obs_port = int(obs_port)
        from ..obs.fleet import ClockSync
        self.clock_offset_ns, self.clock_rtt_ns = \
            ClockSync.estimate(t0_echo, t1, t2, t3)
        if self.peer_executor_id:
            ClockSync.get().observe(self.peer_executor_id,
                                    t0_echo, t1, t2, t3)

    def _send_request(self, sock, mtype: int, req_id: int, body: bytes,
                      ctx) -> None:
        """v2 frame with the packed TraceContext when the peer speaks
        v2 and a context is in hand; plain v1 frame otherwise."""
        if ctx is not None and (self.peer_version or 1) >= 2:
            blob = ctx.pack()
            sock.sendall(_FRAME2.pack(WIRE_V2_MAGIC, WIRE_VERSION,
                                      mtype, req_id, len(body),
                                      len(blob)) + blob + body)
        else:
            _send_frame(sock, mtype, req_id, body)

    def fetch_metadata(self, shuffle_id: int, reduce_id: int,
                       ctx=None) -> Transaction:
        tx = Transaction(next(self._req_ids))
        try:
            with self._lock:
                sock = self._conn()
                self._ensure_hello(sock)
                self._send_request(sock, MSG_METADATA_REQ, tx.request_id,
                                   struct.pack("<qq", shuffle_id,
                                               reduce_id), ctx)
                mtype, rid, body = _recv_frame(sock)
                _check_correlation(tx, rid)
            if mtype == MSG_ERROR:
                _raise_peer_error(body)
                tx.fail(body.decode())
                return tx
            (n,) = struct.unpack_from("<i", body, 0)
            off = 4
            metas = []
            for _ in range(n):
                sid, mid, red, idx = struct.unpack_from("<qqqq", body, off)
                off += 32
                meta = TableMeta.unpack(body[off:off + TableMeta._S.size])
                off += TableMeta._S.size
                metas.append(((sid, mid, red, idx), meta))
            tx.complete(metas)
        except TpuShuffleError as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=ex)
        except socket.timeout as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=TpuShuffleTimeoutError(str(ex)))
        except OSError as ex:
            self._drop_conn()
            tx.fail(str(ex))
        return tx

    def fetch_block(self, sid: int, mid: int, rid: int, idx: int, xp=np,
                    ctx=None) -> Transaction:
        tx = Transaction(next(self._req_ids))
        try:
            with self._lock:
                sock = self._conn()
                self._ensure_hello(sock)
                self._send_request(sock, MSG_TRANSFER_REQ, tx.request_id,
                                   struct.pack("<qqqq", sid, mid, rid, idx),
                                   ctx)
                mtype, req, body = _recv_frame(sock)
                _check_correlation(tx, req)
                if mtype == MSG_ERROR:
                    _raise_peer_error(body)
                    tx.fail(body.decode())
                    return tx
                (total,) = struct.unpack("<q", body)
                payload = _recv_exact(sock, total)
                if payload is None or len(payload) < total:
                    raise TpuShuffleTruncatedFrameError(
                        total, len(payload or b""), what="block body")
            try:
                batch = deserialize_batch(payload, xp=xp)
            except TpuCorruptPayloadError as ex:
                raise TpuShuffleCorruptBlockError(
                    f"({sid},{mid},{rid})[{idx}]: {ex}") from ex
            tx.complete(batch)
        except TpuShuffleError as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=ex)
        except socket.timeout as ex:
            self._drop_conn()
            tx.fail(str(ex), exc=TpuShuffleTimeoutError(str(ex)))
        except OSError as ex:
            self._drop_conn()
            tx.fail(str(ex))
        return tx

    def _drop_conn(self):
        """Connection state after any failure is unknowable (half-read
        frames); reconnect on the next request.  The hello handshake is
        per-connection, so peer facts reset too — the replacement peer
        behind the same address may speak a different version."""
        try:
            self.close()
        except OSError:
            pass
        self.peer_version = None


class AsyncBlockFetcher:
    """Pipelined reduce-side fetch (ref RapidsShuffleClient's
    BufferReceiveState windows + doFetch flow).

    A background thread streams the partition's blocks from the peer
    while the consumer joins the previous block; at most ``window``
    fetched-but-unconsumed blocks buffer in between, so reduce-side host
    memory is bounded at window x block size while transfer overlaps
    per-partition join compute.

    Liveness rides shuffle/heartbeat.py: when a ``heartbeat`` manager
    and ``peer_id`` are wired in, a peer that heartbeat expiry declares
    dead fails the iteration with TpuShufflePeerDeadError immediately —
    before and between block fetches — instead of waiting out a socket
    timeout."""

    _DONE = object()

    def __init__(self, client: "ShuffleClient", shuffle_id: int,
                 reduce_id: int, xp=np, window: int = 4,
                 timeout: float = 30.0, heartbeat=None,
                 peer_id: Optional[str] = None, ctx=None):
        self.client = client
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.xp = xp
        self.window = max(int(window), 1)
        self.timeout = timeout
        self.heartbeat = heartbeat
        self.peer_id = peer_id
        self.ctx = ctx
        self._stop = threading.Event()

    # -- liveness -----------------------------------------------------------
    def _check_peer(self):
        if self.heartbeat is None or self.peer_id is None:
            return
        self.heartbeat.expire_dead()
        live = {p.executor_id for p in self.heartbeat.live_peers()}
        if self.peer_id not in live:
            raise TpuShufflePeerDeadError(self.peer_id)

    # -- pipeline -----------------------------------------------------------
    def _verify_digest(self, key, expected: int, batch) -> None:
        """Read-side content check: re-digest the deserialized batch
        against the write-time digest the metadata response carried.
        Skipped (never guessed) when the writer recorded none or
        digests are disabled locally."""
        from .digest import block_digest, digest_enabled
        if not expected or not digest_enabled():
            return
        got = block_digest(batch)
        from ..obs import metrics as m
        if got != expected:
            m.counter("tpu_shuffle_digest_mismatch_total",
                      "fetched blocks whose content digest did not "
                      "match the map writer's registered digest").inc()
            sid, mid, rid, idx = key
            raise TpuShuffleDigestError((sid, mid, rid), idx,
                                        expected, got)
        m.counter("tpu_shuffle_digest_verified_total",
                  "fetched blocks whose content digest matched the "
                  "map writer's registered digest").inc()

    def _producer(self, metas, q):
        try:
            for (sid, mid, rid, idx), meta in metas:
                if self._stop.is_set():
                    return
                self._check_peer()
                b = self.client.fetch_block(sid, mid, rid, idx,
                                            xp=self.xp,
                                            ctx=self.ctx).wait(self.timeout)
                self._verify_digest((sid, mid, rid, idx),
                                    getattr(meta, "content_digest", 0), b)
                if not self._put(q, b):
                    return
            self._put(q, self._DONE)
        except BaseException as ex:  # noqa: BLE001 — relayed to consumer
            self._put(q, ex)

    def _put(self, q, item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def blocks(self) -> Iterator:
        """Yield the partition's blocks in block order, prefetching up
        to the window ahead of the consumer."""
        from ..obs import metrics as m
        try:
            self._check_peer()
            metas = self.client.fetch_metadata(
                self.shuffle_id, self.reduce_id,
                ctx=self.ctx).wait(self.timeout)
        except TpuShuffleError as ex:
            raise self._classify(ex, m)
        if not metas:
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.window)
        t = threading.Thread(target=self._producer, args=(metas, q),
                             name="shuffle-fetcher", daemon=True)
        t.start()
        blocks_c = m.counter("tpu_shuffle_fetch_blocks_total",
                             "blocks fetched by the async fetcher")
        bytes_c = m.counter("tpu_shuffle_fetch_bytes_total",
                            "device bytes fetched by the async fetcher")
        # cooperative cancel checkpoint: with a query cancel token bound
        # to this thread the blocking q.get() becomes a short poll so a
        # cancel/deadline observed mid-fetch unwinds within ~250ms; the
        # shared finally stops the producer, which drops its in-flight
        # block — no orphaned shuffle state
        from ..obs import progress as prog
        from ..obs.progress import (TpuQueryCancelled,
                                    TpuQueryDeadlineExceeded)
        ctok = prog.current_token()
        try:
            while True:
                if ctok is not None:
                    if ctok.cancelled:
                        raise TpuQueryCancelled(
                            ctok.describe("remote-fetch"),
                            query_id=ctok.query_id,
                            checkpoint="remote-fetch",
                            cause=ctok.cause)
                    if ctok.deadline_exceeded:
                        raise TpuQueryDeadlineExceeded(
                            ctok.describe("remote-fetch"),
                            query_id=ctok.query_id,
                            checkpoint="remote-fetch")
                    try:
                        item = q.get(timeout=0.25)
                    except queue.Empty:
                        continue
                else:
                    item = q.get()
                if item is self._DONE:
                    return
                if isinstance(item, BaseException):
                    raise self._classify(item, m)
                blocks_c.inc()
                if m.enabled():
                    from ..memory.spill import batch_device_bytes
                    bytes_c.inc(batch_device_bytes(item))
                yield item
        finally:
            self._stop.set()

    __iter__ = blocks

    def _classify(self, ex: BaseException, m) -> BaseException:
        """Fold transport failures into the typed error taxonomy and
        count them: a socket error from a heartbeat-dead peer IS a dead
        peer, whatever errno it surfaced as."""
        from ..obs.progress import (TpuQueryCancelled,
                                    TpuQueryDeadlineExceeded)
        if isinstance(ex, (TpuQueryCancelled, TpuQueryDeadlineExceeded)):
            # cancellation is control flow, not a fetch failure: it
            # unwinds with its type/cause/checkpoint intact and is
            # counted once in tpu_cancellations_total, never in the
            # fetch-error counters
            return ex
        if isinstance(ex, TpuShufflePeerDeadError):
            kind = "peer_dead"
        elif isinstance(ex, TpuShuffleTruncatedFrameError):
            kind = "truncated"
        elif isinstance(ex, TpuShuffleStaleFrameError):
            kind = "stale"
        elif isinstance(ex, TpuShuffleCorruptBlockError):
            kind = "corrupt"
        elif isinstance(ex, TpuShuffleDigestError):
            kind = "digest"
        elif isinstance(ex, TpuShuffleBlockMissingError):
            kind = "block_missing"
        elif isinstance(ex, TpuShuffleTimeoutError):
            kind = "timeout"
        else:
            try:
                self._check_peer()
            except TpuShufflePeerDeadError as dead:
                dead.__cause__ = ex
                ex, kind = dead, "peer_dead"
            else:
                kind = "fetch_failed"
                if not isinstance(ex, TpuShuffleError):
                    ex = TpuShuffleFetchFailedError(str(ex))
        m.counter("tpu_shuffle_fetch_errors_total",
                  "async fetch failures by kind",
                  labelnames=("kind",)).labels(kind=kind).inc()
        return ex


def _materialize(b):
    return materialize_block(b, np)


def _check_correlation(tx: Transaction, rid: int) -> None:
    """A response must answer THIS request: a mismatched id is a stale
    frame from a prior timed-out request still in the pipe — accepting
    it would return the wrong partition's bytes.  Fails typed; the
    caller drops the connection (its framing is now unknowable)."""
    if rid != tx.request_id:
        raise TpuShuffleStaleFrameError(tx.request_id, rid)


def _raise_peer_error(body: bytes) -> None:
    """Map a MSG_ERROR 'code:detail' body onto the typed taxonomy."""
    text = body.decode(errors="replace")
    code, _, detail = text.partition(":")
    if code == ERR_BLOCK_MISSING:
        raise TpuShuffleBlockMissingError(detail)
    if code == ERR_BAD_VERSION:
        raise TpuShuffleVersionError(
            int(detail) if detail.isdigit() else -1)
    # ERR_INTERNAL / unknown future codes: still a typed fetch failure
    # carrying the peer's own diagnosis — never fall through silently
    raise TpuShuffleFetchFailedError(text)


def _send_frame(sock, mtype: int, req_id: int, body: bytes):
    sock.sendall(_FRAME.pack(mtype, req_id, len(body)) + body)


def _recv_frame(sock) -> Tuple[int, int, bytes]:
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        raise ConnectionError("peer closed")
    if len(head) < _FRAME.size:
        raise TpuShuffleTruncatedFrameError(_FRAME.size, len(head),
                                            what="frame header")
    if head[0] == WIRE_V2_MAGIC:
        # v2-framed response: _FRAME2 is 4 bytes longer than _FRAME.
        # The (magic, version, mtype, req_id) prefix is frozen, so an
        # unknown version still fails typed instead of corrupting
        # correlation on the bytes after it.
        rest = _recv_exact(sock, _FRAME2.size - _FRAME.size)
        if rest is None or len(rest) < _FRAME2.size - _FRAME.size:
            raise TpuShuffleTruncatedFrameError(
                _FRAME2.size, _FRAME.size + len(rest or b""),
                what="frame header")
        _, ver, mtype, req_id, blen, clen = _FRAME2.unpack(head + rest)
        if ver != WIRE_VERSION:
            raise TpuShuffleVersionError(ver)
        want = clen + blen
        blob = _recv_exact(sock, want) if want else b""
        if want and (blob is None or len(blob) < want):
            raise TpuShuffleTruncatedFrameError(want, len(blob or b""),
                                                what="frame body")
        return mtype, req_id, blob[clen:]
    mtype, req_id, blen = _FRAME.unpack(head)
    body = _recv_exact(sock, blen) if blen else b""
    if blen and (body is None or len(body) < blen):
        raise TpuShuffleTruncatedFrameError(blen, len(body or b""),
                                            what="frame body")
    return mtype, req_id, body


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf
        buf += chunk
    return buf
