"""Shuffle transport: client/server traits, async Transaction model, and a
TCP implementation for cross-process fetches.

Ref: RapidsShuffleTransport.scala:30-120 (transport/client/server traits,
Transaction completion model, MessageType {MetadataRequest, TransferRequest,
Buffer}), RapidsShuffleClient/Server, BufferSendState windows; the UCX
realization lives in shuffle-plugin/.../ucx/UCX.scala.

TPU-native mapping: intra-pod exchanges ride XLA collectives (parallel/
mesh executor — the ICI path); this module is the DCN/cross-process path:
a TCP server serving catalog blocks as (TableMeta, Arrow-IPC body) frames,
an async client with a completion-callback Transaction, and windowed
chunked sends mirroring the bounce-buffer flow control."""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..memory.meta import TableMeta, deserialize_batch, serialize_batch
from .manager import ShuffleBlockId, TpuShuffleManager

# message types (ref RapidsShuffleTransport.scala:96-119)
MSG_METADATA_REQ = 1
MSG_METADATA_RESP = 2
MSG_TRANSFER_REQ = 3
MSG_BUFFER = 4
MSG_ERROR = 5

_FRAME = struct.Struct("<BIq")  # type, request_id, body_len
CHUNK = 1 << 20  # windowed send size (bounce-buffer analog)


class TransactionStatus:
    PENDING = "pending"
    SUCCESS = "success"
    ERROR = "error"


class Transaction:
    """Async completion handle (ref Transaction in the transport trait)."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.status = TransactionStatus.PENDING
        self.error: Optional[str] = None
        self.result = None
        self._done = threading.Event()

    def complete(self, result):
        self.result = result
        self.status = TransactionStatus.SUCCESS
        self._done.set()

    def fail(self, error: str):
        self.error = error
        self.status = TransactionStatus.ERROR
        self._done.set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"shuffle transaction {self.request_id} timed out")
        if self.status == TransactionStatus.ERROR:
            from .errors import TpuShuffleFetchFailedError
            raise TpuShuffleFetchFailedError(self.error or "unknown")
        return self.result


class ShuffleServer:
    """Serves catalog blocks over TCP (ref RapidsShuffleServer.scala)."""

    def __init__(self, manager: Optional[TpuShuffleManager] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.manager = manager or TpuShuffleManager.get()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        head = _recv_exact(self.request, _FRAME.size)
                        if head is None:
                            return
                        mtype, req_id, blen = _FRAME.unpack(head)
                        body = _recv_exact(self.request, blen) if blen else b""
                        if mtype == MSG_METADATA_REQ:
                            outer._handle_metadata(self.request, req_id,
                                                   body)
                        elif mtype == MSG_TRANSFER_REQ:
                            outer._handle_transfer(self.request, req_id,
                                                   body)
                        else:
                            _send_frame(self.request, MSG_ERROR, req_id,
                                        b"bad message")
                except (ConnectionError, OSError):
                    return

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    def _handle_metadata(self, sock, req_id, body):
        shuffle_id, reduce_id = struct.unpack("<qq", body)
        blocks = self.manager.catalog.blocks_for_reduce(shuffle_id,
                                                        reduce_id)
        metas = []
        for blk in blocks:
            for i, b in enumerate(self.manager.catalog.get(blk)):
                b = _materialize(b)
                payload = serialize_batch(b)
                metas.append((blk, i, TableMeta.of(b, payload)))
        out = struct.pack("<i", len(metas))
        for (sid, mid, rid), i, meta in metas:
            out += struct.pack("<qqqq", sid, mid, rid, i) + meta.pack()
        _send_frame(sock, MSG_METADATA_RESP, req_id, out)

    def _handle_transfer(self, sock, req_id, body):
        sid, mid, rid, idx = struct.unpack("<qqqq", body)
        batches = self.manager.catalog.get(ShuffleBlockId(sid, mid, rid))
        if idx >= len(batches):
            _send_frame(sock, MSG_ERROR, req_id, b"no such block")
            return
        payload = serialize_batch(_materialize(batches[idx]))
        # windowed chunked send (bounce-buffer flow, BufferSendState analog)
        total = len(payload)
        _send_frame(sock, MSG_BUFFER, req_id,
                    struct.pack("<q", total))
        for off in range(0, total, CHUNK):
            sock.sendall(payload[off:off + CHUNK])


class ShuffleClient:
    """Fetches remote blocks (ref RapidsShuffleClient + doFetch flow)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._req_ids = iter(range(1, 1 << 62))
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr,
                                                  timeout=self.timeout)
        return self._sock

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def fetch_metadata(self, shuffle_id: int, reduce_id: int) -> Transaction:
        tx = Transaction(next(self._req_ids))
        try:
            with self._lock:
                sock = self._conn()
                _send_frame(sock, MSG_METADATA_REQ, tx.request_id,
                            struct.pack("<qq", shuffle_id, reduce_id))
                mtype, rid, body = _recv_frame(sock)
            if mtype == MSG_ERROR:
                tx.fail(body.decode())
                return tx
            (n,) = struct.unpack_from("<i", body, 0)
            off = 4
            metas = []
            for _ in range(n):
                sid, mid, red, idx = struct.unpack_from("<qqqq", body, off)
                off += 32
                meta = TableMeta.unpack(body[off:off + TableMeta._S.size])
                off += TableMeta._S.size
                metas.append(((sid, mid, red, idx), meta))
            tx.complete(metas)
        except OSError as ex:
            tx.fail(str(ex))
        return tx

    def fetch_block(self, sid: int, mid: int, rid: int, idx: int, xp=np
                    ) -> Transaction:
        tx = Transaction(next(self._req_ids))
        try:
            with self._lock:
                sock = self._conn()
                _send_frame(sock, MSG_TRANSFER_REQ, tx.request_id,
                            struct.pack("<qqqq", sid, mid, rid, idx))
                mtype, req, body = _recv_frame(sock)
                if mtype == MSG_ERROR:
                    tx.fail(body.decode())
                    return tx
                (total,) = struct.unpack("<q", body)
                payload = _recv_exact(sock, total)
            tx.complete(deserialize_batch(payload, xp=xp))
        except OSError as ex:
            tx.fail(str(ex))
        return tx


def _materialize(b):
    from ..memory.spill import SpillableBatch
    if isinstance(b, SpillableBatch):
        return b.get_batch(np)
    return b


def _send_frame(sock, mtype: int, req_id: int, body: bytes):
    sock.sendall(_FRAME.pack(mtype, req_id & 0xFFFFFFFF, len(body)) + body)


def _recv_frame(sock) -> Tuple[int, int, bytes]:
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        raise ConnectionError("peer closed")
    mtype, req_id, blen = _FRAME.unpack(head)
    body = _recv_exact(sock, blen) if blen else b""
    return mtype, req_id, body


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf
        buf += chunk
    return buf
