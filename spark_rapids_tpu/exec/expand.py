"""Expand (grouping sets) and Generate (explode) operators.

Ref: GpuExpandExec.scala (multiple projections per input row, feeding
rollup/cube aggregations) and GpuGenerateExec.scala:560 (explode /
posexplode over array columns).

Generate uses the span-gather technique (ops/gather.py): a count pass
sizes the output (one host sync for the capacity bucket), then every
output slot locates its source row by searchsorted over the cumulative
per-row output counts — static shapes, both engines.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np
from ..ops.scan import cumsum_fast

from .. import types as t
from ..columnar.device import (DEFAULT_ROW_BUCKETS, DeviceBatch, DeviceColumn,
                               bucket_for)
from ..expr.collection import Explode, Generator, PosExplode
from ..expr.core import (ColumnValue, EvalContext, Expression, ScalarValue,
                         bind_expression, make_column)
from ..ops.gather import gather_column
from .base import (maybe_sync,  # noqa: F401
                   NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, Batch, Exec,
                   MetricTimer)


class ExpandExec(Exec):
    """Emit one projected batch per projection list per input batch
    (ref GpuExpandExec)."""

    def __init__(self, projections: List[List[Expression]],
                 names: List[str], child: Exec):
        super().__init__([child])
        self._names = list(names)
        self.projections = [
            [bind_expression(e, child.output_names, child.output_types)
             for e in proj] for proj in projections]
        self._types = [e.data_type() for e in self.projections[0]]

    @property
    def output_names(self):
        return self._names

    @property
    def output_types(self):
        return self._types

    def describe(self):
        return f"Expand [{len(self.projections)} projections]"

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        for b in self.children[0].execute_partition(pid, ctx):
            for proj in self.projections:
                with MetricTimer(self.metrics[OP_TIME]):
                    ectx = EvalContext(xp, b)
                    cols = []
                    for e, dt in zip(proj, self._types):
                        v = e.eval(ectx)
                        if isinstance(v, ScalarValue):
                            v = make_column(
                                ectx, dt if v.value is not None else dt,
                                v.value if v.value is not None else 0,
                                None if v.value is not None else False)
                        cols.append(v.col)
                    out = DeviceBatch(cols, b.num_rows, self._names)
                    maybe_sync(out)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out


class GenerateExec(Exec):
    """explode/posexplode: child columns are repeated per array element,
    generated columns appended (ref GpuGenerateExec)."""

    def __init__(self, generator: Generator, outer: bool,
                 out_names: List[str], child: Exec):
        super().__init__([child])
        self.generator = bind_expression(
            generator, child.output_names, child.output_types)
        self.outer = outer or getattr(generator, "outer", False)
        gnames, gtypes = self.generator.generator_output()
        if out_names:
            gnames = list(out_names)
        self._out_names = list(child.output_names) + gnames
        self._out_types = list(child.output_types) + gtypes

    @property
    def output_names(self):
        return self._out_names

    @property
    def output_types(self):
        return self._out_types

    def describe(self):
        return f"Generate {self.generator.sql()} outer={self.outer}"

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        pos_wanted = isinstance(self.generator, PosExplode)
        for b in self.children[0].execute_partition(pid, ctx):
            with MetricTimer(self.metrics[OP_TIME]):
                ectx = EvalContext(xp, b)
                arr = self.generator.children[0].eval(ectx)
                col = arr.col
                child_col = col.children[0]
                cap = b.capacity
                live = ectx.row_mask()
                valid = col.validity if col.validity is not None else \
                    xp.ones((cap,), bool)
                lens = (col.offsets[1:] - col.offsets[:-1]).astype(np.int32)
                lens = xp.where(valid, lens, 0)
                if self.outer:
                    eff = xp.where(live, xp.maximum(lens, 1), 0)
                else:
                    eff = xp.where(live, lens, 0)
                cum = xp.concatenate([xp.zeros((1,), np.int32),
                                      cumsum_fast(xp, eff, dtype=np.int32)])
                total = int(cum[-1])
                out_cap = bucket_for(max(total, 1), DEFAULT_ROW_BUCKETS)
                p = xp.arange(out_cap, dtype=np.int32)
                row = xp.clip(xp.searchsorted(cum[1:], p, side="right"),
                              0, cap - 1).astype(np.int32)
                in_range = p < total
                pos = p - cum[row]
                is_elem = in_range & (pos < lens[row])
                elem_idx = xp.clip(col.offsets[row] + pos, 0,
                                   max(int(child_col.capacity) - 1, 0))
                # repeated input columns (string bytes scale with repetition)
                from ..columnar.device import DEFAULT_CHAR_BUCKETS
                out_cols = []
                for c in b.columns:
                    ccap = 0
                    if isinstance(c.dtype, (t.StringType, t.BinaryType)):
                        slens = (c.offsets[1:] - c.offsets[:-1]) \
                            .astype(np.int64)
                        need = int(xp.sum(eff.astype(np.int64) * slens))
                        ccap = bucket_for(max(need, 1), DEFAULT_CHAR_BUCKETS)
                    out_cols.append(
                        gather_column(xp, c, row, in_range, ccap))
                if pos_wanted:
                    pos_col = DeviceColumn(
                        t.INT,
                        data=xp.where(is_elem, pos, 0).astype(np.int32),
                        validity=is_elem)
                    out_cols.append(pos_col)
                # the element column: gather from the array's child values
                elem = gather_column(xp, child_col, elem_idx, is_elem)
                out_cols.append(elem)
                out = DeviceBatch(out_cols, total, self._out_names)
            self.metrics[NUM_OUTPUT_ROWS] += total
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield out
