"""Broadcast exchange + broadcast joins.

Ref: execution/GpuBroadcastExchangeExec.scala (serialized host batch
broadcast, built once and reused by every task),
GpuBroadcastHashJoinExec (per-shim), GpuBroadcastNestedLoopJoinExec.scala.

TPU realization: the build side is collected and concatenated ONCE per
query (thread-safe, cached on the exec instance — the analog of a Spark
broadcast variable materialized on the driver and shipped to executors),
then every probe partition joins against the same cached device batch.
Avoids a full shuffle of the big side: the core win of broadcast joins.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

import pyarrow as pa

from ..columnar.device import batch_to_device
from .base import (maybe_sync,
                   NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, Batch, Exec,
                   ExecContext, MetricTimer)
from .concat import concat_batches
from .join import HashJoinExec, NestedLoopJoinExec

BUILD_TIME = "buildTime"
BROADCAST_BYTES = "dataSize"


class BroadcastExchangeExec(Exec):
    """Collects every child partition into one concatenated batch, computed
    once and served to all consumers (num_partitions == 1)."""

    def __init__(self, child: Exec):
        super().__init__([child])
        self.metrics[BUILD_TIME] = self._new_metric(BUILD_TIME)
        self.metrics[BROADCAST_BYTES] = self._new_metric(BROADCAST_BYTES)
        self._lock = threading.Lock()
        self._cached: Optional[Batch] = None

    @staticmethod
    def _new_metric(name):
        from .base import Metric
        return Metric(name)

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    @property
    def num_partitions(self):
        return 1

    def describe(self):
        return "BroadcastExchange"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "whole-side collect concatenates child "
            "partitions in emission order; content multiset is "
            "invariant")

    def memory_effects(self, child_states, conf):
        """Collects + concatenates the whole child once and keeps the
        cached batch device-resident for every consumer until the exec
        instance dies — raw (not spill-managed) retention."""
        from ..analysis.lifetime import MemoryEffects, total_bytes
        if not child_states:
            return None
        whole = total_bytes(child_states[0])
        return MemoryEffects(hold=whole, retained=whole,
                             note="cached broadcast batch")

    def _materialize(self, ctx: ExecContext) -> Batch:
        with self._lock:
            if self._cached is not None:
                return self._cached
            child = self.children[0]
            xp = self.xp
            batches = []
            with MetricTimer(self.metrics[BUILD_TIME]):
                for pid in range(child.num_partitions):
                    batches += list(child.execute_partition(pid, ctx))
                if not batches:
                    from ..columnar.interop import to_arrow_schema
                    schema = to_arrow_schema(child.output_names,
                                             child.output_types)
                    rb = pa.RecordBatch.from_pydict(
                        {n: pa.array([], type=f.type)
                         for n, f in zip(schema.names, schema)})
                    batches = [batch_to_device(rb, xp=xp)]
                out = concat_batches(xp, batches, child.output_names,
                                     child.output_types) \
                    if len(batches) > 1 else batches[0]
                maybe_sync(out)
            from ..memory.spill import batch_device_bytes
            nbytes = batch_device_bytes(out)
            self.metrics[BROADCAST_BYTES] += nbytes
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            self._cached = out
            self._cached_bytes = nbytes
            from ..obs import memprof
            tl = memprof.active_timeline()
            if tl is not None:
                # raw (not spill-managed) retention: the HBM observatory
                # books it as closed-pending — resident until release
                tl.on_broadcast(f"bcast-{id(self):x}", nbytes)
            return out

    def release_shuffle(self):
        """Drop the cached broadcast batch (plan-release hook — rides
        ``session.release_plan_shuffles`` like IciExchangeExec).  Each
        collect re-plans, so releasing the cache is unobservable and
        hands the HBM back at plan teardown instead of exec GC time."""
        with self._lock:
            if self._cached is None:
                return
            self._cached = None
            self._cached_bytes = 0
        from ..obs import memprof
        tl = memprof.active_timeline()
        if tl is not None:
            tl.on_broadcast_release(f"bcast-{id(self):x}")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        yield self._materialize(ctx)


class BroadcastHashJoinExec(HashJoinExec):
    """Equi-join whose build (right) child is a BroadcastExchangeExec
    (ref GpuBroadcastHashJoinExec): no shuffle of the probe side; the
    cached broadcast batch is the hash-build input for every partition."""

    def describe(self):
        ks = ", ".join(f"{a.sql()}={b.sql()}"
                       for a, b in zip(self.left_keys, self.right_keys))
        return f"BroadcastHashJoin {self.how} on [{ks}]"


class BroadcastNestedLoopJoinExec(NestedLoopJoinExec):
    """Cross/conditional join whose build side is broadcast
    (ref GpuBroadcastNestedLoopJoinExec.scala)."""

    def describe(self):
        c = f" on {self.condition.sql()}" if self.condition is not None \
            else ""
        return f"BroadcastNestedLoopJoin {self.how}{c}"
