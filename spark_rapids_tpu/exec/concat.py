"""Device batch concatenation (ref GpuCoalesceBatches concat path and
cudf Table.concatenate usage).

Concatenates batches by gathering from a stacked buffer: the output
capacity is the bucket covering the total row count.  Variable-length
columns re-pack char/child buffers.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .. import types as t
from ..columnar.device import (DEFAULT_CHAR_BUCKETS, DEFAULT_ROW_BUCKETS,
                               DeviceBatch, DeviceColumn, bucket_for)


def _concat_flat(xp, arrays, cap, fill_dtype):
    total = sum(int(a.shape[0]) for a in arrays)
    joined = xp.concatenate(arrays)
    if total == cap:
        return joined
    if total > cap:
        return joined[:cap]
    pad = xp.zeros((cap - total,), dtype=joined.dtype)
    return xp.concatenate([joined, pad])


def _span_counts(xp, cols, counts) -> list:
    """Per-column live child/byte counts (offsets[n]) as host ints, all
    resolved in ONE device transfer (columnar/fetch.py's sanctioned
    crossing) instead of one implicit sync per column."""
    from ..columnar.fetch import fetch_ints
    return fetch_ints([c.offsets[n] for c, n in zip(cols, counts)])


def concat_columns(xp, cols: Sequence[DeviceColumn], counts, cap: int,
                   dtype: t.DataType) -> DeviceColumn:
    """Concatenate column segments where cols[i] contributes its first
    counts[i] rows.  `counts` are python ints (host-known batch sizes)."""
    validity_parts = []
    for c, n in zip(cols, counts):
        v = c.validity if c.validity is not None else \
            xp.ones((c.capacity,), dtype=bool)
        validity_parts.append(v[:n] if xp is np else
                              _take_prefix(xp, v, n, c.capacity))
    validity = _concat_flat(xp, validity_parts, cap, bool)

    if isinstance(dtype, (t.StringType, t.BinaryType)):
        offs_parts = []
        chars_parts = []
        base = 0
        for c, n, nb in zip(cols, counts, _span_counts(xp, cols, counts)):
            o = c.offsets
            offs_parts.append((o[:n] if xp is np else o[:n]) + np.int32(base))
            chars_parts.append(c.data[:nb])
            base += nb
        last = np.int32(base)
        total_rows = sum(counts)
        offs = xp.concatenate(
            offs_parts + [xp.full((cap + 1 - total_rows,), last, xp.int32)])
        char_cap = bucket_for(max(base, 1), DEFAULT_CHAR_BUCKETS)
        chars = _concat_flat(xp, chars_parts, char_cap, np.uint8)
        return DeviceColumn(dtype, data=chars, offsets=offs,
                            validity=validity)

    if isinstance(dtype, t.StructType):
        children = tuple(
            concat_columns(xp, [c.children[i] for c in cols], counts, cap,
                           f.data_type)
            for i, f in enumerate(dtype.fields))
        return DeviceColumn(dtype, validity=validity, children=children)

    if isinstance(dtype, (t.ArrayType, t.MapType)):
        offs_parts = []
        base = 0
        child_counts = []
        for c, n, nb in zip(cols, counts, _span_counts(xp, cols, counts)):
            o = c.offsets
            offs_parts.append(o[:n] + np.int32(base))
            child_counts.append(nb)
            base += nb
        last = np.int32(base)
        total_rows = sum(counts)
        offs = xp.concatenate(
            offs_parts + [xp.full((cap + 1 - total_rows,), last, xp.int32)])
        child_cap = bucket_for(max(base, 1), DEFAULT_ROW_BUCKETS)
        if isinstance(dtype, t.MapType):
            kchild = concat_columns(xp, [c.children[0] for c in cols],
                                    child_counts, child_cap, dtype.key_type)
            vchild = concat_columns(xp, [c.children[1] for c in cols],
                                    child_counts, child_cap,
                                    dtype.value_type)
            return DeviceColumn(dtype, offsets=offs, validity=validity,
                                children=(kchild, vchild))
        child = concat_columns(xp, [c.children[0] for c in cols],
                               child_counts, child_cap, dtype.element_type)
        return DeviceColumn(dtype, offsets=offs, validity=validity,
                            children=(child,))

    data_parts = [c.data[:n] for c, n in zip(cols, counts)]
    data = _concat_flat(xp, data_parts, cap, None)
    out = DeviceColumn(dtype, data=data, validity=validity)
    if cols[0].data_hi is not None:
        hi_parts = [c.data_hi[:n] for c, n in zip(cols, counts)]
        out.data_hi = _concat_flat(xp, hi_parts, cap, None)
    return out


def _take_prefix(xp, arr, n, cap):
    return arr[:n]


def concat_batches(xp, batches: List[DeviceBatch], names, dtypes
                   ) -> DeviceBatch:
    """Concatenate host-length-known batches into one bucketed batch.

    Note: this runs outside jit (batch row counts must be host ints), which
    is fine — coalescing happens at iterator boundaries, like the
    reference's host-side concatenation decisions.
    """
    counts = [int(b.num_rows) for b in batches]
    total = sum(counts)
    cap = bucket_for(max(total, 1), DEFAULT_ROW_BUCKETS)
    cols = []
    for i, dt in enumerate(dtypes):
        cols.append(concat_columns(xp, [b.columns[i] for b in batches],
                                   counts, cap, dt))
    return DeviceBatch(cols, total, names)
