"""Hash aggregate operators.

Ref: sql-plugin/.../aggregate.scala (GpuHashAggregateExec / iterator mode
pipeline at :258-275) — re-designed for TPU as sort+segment-reduce:

  1. per batch: evaluate grouping keys + update inputs, encode keys as
     order-preserving uint64 words, lax.sort (stable, multi-operand),
     boundary-detect, segment-reduce every buffer, compact groups to the
     front — one jitted XLA computation per (schema, capacity);
  2. across batches: concatenate the per-batch partials and run the same
     kernel with merge ops (the analog of tryMergeAggregatedBatches);
  3. Final/Complete mode then evaluates result expressions over buffers.

The CPU-placed aggregate (`CpuHashAggregateExec`) is an independent
pyarrow `Table.group_by` implementation — it both serves as the fallback
for TPU-unsupported types and gives differential tests a second engine.
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import types as t
from ..columnar.device import (DEFAULT_ROW_BUCKETS, DeviceBatch, DeviceColumn,
                               batch_to_arrow, batch_to_device, bucket_for)
from ..expr.aggregates import (COMPLETE, FINAL, PARTIAL, AggregateExpression,
                               AggregateFunction, ApproximatePercentile,
                               Average, CollectList, CollectSet, Count,
                               First, Last, Max, Min, PivotFirst,
                               StddevPop, StddevSamp, Sum, VariancePop,
                               VarianceSamp)
from ..expr.core import (ColumnValue, EvalContext, Expression,
                         bind_expression, output_name)
from ..ops import segmented as seg
from ..ops.gather import gather_column
from .base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU, Batch,
                   Exec, ExecContext, MetricTimer, maybe_sync, process_jit,
                   schema_sig, semantic_sig)
from .concat import concat_batches
from ..ops.scan import cumsum_fast


def _group_reduce(xp, key_cols: List[DeviceColumn],
                  value_cols: List[DeviceColumn], ops: List[str],
                  cap: int, live, global_agg: bool):
    """Core sort+segment kernel.  Returns (out_key_cols, out_value_cols,
    num_groups).

    Round-4 kernel structure (see ops/carry.py docstring for the chip
    measurements behind it):

      1. ONE stable carry-sort by the key words — every flat lane of the
         key and value columns rides the sort as a payload operand, so no
         post-sort row gathers.
      2. Per sum/count: a Hillis-Steele prefix scan + elementwise
         exclusive value — the per-segment total is the difference of the
         exclusive scan at consecutive segment starts.  No 64-bit
         scatters anywhere; float sums scan finite values only and
         rebuild IEEE inf/nan from per-segment special-value counts.
      3. ONE carry-compaction-sort moves the boundary rows (and all
         per-op scan lanes + flat key lanes) to the slot positions.
      4. min/max/first/last use int32 scatter tournaments + one row
         gather; variable-width columns keep the gather-based paths.
    """
    from ..ops import carry
    # --- sort keys, carrying all row data -----------------------------------
    words: List = [(~live).astype(xp.uint8)]  # padding rows sort last
    for kc in key_cols:
        words += seg.key_words_for_column(xp, kc, live, for_grouping=True)
    all_cols = list(key_cols) + list(value_cols)
    order, sorted_cols, ex = carry.sort_rows(
        xp, words, all_cols, cap, extras=[live] + words[1:])
    key_sorted = sorted_cols[:len(key_cols)]
    val_sorted = sorted_cols[len(key_cols):]
    live_sorted = ex[0]
    sorted_words = ex[1:]
    if global_agg:
        new_group = xp.arange(cap, dtype=np.int32) == 0
    else:
        new_group = seg.segment_boundaries(xp, sorted_words, live_sorted)
    seg_ids = seg.segment_ids(xp, new_group)
    seg_ids = xp.clip(seg_ids, 0, cap - 1)
    num_groups = xp.sum(new_group.astype(np.int32)) if not global_agg \
        else xp.int32(1) if xp is not np else np.int32(1)
    iota_slots = xp.arange(cap, dtype=np.int32)
    slot_valid = iota_slots < num_groups

    # --- deferred scan lanes (compacted once, below) ------------------------
    lanes: List = [iota_slots]        # lane 0 -> first row index per slot
    lane_pos: dict = {}

    def enlane(a) -> int:
        k = id(a)
        if k not in lane_pos:
            lane_pos[k] = len(lanes)
            lanes.append(a)
        return lane_pos[k]

    count_cache: dict = {}

    def count_lane(mask) -> tuple:
        """(lane index, total) of the exclusive scan of an int32 mask.
        The cache RETAINS each mask: a bare id() key could alias a new
        mask after a temporary is garbage-collected (np engine path)."""
        k = id(mask)
        hit = count_cache.get(k)
        if hit is not None and hit[0] is mask:
            return hit[1]
        m32 = mask.astype(np.int32)
        cs = seg.cumsum_fast(xp, m32)
        val = (enlane(cs - m32), cs[-1])
        count_cache[k] = (mask, val)
        return val

    sum_jobs: List[dict] = []
    out_values: List[Optional[DeviceColumn]] = [None] * len(ops)

    for oi, (vs, op) in enumerate(zip(val_sorted, ops)):
        validity_sorted = live_sorted if vs.validity is None else \
            (vs.validity & live_sorted)
        if op in ("collect_list", "collect_set"):
            out_values[oi] = _collect_update(
                xp, vs, seg_ids, validity_sorted, cap, slot_valid,
                dedupe=(op == "collect_set"))
            continue
        if op in ("collect_concat", "collect_concat_set"):
            out_values[oi] = _collect_merge(
                xp, value_cols[oi], order, seg_ids, validity_sorted, cap,
                slot_valid, dedupe=(op == "collect_concat_set"))
            continue
        if op == "countvalid":
            li, total = count_lane(validity_sorted)
            sum_jobs.append(dict(kind="count", out=oi, lane=li,
                                 total=total))
            continue
        if op.endswith("_any"):
            base_op = op[:-4]
            contrib = live_sorted
        else:
            base_op = op
            contrib = validity_sorted
        is_dec128 = vs.data_hi is not None
        if is_dec128 and base_op == "sum":
            lo_o, hi_o, cnt = seg.segment_sum128(xp, vs.data, vs.data_hi,
                                                 seg_ids, cap, contrib,
                                                 sorted_ids=True)
            validity_out = (cnt > 0) & slot_valid
            out_values[oi] = DeviceColumn(
                vs.dtype,
                data=xp.where(validity_out, lo_o, xp.zeros_like(lo_o)),
                data_hi=xp.where(validity_out, hi_o, xp.zeros_like(hi_o)),
                validity=validity_out)
            continue
        if op in ("first", "last", "first_any", "last_any") or \
                _needs_index_gather(vs.dtype) or is_dec128:
            if base_op in ("min", "max") and \
                    (is_dec128 or
                     isinstance(vs.dtype, (t.StringType, t.BinaryType))):
                # ordered reduce for variable-width values: secondary sort
                # by (segment, validity, value words), first row per
                # segment wins.  Value words are the same prefix+length
                # encoding the sort exec orders by; max inverts them.
                vwords = seg.key_words_for_column(
                    xp, vs, contrib, for_grouping=False,
                    ascending=(base_op == "min"))
                words2 = [seg_ids.astype(xp.uint32),
                          (~contrib).astype(xp.uint8)] + vwords[1:]
                order2 = seg.lexsort(xp, words2, cap)
                first2 = seg.first_index_per_segment(
                    xp, seg_ids[order2], cap, contrib[order2])
                idx = order2[first2].astype(xp.int32)
                _, cnt = seg.segment_reduce(
                    xp, "sum", xp.zeros((cap,), np.int32), seg_ids, cap,
                    contrib, sorted_ids=True)
            else:
                pos = xp.arange(cap, dtype=np.int32)
                which = "first" if base_op in ("first", "min") else \
                    ("last" if base_op in ("last",) else "first")
                idx, cnt = seg.segment_reduce(xp, which, pos, seg_ids, cap,
                                              contrib, sorted_ids=True)
                idx = idx.astype(xp.int32)
            gathered = gather_column(xp, vs, idx, (cnt > 0) & slot_valid)
            out_values[oi] = gathered
            continue
        if base_op in ("min", "max"):
            out, cnt = seg.segment_reduce(xp, base_op, vs.data, seg_ids,
                                          cap, contrib, sorted_ids=True)
            validity_out = (cnt > 0) & slot_valid
            out = xp.where(validity_out, out, xp.zeros_like(out))
            out_values[oi] = DeviceColumn(vs.dtype, data=out,
                                          validity=validity_out)
            continue
        # sum via prefix scans: integers use global-scan differencing
        # (exact modulo 2^width); floats use a segmented scan — a global
        # float prefix lets one segment's magnitude catastrophically
        # cancel another's, and inf/nan would poison later segments
        data = vs.data
        vals0 = xp.where(contrib, data, xp.zeros_like(data))
        job = dict(kind="sum", out=oi, dtype=vs.dtype)
        if np.dtype(data.dtype).kind == "f":
            finite = xp.isfinite(vals0)
            scan_vals = xp.where(finite, vals0, xp.zeros_like(vals0))
            job["pi"] = count_lane(contrib & (data == xp.inf))
            job["ni"] = count_lane(contrib & (data == -xp.inf))
            job["nan"] = count_lane(contrib & xp.isnan(data))
            from ..ops.scan import segmented_cumsum_fast
            sseg = segmented_cumsum_fast(xp, scan_vals, new_group)
            # at a segment's first row, the PREVIOUS row closes the
            # previous segment — compacting the shifted lane puts each
            # segment's total at slot+1
            shifted = xp.concatenate([xp.zeros((1,), sseg.dtype),
                                      sseg[:-1]])
            job["kind"] = "sum_seg"
            job["lane"] = enlane(shifted)
            job["total"] = sseg[-1]
        else:
            cs = seg.cumsum_fast(xp, vals0)
            job["lane"] = enlane(cs - vals0)
            job["total"] = cs[-1]
        job["cnt"] = count_lane(contrib)
        sum_jobs.append(job)

    # --- flat key lanes join the compaction ---------------------------------
    import jax
    key_plans = []
    for ks in key_sorted:
        if carry.carriable(ks):
            leaves, treedef = jax.tree_util.tree_flatten(ks)
            key_plans.append((treedef, [enlane(l) for l in leaves]))
        else:
            key_plans.append((None, None))

    # --- ONE compaction: boundary rows -> slot positions --------------------
    ckey = (~new_group).astype(xp.uint8)
    _, comp = carry.sort_lanes(xp, [ckey], lanes, cap)
    first_idx = xp.clip(comp[0], 0, cap - 1).astype(xp.int32)

    def span_next(lane_idx, total):
        """Per-slot value from the NEXT slot's compacted lane entry; the
        last live slot reads the whole-array closing value."""
        E = comp[lane_idx]
        nxt = xp.concatenate([E[1:], xp.zeros((1,), E.dtype)])
        last = iota_slots == (num_groups - 1)
        return xp.where(last, xp.asarray(total, dtype=E.dtype), nxt)

    def span_diff(lane_idx, total):
        """Per-slot total from a compacted exclusive scan: the difference
        of consecutive segment starts; the last live slot closes on the
        whole-array total."""
        return span_next(lane_idx, total) - comp[lane_idx]

    for job in sum_jobs:
        cnt_lane, cnt_total = job["cnt"] if job["kind"] != "count" \
            else (job["lane"], job["total"])
        cnt = span_diff(cnt_lane, cnt_total)
        if job["kind"] == "count":
            out_values[job["out"]] = DeviceColumn(
                t.LONG, data=cnt.astype(np.int64), validity=slot_valid)
            continue
        if job["kind"] == "sum_seg":
            out = span_next(job["lane"], job["total"])
        else:
            out = span_diff(job["lane"], job["total"])
        if "pi" in job:
            n_pi = span_diff(*job["pi"])
            n_ni = span_diff(*job["ni"])
            n_nan = span_diff(*job["nan"])
            out = xp.where((n_nan > 0) | ((n_pi > 0) & (n_ni > 0)),
                           xp.full_like(out, xp.nan), out)
            out = xp.where((n_pi > 0) & (n_ni == 0) & (n_nan == 0),
                           xp.full_like(out, xp.inf), out)
            out = xp.where((n_ni > 0) & (n_pi == 0) & (n_nan == 0),
                           xp.full_like(out, -xp.inf), out)
        validity_out = (cnt > 0) & slot_valid
        out = xp.where(validity_out, out, xp.zeros_like(out))
        out_values[job["out"]] = DeviceColumn(job["dtype"], data=out,
                                              validity=validity_out)

    # --- group key values at slot positions ---------------------------------
    out_keys = []
    for ks, (treedef, lidx) in zip(key_sorted, key_plans):
        if treedef is None:
            out_keys.append(gather_column(xp, ks, first_idx, slot_valid))
        else:
            col = jax.tree_util.tree_unflatten(
                treedef, [comp[i] for i in lidx])
            out_keys.append(carry.mask_validity(xp, col, slot_valid))
    return out_keys, out_values, num_groups


def _permuted(xp, col: DeviceColumn, order) -> DeviceColumn:
    all_valid = xp.ones((order.shape[0],), dtype=bool)
    return gather_column(xp, col, order, all_valid)


def _collect_update(xp, vc: DeviceColumn, seg_ids, contrib, cap: int,
                    slot_valid, dedupe: bool) -> DeviceColumn:
    """collect_list / collect_set over key-sorted rows (ref
    AggregateFunctions.scala GpuCollectList/GpuCollectSet).

    `vc` arrives already key-sorted (carried through the main sort).  The
    sort by grouping key makes each group's rows contiguous, so the
    collected child buffer is a stable compaction of contributing values;
    null values are dropped (Spark semantics) and sets dedupe within the
    segment by value words."""
    perm = vc
    keep = contrib
    sids = seg_ids
    if dedupe:
        # order by (segment, value), first occurrence survives
        vwords = seg.key_words_for_column(xp, perm, keep, for_grouping=True)
        words2 = [(~keep).astype(xp.uint8),
                  sids.astype(xp.uint32)] + vwords
        order2 = seg.lexsort(xp, words2, cap)
        keep_s = keep[order2]
        sw = [sids[order2].astype(xp.uint32)] + [w[order2] for w in vwords]
        first = seg.segment_boundaries(xp, sw, keep_s)
        perm = gather_column(xp, perm, order2,
                             xp.ones((cap,), dtype=bool))
        sids = sids[order2]
        keep = keep_s & first
    # stable compaction keeps segment-major order
    if xp is np:
        order3 = np.argsort(~keep, kind="stable").astype(np.int32)
    else:
        from jax import lax
        iota = xp.arange(cap, dtype=xp.int32)
        order3 = lax.sort(  # tpulint: allow[TPU-R017] group-compaction sort inline in the aggregate update/merge; host branch above uses np.argsort
            ((~keep).astype(xp.int32), iota), num_keys=1,
            is_stable=True)[1]
    child = gather_column(xp, perm, order3, keep[order3])
    cnt, _ = seg.segment_reduce(xp, "sum", keep.astype(np.int32), sids,
                                cap, keep, sorted_ids=True)
    offs = xp.concatenate([xp.zeros((1,), np.int32),
                           cumsum_fast(xp, cnt).astype(xp.int32)])
    return DeviceColumn(t.ArrayType(vc.dtype), offsets=offs,
                        validity=slot_valid, children=(child,))


def _collect_merge(xp, vc: DeviceColumn, order, seg_ids, contrib, cap: int,
                   slot_valid, dedupe: bool) -> DeviceColumn:
    """Merge collected array buffers per key: gather rows in key-sorted
    order (which repacks every row's span contiguously, i.e. the
    segment-major concatenation), then optionally dedupe elements within
    each segment (collect_set)."""
    perm = gather_column(xp, vc, order, contrib)
    child = perm.children[0]
    child_cap = child.capacity
    lens = (perm.offsets[1:] - perm.offsets[:-1]).astype(xp.int64)
    if not dedupe:
        cnt, _ = seg.segment_reduce(xp, "sum", lens, seg_ids, cap,
                                    xp.ones((cap,), dtype=bool))
        offs = xp.concatenate([xp.zeros((1,), np.int32),
                               cumsum_fast(xp, cnt).astype(xp.int32)])
        return DeviceColumn(t.ArrayType(child.dtype), offsets=offs,
                            validity=slot_valid, children=(child,))
    # element -> segment mapping via the row each child position came from
    pos = xp.arange(child_cap, dtype=xp.int32)
    crow = xp.clip(xp.searchsorted(perm.offsets[1:], pos, side="right"),
                   0, cap - 1).astype(xp.int32)
    in_range = pos < perm.offsets[-1]
    cseg = seg_ids[crow]
    vwords = seg.key_words_for_column(xp, child, in_range,
                                      for_grouping=True)
    words = [(~in_range).astype(xp.uint64),
             cseg.astype(xp.uint64)] + vwords
    order2 = seg.lexsort(xp, words, child_cap)
    keep_s = in_range[order2]
    sw = [cseg[order2].astype(xp.uint64)] + [w[order2] for w in vwords]
    first = seg.segment_boundaries(xp, sw, keep_s)
    keep = keep_s & first
    child_s = gather_column(xp, child, order2,
                            xp.ones((child_cap,), dtype=bool))
    if xp is np:
        order3 = np.argsort(~keep, kind="stable").astype(np.int32)
    else:
        from jax import lax
        iota = xp.arange(child_cap, dtype=xp.int32)
        order3 = lax.sort(  # tpulint: allow[TPU-R017] group-compaction sort inline in the aggregate update/merge; host branch above uses np.argsort
            ((~keep).astype(xp.int32), iota), num_keys=1,
            is_stable=True)[1]
    final_child = gather_column(xp, child_s, order3, keep[order3])
    cseg_s = cseg[order2]
    cnt, _ = seg.segment_reduce(xp, "sum", keep.astype(np.int64), cseg_s,
                                cap, keep)
    offs = xp.concatenate([xp.zeros((1,), np.int32),
                           cumsum_fast(xp, cnt).astype(xp.int32)])
    return DeviceColumn(t.ArrayType(child.dtype), offsets=offs,
                        validity=slot_valid, children=(final_child,))


def _needs_index_gather(dtype: t.DataType) -> bool:
    return isinstance(dtype, (t.StringType, t.BinaryType, t.StructType,
                              t.ArrayType, t.MapType))


class TpuHashAggregateExec(Exec):
    """TPU hash aggregate (ref GpuHashAggregateExec, aggregate.scala:1450)."""

    placement = TPU

    # Canonical keyed merge (tpudsan): before folding accumulated
    # partials, _merge_batch orders rows by grouping-key AND buffer
    # value words, so the float accumulation order is a function of
    # content, not of batch arrival — the property that lets a
    # recomputed map task reproduce its shuffle blocks bit-for-bit
    # (TPU-R016/L016).  The TPU-L016 pre-flight repair
    # (analysis/determinism.try_stabilize_repair) forces this back on
    # when a plan turns it off.
    stable_merge: bool = True

    def __init__(self, grouping: Sequence[Expression],
                 aggregates: Sequence[AggregateExpression],
                 mode: str, child: Exec):
        super().__init__([child])
        self.grouping = list(grouping)
        from ..expr.aggregates import bind_aggregate
        if mode in (PARTIAL, COMPLETE):
            self.aggregates = [bind_aggregate(a, child.output_names,
                                              child.output_types)
                               for a in aggregates]
        else:
            self.aggregates = list(aggregates)  # FINAL: pre-bound by caller
        self.mode = mode
        self._setup()

    def _setup(self):
        child = self.children[0]
        cn, ct = child.output_names, child.output_types
        self._group_names = [output_name(g) for g in self.grouping]
        if self.mode in (PARTIAL, COMPLETE):
            self._bound_grouping = [bind_expression(g, cn, ct)
                                    for g in self.grouping]
            self._update_inputs = []
            self._update_ops = []
            for ae in self.aggregates:
                for expr, op in ae.func.update():
                    self._update_inputs.append(bind_expression(expr, cn, ct))
                    self._update_ops.append(op)
        if self.mode == FINAL:
            # child layout: group cols then buffers in declaration order
            k = len(self.grouping)
            self._buffer_ordinals = list(range(k, len(cn)))
        self._buffer_names = []
        self._buffer_types = []
        for i, ae in enumerate(self.aggregates):
            for j, bt in enumerate(ae.func.buffer_types()):
                self._buffer_names.append(f"buf{i}_{j}")
                self._buffer_types.append(bt)
        self._merge_ops = []
        for ae in self.aggregates:
            self._merge_ops += ae.func.merge_ops()

    def determinism(self):
        from ..analysis.determinism import (Determinism, ORDER_DEPENDENT,
                                            ORDER_STABLE)
        scoped = self.mode == PARTIAL  # partial buffers regroup with
        #                                the input split
        if any(isinstance(ae.func, CollectList)
               for ae in self.aggregates):
            return Determinism(
                ORDER_DEPENDENT, "collect_list/collect_set element "
                "order follows batch arrival",
                partition_scoped=scoped)
        floaty = any(isinstance(bt, t.FractionalType)
                     for bt in self._buffer_types)
        if floaty and not self.stable_merge:
            return Determinism(
                ORDER_DEPENDENT, "float partial buffers fold in batch "
                "arrival order (stable_merge off): a different arrival "
                "order changes the sums", partition_scoped=scoped,
                canonicalizable=True)
        return Determinism(
            ORDER_STABLE, "group emission order follows arrival; the "
            "canonical keyed merge makes buffer folds "
            "content-determined", partition_scoped=scoped)

    def input_contracts(self):
        if self.mode != FINAL or not self.grouping:
            return None
        from ..analysis.absdomain import ClusteredContract
        # FINAL input layout: grouping columns first — partial buffers
        # for one group must all arrive in this task's partition
        keys = self.children[0].output_names[:len(self.grouping)]
        return ClusteredContract(keys,
                                 what="FINAL-mode grouped aggregate")

    @property
    def output_names(self):
        if self.mode == PARTIAL:
            return self._group_names + self._buffer_names
        return self._group_names + [ae.name for ae in self.aggregates]

    @property
    def output_types(self):
        if self.mode == PARTIAL:
            gt = [g.data_type() for g in
                  (self._bound_grouping if self.mode in (PARTIAL, COMPLETE)
                   else [])]
            return gt + self._buffer_types
        if self.mode == COMPLETE:
            gt = [g.data_type() for g in self._bound_grouping]
        else:
            gt = self.children[0].output_types[:len(self.grouping)]
        return gt + [ae.data_type() for ae in self.aggregates]

    def describe(self):
        return (f"HashAggregate(mode={self.mode}, keys="
                f"[{', '.join(self._group_names)}], fns="
                f"[{', '.join(a.name for a in self.aggregates)}])")

    # --- device kernels -----------------------------------------------------
    def _update_batch(self, xp, batch: Batch) -> Batch:
        ctx = EvalContext(xp, batch)
        live = ctx.row_mask()
        key_cols = [g.eval(ctx).col for g in self._bound_grouping]
        val_cols = []
        for b, op in zip(self._update_inputs, self._update_ops):
            v = b.eval(ctx)
            if not isinstance(v, ColumnValue):
                from ..expr.core import make_column
                v = make_column(ctx, b.data_type(), v.value if v.value
                                is not None else 0,
                                None if v.value is not None else False)
            val_cols.append(v.col)
        ok, ov, n = _group_reduce(xp, key_cols, val_cols, self._update_ops,
                                  batch.capacity, live,
                                  global_agg=not self.grouping)
        return DeviceBatch(ok + ov, n, self._group_names + self._buffer_names)

    def _merge_batch(self, xp, batch: Batch) -> Batch:
        k = len(self.grouping)
        if self.stable_merge:
            batch = self._canonicalize_merge_input(xp, batch)
        live = xp.arange(batch.capacity, dtype=np.int32) < batch.num_rows
        key_cols = list(batch.columns[:k])
        val_cols = list(batch.columns[k:])
        ok, ov, n = _group_reduce(xp, key_cols, val_cols, self._merge_ops,
                                  batch.capacity, live,
                                  global_agg=not self.grouping)
        return DeviceBatch(ok + ov, n, self._group_names + self._buffer_names)

    def _canonicalize_merge_input(self, xp, batch: Batch) -> Batch:
        """Order the concatenated partials by key + buffer value words
        so the within-group fold order is content-determined (the
        stable_merge canonical keyed merge).  Nested buffer columns
        (collect_list arrays) contribute no words — their element
        order is declared order_dependent anyway."""
        cap = batch.capacity
        live = xp.arange(cap, dtype=np.int32) < batch.num_rows
        words: List = [(~live).astype(xp.uint64)]
        for kc in batch.columns[:len(self.grouping)]:
            words += seg.key_words_for_column(xp, kc, live,
                                              for_grouping=True)
        for vc in batch.columns[len(self.grouping):]:
            try:
                words += seg.key_words_for_column(xp, vc, live,
                                                  for_grouping=True)
            except Exception:
                continue  # nested buffer: no sortable words
        order = seg.lexsort(xp, words, cap)
        from ..ops.gather import gather_batch
        out = gather_batch(xp, batch, order, live[order], batch.num_rows)
        return DeviceBatch(out.columns, batch.num_rows, batch.names)

    def _evaluate_batch(self, xp, batch: Batch) -> Batch:
        """buffers -> final results (Final/Complete modes)."""
        k = len(self.grouping)
        ctx = EvalContext(xp, batch)
        out_cols = list(batch.columns[:k])
        pos = k
        for ae in self.aggregates:
            nb = len(ae.func.buffer_types())
            bufs = [ColumnValue(batch.columns[pos + j]) for j in range(nb)]
            res = ae.func.evaluate(ctx, bufs)
            out_cols.append(res.col)
            pos += nb
        return DeviceBatch(out_cols, batch.num_rows, self.output_names)

    @functools.cached_property
    def _jit_key(self):
        return ("TpuHashAggregateExec", self.mode, self.stable_merge,
                schema_sig(self.children[0]),
                tuple(self._group_names), tuple(self._buffer_names),
                tuple(self.output_names),
                semantic_sig(getattr(self, "_bound_grouping",
                                     self.grouping)),
                semantic_sig(self.aggregates))

    @property
    def _jit_update(self):
        return process_jit(self._jit_key + ("update",),
                           lambda: lambda b: self._update_batch(jnp, b))

    @property
    def _jit_merge(self):
        return process_jit(self._jit_key + ("merge",),
                           lambda: lambda b: self._merge_batch(jnp, b))

    @property
    def _jit_merge_eval(self):
        return process_jit(
            self._jit_key + ("merge_eval",),
            lambda: lambda b: self._evaluate_batch(jnp,
                                                   self._merge_batch(jnp, b)))

    @property
    def _jit_eval(self):
        return process_jit(self._jit_key + ("eval",),
                           lambda: lambda b: self._evaluate_batch(jnp, b))

    @property
    def _jit_complete(self):
        """Single-batch Complete mode: update + evaluate fused into ONE
        compiled program — a lone input batch leaves _group_reduce with
        unique keys, so the merge pass would be an expensive no-op."""
        return process_jit(
            self._jit_key + ("complete",),
            lambda: lambda b: self._evaluate_batch(jnp,
                                                   self._update_batch(jnp, b)))

    @property
    def _jit_sortkeys(self):
        return process_jit(self._jit_key + ("sortkeys",),
                           lambda: lambda b: self._sort_by_keys(jnp, b))

    def _sort_by_keys(self, xp, batch: Batch) -> Batch:
        """Order partial-schema rows by grouping key words — the SAME
        for_grouping encoding _group_reduce segments by, so chunked
        re-aggregation's carry logic sees one consistent global order
        (out-of-core sort fallback, ref aggregate.scala:311-314)."""
        cap = batch.capacity
        live = xp.arange(cap, dtype=np.int32) < batch.num_rows
        words: List = [(~live).astype(xp.uint64)]
        for kc in batch.columns[:len(self.grouping)]:
            words += seg.key_words_for_column(xp, kc, live,
                                              for_grouping=True)
        order = seg.lexsort(xp, words, cap)
        from ..ops.gather import gather_batch
        out = gather_batch(xp, batch, order, live[order], batch.num_rows)
        return DeviceBatch(out.columns, batch.num_rows, batch.names)

    def memory_effects(self, child_states, conf):
        """Accumulates registered partial batches then concat + merge:
        ~3x one partition's padded input bytes in-core, or 3x the
        enforced budget out-of-core (bounded by oc_budget when the
        TPU-L014 pre-flight repair forced it)."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         spill_budget)
        if not child_states:
            return None
        pp = padded_partition_bytes(child_states[0])
        budget = float(min(spill_budget(conf),
                           self.oc_budget or (1 << 62)))
        hold = 3.0 * (pp if pp <= budget else budget)
        return MemoryEffects(hold=hold, note="aggregate: spill-managed")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        on_tpu = self.placement == TPU
        partials: List[Batch] = []
        schema_names = self._group_names + self._buffer_names
        kt = ([g.data_type() for g in self._bound_grouping]
              if self.mode in (PARTIAL, COMPLETE)
              else self.children[0].output_types[:len(self.grouping)])
        schema_types = kt + self._buffer_types
        from ..memory.spill import SpillCatalog, SpillPriority
        spill = SpillCatalog.get()
        try:
            it = iter(self.children[0].execute_partition(pid, ctx))
            first = next(it, None)
            second = next(it, None) if first is not None else None
            if first is not None and second is None and \
                    self.mode in (PARTIAL, COMPLETE):
                # single input batch: _group_reduce leaves unique keys, so
                # the cross-batch merge would be a no-op re-sort.  PARTIAL
                # emits the update output directly; COMPLETE fuses
                # update+evaluate into one compiled program.
                with MetricTimer(self.metrics[OP_TIME]):
                    if not on_tpu:
                        out = self._update_batch(np, first)
                        if self.mode == COMPLETE:
                            out = self._evaluate_batch(np, out)
                    elif self.mode == COMPLETE:
                        out = self._jit_complete(first)
                    else:
                        out = self._jit_update(first)
                    maybe_sync(out)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
                return
            import itertools
            stream = (b for b in itertools.chain(
                [x for x in (first, second) if x is not None], it))
            for b in stream:
                with MetricTimer(self.metrics[OP_TIME]):
                    if self.mode in (PARTIAL, COMPLETE):
                        out = self._jit_update(b) if on_tpu else \
                            self._update_batch(np, b)
                    else:
                        out = b  # FINAL: merge happens below
                    maybe_sync(out)
                # accumulated partials are spillable (ref aggregate.scala's
                # spillable batch accumulation before merge)
                partials.append(spill.register(out, SpillPriority.INPUT))
                if self.oc_budget is not None:
                    from .outofcore import enforce_device_budget
                    enforce_device_budget(
                        spill, min(spill.device_budget, self.oc_budget))
            if not partials:
                if self.grouping:
                    return
                # global aggregate over empty input still yields one row
                from ..columnar.interop import to_arrow_schema
                empty = to_arrow_schema(
                    self.children[0].output_names,
                    self.children[0].output_types).empty_table()
                rb = (empty.to_batches() or
                      [pa.RecordBatch.from_pydict(
                          {n: pa.array([], type=f.type)
                           for n, f in zip(empty.schema.names, empty.schema)})])
                eb = batch_to_device(rb[0], xp=xp)
                partials = [spill.register(
                    self._jit_update(eb) if on_tpu
                    else self._update_batch(np, eb), SpillPriority.INPUT)]
            total = sum(p.device_bytes for p in partials)
            budget = min(SpillCatalog.get().device_budget,
                         self.oc_budget or (1 << 62))
            if total <= budget:
                # in-core: one concat + merge
                with MetricTimer(self.metrics[OP_TIME]):
                    mats = [p.get_batch(xp) for p in partials]
                    if len(mats) == 1:
                        merged_in = mats[0]
                    else:
                        merged_in = concat_batches(xp, mats, schema_names,
                                                   schema_types)
                    for p in partials:
                        p.close()
                    if self.mode == PARTIAL:
                        out = self._jit_merge(merged_in) if on_tpu else \
                            self._merge_batch(np, merged_in)
                    else:
                        out = self._jit_merge_eval(merged_in) if on_tpu else \
                            self._evaluate_batch(np,
                                                 self._merge_batch(np,
                                                                   merged_in))
                    maybe_sync(out)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
                return
            # out-of-core: budget-bounded iterative merge with sort-based
            # fallback (ref aggregate.scala:309-314)
            from .outofcore import merge_partials_bounded
            spill = SpillCatalog.get()
            merge_fn = self._jit_merge if on_tpu else \
                (lambda b: self._merge_batch(np, b))
            sortkeys_fn = self._jit_sortkeys if on_tpu else \
                (lambda b: self._sort_by_keys(np, b))
            chunk_rows = max(int(p.num_rows) for p in partials)
            if self.oc_budget is not None:
                # snap down to a capacity bucket (off-bucket chunks pad UP)
                from ..columnar.device import (DEFAULT_ROW_BUCKETS,
                                               bucket_floor)
                rows_total = sum(int(p.num_rows) for p in partials)
                bpr = max(total / max(rows_total, 1), 1.0)
                target = int(budget / (2 * bpr))
                chunk_rows = min(chunk_rows,
                                 bucket_floor(target, DEFAULT_ROW_BUCKETS))
            with MetricTimer(self.metrics[OP_TIME]):
                for m in merge_partials_bounded(
                        xp, partials, merge_fn, sortkeys_fn, schema_names,
                        schema_types, spill, budget, chunk_rows):
                    if self.mode == PARTIAL:
                        out = m
                    else:
                        out = self._jit_eval(m) if on_tpu else \
                            self._evaluate_batch(np, m)
                    self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                    self.metrics[NUM_OUTPUT_BATCHES] += 1
                    yield out
        finally:
            # a raising producer (or an abandoned consumer) must
            # not strand registered spillables: close everything
            # this partition accumulated — idempotent, so batches
            # the merge already consumed are no-ops (tpufsan
            # TPU-R012)
            for p in partials:
                p.close()


# ---------------------------------------------------------------------------
# CPU fallback aggregate: independent pyarrow implementation
# ---------------------------------------------------------------------------

_PA_AGG = {
    Sum: "sum", Count: "count", Average: "mean", Min: "min", Max: "max",
    First: "first", Last: "last", StddevSamp: "stddev", StddevPop: "stddev",
    VarianceSamp: "variance", VariancePop: "variance",
    CollectSet: "distinct", CollectList: "list",
    # PivotFirst: the masked input column + first-non-null
    PivotFirst: "first",
    # ApproximatePercentile: collect the group then rank on host
    ApproximatePercentile: "list",
}


class CpuHashAggregateExec(Exec):
    """Complete-mode aggregate on pyarrow (the 'Spark CPU' role)."""

    def __init__(self, grouping: Sequence[Expression],
                 aggregates: Sequence[AggregateExpression], child: Exec):
        super().__init__([child])
        self.grouping = list(grouping)
        cn, ct = child.output_names, child.output_types
        from ..expr.aggregates import bind_aggregate
        self.aggregates = [bind_aggregate(a, cn, ct) for a in aggregates]
        self._bound_grouping = [bind_expression(g, cn, ct) for g in grouping]
        self._group_names = [output_name(g) for g in grouping]

    @property
    def output_names(self):
        return self._group_names + [a.name for a in self.aggregates]

    @property
    def output_types(self):
        return [g.data_type() for g in self._bound_grouping] + \
            [a.data_type() for a in self.aggregates]

    def describe(self):
        return (f"CpuHashAggregate(keys=[{', '.join(self._group_names)}], "
                f"fns=[{', '.join(a.name for a in self.aggregates)}])")

    def determinism(self):
        from ..analysis.determinism import (Determinism, ORDER_DEPENDENT,
                                            ORDER_STABLE)
        floaty = any(isinstance(bt, t.FractionalType)
                     for bt in (b for ae in self.aggregates
                                for b in ae.func.buffer_types()))
        if floaty or any(isinstance(ae.func, CollectList)
                         for ae in self.aggregates):
            return Determinism(
                ORDER_DEPENDENT, "pyarrow group_by folds the table in "
                "batch-arrival row order (no canonical merge on the "
                "host fallback)")
        return Determinism(
            ORDER_STABLE, "integer/decimal folds are exact; group "
            "emission order follows arrival")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from ..expr.core import EvalContext as EC
        from ..columnar.interop import to_arrow_type
        child = self.children[0]
        tables = []
        for b in child.execute_partition(pid, ctx):
            # evaluate grouping + agg input expressions on host, then arrow
            ec = EC(np, b)
            cols = {}
            for g, nm in zip(self._bound_grouping, self._group_names):
                from ..columnar.device import column_to_arrow
                v = g.eval(ec)
                arr = column_to_arrow(v.col, int(b.num_rows))
                if pa.types.is_struct(arr.type):
                    # pyarrow cannot group struct keys: flatten to field
                    # columns (+ an explicit top-level null flag — field
                    # nulls alone cannot distinguish a null struct from a
                    # struct of nulls) and rebuild after the aggregate
                    import pyarrow.compute as _pc
                    for j in range(arr.type.num_fields):
                        cols[f"__{nm}__f{j}"] = _pc.struct_field(arr, j)
                    cols[f"__{nm}__null"] = _pc.is_null(arr)
                else:
                    cols[nm] = arr
            for i, ae in enumerate(self.aggregates):
                fn = ae.func
                if fn.children:
                    in_expr = fn._masked() if isinstance(fn, PivotFirst) \
                        else fn.child
                    bexpr = bind_expression(in_expr, child.output_names,
                                            child.output_types)
                    v = bexpr.eval(ec)
                    from ..expr.core import ScalarValue, make_column
                    if isinstance(v, ScalarValue):
                        v = make_column(ec, bexpr.data_type(),
                                        v.value if v.value is not None else 0,
                                        None if v.value is not None else False)
                    from ..columnar.device import column_to_arrow
                    cols[f"__in{i}"] = column_to_arrow(v.col, int(b.num_rows))
                else:
                    cols[f"__in{i}"] = pa.array([1] * int(b.num_rows),
                                                type=pa.int64())
            tables.append(pa.table(cols))
        if not tables:
            if self.grouping:
                return
            tables = [pa.table({nm: pa.array([], to_arrow_type(dt))
                                for nm, dt in
                                zip(self._group_names +
                                    [f"__in{i}" for i in
                                     range(len(self.aggregates))],
                                    [g.data_type() for g in
                                     self._bound_grouping] +
                                    [a.func.child.data_type() if
                                     a.func.children else t.INT
                                     for a in self.aggregates])})]
        table = pa.concat_tables(tables)
        struct_types = {nm: to_arrow_type(g.data_type())
                        for g, nm in zip(self._bound_grouping,
                                         self._group_names)
                        if pa.types.is_struct(
                            to_arrow_type(g.data_type()))}
        group_cols = []
        for nm, g in zip(self._group_names, self._bound_grouping):
            if nm in struct_types:
                group_cols += [f"__{nm}__f{j}" for j in
                               range(struct_types[nm].num_fields)]
                group_cols.append(f"__{nm}__null")
            else:
                group_cols.append(nm)
        from ..shims import active_shim
        legacy_stat = active_shim().legacy_statistical_aggregate()
        aggs = []
        for i, ae in enumerate(self.aggregates):
            kind = _PA_AGG[type(ae.func)]
            opts = None
            if kind in ("stddev", "variance"):
                ddof = 0 if isinstance(ae.func, (StddevPop, VariancePop)) else 1
                opts = pc.VarianceOptions(ddof=ddof)
                if legacy_stat:
                    # 3.0 dialect needs the group's row count to turn
                    # divide-by-zero nulls into NaN (same rule as the
                    # TPU path's _MomentAgg._var)
                    aggs.append((f"__in{i}", "count", None))
            if kind in ("first", "last"):
                skip = True if isinstance(ae.func, PivotFirst) \
                    else ae.func.ignore_nulls
                opts = pc.ScalarAggregateOptions(skip_nulls=skip)
            aggs.append((f"__in{i}", kind, opts))
        if self.grouping:
            res = pa.TableGroupBy(table, group_cols,
                                  use_threads=False).aggregate(aggs)
        elif table.num_rows == 0:
            # Spark: a global aggregate over empty input yields one row
            cols = {}
            for (cname, kind, opts) in aggs:
                if kind in ("list", "distinct"):
                    # empty input collects to the empty list (Spark's
                    # collect_*), which percentile evaluates to null
                    cols[f"{cname}_{kind}"] = pa.array(
                        [[]], type=pa.list_(table.column(cname).type))
                    continue
                fn = {"sum": pc.sum, "count": pc.count, "mean": pc.mean,
                      "min": pc.min, "max": pc.max,
                      "stddev": pc.stddev, "variance": pc.variance,
                      "first": pc.first, "last": pc.last}[kind]
                scalar = fn(table.column(cname))
                cols[f"{cname}_{kind}"] = pa.array([scalar.as_py()],
                                                   type=scalar.type)
            res = pa.table(cols)
        else:
            res = pa.TableGroupBy(
                table.append_column("__g", pa.array([1] * table.num_rows)),
                ["__g"], use_threads=False).aggregate(aggs)
            res = res.drop_columns(["__g"])
        # rename/cast to declared output schema
        out_cols = []
        for nm in self._group_names:
            if nm in struct_types:
                st = struct_types[nm]
                fields = [res.column(f"__{nm}__f{j}").combine_chunks()
                          for j in range(st.num_fields)]
                arrs = [f.chunk(0) if isinstance(f, pa.ChunkedArray)
                        else f for f in fields]
                nulls = res.column(f"__{nm}__null").combine_chunks()
                nulls = nulls.chunk(0) if isinstance(
                    nulls, pa.ChunkedArray) else nulls
                mask = pa.array([bool(x) if x is not None else True
                                 for x in nulls.to_pylist()])
                out_cols.append(pa.StructArray.from_arrays(
                    arrs, fields=list(st), mask=mask))
            else:
                out_cols.append(res.column(nm))
        for i, ae in enumerate(self.aggregates):
            kind = _PA_AGG[type(ae.func)]
            cname = f"__in{i}_{kind}"
            col = res.column(cname)
            if legacy_stat and kind in ("stddev", "variance"):
                import math
                counts = res.column(f"__in{i}_count").to_pylist()
                vals = [v if v is not None else
                        (float("nan") if (n or 0) > 0 else None)
                        for v, n in zip(col.to_pylist(), counts)]
                col = pa.chunked_array([pa.array(vals,
                                                 type=pa.float64())])
            if isinstance(ae.func, ApproximatePercentile):
                p = ae.func.percentage
                vals = []
                for row in col.to_pylist():
                    grp = sorted(v for v in row if v is not None)
                    if not grp:
                        vals.append(None)
                        continue
                    import math
                    k = max(math.ceil(p * len(grp)) - 1, 0)
                    vals.append(grp[min(k, len(grp) - 1)])
                col = pa.chunked_array([pa.array(
                    vals, type=to_arrow_type(ae.data_type()))])
            if isinstance(ae.func, CollectList) and \
                    not isinstance(ae.func, CollectSet):
                # Spark's collect_list drops nulls; pyarrow's keeps them
                col = pa.chunked_array([pa.array(
                    [[v for v in row if v is not None]
                     for row in chunk.to_pylist()],
                    type=chunk.type) for chunk in col.chunks])
            col = col.cast(to_arrow_type(ae.data_type()))
            out_cols.append(col)
        out = pa.table(dict(zip(self.output_names, out_cols)))
        for rb in out.combine_chunks().to_batches():
            yield batch_to_device(rb, xp=np)
        if out.num_rows == 0 and not self.grouping:
            pass
