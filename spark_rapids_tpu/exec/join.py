"""Join operators.

Ref: sql-plugin/.../GpuHashJoin.scala:96-377 (HashJoinIterator),
JoinGatherer.scala (gather-map chunked output),
GpuShuffledHashJoinBase.scala, GpuBroadcastNestedLoopJoinExec.scala,
GpuCartesianProductExec.scala.  Sort-merge joins are replaced by hash
joins exactly like the reference (RapidsConf replaceSortMergeJoin).

TPU realization (ops/join_kernels.py): build side concatenates and its
combined 64-bit key hash sorts once; each probe batch runs a jitted
count phase (binary-search match ranges + exact output sizing incl.
string bytes), one host sync picks the bucketed output capacity, and a
jitted expand phase materializes gather maps for both sides — the
static-shape answer to cuDF's dynamic gather maps.

CpuJoinExec is an independent pyarrow Table.join implementation (CPU
fallback engine + differential oracle).
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import (DEFAULT_CHAR_BUCKETS, DEFAULT_ROW_BUCKETS,
                               DeviceBatch, DeviceColumn, batch_to_arrow,
                               batch_to_device, bucket_for)
from ..expr.core import (BoundReference, EvalContext, Expression,
                         bind_expression)
from ..expr.predicates import And, EqualTo
from ..ops import join_kernels as jk
from ..ops.gather import gather_batch, gather_column
from .base import (maybe_sync,  # noqa: F401
                   NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU, Batch,
                   Exec, MetricTimer, process_jit, schema_sig, semantic_sig)
from .concat import concat_batches
from .filter_common import apply_filter, compact
from ..ops.scan import cumsum_fast

JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti",
              "cross")


def split_equi_condition(cond: Optional[Expression], left_names, right_names
                         ) -> Tuple[List[Expression], List[Expression],
                                    Optional[Expression]]:
    """Split a join condition into equi key pairs + residual
    (ref GpuHashJoin extractTopLevelAttributes / Spark's ExtractEquiJoinKeys)."""
    from ..expr.core import AttributeReference
    lset, rset = set(left_names), set(right_names)

    def refs(e: Expression):
        return {x.name for x in e.collect(
            lambda n: isinstance(n, AttributeReference))}

    conjuncts: List[Expression] = []

    def flatten(e):
        if isinstance(e, And):
            flatten(e.children[0])
            flatten(e.children[1])
        else:
            conjuncts.append(e)
    if cond is not None:
        flatten(cond)
    lkeys, rkeys, residual = [], [], []
    for c in conjuncts:
        if isinstance(c, EqualTo):
            a, b = c.children
            ra, rb = refs(a), refs(b)
            if ra <= lset and rb <= rset and ra and rb:
                lkeys.append(a)
                rkeys.append(b)
                continue
            if ra <= rset and rb <= lset and ra and rb:
                lkeys.append(b)
                rkeys.append(a)
                continue
        residual.append(c)
    res = None
    for c in residual:
        res = c if res is None else And(res, c)
    return lkeys, rkeys, res


class HashJoinExec(Exec):
    """TPU equi-join; build side is always the right child
    (right joins are planned flipped, like the reference's build-side
    selection in GpuShuffledHashJoinBase)."""

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], how: str,
                 condition: Optional[Expression],
                 left: Exec, right: Exec, colocated: bool = False):
        super().__init__([left, right])
        assert how in JOIN_TYPES
        self.how = how
        self.colocated = colocated
        self.left_keys = [bind_expression(k, left.output_names,
                                          left.output_types)
                          for k in left_keys]
        self.right_keys = [bind_expression(k, right.output_names,
                                           right.output_types)
                           for k in right_keys]
        self.condition = condition
        self._bound_condition = (
            bind_expression(condition, self.output_names, self.output_types)
            if condition is not None else None)

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "probe-order emission: output row order "
            "follows probe-side arrival, matched multiset is invariant")

    def input_contracts(self):
        if not self.colocated:
            return None
        from ..analysis.absdomain import CoClusteredContract, key_names
        l, r = self.children
        lk = key_names(self.left_keys, l.output_names)
        rk = key_names(self.right_keys, r.output_names)
        if lk is None or rk is None:
            return None  # computed keys: no nameable clustering fact
        return CoClusteredContract(lk, rk)

    def memory_effects(self, child_states, conf):
        """The build side is concatenated into ONE raw device batch per
        probe partition (whole right side unless colocated) — not
        spill-managed, so the full build bytes count against peak; plus
        the probe's in-flight batch and the expanded output."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         total_bytes)
        if len(child_states) < 2:
            return None
        build = padded_partition_bytes(child_states[1]) if self.colocated \
            else total_bytes(child_states[1])
        # 2x build (collected batches + concat) + probe batch + output
        return MemoryEffects(
            hold=2.0 * build + 2.0 * padded_partition_bytes(
                child_states[0]) + build,
            note="raw build-side concat")

    @property
    def output_names(self):
        l, r = self.children
        if self.how in ("left_semi", "left_anti"):
            return l.output_names
        return l.output_names + r.output_names

    @property
    def output_types(self):
        l, r = self.children
        lt = list(l.output_types)
        rt = list(r.output_types)
        if self.how in ("left_semi", "left_anti"):
            return lt
        return lt + rt

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def describe(self):
        ks = ", ".join(f"{a.sql()}={b.sql()}"
                       for a, b in zip(self.left_keys, self.right_keys))
        return f"HashJoin {self.how} on [{ks}]"

    # --- phase 1: count + sizing -------------------------------------------
    def _count(self, xp, build: Batch, probe: Batch):
        bctx = EvalContext(xp, build)
        pctx = EvalContext(xp, probe)
        bkeys = [k.eval(bctx).col for k in self.right_keys]
        pkeys = [k.eval(pctx).col for k in self.left_keys]
        blive = bctx.row_mask()
        plive = pctx.row_mask()
        bh = jk.combined_key_hash(xp, bkeys, build.capacity, side="build")
        ph = jk.combined_key_hash(xp, pkeys, probe.capacity, side="probe")
        order, lo, counts = jk.count_matches(xp, bh, blive, ph, plive)
        outer = self.how in ("left", "full")
        eff = xp.maximum(counts, 1) if outer else counts
        eff = xp.where(plive, eff, 0)
        total = xp.sum(eff)
        # span sizing: strings count output BYTES, arrays/maps count
        # output CHILD ROWS — a row-duplicating gather must size the
        # child buffer to the duplicated total, not the source capacity
        # (a source-cap default silently truncates join expansions)
        def span_lens(c):
            return (c.offsets[1:] - c.offsets[:-1]).astype(xp.int64)

        pbytes = []
        for c in probe.columns:
            if c.offsets is not None:
                pbytes.append(xp.sum(eff * span_lens(c)))
            else:
                pbytes.append(xp.int64(0) if xp is not np else np.int64(0))
        bbytes = []
        for c in build.columns:
            if c.offsets is not None:
                sl = span_lens(c)[order]
                pre = xp.concatenate([xp.zeros((1,), xp.int64),
                                      cumsum_fast(xp, sl)])
                per = pre[lo + counts.astype(xp.int32)] - pre[lo]
                bbytes.append(xp.sum(xp.where(plive, per, 0)))
            else:
                bbytes.append(xp.int64(0) if xp is not np else np.int64(0))
        matched = jk.build_matched_flags(xp, order, lo, counts, plive,
                                         build.capacity)
        # all host-needed sizes ride ONE array so the caller pays a single
        # device round trip, not one per column (tunnel latency)
        sizes = xp.stack([xp.asarray(total, dtype=xp.int64)]
                         + [xp.asarray(x, dtype=xp.int64) for x in pbytes]
                         + [xp.asarray(x, dtype=xp.int64) for x in bbytes])
        return (order, lo, counts, sizes, matched)

    @functools.cached_property
    def _jit_key(self):
        return ("HashJoinExec", self.how,
                schema_sig(self.children[0]), schema_sig(self.children[1]),
                semantic_sig(self.left_keys),
                semantic_sig(self.right_keys),
                semantic_sig(self._bound_condition))

    @property
    def _jit_count(self):
        return process_jit(self._jit_key + ("count",),
                           lambda: lambda b, p: self._count(jnp, b, p))

    # --- phase 2: expansion -------------------------------------------------
    def _expand(self, xp, build: Batch, probe: Batch, order, lo, counts,
                out_cap: int, pchar_caps, bchar_caps) -> Batch:
        plive = xp.arange(probe.capacity, dtype=np.int32) < probe.num_rows
        (pidx, bidx, pair_valid, pvalid, bvalid, total) = jk.expand_pairs(
            xp, order, lo, counts, plive, out_cap, self.how)
        lcols = [gather_column(xp, c, pidx, pvalid, cc)
                 for c, cc in zip(probe.columns, pchar_caps)]
        rcols = [gather_column(xp, c, bidx, bvalid, cc)
                 for c, cc in zip(build.columns, bchar_caps)]
        return DeviceBatch(lcols + rcols, total, self.output_names)

    def _expand_call(self, xp, build, probe, order, lo, counts, out_cap,
                     pchar_caps, bchar_caps):
        if xp is np:
            return self._expand(np, build, probe, order, lo, counts,
                                out_cap, pchar_caps, bchar_caps)
        key = self._jit_key + ("expand", out_cap, tuple(pchar_caps),
                               tuple(bchar_caps))
        fn = process_jit(key, lambda: lambda b, p, o, l, c: self._expand(
            jnp, b, p, o, l, c, out_cap, pchar_caps, bchar_caps))
        return fn(build, probe, order, lo, counts)

    # --- conditional left join ---------------------------------------------
    def _expand_left_cond(self, xp, build: Batch, probe: Batch, order, lo,
                          counts, out_cap: int, pchar_caps, bchar_caps
                          ) -> Batch:
        """LEFT join with a residual condition, one traced function:
        expand all candidate pairs, evaluate the condition, keep passing
        pairs, and REPAIR probe rows whose candidates all failed — their
        first pair survives with the build side nulled (Spark's outer
        conditional-join semantics; ref GpuHashJoin's post-filter with
        unmatched-row emission, GpuOverrides.scala:3352-3355)."""
        from ..ops.carry import mask_validity
        plive = xp.arange(probe.capacity, dtype=np.int32) < probe.num_rows
        (pidx, bidx, pair_valid, pvalid, bvalid, total) = jk.expand_pairs(
            xp, order, lo, counts, plive, out_cap, "left")
        lcols = [gather_column(xp, c, pidx, pvalid, cc)
                 for c, cc in zip(probe.columns, pchar_caps)]
        rcols = [gather_column(xp, c, bidx, bvalid, cc)
                 for c, cc in zip(build.columns, bchar_caps)]
        out = DeviceBatch(lcols + rcols, total, self.output_names)
        ctx = EvalContext(xp, out)
        v = self._bound_condition.eval(ctx)
        from ..expr.core import ColumnValue, make_column
        if not isinstance(v, ColumnValue):
            v = make_column(ctx, self._bound_condition.data_type(),
                            v.value if v.value is not None else False,
                            None if v.value is not None else False)
        passes = v.col.data.astype(bool)
        if v.col.validity is not None:
            passes = passes & v.col.validity
        real = counts.astype(xp.int32)[pidx] > 0     # vs synthesized null
        pred_true = passes & real & pair_valid
        if xp is np:
            pass_cnt = np.zeros((probe.capacity,), np.int32)
            np.add.at(pass_cnt, np.clip(pidx, 0, probe.capacity - 1),
                      pred_true.astype(np.int32))
        else:
            pass_cnt = xp.zeros((probe.capacity,), xp.int32).at[pidx].add(
                pred_true.astype(xp.int32), mode="drop")
        # pairs are emitted grouped per probe row, so a boundary marks
        # each row's first candidate
        first = xp.concatenate(
            [xp.ones((1,), bool), pidx[1:] != pidx[:-1]]) & pair_valid
        convert = first & real & (pass_cnt[pidx] == 0)
        keep = pair_valid & (~real | pred_true | convert)
        null_build = ~real | convert
        nb = len(probe.columns)
        fixed = list(out.columns[:nb]) + [
            mask_validity(xp, c, ~null_build) for c in out.columns[nb:]]
        out = DeviceBatch(fixed, total, self.output_names)
        return compact(xp, out, keep, self.output_names)

    def _expand_left_cond_call(self, xp, build, probe, order, lo, counts,
                               out_cap, pchar_caps, bchar_caps):
        if xp is np:
            return self._expand_left_cond(np, build, probe, order, lo,
                                          counts, out_cap, pchar_caps,
                                          bchar_caps)
        key = self._jit_key + ("expand_leftcond", out_cap,
                               tuple(pchar_caps), tuple(bchar_caps))
        fn = process_jit(key, lambda: lambda b, p, o, l, c:
                         self._expand_left_cond(jnp, b, p, o, l, c,
                                                out_cap, pchar_caps,
                                                bchar_caps))
        return fn(build, probe, order, lo, counts)

    # --- unmatched build rows for right/full --------------------------------
    def _unmatched_build(self, xp, build: Batch, matched_any) -> Batch:
        keep = (xp.arange(build.capacity, dtype=np.int32) < build.num_rows) \
            & ~matched_any
        compacted = compact(xp, build, keep, self.children[1].output_names)
        n = compacted.num_rows
        from ..expr.core import EvalContext as EC, all_null_column
        ctx = EC(xp, compacted)
        lcols = [all_null_column(ctx, dt).col
                 for dt in self.children[0].output_types]
        return DeviceBatch(lcols + list(compacted.columns), n,
                           self.output_names)

    # --- speculative sizing: count+expand fused, zero sizing syncs ----------
    def _spec_supported(self, build: Batch, probe: Batch) -> bool:
        """Speculation needs a capacity guess that is usually right and a
        truncation that a single guard detects: flat fixed-width lanes
        (span columns would need char-cap guesses too) and join types
        whose output rides the (probe, build) gather maps only."""
        if self.how not in ("inner", "left"):
            return False
        def flat(c):
            return c.offsets is None and c.data_hi is None and \
                not c.children
        return all(flat(c) for c in probe.columns) and \
            all(flat(c) for c in build.columns)

    def _spec_join(self, build: Batch, probe: Batch, out_cap: int):
        """One fused program: count, expand at the guessed capacity, and
        the guard `total <= out_cap` (validated later from the result
        fetch — a miss means truncated output, never surfaced)."""
        order, lo, counts, sizes, _ = self._count(jnp, build, probe)
        zeros_p = [0] * len(probe.columns)
        zeros_b = [0] * len(build.columns)
        if self._bound_condition is not None and self.how == "left":
            # the conditional-left expand+repair kernel fuses in too;
            # its output never exceeds the sizing bound (eff counts
            # already include the null-extension rows, and the repair
            # only shrinks)
            out = self._expand_left_cond(jnp, build, probe, order, lo,
                                         counts, out_cap, zeros_p,
                                         zeros_b)
        else:
            out = self._expand(jnp, build, probe, order, lo, counts,
                               out_cap, zeros_p, zeros_b)
            if self._bound_condition is not None and self.how == "inner":
                pctx = EvalContext(jnp, out)
                out = apply_filter(jnp, out,
                                   self._bound_condition.eval(pctx),
                                   self.output_names)
        return out, sizes[0] <= np.int64(out_cap)

    def _collect_build(self, pid, ctx) -> Batch:
        """Materialize the build side as ONE device batch: this
        partition's co-clustered shard when colocated, the whole right
        side otherwise."""
        xp = self.xp
        right = self.children[1]
        build_batches = []
        if self.colocated:
            build_pids = [pid]
        else:
            build_pids = list(range(right.num_partitions))
        for bpid in build_pids:
            build_batches += list(right.execute_partition(bpid, ctx))
        if not build_batches:
            from ..columnar.interop import to_arrow_schema
            schema = to_arrow_schema(right.output_names, right.output_types)
            rb = pa.RecordBatch.from_pydict(
                {n: pa.array([], type=f.type)
                 for n, f in zip(schema.names, schema)})
            build_batches = [batch_to_device(rb, xp=xp)]
        return concat_batches(xp, build_batches, right.output_names,
                              right.output_types) \
            if len(build_batches) > 1 else build_batches[0]

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from .. import config as cfg
        xp = self.xp
        on_tpu = self.placement == TPU
        speculate = (on_tpu and ctx.speculation_enabled and
                     ctx.conf.get(cfg.JOIN_SPECULATIVE_SIZING))
        build = self._collect_build(pid, ctx)
        matched_acc = None
        for probe in self.children[0].execute_partition(pid, ctx):
            if speculate and self._spec_supported(build, probe):
                # guess: output rows <= probe capacity (exact when build
                # keys are unique — the FK->PK case); the deferred guard
                # rides the result fetch, so the sizing round trip that
                # serializes every other join disappears entirely
                out_cap = int(probe.capacity)
                with MetricTimer(self.metrics[OP_TIME]):
                    fn = process_jit(
                        self._jit_key + ("spec", out_cap),
                        lambda: lambda b, p: self._spec_join(b, p, out_cap))
                    out, guard = fn(build, probe)
                    ctx.add_spec_guard(guard)
                    maybe_sync(out)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
                continue
            with MetricTimer(self.metrics[OP_TIME]):
                if on_tpu:
                    (order, lo, counts, sizes,
                     matched) = self._jit_count(build, probe)
                else:
                    (order, lo, counts, sizes,
                     matched) = self._count(np, build, probe)
                if self.how in ("right", "full"):
                    matched_acc = matched if matched_acc is None else \
                        (matched_acc | matched)
                if self.how == "left_semi":
                    keep = counts > 0
                    live = xp.arange(probe.capacity, dtype=np.int32) < \
                        probe.num_rows
                    yield compact(xp, probe, keep & live, self.output_names)
                    continue
                if self.how == "left_anti":
                    live = xp.arange(probe.capacity, dtype=np.int32) < \
                        probe.num_rows
                    yield compact(xp, probe, (counts == 0) & live,
                                  self.output_names)
                    continue
                if self.how == "right":
                    # planned flipped; only unmatched emission remains here
                    pass
                from ..columnar.fetch import fetch_array
                sizes = fetch_array(sizes)         # one round trip
                ntotal = int(sizes[0])
                if ntotal >= (1 << 31):
                    # expand_pairs builds pair offsets in int32; a wrap
                    # would silently corrupt gather indices
                    raise RuntimeError(
                        f"join expansion of {ntotal} rows exceeds the "
                        f"2^31-1 per-batch capacity; split the inputs")
                pbytes = sizes[1:1 + len(probe.columns)]
                bbytes = sizes[1 + len(probe.columns):]
                out_cap = bucket_for(max(ntotal, 1), DEFAULT_ROW_BUCKETS)

                def span_cap(x, c):
                    """Output child capacity for a span column: char
                    bucket for strings, row bucket for array/map child
                    rows; 0 = not a span column."""
                    if isinstance(c.dtype, (t.StringType, t.BinaryType)):
                        return bucket_for(max(int(x), 1),
                                          DEFAULT_CHAR_BUCKETS)
                    if isinstance(c.dtype, (t.ArrayType, t.MapType)):
                        return bucket_for(max(int(x), 1),
                                          DEFAULT_ROW_BUCKETS)
                    return 0

                pchar_caps = [span_cap(x, c)
                              for x, c in zip(pbytes, probe.columns)]
                bchar_caps = [span_cap(x, c)
                              for x, c in zip(bbytes, build.columns)]
                if self._bound_condition is not None and \
                        self.how == "left":
                    out = self._expand_left_cond_call(
                        xp, build, probe, order, lo, counts, out_cap,
                        pchar_caps, bchar_caps)
                else:
                    out = self._expand_call(xp, build, probe, order, lo,
                                            counts, out_cap, pchar_caps,
                                            bchar_caps)
                    if self._bound_condition is not None and \
                            self.how == "inner":
                        pctx = EvalContext(xp, out)
                        pred = self._bound_condition.eval(pctx)
                        out = apply_filter(xp, out, pred,
                                           self.output_names)
                maybe_sync(out)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield out
        if self.how in ("right", "full") and matched_acc is not None:
            out = self._unmatched_build(xp, build, matched_acc)
            if int(out.num_rows):
                yield out


class ShuffledHashJoinExec(HashJoinExec):
    """Co-partitioned hash join over spill-backed shuffle catalog
    partitions (ref GpuShuffledHashJoinExec.scala).

    Both children are hash-exchanged on the join keys (declared via
    ``CoClusteredContract``), so partition ``pid`` joins ONLY its own
    shard on each side — the build side is one catalog partition, not
    the whole table, which is what lets joins scale past single-device
    memory: the exchanged blocks are spill-managed (DEVICE->HOST->DISK),
    and the build materialization retries under synchronous spill when
    concatenating a shard would overflow HBM.  On a mesh, this node
    rewrites further into IciJoinExec (in-shard all_to_all); this class
    is the single-host / DCN realization."""

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], how: str,
                 condition: Optional[Expression],
                 left: Exec, right: Exec, colocated: bool = True):
        # co-partitioning is this node's reason to exist
        super().__init__(left_keys, right_keys, how, condition, left,
                         right, colocated=True)

    def describe(self):
        ks = ", ".join(f"{a.sql()}={b.sql()}"
                       for a, b in zip(self.left_keys, self.right_keys))
        return f"ShuffledHashJoin {self.how} on [{ks}]"

    def memory_effects(self, child_states, conf):
        """One co-clustered shard per side is live at a time; the rest
        of both exchanged datasets is shuffle retention already modeled
        (and spill-bounded) by the exchange children.  The shard's
        concat + expand still holds raw device bytes, so the bound keeps
        the parent's 2x-build + probe + output shape — over one
        partition, not the whole build side."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes)
        if len(child_states) < 2:
            return None
        build = padded_partition_bytes(child_states[1])
        probe = padded_partition_bytes(child_states[0])
        return MemoryEffects(
            hold=2.0 * build + 2.0 * probe + build,
            note="co-partitioned spill-backed build shard")

    def _collect_build(self, pid, ctx) -> Batch:
        """Materialize this partition's build shard under OOM-retry:
        running out of device memory synchronously spills lower-priority
        registrations (shuffle blocks first) and tries again, instead of
        failing the join."""
        from ..memory.spill import SpillCatalog, with_retry_spill
        return with_retry_spill(
            lambda: super(ShuffledHashJoinExec, self)._collect_build(
                pid, ctx),
            SpillCatalog.get())


class NestedLoopJoinExec(Exec):
    """Cross product + optional condition (ref
    GpuBroadcastNestedLoopJoinExec / GpuCartesianProductExec)."""

    def __init__(self, how: str, condition: Optional[Expression],
                 left: Exec, right: Exec):
        super().__init__([left, right])
        self.how = how
        self.condition = condition
        self._bound_condition = (
            bind_expression(condition, self.output_names, self.output_types)
            if condition is not None else None)

    @property
    def output_names(self):
        return self.children[0].output_names + self.children[1].output_names

    @property
    def output_types(self):
        return self.children[0].output_types + self.children[1].output_types

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "cross-product emission order follows both "
            "sides' arrival; matched multiset is invariant")

    def memory_effects(self, child_states, conf):
        """Collects the whole right side raw per probe partition, and
        the cross-product output amplifies: both sides' bytes plus the
        expanded batch count against peak."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         total_bytes)
        if len(child_states) < 2:
            return None
        return MemoryEffects(
            hold=3.0 * total_bytes(child_states[1]) +
            2.0 * padded_partition_bytes(child_states[0]),
            note="raw build-side concat")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        right = self.children[1]
        rbatches = []
        for rp in range(right.num_partitions):
            rbatches += list(right.execute_partition(rp, ctx))
        if not rbatches:
            return
        build = concat_batches(xp, rbatches, right.output_names,
                               right.output_types) if len(rbatches) > 1 \
            else rbatches[0]
        nb = int(build.num_rows)
        for probe in self.children[0].execute_partition(pid, ctx):
            np_rows = int(probe.num_rows)
            total = np_rows * nb
            out_cap = bucket_for(max(total, 1), DEFAULT_ROW_BUCKETS)
            pidx = xp.arange(out_cap, dtype=np.int32) // max(nb, 1)
            bidx = xp.arange(out_cap, dtype=np.int32) % max(nb, 1)
            valid = xp.arange(out_cap, dtype=np.int32) < total
            pchar = [int(c.data.shape[0]) * max(nb, 1)
                     if isinstance(c.dtype, (t.StringType, t.BinaryType))
                     else 0 for c in probe.columns]
            bchar = [int(c.data.shape[0]) * max(np_rows, 1)
                     if isinstance(c.dtype, (t.StringType, t.BinaryType))
                     else 0 for c in build.columns]
            lcols = [gather_column(xp, c, pidx, valid,
                                   bucket_for(max(cc, 1),
                                              DEFAULT_CHAR_BUCKETS)
                                   if cc else 0)
                     for c, cc in zip(probe.columns, pchar)]
            rcols = [gather_column(xp, c, bidx, valid,
                                   bucket_for(max(cc, 1),
                                              DEFAULT_CHAR_BUCKETS)
                                   if cc else 0)
                     for c, cc in zip(build.columns, bchar)]
            out = DeviceBatch(lcols + rcols, total, self.output_names)
            if self._bound_condition is not None:
                ectx = EvalContext(xp, out)
                out = apply_filter(xp, out, self._bound_condition.eval(ectx),
                                   self.output_names)
            yield out


# ---------------------------------------------------------------------------
# CPU fallback: pyarrow Table.join
# ---------------------------------------------------------------------------

_PA_JOIN = {"inner": "inner", "left": "left outer", "right": "right outer",
            "full": "full outer", "left_semi": "left semi",
            "left_anti": "left anti"}


class CpuJoinExec(Exec):
    def __init__(self, left_keys, right_keys, how, condition,
                 left: Exec, right: Exec, colocated: bool = False):
        super().__init__([left, right])
        self.how = how
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.condition = condition
        self.colocated = colocated

    @property
    def output_names(self):
        l, r = self.children
        if self.how in ("left_semi", "left_anti"):
            return l.output_names
        return l.output_names + r.output_names

    @property
    def output_types(self):
        l, r = self.children
        if self.how in ("left_semi", "left_anti"):
            return list(l.output_types)
        return list(l.output_types) + list(r.output_types)

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def describe(self):
        return f"CpuJoin {self.how}"

    def _collect_side(self, side: int, ctx, pid=None) -> pa.Table:
        child = self.children[side]
        rbs = []
        pids = range(child.num_partitions) if pid is None else [pid]
        for p in pids:
            for b in child.execute_partition(p, ctx):
                rb = batch_to_arrow(DeviceBatch(b.columns, b.num_rows,
                                                child.output_names))
                if rb.num_rows:
                    rbs.append(rb)
        from ..columnar.interop import to_arrow_schema
        schema = to_arrow_schema(child.output_names, child.output_types)
        if not rbs:
            return schema.empty_table()
        return pa.Table.from_batches([r.cast(schema) for r in rbs])

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        import pyarrow.compute as pc
        left = self._collect_side(0, ctx, pid)
        right = self._collect_side(1, ctx, pid if self.colocated else None)
        # materialize key columns (they may be expressions)
        lkn, rkn = [], []
        lt, rt = left, right
        for i, (lk, rk) in enumerate(zip(self.left_keys, self.right_keys)):
            ln_, rn_ = f"__lk{i}", f"__rk{i}"
            lt = lt.append_column(ln_, _eval_arrow(lk, left,
                                                   self.children[0]))
            rt = rt.append_column(rn_, _eval_arrow(rk, right,
                                                   self.children[1]))
            lkn.append(ln_)
            rkn.append(rn_)
        # avoid output name collisions: temporarily rename
        lnames = [f"l_{i}" for i in range(len(left.schema.names))]
        rnames = [f"r_{i}" for i in range(len(right.schema.names))]
        lt = lt.rename_columns(lnames + lkn)
        rt = rt.rename_columns(rnames + rkn)
        # Spark equi-joins never match null keys; split them out so Acero's
        # null handling can't differ
        def null_key_mask(tbl, keys):
            m = None
            for k in keys:
                kn = pc.is_null(tbl.column(k))
                m = kn if m is None else pc.or_(m, kn)
            return m
        l_null = null_key_mask(lt, lkn)
        r_null = null_key_mask(rt, rkn)
        lt_nn = lt.filter(pc.invert(l_null)) if l_null is not None else lt
        rt_nn = rt.filter(pc.invert(r_null)) if r_null is not None else rt
        joined = lt_nn.join(rt_nn, keys=lkn, right_keys=rkn,
                            join_type=_PA_JOIN[self.how],
                            coalesce_keys=False, use_threads=False)
        if self.how in ("left_semi", "left_anti"):
            out = joined.select(lnames).rename_columns(
                self.children[0].output_names)
            if self.how == "left_anti" and l_null is not None:
                extra = lt.filter(l_null).select(lnames).rename_columns(
                    self.children[0].output_names)
                out = pa.concat_tables([out, extra]) if extra.num_rows else out
        else:
            out = joined.select(lnames + rnames).rename_columns(
                self.output_names)
            if self.how in ("left", "full") and l_null is not None:
                nulls_l = lt.filter(l_null).select(lnames)
                if nulls_l.num_rows:
                    pad = {n: pa.nulls(nulls_l.num_rows, f.type)
                           for n, f in zip(rnames,
                                           [rt.schema.field(x)
                                            for x in rnames])}
                    extra = nulls_l.rename_columns(
                        self.children[0].output_names)
                    for (n, arr), on in zip(pad.items(),
                                            self.children[1].output_names):
                        extra = extra.append_column(on, arr)
                    out = pa.concat_tables(
                        [out, extra.rename_columns(self.output_names)])
            if self.how in ("right", "full") and r_null is not None:
                nulls_r = rt.filter(r_null).select(rnames)
                if nulls_r.num_rows:
                    extra = pa.table(
                        {n: pa.nulls(nulls_r.num_rows,
                                     lt.schema.field(ln).type)
                         for n, ln in zip(self.children[0].output_names,
                                          lnames)})
                    for arr, on in zip(nulls_r.columns,
                                       self.children[1].output_names):
                        extra = extra.append_column(on, arr)
                    out = pa.concat_tables(
                        [out, extra.rename_columns(self.output_names)])
        if self.condition is not None:
            if self.how == "inner":
                mask = _eval_arrow(self.condition, out, self)
                out = out.filter(mask)
            elif self.how == "left":
                # conditional LEFT: keep matched pairs passing the
                # condition; probe rows with no passing pair emit once,
                # build side nulled (Spark's outer-join semantics)
                out = _left_conditional_impl(self, lt, rt, lkn, rkn,
                                             lnames, rnames, l_null,
                                             r_null)
            else:
                raise NotImplementedError(
                    f"conditional {self.how} join on CPU engine")
        from ..columnar.interop import to_arrow_schema
        schema = to_arrow_schema(self.output_names, self.output_types)
        out = out.cast(schema)
        for rb in out.combine_chunks().to_batches():
            yield batch_to_device(rb, xp=np)


def _left_conditional_impl(join_exec: "CpuJoinExec", lt, rt, lkn, rkn,
                           lnames, rnames, l_null, r_null) -> pa.Table:
    """Conditional LEFT join on the CPU oracle: re-join with a probe row
    id and a build marker, filter pairs by the condition, and null-extend
    every probe row without a passing pair."""
    import pyarrow.compute as pc
    lt2 = lt.append_column(
        "__pid__", pa.array(np.arange(lt.num_rows, dtype=np.int64)))
    rt2 = rt.append_column(
        "__bmark__", pa.array(np.ones(rt.num_rows, dtype=np.int8)))
    l_nn = lt2.filter(pc.invert(l_null)) if l_null is not None else lt2
    r_nn = rt2.filter(pc.invert(r_null)) if r_null is not None else rt2
    joined = l_nn.join(r_nn, keys=lkn, right_keys=rkn,
                       join_type="left outer", coalesce_keys=False,
                       use_threads=False)
    mask = _eval_arrow(
        join_exec.condition,
        joined.select(lnames + rnames).rename_columns(
            join_exec.output_names),
        join_exec)
    if isinstance(mask, pa.ChunkedArray):
        mask = mask.combine_chunks()
    mask = pc.fill_null(mask, False)
    real = pc.is_valid(joined.column("__bmark__"))
    passing = pc.and_(mask, real)
    pass_rows = joined.filter(passing)
    # pure host data (pyarrow chunked arrays), no device crossing here
    passed = np.unique(pass_rows.column("__pid__").combine_chunks()
                       .to_numpy(zero_copy_only=False))
    all_pids = lt2.column("__pid__").combine_chunks() \
        .to_numpy(zero_copy_only=False)
    missing = lt2.take(np.flatnonzero(~np.isin(all_pids, passed)))
    out = pass_rows.select(lnames + rnames)
    if missing.num_rows:
        pad = missing.select(lnames)
        for rn_ in rnames:
            pad = pad.append_column(
                rn_, pa.nulls(missing.num_rows,
                              rt.schema.field(rn_).type))
        out = pa.concat_tables([out, pad])
    return out.rename_columns(join_exec.output_names)


def _eval_arrow(expr: Expression, table: pa.Table, child_like) -> pa.Array:
    """Evaluate an expression over an arrow table via the numpy engine."""
    from ..columnar.device import batch_to_device, column_to_arrow
    from ..expr.core import ColumnValue, EvalContext, make_column
    names = child_like.output_names
    dtypes = child_like.output_types
    tbl = table.rename_columns(names) if list(table.schema.names) != names \
        else table
    tbl = tbl.combine_chunks()
    rbs = tbl.to_batches() or [pa.RecordBatch.from_pydict(
        {n: pa.array([], type=f.type) for n, f in
         zip(tbl.schema.names, tbl.schema)})]
    outs = []
    bound = bind_expression(expr, names, dtypes)
    for rb in rbs:
        b = batch_to_device(rb, xp=np)
        ec = EvalContext(np, b)
        v = bound.eval(ec)
        if not isinstance(v, ColumnValue):
            v = make_column(ec, bound.data_type(),
                            v.value if v.value is not None else 0,
                            None if v.value is not None else False)
        outs.append(column_to_arrow(v.col, rb.num_rows))
    return pa.chunked_array(outs) if len(outs) > 1 else outs[0]


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

def plan_join(lp, left: Exec, right: Exec, conf) -> Exec:
    """Logical Join -> physical (ref GpuOverrides join rules +
    ExtractEquiJoinKeys)."""
    from ..expr.core import AttributeReference, Alias
    from ..plan import logical as L
    how = lp.how
    cond = lp.condition
    using = lp.using
    if using:
        c = None
        for k in using:
            eq = EqualTo(AttributeReference(k), AttributeReference(k))
            # disambiguate: bind left occurrence to left, right to right
            c = eq if c is None else And(c, eq)
        lkeys = [AttributeReference(k) for k in using]
        rkeys = [AttributeReference(k) for k in using]
        residual = None
    else:
        lkeys, rkeys, residual = split_equi_condition(
            cond, left.output_names, right.output_names)
    from ..config import AUTO_BROADCAST_JOIN_THRESHOLD
    threshold = conf.get(AUTO_BROADCAST_JOIN_THRESHOLD)
    lsz = left.estimated_size_bytes()
    rsz = right.estimated_size_bytes()

    # ---- build-side selection (ref GpuShuffledHashJoinBase build side +
    # Spark's broadcast side selection): the build side is always planned
    # as the RIGHT child; flip when the join type forces it (right outer)
    # or when an inner join's smaller side is on the left.
    flipped = False

    def flip():
        nonlocal left, right, lkeys, rkeys, lsz, rsz, flipped, how
        left, right = right, left
        lkeys, rkeys = rkeys, lkeys
        lsz, rsz = rsz, lsz
        flipped = not flipped

    if how == "right" and lkeys:
        flip()
        how = "left"
    elif how == "inner" and lkeys and lsz is not None and rsz is not None \
            and lsz < rsz:
        flip()

    multi = left.num_partitions > 1 or right.num_partitions > 1

    # ---- non-equi paths (nested loop); broadcast the build side so it is
    # collected once, not once per probe partition
    # (ref GpuBroadcastNestedLoopJoinExec / GpuCartesianProductExec)
    if not lkeys:
        from .broadcast import BroadcastExchangeExec, \
            BroadcastNestedLoopJoinExec
        if how == "cross" or (how == "inner" and cond is not None):
            r = BroadcastExchangeExec(right) if multi else right
            cls = BroadcastNestedLoopJoinExec if multi else NestedLoopJoinExec
            return cls("cross" if how == "cross" else how, cond, left, r)
        if how == "inner" and cond is None:
            r = BroadcastExchangeExec(right) if multi else right
            cls = BroadcastNestedLoopJoinExec if multi else NestedLoopJoinExec
            return cls("cross", None, left, r)
        raise NotImplementedError(
            f"non-equi {how} join is not supported yet")

    # ---- equi joins: broadcast-hash vs shuffled-hash.  The bridge pins
    # oversized-build joins to the shuffled path (force_shuffled): their
    # build side exceeded the broadcast/collect threshold, so the only
    # scalable plan is co-partitioning both sides through the
    # spill-backed shuffle catalog.
    force_shuffled = bool(getattr(lp, "force_shuffled", False))
    colocated = False
    if multi and not force_shuffled and threshold >= 0 \
            and rsz is not None and rsz <= threshold \
            and how in ("inner", "left", "left_semi", "left_anti", "cross"):
        from .broadcast import BroadcastExchangeExec
        right = BroadcastExchangeExec(right)
    elif multi or force_shuffled:
        # shuffled hash join: co-partition both sides on the join keys
        from ..shuffle.exchange import ShuffleExchangeExec
        from ..shuffle.partitioning import HashPartitioning
        n = max(left.num_partitions, right.num_partitions)
        left = ShuffleExchangeExec(HashPartitioning(lkeys, n), left)
        right = ShuffleExchangeExec(HashPartitioning(rkeys, n), right)
        colocated = True

    join: Exec = CpuJoinExec(lkeys, rkeys, how, residual, left, right,
                             colocated=colocated)
    out_exec = join
    if flipped or using:
        from .basic import ProjectExec
        names = join.output_names
        types = join.output_types
        nl = len(left.output_names)
        if flipped:
            # output order: original-left (= current right side) first
            exprs = [BoundReference(nl + i, types[nl + i], names[nl + i])
                     for i in range(len(right.output_names))] + \
                    [BoundReference(i, types[i], names[i])
                     for i in range(nl)]
            out_exec = ProjectExec(
                [Alias(e, e.name) for e in exprs], join)
            names = out_exec.output_names
            types = out_exec.output_types
        if using and how not in ("left_semi", "left_anti"):
            from ..expr.conditional import Coalesce
            lnames = lp.children[0].schema()[0]
            rnames = lp.children[1].schema()[0]
            n_l = len(lnames)
            exprs = []
            for k in using:
                li = lnames.index(k)
                ri = n_l + rnames.index(k)
                if lp.how == "full":
                    exprs.append(Alias(Coalesce(
                        BoundReference(li, types[li], k),
                        BoundReference(ri, types[ri], k)), k))
                elif lp.how == "right":
                    exprs.append(Alias(
                        BoundReference(ri, types[ri], k), k))
                else:
                    exprs.append(Alias(
                        BoundReference(li, types[li], k), k))
            for i, n in enumerate(lnames):
                if n not in using:
                    exprs.append(Alias(BoundReference(i, types[i], n), n))
            for j, n in enumerate(rnames):
                if n not in using:
                    exprs.append(Alias(
                        BoundReference(n_l + j, types[n_l + j], n), n))
            out_exec = ProjectExec(exprs, out_exec)
    return out_exec
