"""Window operator.

Ref: sql-plugin/.../GpuWindowExec.scala (running + partitioned paths,
pre/post projection splicing at :143-161) and GpuWindowExpression.scala.

TPU realization: one sort by (partition keys, order keys) per window spec,
then every function is a segmented vector computation over the sorted
view — prefix sums for running/bounded-rows aggregates, run-boundary
cummax for rank/dense_rank, shifted gathers for lead/lag, segment-reduce +
broadcast for whole-partition aggregates — and an inverse permutation
restores input order.  RANGE UNBOUNDED..CURRENT (Spark's default with
ORDER BY) evaluates at peer-run ends, matching Spark's peer semantics.
"""

from __future__ import annotations

import functools
from typing import Iterator, List

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..expr.aggregates import (AggregateExpression, AggregateFunction,
                               Average, Count, Max, Min, Sum, bind_aggregate)
from ..expr.core import (ColumnValue, EvalContext, Expression,
                         bind_expression, make_column)
from ..expr.window import (CURRENT_ROW, UNBOUNDED_FOLLOWING,
                           UNBOUNDED_PRECEDING, DenseRank, Lag, Lead, NTile,
                           Rank, RowNumber, WindowExpression)
from ..ops import segmented as seg
from ..ops.gather import gather_column
from .base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU, Batch,
                   Exec, MetricTimer)
from .concat import concat_batches


def _seg_start_positions(xp, new_seg):
    """pos of the segment start for every sorted row (cummax trick)."""
    n = new_seg.shape[0]
    pos = xp.arange(n, dtype=xp.int64)
    starts = xp.where(new_seg, pos, xp.int64(-1))
    if xp is np:
        return np.maximum.accumulate(starts)
    return jax.lax.associative_scan(jnp.maximum, starts)


def _run_end_positions(xp, new_run):
    """pos of the last row of each peer run: run id per row, then the max
    position within each run, broadcast back."""
    n = new_run.shape[0]
    pos = xp.arange(n, dtype=xp.int64)
    run_id = (xp.cumsum(new_run.astype(xp.int64)) - 1).astype(xp.int32)
    run_id = xp.clip(run_id, 0, n - 1)
    last, _ = seg.segment_reduce(xp, "max", pos, run_id, n,
                                 xp.ones((n,), dtype=bool))
    return xp.clip(last[run_id], 0, n - 1)


def _segmented_running_minmax(xp, v, new_seg, is_min: bool):
    if xp is np:
        out = v.copy()
        for i in range(1, len(v)):
            if not new_seg[i]:
                out[i] = min(out[i - 1], out[i]) if is_min else \
                    max(out[i - 1], out[i])
        return out
    neutral = seg._extreme_init(jnp, v.dtype, is_min)
    op = jnp.minimum if is_min else jnp.maximum

    def combine(a, b):
        av, aseg = a
        bv, bseg = b
        # if b starts a new segment, ignore a's value
        nv = jnp.where(bseg, bv, op(av, bv))
        return nv, aseg | bseg
    out, _ = jax.lax.associative_scan(combine, (v, new_seg))
    return out


class WindowExec(Exec):
    def __init__(self, window_exprs: List[WindowExpression], child: Exec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cn, ct = child.output_names, child.output_types

    @property
    def output_names(self):
        return self.children[0].output_names + \
            [w.name for w in self.window_exprs]

    @property
    def output_types(self):
        cn, ct = (self.children[0].output_names,
                  self.children[0].output_types)
        return list(ct) + [w.resolved_type(cn, ct)
                           for w in self.window_exprs]

    def describe(self):
        return f"Window [{', '.join(w.name for w in self.window_exprs)}]"

    # ------------------------------------------------------------------
    def _compute_one(self, xp, batch: Batch, wexpr: WindowExpression
                     ) -> DeviceColumn:
        cn = self.children[0].output_names
        ct = self.children[0].output_types
        ctx = EvalContext(xp, batch)
        live = ctx.row_mask()
        cap = batch.capacity
        spec = wexpr.spec
        pkeys = [bind_expression(p, cn, ct).eval(ctx).col
                 for p in spec.partition_by]
        okeys = [(bind_expression(o, cn, ct).eval(ctx).col, asc, nf)
                 for o, asc, nf in spec.order_by]
        words = [(~live).astype(xp.uint64)]
        pwords: List = []
        for pk in pkeys:
            pwords += seg.key_words_for_column(xp, pk, live,
                                               for_grouping=True)
        owords: List = []
        for ok, asc, nf in okeys:
            owords += seg.key_words_for_column(xp, ok, live,
                                               for_grouping=False,
                                               nulls_first=nf, ascending=asc)
        order = seg.lexsort(xp, words + pwords + owords, cap)
        inv = xp.zeros((cap,), dtype=xp.int32)
        if xp is np:
            inv[order] = np.arange(cap, dtype=np.int32)
        else:
            inv = inv.at[order].set(xp.arange(cap, dtype=xp.int32))
        live_s = live[order]
        psorted = [w[order] for w in pwords]
        osorted = [w[order] for w in owords]
        new_seg = seg.segment_boundaries(xp, psorted if psorted else
                                         [live_s.astype(xp.uint64) * 0],
                                         live_s)
        if not pkeys:
            new_seg = (xp.arange(cap) == 0)
        new_run = seg.segment_boundaries(xp, psorted + osorted, live_s) \
            if okeys else new_seg
        seg_ids = xp.clip(seg.segment_ids(xp, new_seg), 0, cap - 1)
        pos = xp.arange(cap, dtype=xp.int64)
        seg_start = _seg_start_positions(xp, new_seg)
        idx_in_seg = pos - seg_start

        func = wexpr.func
        out_dtype = wexpr.resolved_type(cn, ct)

        def finish(sorted_data, sorted_valid):
            data = sorted_data[inv]
            valid = sorted_valid[inv] & live
            if not isinstance(out_dtype, (t.StringType, t.BinaryType)):
                data = xp.where(valid, data, xp.zeros_like(data))
            return DeviceColumn(out_dtype, data=data, validity=valid)

        if isinstance(func, (RowNumber, Rank, DenseRank)) and \
                type(func) is RowNumber:
            return finish((idx_in_seg + 1).astype(np.int32), live_s)
        if type(func) is Rank:
            run_start = _seg_start_positions(xp, new_run)
            return finish((run_start - seg_start + 1).astype(np.int32),
                          live_s)
        if type(func) is DenseRank:
            runs_cum = xp.cumsum(new_run.astype(xp.int64))
            base = runs_cum[xp.clip(seg_start, 0, cap - 1)] - \
                new_run[xp.clip(seg_start, 0, cap - 1)].astype(xp.int64)
            return finish((runs_cum - base).astype(np.int32), live_s)
        if isinstance(func, NTile):
            seg_len, _ = seg.segment_reduce(
                xp, "max", idx_in_seg + 1, seg_ids, cap,
                xp.ones((cap,), dtype=bool))
            n_rows = seg_len[seg_ids]
            nt = np.int64(func.n)
            base = n_rows // nt
            rem = n_rows % nt
            # first `rem` buckets get base+1 rows
            big = rem * (base + 1)
            bucket = xp.where(idx_in_seg < big,
                              idx_in_seg // xp.maximum(base + 1, 1),
                              rem + (idx_in_seg - big) //
                              xp.maximum(base, 1))
            return finish((bucket + 1).astype(np.int32), live_s)

        if isinstance(func, (Lead, Lag)):
            child = bind_expression(func.children[0], cn, ct)
            v = child.eval(ctx)
            if not isinstance(v, ColumnValue):
                v = make_column(ctx, child.data_type(),
                                v.value if v.value is not None else 0,
                                None if v.value is not None else False)
            col_s = gather_column(xp, v.col, order,
                                  xp.ones((cap,), dtype=bool))
            k = -func.offset if isinstance(func, Lag) else func.offset
            src = xp.clip(pos + k, 0, cap - 1).astype(xp.int32)
            same_seg = (seg_ids[src] == seg_ids) & \
                (pos + k >= 0) & (pos + k < cap) & live_s[src]
            shifted = gather_column(xp, col_s, src, same_seg)
            return finish(shifted.data,
                          shifted.validity if shifted.validity is not None
                          else same_seg)

        if isinstance(func, AggregateFunction):
            ae = bind_aggregate(AggregateExpression(func), cn, ct)
            f = ae.func
            kind, lo_b, hi_b = spec.effective_frame(False)
            # evaluate update inputs in sorted order
            upd = f.update()
            bufs_sorted = []
            for expr, op in upd:
                v = expr.eval(ctx)
                if not isinstance(v, ColumnValue):
                    v = make_column(ctx, expr.data_type(),
                                    v.value if v.value is not None else 0,
                                    None if v.value is not None else False)
                vs = v.col.data[order] if v.col.data is not None else None
                val = (v.col.validity[order]
                       if v.col.validity is not None else
                       xp.ones((cap,), dtype=bool)) & live_s
                bufs_sorted.append((vs, val, op))
            whole = (lo_b == UNBOUNDED_PRECEDING and
                     hi_b == UNBOUNDED_FOLLOWING)
            results = []
            for vs, val, op in bufs_sorted:
                if op == "countvalid":
                    contrib = val.astype(xp.int64)
                    red_op = "sum"
                    vv = contrib
                elif op in ("sum",):
                    red_op = "sum"
                    vv = xp.where(val, vs, xp.zeros_like(vs))
                elif op in ("min", "max"):
                    red_op = op
                    init = seg._extreme_init(xp, vs.dtype, op == "min")
                    vv = xp.where(val, vs, xp.full_like(vs, init))
                else:  # first/last etc -> whole-partition only
                    red_op = op
                    vv = vs
                if whole:
                    out, cnt = seg.segment_reduce(xp, red_op if red_op in
                                                  ("sum", "min", "max",
                                                   "first", "last")
                                                  else "sum",
                                                  vv, seg_ids, cap, val)
                    results.append((out[seg_ids], cnt[seg_ids]))
                elif kind == "rows" and lo_b == UNBOUNDED_PRECEDING and \
                        hi_b == CURRENT_ROW:
                    results.append(self._running(xp, red_op, vv, val,
                                                 new_seg, seg_start))
                elif kind == "range" and lo_b == UNBOUNDED_PRECEDING and \
                        hi_b == CURRENT_ROW:
                    r, c = self._running(xp, red_op, vv, val, new_seg,
                                         seg_start)
                    run_end = _run_end_positions(xp, new_run)
                    results.append((r[run_end], c[run_end]))
                elif kind == "rows":
                    if red_op != "sum":
                        raise NotImplementedError(
                            "bounded rows frame supports sum/count/avg")
                    pre = xp.concatenate([xp.zeros((1,), vv.dtype),
                                          xp.cumsum(vv)])
                    cpre = xp.concatenate([xp.zeros((1,), xp.int64),
                                           xp.cumsum(val.astype(xp.int64))])
                    seg_end = _run_end_positions(xp, new_seg)
                    lo_i = xp.clip(pos + lo_b, seg_start, pos + cap)
                    lo_i = xp.maximum(pos + max(lo_b, -cap), seg_start) \
                        if lo_b != UNBOUNDED_PRECEDING else seg_start
                    hi_i = xp.minimum(pos + min(hi_b, cap), seg_end) \
                        if hi_b != UNBOUNDED_FOLLOWING else seg_end
                    lo_i = xp.clip(lo_i, 0, cap - 1)
                    hi_i = xp.clip(hi_i, -1, cap - 1)
                    empty = hi_i < lo_i
                    s = pre[hi_i + 1] - pre[lo_i]
                    c = cpre[hi_i + 1] - cpre[lo_i]
                    s = xp.where(empty, xp.zeros_like(s), s)
                    c = xp.where(empty, xp.zeros_like(c), c)
                    results.append((s, c))
                else:
                    raise NotImplementedError(f"frame {kind} {lo_b} {hi_b}")
            # evaluate the aggregate from its (broadcast) buffers
            buf_cols = []
            for (data, cnt), (expr, op) in zip(results, upd):
                if op == "countvalid":
                    buf_cols.append(ColumnValue(DeviceColumn(
                        t.LONG, data=data.astype(np.int64),
                        validity=xp.ones((cap,), dtype=bool))))
                else:
                    buf_cols.append(ColumnValue(DeviceColumn(
                        expr.data_type(), data=data, validity=cnt > 0)))
            fctx = EvalContext(xp, DeviceBatch(
                [c.col for c in buf_cols], batch.num_rows, None))
            res = f.evaluate(fctx, buf_cols)
            valid = res.col.validity if res.col.validity is not None else \
                xp.ones((cap,), dtype=bool)
            return finish(res.col.data, valid)
        raise NotImplementedError(f"window function {type(func).__name__}")

    def _running(self, xp, red_op, vv, val, new_seg, seg_start):
        if red_op == "sum":
            cs = xp.cumsum(vv)
            base = xp.where(seg_start > 0,
                            cs[xp.clip(seg_start - 1, 0, None)],
                            xp.zeros((), dtype=cs.dtype))
            ccs = xp.cumsum(val.astype(xp.int64))
            cbase = xp.where(seg_start > 0,
                             ccs[xp.clip(seg_start - 1, 0, None)],
                             xp.zeros((), dtype=xp.int64))
            return cs - base, ccs - cbase
        if red_op in ("min", "max"):
            out = _segmented_running_minmax(xp, vv, new_seg,
                                            red_op == "min")
            ccs = xp.cumsum(val.astype(xp.int64))
            cbase = xp.where(seg_start > 0,
                             ccs[xp.clip(seg_start - 1, 0, None)],
                             xp.zeros((), dtype=xp.int64))
            return out, ccs - cbase
        raise NotImplementedError(f"running {red_op}")

    def _compute(self, xp, batch: Batch) -> Batch:
        cols = list(batch.columns)
        for w in self.window_exprs:
            cols.append(self._compute_one(xp, batch, w))
        return DeviceBatch(cols, batch.num_rows, self.output_names)

    @functools.cached_property
    def _jitted(self):
        return jax.jit(lambda b: self._compute(jnp, b))

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        child = self.children[0]
        batches = list(child.execute_partition(pid, ctx))
        if not batches:
            return
        with MetricTimer(self.metrics[OP_TIME]):
            merged = concat_batches(xp, batches, child.output_names,
                                    child.output_types) \
                if len(batches) > 1 else batches[0]
            out = self._jitted(merged) if self.placement == TPU \
                else self._compute(np, merged)
        self.metrics[NUM_OUTPUT_ROWS] += int(out.num_rows)
        self.metrics[NUM_OUTPUT_BATCHES] += 1
        yield out
