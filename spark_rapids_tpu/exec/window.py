"""Window operator.

Ref: sql-plugin/.../GpuWindowExec.scala (running + partitioned paths,
pre/post projection splicing at :143-161) and GpuWindowExpression.scala.

TPU realization: one sort by (partition keys, order keys) per window spec,
then every function is a segmented vector computation over the sorted
view — prefix sums for running/bounded-rows aggregates, run-boundary
cummax for rank/dense_rank, shifted gathers for lead/lag, segment-reduce +
broadcast for whole-partition aggregates — and an inverse permutation
restores input order.  RANGE UNBOUNDED..CURRENT (Spark's default with
ORDER BY) evaluates at peer-run ends, matching Spark's peer semantics.
"""

from __future__ import annotations

import functools
from typing import Iterator, List

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..expr.aggregates import (AggregateExpression, AggregateFunction,
                               Average, Count, Max, Min, Sum, bind_aggregate)
from ..expr.core import (ColumnValue, EvalContext, Expression,
                         bind_expression, make_column)
from ..expr.window import (CURRENT_ROW, UNBOUNDED_FOLLOWING,
                           UNBOUNDED_PRECEDING, CumeDist, DenseRank, Lag,
                           Lead, NTile, PercentRank, Rank, RowNumber,
                           WindowExpression)
from ..ops import segmented as seg
from ..ops.gather import gather_column
from .base import (maybe_sync,  # noqa: F401
                   NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU, Batch,
                   Exec, MetricTimer, process_jit, schema_sig, semantic_sig)
from .concat import concat_batches
from ..ops.scan import cumsum_fast


from ..ops.scan import cummax_i32 as _cummax_i32


def _seg_start_positions(xp, new_seg):
    """pos of the segment start for every sorted row (cummax trick)."""
    n = new_seg.shape[0]
    pos = xp.arange(n, dtype=xp.int32)
    starts = xp.where(new_seg, pos, xp.int32(-1))
    return _cummax_i32(xp, starts)


def _run_end_positions(xp, new_run):
    """pos of the last row of each peer run: the NEXT run's start minus
    one (runs are contiguous; the final run closes at the array end)."""
    n = new_run.shape[0]
    pos = xp.arange(n, dtype=xp.int32)
    # reversed cummin of next-run starts == next run-start after each row
    nxt = xp.concatenate([new_run[1:], xp.ones((1,), dtype=bool)])
    ends = xp.where(nxt, pos, xp.int32(n - 1))
    # running min from the right: reverse, cummin (== -cummax of negation)
    rev = -ends[::-1]
    return xp.clip(-(_cummax_i32(xp, rev)[::-1]), 0, n - 1)


def _segmented_running_minmax(xp, v, new_seg, is_min: bool):
    """Per-segment running min/max via the segmented pad-shift
    recurrence (v[i] = op(v[i], v[i-d]) unless a boundary intervenes)."""
    n = v.shape[0]
    op = xp.minimum if is_min else xp.maximum
    init = seg._extreme_init(xp, v.dtype, is_min)
    f = new_seg.astype(bool)
    d = 1
    while d < n:
        if xp is np:
            pv = np.concatenate([np.full((d,), init, v.dtype), v[:-d]])
            pf = np.concatenate([np.ones((d,), bool), f[:-d]])
        else:
            pv = xp.pad(v, (d, 0), constant_values=init)[:n]
            pf = xp.pad(f, (d, 0), constant_values=True)[:n]
        v = xp.where(f, v, op(v, pv))
        f = f | pf
        d *= 2
    return v


class WindowExec(Exec):
    def __init__(self, window_exprs: List[WindowExpression], child: Exec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cn, ct = child.output_names, child.output_types

    @property
    def output_names(self):
        return self.children[0].output_names + \
            [w.name for w in self.window_exprs]

    @property
    def output_types(self):
        cn, ct = (self.children[0].output_names,
                  self.children[0].output_types)
        return list(ct) + [w.resolved_type(cn, ct)
                           for w in self.window_exprs]

    def describe(self):
        return f"Window [{', '.join(w.name for w in self.window_exprs)}]"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "frames evaluate over the per-spec sorted "
            "space (content-determined); rank/row_number over tied "
            "order keys follow arrival within the tie")

    # ------------------------------------------------------------------
    class _Layout:
        """Sorted-space layout shared by every window expr on one spec:
        the sort happens ONCE per spec, inputs ride it as carry lanes,
        and results ride ONE carry-sort back to input order."""
        __slots__ = ("order", "live_s", "new_seg", "new_run", "seg_ids",
                     "pos", "seg_start", "idx_in_seg", "okeys_sorted",
                     "input_sorted")

    def _build_layout(self, xp, batch, live, cap, spec, ctx, input_cols):
        cn, ct = self.children[0].output_names, self.children[0].output_types
        pkeys = [bind_expression(p, cn, ct).eval(ctx).col
                 for p in spec.partition_by]
        okeys = [(bind_expression(o, cn, ct).eval(ctx).col, asc, nf)
                 for o, asc, nf in spec.order_by]
        words = [(~live).astype(xp.uint8)]
        pwords: List = []
        for pk in pkeys:
            pwords += seg.key_words_for_column(xp, pk, live,
                                               for_grouping=True)
        owords: List = []
        for ok, asc, nf in okeys:
            owords += seg.key_words_for_column(xp, ok, live,
                                               for_grouping=False,
                                               nulls_first=nf, ascending=asc)
        from ..ops import carry
        okey_cols = [ok for ok, _, _ in okeys]
        order, sorted_cols, ex = carry.sort_rows(
            xp, words + pwords + owords, list(input_cols) + okey_cols,
            cap, extras=[live] + pwords + owords)
        lay = WindowExec._Layout()
        lay.order = order
        lay.input_sorted = sorted_cols[:len(input_cols)]
        osorted_cols = sorted_cols[len(input_cols):]
        lay.okeys_sorted = [(c, asc, nf) for c, (_, asc, nf) in
                            zip(osorted_cols, okeys)]
        lay.live_s = ex[0]
        psorted = ex[1:1 + len(pwords)]
        osorted = ex[1 + len(pwords):]
        live_s = lay.live_s
        new_seg = seg.segment_boundaries(xp, psorted if psorted else
                                         [live_s.astype(xp.uint8) * 0],
                                         live_s)
        if not pkeys:
            new_seg = (xp.arange(cap) == 0)
        lay.new_seg = new_seg
        lay.new_run = seg.segment_boundaries(xp, psorted + osorted, live_s) \
            if okeys else new_seg
        lay.seg_ids = xp.clip(seg.segment_ids(xp, new_seg), 0, cap - 1)
        lay.pos = xp.arange(cap, dtype=xp.int32)
        lay.seg_start = _seg_start_positions(xp, new_seg)
        lay.idx_in_seg = lay.pos - lay.seg_start
        return lay

    def _compute_one(self, xp, batch: Batch, wexpr: WindowExpression,
                     lay, sorted_inputs) -> tuple:
        """Returns ("lanes", sorted_data, sorted_valid) for flat results
        (the caller carries them back to input order in one sort) or
        ("col", device_column) for span results like strings (a char
        buffer cannot ride a row carry-sort; the caller gathers it back
        by the inverse permutation instead)."""
        cn = self.children[0].output_names
        ct = self.children[0].output_types
        cap = batch.capacity
        spec = wexpr.spec
        okeys = lay.okeys_sorted
        live_s = lay.live_s
        new_seg, new_run = lay.new_seg, lay.new_run
        seg_ids = lay.seg_ids
        pos = lay.pos
        seg_start = lay.seg_start
        idx_in_seg = lay.idx_in_seg

        func = wexpr.func
        out_dtype = wexpr.resolved_type(cn, ct)
        span_result = isinstance(out_dtype, (t.StringType, t.BinaryType,
                                             t.ArrayType, t.StructType,
                                             t.MapType))

        def finish(sorted_data, sorted_valid):
            return ("lanes", sorted_data, sorted_valid)

        if isinstance(func, (RowNumber, Rank, DenseRank)) and \
                type(func) is RowNumber:
            return finish((idx_in_seg + 1).astype(np.int32), live_s)
        if type(func) is Rank:
            run_start = _seg_start_positions(xp, new_run)
            return finish((run_start - seg_start + 1).astype(np.int32),
                          live_s)
        if type(func) is DenseRank:
            runs_cum = cumsum_fast(xp, new_run.astype(xp.int32))
            base = runs_cum[xp.clip(seg_start, 0, cap - 1)] - \
                new_run[xp.clip(seg_start, 0, cap - 1)].astype(xp.int32)
            return finish((runs_cum - base).astype(np.int32), live_s)
        # partition row counts must exclude batch PADDING rows: dead
        # tail rows inherit the last live segment id in the sorted
        # layout, so an unmasked reduce inflates the final partition
        def live_seg_len():
            out, _ = seg.segment_reduce(xp, "max", idx_in_seg + 1,
                                        seg_ids, cap, live_s)
            return out[seg_ids]

        if type(func) is PercentRank:
            run_start = _seg_start_positions(xp, new_run)
            rank = (run_start - seg_start + 1).astype(np.float64)
            n_rows = live_seg_len().astype(np.float64)
            pr = xp.where(n_rows > 1, (rank - 1.0) /
                          xp.maximum(n_rows - 1.0, 1.0), 0.0)
            return finish(pr, live_s)
        if type(func) is CumeDist:
            # last LIVE row of the current peer run (padding excluded)
            run_id = xp.clip(
                cumsum_fast(xp, new_run.astype(xp.int32)) - 1, 0, cap - 1)
            run_max, _ = seg.segment_reduce(xp, "max", pos, run_id, cap,
                                            live_s)
            run_end = run_max[run_id]
            n_rows = live_seg_len().astype(np.float64)
            cd = (run_end - seg_start + 1).astype(np.float64) / \
                xp.maximum(n_rows, 1.0)
            return finish(cd, live_s)
        if isinstance(func, NTile):
            n_rows = live_seg_len()
            nt = np.int64(func.n)
            base = n_rows // nt
            rem = n_rows % nt
            # first `rem` buckets get base+1 rows
            big = rem * (base + 1)
            bucket = xp.where(idx_in_seg < big,
                              idx_in_seg // xp.maximum(base + 1, 1),
                              rem + (idx_in_seg - big) //
                              xp.maximum(base, 1))
            return finish((bucket + 1).astype(np.int32), live_s)

        if isinstance(func, (Lead, Lag)):
            col_s = sorted_inputs[0]
            k = -func.offset if isinstance(func, Lag) else func.offset
            src = xp.clip(pos + k, 0, cap - 1).astype(xp.int32)
            same_seg = (seg_ids[src] == seg_ids) & \
                (pos + k >= 0) & (pos + k < cap) & live_s[src]
            shifted = gather_column(xp, col_s, src, same_seg)
            if span_result:
                return ("col", shifted)
            return finish(shifted.data,
                          shifted.validity if shifted.validity is not None
                          else same_seg)

        if isinstance(func, AggregateFunction):
            ae = bind_aggregate(AggregateExpression(func), cn, ct)
            f = ae.func
            kind, lo_b, hi_b = spec.effective_frame(False)
            # update inputs arrived in sorted order via the carry-sort
            upd = f.update()
            bufs_sorted = []
            for scol, (expr, op) in zip(sorted_inputs, upd):
                vs = scol.data
                val = (scol.validity if scol.validity is not None else
                       xp.ones((cap,), dtype=bool)) & live_s
                bufs_sorted.append((vs, val, op))
            whole = (lo_b == UNBOUNDED_PRECEDING and
                     hi_b == UNBOUNDED_FOLLOWING)
            running = (lo_b == UNBOUNDED_PRECEDING and hi_b == CURRENT_ROW)
            bounds = None
            if not whole:
                seg_end_pos = _run_end_positions(xp, new_seg)
                run_start_pos = _seg_start_positions(xp, new_run)
                run_end_pos = _run_end_positions(xp, new_run)
                bounds = self._frame_bounds(
                    xp, kind, lo_b, hi_b, pos, seg_start, seg_end_pos,
                    run_start_pos, run_end_pos, okeys, cap, live_s)
            results = []
            for vs, val, op in bufs_sorted:
                if op == "countvalid":
                    contrib = val.astype(xp.int32)
                    red_op = "sum"
                    vv = contrib
                elif op in ("sum",):
                    red_op = "sum"
                    vv = xp.where(val, vs, xp.zeros_like(vs))
                elif op in ("min", "max"):
                    red_op = op
                    init = seg._extreme_init(xp, vs.dtype, op == "min")
                    vv = xp.where(val, vs, xp.full_like(vs, init))
                else:  # first/last etc -> whole-partition only
                    red_op = op
                    vv = vs
                if whole:
                    out, cnt = seg.segment_reduce(xp, red_op if red_op in
                                                  ("sum", "min", "max",
                                                   "first", "last")
                                                  else "sum",
                                                  vv, seg_ids, cap, val)
                    results.append((out[seg_ids], cnt[seg_ids]))
                elif running and kind == "rows" and \
                        red_op in ("sum", "min", "max"):
                    results.append(self._running(xp, red_op, vv, val,
                                                 new_seg, seg_start))
                elif running and kind == "range" and \
                        red_op in ("sum", "min", "max"):
                    r, c = self._running(xp, red_op, vv, val, new_seg,
                                         seg_start)
                    run_end = _run_end_positions(xp, new_run)
                    results.append((r[run_end], c[run_end]))
                else:
                    lo_i, hi_i = bounds
                    lo_c = xp.clip(lo_i, 0, cap - 1)
                    hi_c = xp.clip(hi_i, -1, cap - 1)
                    empty = hi_c < lo_c
                    cpre = xp.concatenate([
                        xp.zeros((1,), xp.int32),
                        cumsum_fast(xp, val.astype(xp.int32))])
                    c = cpre[hi_c + 1] - cpre[lo_c]
                    c = xp.where(empty, xp.zeros_like(c), c)
                    if red_op == "sum":
                        pre = xp.concatenate([xp.zeros((1,), vv.dtype),
                                              cumsum_fast(xp, vv)])
                        s = pre[hi_c + 1] - pre[lo_c]
                        s = xp.where(empty, xp.zeros_like(s), s)
                        results.append((s, c))
                    elif red_op in ("min", "max"):
                        # vv is already init-masked under invalid rows
                        s = _rmq_query(xp, vv, lo_c, hi_c, cap, red_op)
                        results.append((s, c))
                    elif red_op in ("first", "last"):
                        if red_op == "first":
                            # first VALID index >= lo_i (ignore-nulls uses
                            # the valid-count prefix; include-nulls is the
                            # frame head itself)
                            idx = xp.searchsorted(
                                cpre, cpre[lo_c] + 1, side="left") - 1 \
                                if op == "first" else lo_c
                        else:
                            idx = xp.searchsorted(
                                cpre, cpre[hi_c + 1], side="left") - 1 \
                                if op == "last" else hi_c
                        idx = xp.clip(idx, 0, cap - 1)
                        in_frame = (idx >= lo_c) & (idx <= hi_c) & ~empty
                        s = vs[idx]
                        c = xp.where(in_frame & val[idx],
                                     xp.ones_like(c), xp.zeros_like(c))
                        results.append((s, c))
                    else:
                        raise NotImplementedError(
                            f"bounded frame op {red_op}")
            # evaluate the aggregate from its (broadcast) buffers
            buf_cols = []
            for (data, cnt), (expr, op) in zip(results, upd):
                if op == "countvalid":
                    buf_cols.append(ColumnValue(DeviceColumn(
                        t.LONG, data=data.astype(np.int64),
                        validity=xp.ones((cap,), dtype=bool))))
                else:
                    buf_cols.append(ColumnValue(DeviceColumn(
                        expr.data_type(), data=data, validity=cnt > 0)))
            fctx = EvalContext(xp, DeviceBatch(
                [c.col for c in buf_cols], batch.num_rows, None))
            res = f.evaluate(fctx, buf_cols)
            if span_result:
                return ("col", res.col)
            valid = res.col.validity if res.col.validity is not None else \
                xp.ones((cap,), dtype=bool)
            return finish(res.col.data, valid)
        raise NotImplementedError(f"window function {type(func).__name__}")

    def _frame_bounds(self, xp, kind, lo_b, hi_b, pos, seg_start, seg_end,
                      run_start, run_end, okeys_sorted, cap, live_s):
        """Per-row inclusive [lo_i, hi_i] frame index bounds over the
        sorted row space, for bounded ROWS and RANGE frames."""
        if kind == "rows":
            lo_i = seg_start.astype(xp.int32) \
                if lo_b == UNBOUNDED_PRECEDING else \
                xp.clip(pos + lo_b, seg_start, seg_end + 1)
            hi_i = seg_end.astype(xp.int32) \
                if hi_b == UNBOUNDED_FOLLOWING else \
                xp.clip(pos + hi_b, seg_start - 1, seg_end)
            return lo_i.astype(xp.int32), hi_i.astype(xp.int32)
        # range: exactly one ascending flat-numeric order key (tagging
        # enforces this); null order rows frame over their peer run.
        # Order keys arrive already sorted (carried through the layout
        # sort).
        oc, _, nf = okeys_sorted[0]
        vals_s = oc.data
        ovalid_s = oc.validity if oc.validity is not None else \
            xp.ones((cap,), dtype=bool)
        # park nulls outside every finite search window
        park = seg._extreme_init(xp, vals_s.dtype, is_min=not nf)
        masked = xp.where(ovalid_s, vals_s, xp.full_like(vals_s, park))
        # dead padding rows sort after every live row (the lexsort's first
        # word is ~live), so they must carry the +extreme — otherwise the
        # last partition's search window [seg_start, seg_end+1) is not
        # ascending and _vec_bound lands at capacity (empty frames)
        dead_park = seg._extreme_init(xp, vals_s.dtype, is_min=True)
        masked = xp.where(live_s, masked, xp.full_like(vals_s, dead_park))
        if lo_b == UNBOUNDED_PRECEDING:
            lo_i = seg_start.astype(xp.int32)
        elif lo_b == CURRENT_ROW:
            lo_i = run_start.astype(xp.int32)
        else:
            lo_i = _vec_bound(xp, masked, vals_s + lo_b, seg_start,
                              seg_end + 1, cap, left=True)
        if hi_b == UNBOUNDED_FOLLOWING:
            hi_i = seg_end.astype(xp.int32)
        elif hi_b == CURRENT_ROW:
            hi_i = run_end.astype(xp.int32)
        else:
            hi_i = _vec_bound(xp, masked, vals_s + hi_b, seg_start,
                              seg_end + 1, cap, left=False) - 1
        null_row = ~ovalid_s
        lo_i = xp.where(null_row, run_start.astype(xp.int32),
                        lo_i.astype(xp.int32))
        hi_i = xp.where(null_row, run_end.astype(xp.int32),
                        hi_i.astype(xp.int32))
        return lo_i, hi_i

    def _running(self, xp, red_op, vv, val, new_seg, seg_start):
        if red_op == "sum":
            cs = cumsum_fast(xp, vv)
            base = xp.where(seg_start > 0,
                            cs[xp.clip(seg_start - 1, 0, None)],
                            xp.zeros((), dtype=cs.dtype))
            ccs = cumsum_fast(xp, val.astype(xp.int32))
            cbase = xp.where(seg_start > 0,
                             ccs[xp.clip(seg_start - 1, 0, None)],
                             xp.zeros((), dtype=xp.int32))
            return cs - base, ccs - cbase
        if red_op in ("min", "max"):
            out = _segmented_running_minmax(xp, vv, new_seg,
                                            red_op == "min")
            ccs = cumsum_fast(xp, val.astype(xp.int32))
            cbase = xp.where(seg_start > 0,
                             ccs[xp.clip(seg_start - 1, 0, None)],
                             xp.zeros((), dtype=xp.int32))
            return out, ccs - cbase
        raise NotImplementedError(f"running {red_op}")

    def _input_exprs(self, wexpr):
        """Bound input expressions whose columns must ride the layout
        sort (order matches _compute_one's consumption)."""
        cn, ct = self.children[0].output_names, self.children[0].output_types
        func = wexpr.func
        if isinstance(func, (Lead, Lag)):
            return [bind_expression(func.children[0], cn, ct)]
        if isinstance(func, AggregateFunction):
            ae = bind_aggregate(AggregateExpression(func), cn, ct)
            return [expr for expr, _op in ae.func.update()]
        return []

    def _compute(self, xp, batch: Batch) -> Batch:
        from ..ops import carry
        cn, ct = self.children[0].output_names, self.children[0].output_types
        ctx = EvalContext(xp, batch)
        live = ctx.row_mask()
        cap = batch.capacity

        def eval_col(e):
            v = e.eval(ctx)
            if not isinstance(v, ColumnValue):
                v = make_column(ctx, e.data_type(),
                                v.value if v.value is not None else 0,
                                None if v.value is not None else False)
            return v.col

        # group exprs by window spec; each group shares one sorted layout
        specs: dict = {}
        group_inputs: dict = {}
        group_slices: dict = {}
        for w in self.window_exprs:
            sig = semantic_sig(w.spec)
            specs.setdefault(sig, w.spec)
            gi = group_inputs.setdefault(sig, [])
            cols = [eval_col(e) for e in self._input_exprs(w)]
            group_slices.setdefault(sig, []).append((w, len(gi), len(cols)))
            gi.extend(cols)

        out_by_expr: dict = {}
        for sig, spec in specs.items():
            lay = self._build_layout(xp, batch, live, cap, spec, ctx,
                                     group_inputs[sig])
            per = []
            inv = None
            for (w, start, ncols) in group_slices[sig]:
                res = self._compute_one(
                    xp, batch, w, lay, lay.input_sorted[start:start + ncols])
                if res[0] == "col":
                    # span results (strings etc.) cannot ride the row
                    # carry-sort; gather back by the inverse permutation
                    if inv is None:
                        iota = xp.arange(cap, dtype=xp.int32)
                        if xp is np:
                            inv = np.zeros((cap,), np.int32)
                            # tpulint: allow[TPU-R001] host-engine branch:
                            # lay.order is numpy here, no device crossing
                            inv[np.asarray(lay.order)] = iota
                        else:
                            inv = xp.zeros((cap,), xp.int32).at[
                                lay.order].set(iota, unique_indices=True)
                    out_by_expr[id(w)] = gather_column(xp, res[1], inv,
                                                       live)
                    continue
                per.append((w, res[1], res[2]))
            if not per:
                continue
            # ONE carry-sort back to input order for the whole group
            back_key = lay.order.astype(xp.uint32)
            flat: List = []
            for _, d, v in per:
                flat += [d, v]
            _, back = carry.sort_lanes(xp, [back_key], flat, cap)
            for i, (w, _, _) in enumerate(per):
                d, v = back[2 * i], back[2 * i + 1]
                out_dtype = w.resolved_type(cn, ct)
                valid = v & live
                d = xp.where(valid, d, xp.zeros_like(d))
                out_by_expr[id(w)] = DeviceColumn(out_dtype, data=d,
                                                  validity=valid)
        cols = list(batch.columns) + [out_by_expr[id(w)]
                                      for w in self.window_exprs]
        return DeviceBatch(cols, batch.num_rows, self.output_names)

    @functools.cached_property
    def _jit_key(self):
        return ("WindowExec", schema_sig(self.children[0]),
                semantic_sig(self.window_exprs))

    @property
    def _jitted(self):
        return process_jit(self._jit_key,
                           lambda: lambda b: self._compute(jnp, b))

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        child = self.children[0]
        batches = list(child.execute_partition(pid, ctx))
        if not batches:
            return
        with MetricTimer(self.metrics[OP_TIME]):
            merged = concat_batches(xp, batches, child.output_names,
                                    child.output_types) \
                if len(batches) > 1 else batches[0]
            out = self._jitted(merged) if self.placement == TPU \
                else self._compute(np, merged)
            maybe_sync(out)
        self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
        self.metrics[NUM_OUTPUT_BATCHES] += 1
        yield out


def _vec_bound(xp, values, target, lo0, hi0, cap, left: bool):
    """Vectorized per-row binary search: first index in [lo0, hi0) where
    values[i] >= target (left) / > target (right).  `values` must be
    ascending within each row's [lo0, hi0) window."""
    import math
    lo = lo0.astype(xp.int32)
    hi = hi0.astype(xp.int32)
    iters = max(1, int(math.ceil(math.log2(max(cap, 2)))) + 1)
    for _ in range(iters):
        active = lo < hi
        mid = (lo + hi) // 2
        v = values[xp.clip(mid, 0, cap - 1)]
        pred = (v < target) if left else (v <= target)
        lo = xp.where(active & pred, mid + 1, lo)
        hi = xp.where(active & ~pred, mid, hi)
    return lo


def _rmq_query(xp, vv, lo_i, hi_i, cap, op: str):
    """min/max over inclusive [lo_i, hi_i] per row via doubling (sparse
    table) — O(cap log cap), idempotent ops only."""
    import math
    from ..ops import segmented as seg
    is_min = op == "min"
    init = seg._extreme_init(xp, vv.dtype, is_min)
    fn = xp.minimum if is_min else xp.maximum
    levels = max(1, int(math.ceil(math.log2(max(cap, 2)))))
    st = [vv]
    for k in range(levels):
        sh = 1 << k
        cur = st[-1]
        shifted = xp.concatenate(
            [cur[sh:], xp.full((sh,), init, cur.dtype)])
        st.append(fn(cur, shifted))
    length = hi_i - lo_i + 1
    k_row = xp.zeros((cap,), xp.int32)
    for j in range(1, levels + 1):
        k_row = xp.where(length >= (1 << j), j, k_row)
    lo_c = xp.clip(lo_i, 0, cap - 1).astype(xp.int32)
    res = xp.full((cap,), init, vv.dtype)
    for j in range(levels + 1):
        span = 1 << j
        b = xp.clip(hi_i - span + 1, 0, cap - 1).astype(xp.int32)
        val = fn(st[j][lo_c], st[j][b])
        res = xp.where((k_row == j) & (length >= 1), val, res)
    return res
