"""Out-of-core execution: bounded-memory sort and aggregate merge.

Ref: GpuSortExec.scala:231 (GpuOutOfCoreSortIterator — spillable pending/
sorted queues, boundary-key splitting) and aggregate.scala:309-314
(tryMergeAggregatedBatches + sort-based re-aggregation fallback when the
merged output exceeds one batch).

TPU redesign: XLA has no streaming merge primitive, but its sort is fast
and jit-cached per capacity bucket — so the external merge step IS a
re-sort of a budget-bounded group of runs (memory is the scarce resource
out-of-core, not FLOPs).  All host-driven control flow here runs outside
jit; the per-chunk kernels (sort, merge, gather) are the process-cached
jitted ones.

  * external_merge_sort: sort each input batch -> spillable single-chunk
    runs -> repeatedly merge groups of runs whose total device footprint
    fits the budget (concat + re-sort + re-chunk, chunks spilled as they
    are produced) until one globally sorted run remains.
  * merge_partials_bounded: iteratively merge aggregate partials in
    budget-bounded groups (each merge compacts to the group's distinct
    keys); if a pass cannot pair any two batches under the budget, fall
    back to sort-by-key + carry re-aggregation, emitting completed key
    ranges incrementally exactly like the reference's sort fallback.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterator, List, Sequence

import numpy as np

from .. import types as t
from ..columnar.device import (DEFAULT_CHAR_BUCKETS, DEFAULT_ROW_BUCKETS,
                               DeviceBatch, bucket_for)
from ..memory.spill import SpillableBatch, SpillCatalog, SpillPriority
from ..obs.tracer import trace_event, trace_span
from ..ops.gather import gather_batch
from .base import Exec
from .concat import concat_batches


def slice_batch(xp, batch: DeviceBatch, names, types, start: int,
                length: int) -> DeviceBatch:
    """Host-driven row slice [start, start+length) re-bucketed to the
    smallest covering capacity (variable-length columns re-pack)."""
    from ..columnar.fetch import fetch_ints
    cap = bucket_for(max(length, 1), DEFAULT_ROW_BUCKETS)
    idx = xp.arange(cap, dtype=xp.int32) + np.int32(start)
    valid = xp.arange(cap, dtype=xp.int32) < length
    # span columns need their [start, start+length) child extents to pick
    # output buckets: gather every lo/hi scalar in ONE batched fetch
    # (fetch_ints) rather than pulling each column's whole offsets lane
    span_cols = [c for c, dt in zip(batch.columns, types)
                 if isinstance(dt, (t.StringType, t.BinaryType,
                                    t.ArrayType, t.MapType))]
    wanted = []
    for c in span_cols:
        last = int(c.offsets.shape[0]) - 1
        wanted.append(c.offsets[min(start, last)])
        wanted.append(c.offsets[min(start + length, last)])
    bounds = iter(fetch_ints(wanted))
    char_caps = []
    for c, dt in zip(batch.columns, types):
        if isinstance(dt, (t.StringType, t.BinaryType)):
            lo, hi = next(bounds), next(bounds)
            char_caps.append(bucket_for(max(hi - lo, 1),
                                        DEFAULT_CHAR_BUCKETS))
        elif isinstance(dt, (t.ArrayType, t.MapType)):
            lo, hi = next(bounds), next(bounds)
            char_caps.append(bucket_for(max(hi - lo, 1),
                                        DEFAULT_ROW_BUCKETS))
        else:
            char_caps.append(0)
    out = gather_batch(xp, batch, idx, valid, length, char_caps)
    return DeviceBatch(out.columns, length, names)


def rechunk(xp, batch: DeviceBatch, names, types,
            chunk_rows: int) -> List[DeviceBatch]:
    """Split a batch into row-bounded chunks (order preserved)."""
    n = int(batch.num_rows)
    if n <= chunk_rows:
        return [batch]
    out = []
    for start in range(0, n, chunk_rows):
        out.append(slice_batch(xp, batch, names, types, start,
                               min(chunk_rows, n - start)))
    return out


Run = List[SpillableBatch]


def _run_bytes(run: Run) -> int:
    return sum(c.device_bytes for c in run)


def enforce_device_budget(spill: SpillCatalog, budget: int) -> None:
    """Keep REGISTERED device bytes at or under `budget` — the stronger
    form of maybe_spill the out-of-core paths use: maybe_spill only
    reacts to the catalog-wide threshold, while a forced out-of-core
    budget (Exec.oc_budget, the TPU-L014 repair) must bound the working
    set even when the catalog as a whole is far from pressure."""
    over = spill.device_bytes_registered() - min(budget,
                                                 spill.device_budget)
    if over > 0:
        spill.synchronous_spill(over)
    else:
        spill.maybe_spill()


def external_merge_sort(xp, inputs: Sequence[SpillableBatch],
                        sort_fn: Callable[[DeviceBatch], DeviceBatch],
                        names, types, spill: SpillCatalog, budget: int,
                        chunk_rows: int) -> Iterator[DeviceBatch]:
    """Globally sort arbitrarily many spilled batches within `budget`
    device bytes (ref GpuOutOfCoreSortIterator, GpuSortExec.scala:231)."""
    runs: List[Run] = []
    for p in inputs:
        with trace_span("oc.sort_run") as obs_sp:
            b = p.get_batch(xp)
            p.close()
            sb = sort_fn(b)
            run = [spill.register(c, SpillPriority.INPUT)
                   for c in rechunk(xp, sb, names, types, chunk_rows)]
            obs_sp.set(chunks=len(run), bytes=_run_bytes(run))
        runs.append(run)
        enforce_device_budget(spill, budget)
    while len(runs) > 1:
        # greedy budget-bounded fan-in (always >= 2: correctness over a
        # transient overshoot when two single runs already exceed budget)
        group = [runs.pop(0)]
        total = _run_bytes(group[0])
        while runs and (len(group) < 2 or
                        total + _run_bytes(runs[0]) <= budget):
            total += _run_bytes(runs[0])
            group.append(runs.pop(0))
        with trace_span("oc.merge", fan_in=len(group),
                        bytes=total) as obs_sp:
            chunks = [c.get_batch(xp) for r in group for c in r]
            for r in group:
                for c in r:
                    c.close()
            merged = concat_batches(xp, chunks, names, types) \
                if len(chunks) > 1 else chunks[0]
            del chunks
            sb = sort_fn(merged)
            del merged
            new_run = [spill.register(c, SpillPriority.INPUT)
                       for c in rechunk(xp, sb, names, types,
                                        chunk_rows)]
            obs_sp.set(chunks=len(new_run))
        runs.append(new_run)
        enforce_device_budget(spill, budget)
    for c in runs[0]:
        out = c.get_batch(xp)
        c.close()
        yield out


def merge_partials_bounded(xp, partials: List[SpillableBatch],
                           merge_fn: Callable[[DeviceBatch], DeviceBatch],
                           sort_by_keys_fn: Callable[[DeviceBatch],
                                                     DeviceBatch],
                           names, types, spill: SpillCatalog, budget: int,
                           chunk_rows: int) -> Iterator[DeviceBatch]:
    """Merge aggregate partial batches without ever concatenating more
    than `budget` device bytes (ref aggregate.scala:309-314).

    merge_fn must combine duplicate keys of ONE batch and leave output
    groups in sorted key order, live rows first (the segment-reduce
    kernel's contract)."""
    def _merge_compact(group: List[SpillableBatch]) -> SpillableBatch:
        with trace_span("oc.merge_partials", fan_in=len(group),
                        bytes=sum(p.device_bytes for p in group)):
            mats = [p.get_batch(xp) for p in group]
            for p in group:
                p.close()
            merged_in = concat_batches(xp, mats, names, types) \
                if len(mats) > 1 else mats[0]
            del mats
            out = merge_fn(merged_in)
            # re-bucket to the surviving group count so batches genuinely
            # shrink (the merge kernel keeps its input capacity)
            compacted = slice_batch(xp, out, names, types, 0,
                                    int(out.num_rows))
            return spill.register(compacted, SpillPriority.INPUT)

    while len(partials) > 1:
        nxt: List[SpillableBatch] = []
        progress = False
        i = 0
        while i < len(partials):
            group = [partials[i]]
            total = partials[i].device_bytes
            i += 1
            while i < len(partials) and \
                    total + partials[i].device_bytes <= budget:
                total += partials[i].device_bytes
                group.append(partials[i])
                i += 1
            if len(group) == 1:
                nxt.append(group[0])
                continue
            nxt.append(_merge_compact(group))
            progress = True
            enforce_device_budget(spill, budget)
        partials = nxt
        if not progress:
            break
    if len(partials) == 1:
        out = partials[0].get_batch(xp)
        partials[0].close()
        yield out
        return
    # Sort-based fallback: no two batches fit the budget together.  Sort
    # everything by grouping key, then stream chunks; merge_fn leaves
    # groups key-sorted, so only the LAST group of each merged chunk can
    # continue into the next chunk — carry it forward (the reference's
    # sort-fallback re-aggregation emits completed keys the same way).
    sorted_chunks = external_merge_sort(xp, partials, sort_by_keys_fn,
                                        names, types, spill, budget,
                                        chunk_rows)
    carry: DeviceBatch | None = None
    for chunk in sorted_chunks:
        merged_in = concat_batches(xp, [carry, chunk], names, types) \
            if carry is not None else chunk
        merged = merge_fn(merged_in)
        n = int(merged.num_rows)
        if n > 1:
            yield slice_batch(xp, merged, names, types, 0, n - 1)
        carry = slice_batch(xp, merged, names, types, max(n - 1, 0),
                            min(n, 1))
    if carry is not None and int(carry.num_rows) > 0:
        yield carry


class SpillBoundaryExec(Exec):
    """Out-of-core boundary: registers the child's batches in the
    SpillCatalog so everything staged below a materializing consumer is
    spill-managed (demotable under pressure instead of raw HBM), and
    memoizes the registered handles per (query, partition) so a REUSED
    subtree executes its child exactly once (the IciExchangeExec memo
    discipline for ordinary pipelines).

    Ownership protocol: the handles close after `consumers` full
    consumptions.  That number is part of the PLAN — a rewrite that
    shares or un-shares this node must re-derive it, which is exactly
    what the static lifetime pass checks: more parents than declared
    consumers is a use-after-close along the extra path (TPU-L013),
    fewer means the close never fires (TPU-L015).  The runtime shadow
    ledger (spark.rapids.tpu.memsan.enabled) catches either one as it
    happens."""

    def __init__(self, child: Exec, consumers: int = 1,
                 close_on_exhaust: bool = True):
        super().__init__([child])
        self.placement = child.placement
        self.consumers = consumers
        # False = this node declares it never closes (only sound when a
        # downstream owner takes over — no such owner exists today, so
        # the lifetime pass flags it as a plan-level leak)
        self.close_on_exhaust = close_on_exhaust
        self._memo: dict = {}
        self._lock = threading.Lock()

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def describe(self):
        return f"SpillBoundary consumers={self.consumers}"

    def memory_effects(self, child_states, conf):
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         spill_budget)
        pp = padded_partition_bytes(child_states[0]) if child_states \
            else 0.0
        return MemoryEffects(
            hold=min(pp, float(spill_budget(conf))) + pp,
            handles=True, handle_consumers=self.consumers,
            closes_handles=self.close_on_exhaust,
            note="spill-managed staging")

    def execute_partition(self, pid, ctx) -> Iterator[DeviceBatch]:
        xp = self.xp
        spill = SpillCatalog.get()
        key = (ctx.uid, pid)
        with self._lock:
            entry = self._memo.get(key)
        if entry is None:
            handles = [spill.register(b, SpillPriority.INPUT)
                       for b in
                       self.children[0].execute_partition(pid, ctx)]
            entry = {"handles": handles, "reads": 0}
            trace_event("oc.boundary_stage", pid=pid,
                        handles=len(handles),
                        bytes=sum(h.device_bytes for h in handles))
            with self._lock:
                self._memo[key] = entry
        # a consumer past the declared count materializes CLOSED handles
        # here — the runtime shape of TPU-L013 (get_batch raises; under
        # the shadow ledger, as a LifecycleViolation with provenance)
        for h in entry["handles"]:
            yield h.get_batch(xp)
        entry["reads"] += 1
        if self.close_on_exhaust and entry["reads"] >= self.consumers:
            for h in entry["handles"]:
                h.close()
