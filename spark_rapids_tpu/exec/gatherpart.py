"""GatherPartitionsExec: funnel all child partitions into one.

Stand-in exchange used where an operator needs co-located data and the
planner has not inserted a real shuffle (analog of Spark's coalesce(1) /
single-partition exchange).  The accelerated shuffle (shuffle/) replaces
this in distributed plans.
"""

from __future__ import annotations

from typing import Iterator

from .base import Batch, Exec


class GatherPartitionsExec(Exec):
    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    @property
    def num_partitions(self):
        return 1

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        assert pid == 0
        child = self.children[0]
        for cpid in range(child.num_partitions):
            yield from child.execute_partition(cpid, ctx)
