"""Sort operator.

Ref: sql-plugin/.../GpuSortExec.scala:39-534 (single-batch, per-batch and
out-of-core modes) + SortUtils.scala.

TPU realization: order-preserving uint64 key-word encoding per sort column
(ops/segmented.key_words_for_column with true string ordering) feeding one
stable multi-operand lax.sort; rows then move via gather.  Multi-batch
partitions concatenate before sorting (spillable out-of-core merge arrives
with the memory framework; the concat path is the reference's
single-batch-goal mode).
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch
from ..expr.core import EvalContext, Expression, bind_expression
from ..ops import segmented as seg
from ..ops.gather import gather_batch
from .base import (maybe_sync,  # noqa: F401
                   NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU, Batch,
                   Exec, MetricTimer, process_jit, schema_sig, semantic_sig)
from .concat import concat_batches


class SortExec(Exec):
    """orders: [(expr, ascending, nulls_first)]."""

    def __init__(self, orders, child: Exec, is_global: bool = True):
        super().__init__([child])
        self.orders = list(orders)
        self.is_global = is_global
        cn, ct = child.output_names, child.output_types
        self._bound = [(bind_expression(e, cn, ct), asc, nf)
                       for e, asc, nf in self.orders]

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def describe(self):
        os = ", ".join(f"{e.sql()} {'ASC' if a else 'DESC'}"
                       for e, a, _ in self._bound)
        return f"Sort [{os}] global={self.is_global}"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "stable sort: key order is a function of "
            "content, tie order follows arrival",
            establishes_order=True)

    def _sort_batch(self, xp, batch: Batch) -> Batch:
        ctx = EvalContext(xp, batch)
        live = ctx.row_mask()
        words: List = [(~live).astype(xp.uint8)]  # padding last
        for e, asc, nulls_first in self._bound:
            v = e.eval(ctx)
            from ..expr.core import ColumnValue, make_column
            if not isinstance(v, ColumnValue):
                v = make_column(ctx, e.data_type(),
                                v.value if v.value is not None else 0,
                                None if v.value is not None else False)
            words += seg.key_words_for_column(
                xp, v.col, live, for_grouping=False,
                nulls_first=nulls_first, ascending=asc)
        from ..ops import carry
        _, cols, _ = carry.sort_rows(xp, words, batch.columns,
                                     batch.capacity)
        return DeviceBatch(cols, batch.num_rows, batch.names)

    @functools.cached_property
    def _jit_key(self):
        return ("SortExec", schema_sig(self.children[0]),
                semantic_sig(self._bound))

    @property
    def _jitted(self):
        return process_jit(self._jit_key,
                           lambda: lambda b: self._sort_batch(jnp, b))

    def memory_effects(self, child_states, conf):
        """Materializes its whole input as registered spillables, then
        concat + sorted copy: ~3x one partition's padded bytes in-core,
        or 3x the enforced budget out-of-core (the working set the
        TPU-L014 repair bounds by setting oc_budget)."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         spill_budget)
        if not child_states:
            return None
        pp = padded_partition_bytes(child_states[0])
        budget = float(min(spill_budget(conf),
                           self.oc_budget or (1 << 62)))
        hold = 3.0 * (pp if pp <= budget else budget)
        return MemoryEffects(hold=hold, note="sort: spill-managed")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        from ..memory.spill import SpillCatalog, SpillPriority
        from .outofcore import enforce_device_budget
        spill = SpillCatalog.get()
        # a forced out-of-core budget (the TPU-L014 pre-flight repair)
        # lowers the in-core threshold below the catalog's and bounds
        # registered device bytes while the input streams in
        budget = min(spill.device_budget, self.oc_budget or (1 << 62))
        pending = []
        try:
            for b in self.children[0].execute_partition(pid, ctx):
                pending.append(spill.register(b, SpillPriority.INPUT))
                if self.oc_budget is not None:
                    enforce_device_budget(spill, budget)
            if not pending:
                return
            sort_fn = self._jitted if self.placement == TPU \
                else lambda b: self._sort_batch(np, b)
            total = sum(p.device_bytes for p in pending)
            if total <= budget:
                # in-core: concat everything and sort once
                with MetricTimer(self.metrics[OP_TIME]):
                    batches = [p.get_batch(xp) for p in pending]
                    merged = concat_batches(xp, batches, self.output_names,
                                            self.output_types) \
                        if len(batches) > 1 else batches[0]
                    for p in pending:
                        p.close()
                    out = sort_fn(merged)
                    maybe_sync(out)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
                return
            # out-of-core external merge sort (ref GpuSortExec.scala:231)
            from .outofcore import external_merge_sort
            chunk_rows = max(int(p.num_rows) for p in pending)
            if self.oc_budget is not None:
                # keep each run chunk at ~half the enforced budget so a
                # two-run merge group stays within it; snap DOWN to a
                # capacity bucket — an off-bucket chunk pads UP to the next
                # bucket and would inflate real memory instead
                from ..columnar.device import (DEFAULT_ROW_BUCKETS,
                                               bucket_floor)
                rows_total = sum(int(p.num_rows) for p in pending)
                bpr = max(total / max(rows_total, 1), 1.0)
                target = int(budget / (2 * bpr))
                chunk_rows = min(chunk_rows,
                                 bucket_floor(target, DEFAULT_ROW_BUCKETS))
            with MetricTimer(self.metrics[OP_TIME]):
                for out in external_merge_sort(
                        xp, pending, sort_fn, self.output_names,
                        self.output_types, spill, budget,
                        chunk_rows):
                    self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                    self.metrics[NUM_OUTPUT_BATCHES] += 1
                    yield out
        finally:
            # a raising producer (or an abandoned consumer) must
            # not strand registered spillables: close everything
            # this partition accumulated — idempotent, so batches
            # the merge already consumed are no-ops (tpufsan
            # TPU-R012)
            for p in pending:
                p.close()
