"""ArrowEvalPythonExec: evaluate opaque Python UDFs over columnar batches.

Analog of the reference's GpuArrowEvalPythonExec
(ref: sql-plugin/.../execution/python/GpuArrowEvalPythonExec.scala:58-260),
which streams Arrow batches to out-of-process Python workers and pairs the
results back with the inputs (BatchQueue, RebatchingRoundoffIterator).

Our executor processes are already Python, so the exchange is in-process:
the child's batches are brought to the host (the rewrite engine places
this exec on CPU and inserts a DeviceToHost transition), each UDF is
evaluated through its host evaluator, and the UDF outputs are appended as
new columns after the child's output — the downstream Project refers to
them by name.  Rebatching to the UDF target size is preserved: oversize
batches are split so Python never sees more than `arrow_max_records_per_batch`
rows at once (ref RebatchingRoundoffIterator's size goal).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch
from ..expr.core import (ColumnValue, EvalContext, Expression, ScalarValue,
                         bind_expression, scalar_to_column)
from ..udf.python_udf import PythonUDF
from .base import (CPU, NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, Batch,
                   Exec, ExecContext, MetricTimer)


class ArrowEvalPythonExec(Exec):
    """Appends one output column per UDF to the child's columns."""

    placement = CPU

    def __init__(self, udfs: Sequence[Tuple[str, PythonUDF]], child: Exec):
        super().__init__([child])
        self.udf_names = [n for n, _ in udfs]
        self.udfs = [u for _, u in udfs]
        self._bound = [bind_expression(u, child.output_names,
                                       child.output_types)
                       for u in self.udfs]

    @property
    def output_names(self):
        return list(self.children[0].output_names) + self.udf_names

    @property
    def output_types(self):
        return list(self.children[0].output_types) + \
            [u.data_type() for u in self._bound]

    def describe(self):
        return f"ArrowEvalPython [{', '.join(self.udf_names)}]"

    def _split(self, b: Batch, limit: int) -> Iterator[Batch]:
        n = int(b.num_rows)
        if n <= limit:
            yield b
            return
        # slice the host batch into UDF-sized windows
        from ..columnar.device import batch_to_arrow, batch_to_device
        import pyarrow as pa
        rb = batch_to_arrow(DeviceBatch(b.columns, n,
                                        self.children[0].output_names))
        tbl = pa.Table.from_batches([rb])
        for off in range(0, n, limit):
            piece = tbl.slice(off, min(limit, n - off)).combine_chunks()
            yield batch_to_device(piece.to_batches()[0], xp=np)

    def execute_partition(self, pid, ctx: ExecContext) -> Iterator[Batch]:
        from ..udf import worker as w
        limit = ctx.conf.arrow_max_records_per_batch
        use_worker = w.worker_path_usable(ctx.conf, *self._bound)
        child = self.children[0]
        for big in child.execute_partition(pid, ctx):
            for b in self._split(big, limit):
                with MetricTimer(self.metrics[OP_TIME]):
                    if use_worker:
                        out = self._eval_in_worker(b, ctx)
                    else:
                        ectx = EvalContext(np, b,
                                           ansi=ctx.conf.ansi_enabled)
                        cols = list(b.columns)
                        for u in self._bound:
                            v = u.eval(ectx)
                            if isinstance(v, ScalarValue):
                                v = scalar_to_column(ectx, v)
                            cols.append(v.col)
                        out = DeviceBatch(cols, b.num_rows,
                                          self.output_names)
                self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out

    def _eval_in_worker(self, b: Batch, ctx: ExecContext) -> Batch:
        """Ship the batch over Arrow IPC; the worker runs the SAME bound
        expression evaluator, then the UDF columns come back columnar
        (ref GpuArrowEvalPythonExec's worker exchange + BatchQueue input
        pairing — here the child columns never leave this process)."""
        import pyarrow as pa
        from ..columnar.device import batch_to_arrow, batch_to_device
        from ..udf import worker as w
        child = self.children[0]
        rb = batch_to_arrow(DeviceBatch(b.columns, int(b.num_rows),
                                        child.output_names))
        aux = (self._bound, child.output_names, child.output_types,
               self.udf_names, ctx.conf.ansi_enabled)
        tables, _ = w.pool_from_conf(ctx.conf).run(
            w.task_eval_bound, aux, [pa.Table.from_batches([rb])])
        # pair the child columns with the worker's UDF columns through one
        # Arrow table so every lane shares a single capacity bucket
        udf_tbl = tables[0].combine_chunks()
        paired = pa.Table.from_arrays(
            list(pa.Table.from_batches([rb]).columns) +
            [udf_tbl.column(i) for i in range(udf_tbl.num_columns)],
            names=self.output_names)
        rbs = paired.combine_chunks().to_batches()
        if not rbs:
            # a 0-row table flattens to no batches; keep the DECLARED
            # schema (from_pydict would infer null type for every column)
            from ..columnar.interop import to_arrow_schema
            rbs = to_arrow_schema(self.output_names,
                                  self.output_types).empty_table() \
                .to_batches(max_chunksize=1)
            if not rbs:
                rbs = [pa.RecordBatch.from_arrays(
                    [pa.array([], type=f.type)
                     for f in to_arrow_schema(self.output_names,
                                              self.output_types)],
                    names=list(self.output_names))]
        return batch_to_device(rbs[0], xp=np)
