"""ArrowEvalPythonExec: evaluate opaque Python UDFs over columnar batches.

Analog of the reference's GpuArrowEvalPythonExec
(ref: sql-plugin/.../execution/python/GpuArrowEvalPythonExec.scala:58-260),
which streams Arrow batches to out-of-process Python workers and pairs the
results back with the inputs (BatchQueue, RebatchingRoundoffIterator).

Default path (spark.rapids.sql.python.worker.enabled): the UDF input
columns stream over Arrow IPC to an out-of-process worker
(udf/worker.py) which runs the SAME bound-expression evaluator, and the
UDF output columns are paired back with the locally-retained child
batches — the BatchQueue design.  Unpicklable UDFs (or worker disabled)
evaluate in-process with identical semantics.  Rebatching to the UDF
target size is preserved either way: oversize batches split so Python
never sees more than `arrow_max_records_per_batch` rows at once
(ref RebatchingRoundoffIterator's size goal).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch
from ..expr.core import (BoundReference, ColumnValue, EvalContext,
                         Expression, ScalarValue, bind_expression,
                         scalar_to_column)
from ..udf.python_udf import PythonUDF
from .base import (CPU, NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, Batch,
                   Exec, ExecContext, MetricTimer)


class ArrowEvalPythonExec(Exec):
    """Appends one output column per UDF to the child's columns."""

    placement = CPU

    def __init__(self, udfs: Sequence[Tuple[str, PythonUDF]], child: Exec):
        super().__init__([child])
        self.udf_names = [n for n, _ in udfs]
        self.udfs = [u for _, u in udfs]
        self._bound = [bind_expression(u, child.output_names,
                                       child.output_types)
                       for u in self.udfs]

    @property
    def output_names(self):
        return list(self.children[0].output_names) + self.udf_names

    @property
    def output_types(self):
        return list(self.children[0].output_types) + \
            [u.data_type() for u in self._bound]

    def describe(self):
        return f"ArrowEvalPython [{', '.join(self.udf_names)}]"

    def determinism(self):
        from ..analysis.determinism import Determinism, NONDETERMINISTIC
        return Determinism(
            NONDETERMINISTIC, "opaque Python UDF (clock/RNG/iteration "
            "order unprovable); a recomputed partition may differ")

    def _split(self, b: Batch, limit: int) -> Iterator[Batch]:
        n = int(b.num_rows)
        if n <= limit:
            yield b
            return
        # slice the host batch into UDF-sized windows
        from ..columnar.device import batch_to_arrow, batch_to_device
        import pyarrow as pa
        rb = batch_to_arrow(DeviceBatch(b.columns, n,
                                        self.children[0].output_names))
        tbl = pa.Table.from_batches([rb])
        for off in range(0, n, limit):
            piece = tbl.slice(off, min(limit, n - off)).combine_chunks()
            yield batch_to_device(piece.to_batches()[0], xp=np)

    def execute_partition(self, pid, ctx: ExecContext) -> Iterator[Batch]:
        from ..udf import worker as w
        limit = ctx.conf.arrow_max_records_per_batch
        use_worker = w.worker_path_usable(ctx.conf, *self._bound)
        child = self.children[0]
        if use_worker:
            yield from self._execute_via_worker(pid, ctx, limit)
            return
        for big in child.execute_partition(pid, ctx):
            for b in self._split(big, limit):
                with MetricTimer(self.metrics[OP_TIME]):
                    ectx = EvalContext(np, b,
                                       ansi=ctx.conf.ansi_enabled)
                    cols = list(b.columns)
                    for u in self._bound:
                        v = u.eval(ectx)
                        if isinstance(v, ScalarValue):
                            v = scalar_to_column(ectx, v)
                        cols.append(v.col)
                    out = DeviceBatch(cols, b.num_rows,
                                      self.output_names)
                self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out

    def _shipped_exprs(self):
        """(remapped bound exprs, used ordinals): only the columns the
        UDFs reference cross the process boundary; expressions are
        re-bound to the pruned ordinal space."""
        child = self.children[0]
        used = sorted({br.ordinal for u in self._bound
                       for br in u.collect(
                           lambda e: isinstance(e, BoundReference))})
        if not used and child.output_names:
            used = [0]  # constant UDFs still need a row-count carrier
        remap = {old: new for new, old in enumerate(used)}

        def rebind(e):
            if isinstance(e, BoundReference):
                return BoundReference(remap[e.ordinal], e.dtype, e.name)
            return e

        shipped = [u.transform_up(rebind) for u in self._bound]
        names = [child.output_names[i] for i in used]
        types = [child.output_types[i] for i in used]
        return shipped, used, names, types

    def _execute_via_worker(self, pid, ctx: ExecContext,
                            limit: int) -> Iterator[Batch]:
        """Streaming exchange (ref GpuArrowEvalPythonExec's BatchQueue:
        inputs are retained locally and paired 1:1 with the worker's UDF
        output batches; the closure ships once per partition)."""
        import collections

        import pyarrow as pa

        from ..columnar.device import batch_to_arrow, batch_to_device
        from ..udf import worker as w
        child = self.children[0]
        shipped, used, in_names, in_types = self._shipped_exprs()
        aux = (shipped, in_names, in_types, self.udf_names,
               ctx.conf.ansi_enabled)
        pending = collections.deque()  # (batch, full arrow RecordBatch)

        def in_iter():
            for big in child.execute_partition(pid, ctx):
                for b in self._split(big, limit):
                    rb = batch_to_arrow(
                        DeviceBatch(b.columns, int(b.num_rows),
                                    child.output_names))
                    pending.append(rb)
                    # select by ORDINAL: child schemas may carry
                    # duplicate names (join outputs concatenate sides)
                    yield pa.Table.from_batches([rb]).select(used)

        out_iter = w.pool_from_conf(ctx.conf).run_stream(
            w.task_stream_eval_bound, aux, in_iter())
        while True:
            with MetricTimer(self.metrics[OP_TIME]):
                try:
                    udf_tbl = next(out_iter).combine_chunks()
                except StopIteration:
                    break
                rb = pending.popleft()
                # pair through one Arrow table so every lane shares a
                # single capacity bucket
                paired = pa.Table.from_arrays(
                    list(pa.Table.from_batches([rb]).columns) +
                    [udf_tbl.column(i)
                     for i in range(udf_tbl.num_columns)],
                    names=self.output_names)
                rbs = paired.combine_chunks().to_batches()
                if not rbs:
                    # 0-row: keep the DECLARED schema (from_pydict would
                    # infer null type for every column)
                    from ..columnar.interop import to_arrow_schema
                    rbs = [pa.RecordBatch.from_arrays(
                        [pa.array([], type=f.type)
                         for f in to_arrow_schema(self.output_names,
                                                  self.output_types)],
                        names=list(self.output_names))]
                out = batch_to_device(rbs[0], xp=np)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield out
