"""Filter compaction shared by FilterExec / conditional joins / having.

Static-shape compaction: stable-sort rows on the (negated) keep flag so
survivors move to the front in original order, then gather every column.
One lax.sort + gathers — no dynamic shapes, no host sync.
"""

from __future__ import annotations

import numpy as np

from ..columnar.device import DeviceBatch
from ..ops.gather import gather_batch


def keep_flags(xp, batch: DeviceBatch, pred_value):
    """bool[cap] from a predicate value (null -> drop, Spark)."""
    live = xp.arange(batch.capacity, dtype=np.int32) < batch.num_rows
    from ..expr.core import ScalarValue
    if isinstance(pred_value, ScalarValue):
        if pred_value.value is None or not bool(pred_value.value):
            return xp.zeros((batch.capacity,), dtype=bool)
        return live
    col = pred_value.col
    keep = col.data.astype(bool)
    if col.validity is not None:
        keep = keep & col.validity
    return keep & live


def compact(xp, batch: DeviceBatch, keep, names):
    """Move kept rows to the front (stable), shrink num_rows."""
    cap = batch.capacity
    if xp is np:
        order = np.argsort(~keep, kind="stable").astype(np.int32)
    else:
        from jax import lax
        iota = xp.arange(cap, dtype=xp.int32)
        order = lax.sort(((~keep).astype(xp.int32), iota), num_keys=1,
                         is_stable=True)[1]
    new_n = xp.sum(keep.astype(np.int32))
    valid_slot = xp.arange(cap, dtype=np.int32) < new_n
    out = gather_batch(xp, batch, order, valid_slot, new_n)
    return DeviceBatch(out.columns, new_n, names)


def apply_filter(xp, batch: DeviceBatch, pred_value, names):
    return compact(xp, batch, keep_flags(xp, batch, pred_value), names)
