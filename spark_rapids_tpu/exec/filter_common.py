"""Filter compaction shared by FilterExec / conditional joins / having.

Static-shape compaction: stable-sort rows on the (negated) keep flag so
survivors move to the front in original order, then gather every column.
One lax.sort + gathers — no dynamic shapes, no host sync.
"""

from __future__ import annotations

import numpy as np

from ..columnar.device import DeviceBatch
from ..ops.gather import gather_batch


def keep_flags(xp, batch: DeviceBatch, pred_value):
    """bool[cap] from a predicate value (null -> drop, Spark)."""
    live = xp.arange(batch.capacity, dtype=np.int32) < batch.num_rows
    from ..expr.core import ScalarValue
    if isinstance(pred_value, ScalarValue):
        if pred_value.value is None or not bool(pred_value.value):
            return xp.zeros((batch.capacity,), dtype=bool)
        return live
    col = pred_value.col
    keep = col.data.astype(bool)
    if col.validity is not None:
        keep = keep & col.validity
    return keep & live


def compact(xp, batch: DeviceBatch, keep, names):
    """Move kept rows to the front (stable), shrink num_rows.  One
    carry-sort on the keep flag; dropped rows become padding (validity
    masked off per the batch contract)."""
    from ..ops.carry import compact_rows, mask_validity
    cap = batch.capacity
    new_n = xp.sum(keep.astype(np.int32))
    valid_slot = xp.arange(cap, dtype=np.int32) < new_n
    _, cols, _ = compact_rows(xp, keep, batch.columns, cap)
    cols = [mask_validity(xp, c, valid_slot) for c in cols]
    return DeviceBatch(cols, new_n, names)


def apply_filter(xp, batch: DeviceBatch, pred_value, names):
    return compact(xp, batch, keep_flags(xp, batch, pred_value), names)
