"""Pandas UDF operator family: map / grouped-map / grouped-aggregate /
cogrouped-map.

Ref: sql-plugin/.../execution/python/{GpuMapInPandasExec,
GpuFlatMapGroupsInPandasExec, GpuAggregateInPandasExec,
GpuFlatMapCoGroupsInPandasExec}.scala — the reference streams Arrow
batches to out-of-process pandas workers and reassembles columnar
output.  This engine does the same by default: udf/worker.py hosts the
pandas exchange in pooled subprocesses (mapInPandas streams; the grouped
family ships its co-located partition table per request), with an
in-process fallback for unpicklable functions.  All placements are CPU —
the data leaves the device for Python either way, and the rewrite engine
inserts the DeviceToHost transition exactly as the reference schedules
its device->Arrow copy.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Sequence, Tuple

import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import DeviceBatch, batch_to_device
from ..columnar.interop import to_arrow_schema
from .base import (CPU, NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, Batch,
                   Exec, ExecContext, MetricTimer, to_host_batch)


# canonical pandas<->arrow helpers live in udf/worker.py so the worker
# path and the in-process fallback share ONE implementation of the
# schema-cast and null-safe grouping semantics
from ..udf.worker import _cast_result as _from_pandas  # noqa: E402
from ..udf.worker import _group_pandas  # noqa: E402


def _batches_to_table(exec_node: Exec, pid: int, ctx) -> pa.Table:
    rbs = []
    for b in exec_node.execute_partition(pid, ctx):
        rb = to_host_batch(b, exec_node.output_names)
        if rb.num_rows:
            rbs.append(rb)
    schema = to_arrow_schema(exec_node.output_names, exec_node.output_types)
    if not rbs:
        return schema.empty_table()
    return pa.Table.from_batches([rb.cast(schema) for rb in rbs])


def _emit_table(self_node: Exec, tbl: pa.Table,
                max_rows: int) -> Iterator[Batch]:
    schema = to_arrow_schema(self_node.output_names, self_node.output_types)
    tbl = tbl.cast(schema)
    for rb in tbl.combine_chunks().to_batches(max_chunksize=max_rows):
        if rb.num_rows == 0:
            continue
        b = batch_to_device(rb, xp=np)
        self_node.metrics[NUM_OUTPUT_ROWS] += rb.num_rows
        self_node.metrics[NUM_OUTPUT_BATCHES] += 1
        yield b


def _opaque_udf_determinism(what: str):
    """Pandas-UDF boundaries run arbitrary user code: nothing provable
    about clock/RNG/iteration-order use, so the replay class bottoms
    out (the recompute may legitimately differ)."""
    from ..analysis.determinism import Determinism, NONDETERMINISTIC
    return Determinism(
        NONDETERMINISTIC,
        f"{what}: opaque user code (clock/RNG/iteration order "
        f"unprovable); a recomputed partition may differ")


class MapInPandasExec(Exec):
    """df.mapInPandas(fn, schema): fn(iterator[pd.DataFrame]) ->
    iterator[pd.DataFrame] (ref GpuMapInPandasExec)."""

    deliberate_cpu = True

    placement = CPU

    def __init__(self, fn: Callable, names, dtypes, child: Exec):
        super().__init__([child])
        self.fn = fn
        self._names = list(names)
        self._types = list(dtypes)

    @property
    def output_names(self):
        return self._names

    @property
    def output_types(self):
        return self._types

    def describe(self):
        return f"MapInPandas({getattr(self.fn, '__name__', 'fn')})"

    def determinism(self):
        return _opaque_udf_determinism("mapInPandas user function")

    def execute_partition(self, pid, ctx: ExecContext) -> Iterator[Batch]:
        from ..udf import worker as w
        limit = ctx.conf.arrow_max_records_per_batch
        child = self.children[0]
        schema = to_arrow_schema(self.output_names, self.output_types)
        if w.worker_path_usable(ctx.conf, self.fn):
            # streaming exchange: one batch in flight per direction, so a
            # partition larger than RAM flows through the worker the same
            # way the in-process iterator path streams it
            def table_iter():
                for b in child.execute_partition(pid, ctx):
                    rb = to_host_batch(b, child.output_names)
                    if rb.num_rows:
                        yield pa.Table.from_batches([rb])

            out_iter = w.pool_from_conf(ctx.conf).run_stream(
                w.task_stream_map_in_pandas, (self.fn, schema),
                table_iter())
            while True:
                with MetricTimer(self.metrics[OP_TIME]):
                    try:
                        tbl = next(out_iter)
                    except StopIteration:
                        break
                yield from _emit_table(self, tbl, limit)
            return

        def pdf_iter():
            for b in child.execute_partition(pid, ctx):
                rb = to_host_batch(b, child.output_names)
                if rb.num_rows:
                    yield rb.to_pandas()

        with MetricTimer(self.metrics[OP_TIME]):
            outs = [_from_pandas(pdf, schema)
                    for pdf in self.fn(pdf_iter()) if len(pdf)]
        if not outs:
            return
        yield from _emit_table(self, pa.concat_tables(outs), limit)


class FlatMapGroupsInPandasExec(Exec):
    """groupBy(k).applyInPandas(fn, schema)
    (ref GpuFlatMapGroupsInPandasExec).  The planner co-locates groups
    with a hash exchange first, like the aggregate path."""

    deliberate_cpu = True

    placement = CPU

    def __init__(self, key_names: List[str], fn: Callable, names, dtypes,
                 child: Exec):
        super().__init__([child])
        self.key_names = list(key_names)
        self.fn = fn
        self._names = list(names)
        self._types = list(dtypes)

    @property
    def output_names(self):
        return self._names

    @property
    def output_types(self):
        return self._types

    def describe(self):
        return (f"FlatMapGroupsInPandas(keys=[{', '.join(self.key_names)}],"
                f" {getattr(self.fn, '__name__', 'fn')})")

    def determinism(self):
        return _opaque_udf_determinism("grouped-map user function")

    def execute_partition(self, pid, ctx: ExecContext) -> Iterator[Batch]:
        from ..udf import worker as w
        limit = ctx.conf.arrow_max_records_per_batch
        tbl = _batches_to_table(self.children[0], pid, ctx)
        schema = to_arrow_schema(self.output_names, self.output_types)
        if w.worker_path_usable(ctx.conf, self.fn):
            with MetricTimer(self.metrics[OP_TIME]):
                tables, _ = w.pool_from_conf(ctx.conf).run(
                    w.task_grouped_map,
                    (self.fn, schema, self.key_names), [tbl])
            if not tables:
                return
            yield from _emit_table(self, tables[0], limit)
            return
        with MetricTimer(self.metrics[OP_TIME]):
            outs = []
            for _, pdf in _group_pandas(tbl, self.key_names):
                res = self.fn(pdf)
                if len(res):
                    outs.append(_from_pandas(res, schema))
        if not outs:
            return
        yield from _emit_table(self, pa.concat_tables(outs), limit)


class AggregateInPandasExec(Exec):
    """groupBy(k).agg(pandas_udf_series_to_scalar(col))
    (ref GpuAggregateInPandasExec): one output row per group, keys then
    one column per UDF."""

    deliberate_cpu = True

    placement = CPU

    def __init__(self, key_names: List[str],
                 udfs: Sequence[Tuple[str, Callable, t.DataType,
                                      List[str]]],
                 child: Exec):
        super().__init__([child])
        self.key_names = list(key_names)
        self.udfs = list(udfs)  # (out_name, fn, ret_type, input_col_names)

    @property
    def output_names(self):
        return self.key_names + [n for n, *_ in self.udfs]

    @property
    def output_types(self):
        child = self.children[0]
        by_name = dict(zip(child.output_names, child.output_types))
        return [by_name[k] for k in self.key_names] + \
            [rt for _, _, rt, _ in self.udfs]

    def describe(self):
        return (f"AggregateInPandas(keys=[{', '.join(self.key_names)}], "
                f"fns=[{', '.join(n for n, *_ in self.udfs)}])")

    def determinism(self):
        return _opaque_udf_determinism("grouped-aggregate user function")

    def execute_partition(self, pid, ctx: ExecContext) -> Iterator[Batch]:
        from ..udf import worker as w
        limit = ctx.conf.arrow_max_records_per_batch
        tbl = _batches_to_table(self.children[0], pid, ctx)
        if w.worker_path_usable(ctx.conf,
                                *[fn for _, fn, _, _ in self.udfs]):
            specs = [(n, fn, in_cols) for n, fn, _, in_cols in self.udfs]
            with MetricTimer(self.metrics[OP_TIME]):
                _, rows = w.pool_from_conf(ctx.conf).run(
                    w.task_grouped_agg, (specs, self.key_names), [tbl])
        else:
            with MetricTimer(self.metrics[OP_TIME]):
                rows = {n: [] for n in self.output_names}
                if self.key_names:
                    groups = _group_pandas(tbl, self.key_names)
                else:
                    groups = [((), tbl.to_pandas())]  # global aggregate
                for key, pdf in groups:
                    for k_name, k_val in zip(self.key_names, key):
                        rows[k_name].append(k_val)
                    for out_name, fn, _, in_cols in self.udfs:
                        args = [pdf[c] for c in in_cols]
                        rows[out_name].append(fn(*args))
        first = self.output_names[0]
        if not rows[first]:
            return
        arrays = []
        schema = to_arrow_schema(self.output_names, self.output_types)
        for f in schema:
            arrays.append(pa.array(rows[f.name], type=f.type))
        tbl_out = pa.Table.from_arrays(arrays, schema=schema)
        yield from _emit_table(self, tbl_out, limit)


class FlatMapCoGroupsInPandasExec(Exec):
    """a.groupBy(k).cogroup(b.groupBy(k)).applyInPandas(fn, schema)
    (ref GpuFlatMapCoGroupsInPandasExec): fn(left_pdf, right_pdf) per key
    present on either side."""

    deliberate_cpu = True

    placement = CPU

    def __init__(self, left_keys: List[str], right_keys: List[str],
                 fn: Callable, names, dtypes, left: Exec, right: Exec):
        super().__init__([left, right])
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._names = list(names)
        self._types = list(dtypes)

    @property
    def output_names(self):
        return self._names

    @property
    def output_types(self):
        return self._types

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def describe(self):
        return (f"FlatMapCoGroupsInPandas(keys="
                f"[{', '.join(self.left_keys)}])")

    def determinism(self):
        return _opaque_udf_determinism("cogrouped-map user function")

    def execute_partition(self, pid, ctx: ExecContext) -> Iterator[Batch]:
        from ..udf import worker as w
        limit = ctx.conf.arrow_max_records_per_batch
        ltbl = _batches_to_table(self.children[0], pid, ctx)
        rtbl = _batches_to_table(self.children[1], pid, ctx)
        schema0 = to_arrow_schema(self.output_names, self.output_types)
        if w.worker_path_usable(ctx.conf, self.fn):
            with MetricTimer(self.metrics[OP_TIME]):
                tables, _ = w.pool_from_conf(ctx.conf).run(
                    w.task_cogrouped_map,
                    (self.fn, schema0, self.left_keys, self.right_keys),
                    [ltbl, rtbl])
            if not tables:
                return
            yield from _emit_table(self, tables[0], limit)
            return
        lgroups = dict(_group_pandas(ltbl, self.left_keys))
        rgroups = dict(_group_pandas(rtbl, self.right_keys))
        keys = sorted(set(lgroups) | set(rgroups),
                      key=lambda kv: tuple((k is None, k) for k in kv))
        schema = to_arrow_schema(self.output_names, self.output_types)
        with MetricTimer(self.metrics[OP_TIME]):
            outs = []
            for key in keys:
                lpdf = lgroups.get(key)
                rpdf = rgroups.get(key)
                if lpdf is None:
                    lpdf = ltbl.schema.empty_table().to_pandas()
                if rpdf is None:
                    rpdf = rtbl.schema.empty_table().to_pandas()
                res = self.fn(lpdf, rpdf)
                if len(res):
                    outs.append(_from_pandas(res, schema))
        if not outs:
            return
        yield from _emit_table(self, pa.concat_tables(outs), limit)
