"""Physical execution layer: operator base classes, metrics, transitions.

TPU-native analog of the reference's GpuExec contract
(ref: sql-plugin/.../GpuExec.scala:196 `doExecuteColumnar(): RDD[ColumnarBatch]`).

Execution model: a physical plan is a tree of `Exec` nodes.  Each node
declares a placement (TPU or CPU) decided by the overrides engine
(plan/overrides.py).  Data flows as iterators of batches per partition:

  * TPU-placed nodes stream `DeviceBatch` (JAX arrays, bucketed capacity);
    their compute is jit-compiled once per (schema, capacity) signature.
  * CPU-placed nodes stream the same batch structure backed by numpy —
    the CPU fallback engine runs identical operator semantics through the
    shared xp-parameterized kernels (playing the role Spark's own row/
    columnar operators play for the reference).
  * `HostToDeviceExec` / `DeviceToHostExec` transitions are inserted by the
    rewrite engine exactly like GpuRowToColumnarExec/GpuColumnarToRowExec
    (ref GpuTransitionOverrides.scala:48).
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn, batch_to_arrow, batch_to_device
from ..config import RapidsConf

Batch = DeviceBatch  # alias: same structure on both engines


# ---------------------------------------------------------------------------
# flight-recorder hooks (obs/tracer.py)
# ---------------------------------------------------------------------------
# The tracer is opt-in per query; with none installed every hook is one
# module-attribute read + a None check — cheap enough to sit on the
# per-partition (never per-row) paths.

_obs_mod = None


def _active_tracer():
    global _obs_mod
    if _obs_mod is None:
        from ..obs import tracer as _t
        _obs_mod = _t
    return _obs_mod.active_tracer()


# ---------------------------------------------------------------------------
# Process-level jit cache
# ---------------------------------------------------------------------------
# Every collect() builds fresh Exec instances, so per-instance caches
# (functools.cached_property) re-trace the whole operator every query —
# the round-1 engine was compile-bound, not compute-bound.  Instead, jitted
# operator functions live in ONE process-level table keyed by the op's
# semantic signature (operator kind + bound expression trees + input
# schema); a repeated query shape re-traces nothing.  The analog of the
# reference loading its CUDA kernels once per process, not per query.

_JIT_CACHE: Dict[tuple, object] = {}

# Live-executable budget.  Every compiled XLA:CPU executable keeps LLVM
# JIT code segments mapped (3 mappings per module; the thunk runtime
# emits MANY modules per program), and a long-lived process that compiles
# unboundedly walks into the kernel's vm.max_map_count — after which any
# native allocation segfaults.  The table is an LRU: evicting a jitted
# fn drops the executable and unmaps its code; a re-entry re-traces and
# (persistent cache permitting) reloads instead of recompiling.  The
# default keeps far more kernels live than any single query uses (a big
# fused program carries ~40 kernel modules ≈ 120 mappings, so ~192 live
# programs stay well inside the default 65530-map budget).  Override
# with SPARK_RAPIDS_TPU_JIT_CACHE_MAX for hosts with a raised
# vm.max_map_count or unusually many distinct query shapes per process.
import os as _os

_JIT_CACHE_MAX = int(_os.environ.get("SPARK_RAPIDS_TPU_JIT_CACHE_MAX",
                                     "192"))

_compileprof_mod = None


def _observatory():
    """The compile observatory (obs/compileprof.py): every build,
    hit and eviction at this seam is attributed, classified and
    persisted there.  Lazy module load, cached like the tracer hook."""
    global _compileprof_mod
    if _compileprof_mod is None:
        from ..obs import compileprof as _c
        _compileprof_mod = _c
    return _compileprof_mod.CompileObservatory.get()


def process_jit(key: tuple, make_fn):
    """Return the process-cached jitted function for `key`, building it
    with make_fn() (a 0-arg factory returning the python callable) on
    first use.  Per input-shape compilation under one entry is handled
    by the compile observatory's AOT proxy (or jax.jit's own cache when
    the observatory is disabled), so capacity buckets share one entry
    here.

    The active shim version joins the key: dialect-sensitive expressions
    (legacy stddev, lenient date cast) trace DIFFERENT computations per
    Spark version, and a cached kernel from one dialect must never serve
    another."""
    from ..shims import active_shim
    key = (active_shim().version,) + key
    f = _JIT_CACHE.get(key)
    if f is None:
        obs = _observatory()
        # warm-start tier first: a recipe replayed at session init (or
        # by `tools prewarm`) may have a dispatch-ready proxy staged
        # for this exact key — claim it instead of building
        f = obs.take_prewarmed(key)
        if f is None:
            f = obs.build(key, make_fn)
        while len(_JIT_CACHE) >= _JIT_CACHE_MAX:
            ekey = next(iter(_JIT_CACHE))
            # never evict silently: count it, ledger it, and remember
            # the evicted fingerprints so a rebuild classifies as
            # eviction_refault (thrash becomes visible, not weather)
            obs.note_eviction(ekey, _JIT_CACHE.pop(ekey))
        _JIT_CACHE[key] = f
        obs.note_cache_size(len(_JIT_CACHE))
    else:
        # move-to-end: LRU order rides dict insertion order
        _JIT_CACHE.pop(key)
        _JIT_CACHE[key] = f
        _observatory().note_hit(key)
    return f


def clear_jit_cache() -> None:
    _JIT_CACHE.clear()
    # a deliberate reset, not LRU pressure: programs become
    # non-resident (honest refault classification) without counting
    # evictions or arming the thrash warning
    try:
        _observatory().note_clear()
    except Exception:
        pass


def jit_cache_size() -> int:
    return len(_JIT_CACHE)


_SIG_ATOMS = (str, bytes, int, float, bool, type(None), complex)


def semantic_sig(v) -> object:
    """Canonical, hashable signature of a value that determines traced
    computation: expression trees walk (class, fields, children); types
    use their stable repr; containers recurse; arrays hash content.
    Objects without a stable identity fall back to their id() — that can
    only cause cache MISSES (fresh objects per query), never wrong hits."""
    if isinstance(v, _SIG_ATOMS):
        return v
    if isinstance(v, t.DataType):
        return repr(v)
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()
    if isinstance(v, np.dtype):
        return v.str
    if isinstance(v, (list, tuple)):
        return (type(v).__name__,) + tuple(semantic_sig(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, semantic_sig(x)) for k, x in v.items()))
    if isinstance(v, (set, frozenset)):
        return ("set",) + tuple(sorted(map(semantic_sig, v),
                                       key=repr))
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        if getattr(v, "nbytes", 0) > (1 << 20):
            return ("bigarr", np.dtype(v.dtype).str, v.shape, id(v))
        from ..columnar.fetch import fetch_array
        a = fetch_array(v)  # sanctioned single-transfer materialization
        return ("arr", a.dtype.str, a.shape, a.tobytes())
    if callable(v) and not hasattr(v, "children"):
        # user functions (UDFs): key by BYTECODE + captured VALUES
        # (closure cells, referenced globals, bound self), so a
        # re-created but identical lambda hits the cache (a fresh trace
        # costs minutes on a remote-compile TPU — round-2 verdict weak
        # #7).  Any captured value without a stable content signature
        # downgrades the whole function to identity keying: misses are
        # safe, wrong hits are not.
        sig = _function_sig(v)
        if sig is not None:
            return sig
        return ("callable", getattr(v, "__qualname__", ""), id(v))
    hook = getattr(v, "_semantic_sig_", None)
    if hook is not None:
        # nodes that key on less than their full field set (e.g.
        # ParamLiteral excludes its VALUE — the hoisted constant rides
        # in as a traced argument, so it must not fork the key space)
        return hook()
    try:
        fields = vars(v)
    except TypeError:
        return (type(v).__name__, id(v))
    return (type(v).__name__,) + tuple(
        (k, semantic_sig(x)) for k, x in sorted(fields.items())
        if not k.startswith("__"))




_SIG_SIMPLE = (str, bytes, int, float, bool, type(None), complex)

# distinct sentinel: None is a perfectly common captured VALUE
# (def f(x, y=None)) and must not read as "unsignable"
_UNSIGNABLE = object()


def _value_sig(x):
    """Content signature for a captured value, or _UNSIGNABLE when no
    stable one exists (unknown objects / huge arrays would alias)."""
    import types as _pytypes
    if isinstance(x, _SIG_SIMPLE):
        return ("v", x)
    if isinstance(x, (np.integer, np.floating, np.bool_)):
        return ("v", x.item())
    if isinstance(x, _pytypes.ModuleType):
        # module bindings are stable per process; key by name
        return ("module", x.__name__)
    if isinstance(x, _pytypes.CodeType):
        return _code_sig(x)
    if isinstance(x, (np.ndarray, jnp.ndarray)):
        if getattr(x, "nbytes", 0) > (1 << 16):
            return _UNSIGNABLE
        from ..columnar.fetch import fetch_array
        a = fetch_array(x)  # sanctioned single-transfer materialization
        return ("arr", a.dtype.str, a.shape, a.tobytes())
    if isinstance(x, (tuple, list)):
        parts = tuple(_value_sig(i) for i in x)
        return _UNSIGNABLE if any(p is _UNSIGNABLE for p in parts) \
            else (type(x).__name__,) + parts
    return _UNSIGNABLE


def _code_sig(code):
    """Recursive code-object signature: co_consts may hold NESTED code
    objects (inner lambdas/genexps) whose repr would embed memory
    addresses — recurse instead."""
    consts = tuple(_value_sig(c) for c in code.co_consts)
    if any(c is _UNSIGNABLE for c in consts):
        return _UNSIGNABLE
    return ("code", code.co_code, consts, code.co_names,
            code.co_varnames, code.co_freevars)


def _function_sig(fn):
    """Bytecode+captures signature of a plain function / bound method,
    or None if any capture lacks a stable signature."""
    self_sig = ()
    target = fn
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        s = _value_sig(bound_self)
        if s is _UNSIGNABLE:
            return None
        self_sig = ("self", s)
        target = fn.__func__
    code = getattr(target, "__code__", None)
    if code is None:
        return None
    csig = _code_sig(code)
    if csig is _UNSIGNABLE:
        return None
    captures = []
    cells = getattr(target, "__closure__", None)
    if cells:
        for c in cells:
            try:
                s = _value_sig(c.cell_contents)
            except ValueError:   # empty cell
                s = ("emptycell",)
            if s is _UNSIGNABLE:
                return None
            captures.append(s)
    gl = getattr(target, "__globals__", {})
    for name in code.co_names:
        if name in gl:
            s = _value_sig(gl[name])
            if s is _UNSIGNABLE:
                return None
            captures.append((name, s))
        else:
            captures.append((name, "builtin"))
    defaults = _value_sig(getattr(target, "__defaults__", None))
    kwdefaults = _value_sig(getattr(target, "__kwdefaults__", None))
    if defaults is _UNSIGNABLE or kwdefaults is _UNSIGNABLE:
        return None
    return ("pyfn", csig, tuple(captures), defaults, kwdefaults,
            self_sig)
def schema_sig(node: "Exec") -> tuple:
    return tuple(zip(node.output_names, map(repr, node.output_types)))


# metric verbosity levels (ref GpuExec.scala:32-45, conf
# spark.rapids.sql.metrics.level)
ESSENTIAL = "ESSENTIAL"
MODERATE = "MODERATE"
DEBUG = "DEBUG"
_LEVEL_ORDER = {ESSENTIAL: 0, MODERATE: 1, DEBUG: 2}


class Metric:
    """Operator metric (ref GpuMetric / GpuExec.scala:45-104).

    Accepts device scalars without forcing a sync: `add` stashes traced
    values and `value` resolves them only when the metric is read — the
    execution hot path must never block on the device for bookkeeping
    (each host<->device round trip costs ~tens of ms on a tunneled TPU)."""

    __slots__ = ("name", "_value", "level", "_pending")

    def __init__(self, name: str, level: str = MODERATE):
        self.name = name
        self._value = 0
        self.level = level
        self._pending: list = []

    @property
    def value(self):
        if self._pending:
            # resolve all deferred device scalars through the sanctioned
            # batched crossing (ONE transfer; a per-scalar fetch would
            # pay one tunnel round trip each)
            from ..columnar.fetch import fetch_ints
            self._value += sum(fetch_ints(self._pending))
            self._pending.clear()
        return self._value

    @value.setter
    def value(self, v):
        self._value = v
        self._pending.clear()

    def add(self, v):
        if isinstance(v, (int, float, np.integer, np.floating)):
            self._value += v
        else:
            self._pending.append(v)

    def __iadd__(self, v):
        self.add(v)
        return self


_device_timing_enabled = False


def set_device_timing(enabled: bool) -> None:
    """DEBUG metrics mode: each operator blocks on its own outputs so
    opTime records real device time per op instead of async dispatch time
    (the role NvtxWithMetrics plays for the reference,
    ref NvtxWithMetrics.scala:22-49).  Costs one device sync per operator
    per batch — diagnostics only, off for production runs."""
    global _device_timing_enabled
    _device_timing_enabled = enabled


def device_timing_enabled() -> bool:
    return _device_timing_enabled


def maybe_sync(out) -> None:
    """Under device-timing mode, block until `out`'s arrays are resolved.
    Call as the last statement inside a MetricTimer block.

    On tunneled platforms (axon) `block_until_ready` returns before the
    program executes, which would attribute every op's time to whichever
    later op fetches — so this also forces a one-element fetch, the only
    reliable execution barrier there.  Costs one tunnel round trip per
    op per batch; diagnostics mode only."""
    if _device_timing_enabled:
        leaves = [l for l in jax.tree_util.tree_leaves(out)
                  if isinstance(l, jax.Array)]
        # tpulint: allow[TPU-R001] this function IS the sanctioned sync:
        # device-timing diagnostics exist to pay the barrier on purpose
        jax.block_until_ready(leaves)
        if leaves:
            # tpulint: allow[TPU-R001] deliberate one-element fetch — the
            # only reliable execution barrier on tunneled platforms
            np.asarray(leaves[-1].ravel()[-1:])


_trace_annotations_enabled = False


def set_trace_annotations(enabled: bool) -> None:
    """Toggle jax.profiler trace annotations around timed operator work —
    the NVTX-range analog (ref NvtxWithMetrics.scala:22-49; ranges show
    up in the TensorBoard/XPlane trace viewer instead of Nsight)."""
    global _trace_annotations_enabled
    _trace_annotations_enabled = enabled


class MetricTimer:
    """Times a block into a metric; optionally also opens a profiler
    trace annotation of the same name (NvtxWithMetrics)."""

    def __init__(self, metric: Metric, name: Optional[str] = None):
        self.metric = metric
        self.name = name
        self._ann = None

    def __enter__(self):
        if _trace_annotations_enabled:
            from jax.profiler import TraceAnnotation
            # tpulint: allow[TPU-R006] MetricTimer IS the sanctioned
            # timing path; the annotation lives here so every operator
            # shares one NVTX-analog range implementation
            self._ann = TraceAnnotation(self.name or self.metric.name)
            self._ann.__enter__()
        # tpulint: allow[TPU-R006] the one sanctioned raw clock read
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        # tpulint: allow[TPU-R006] the one sanctioned raw clock read
        self.metric.add(time.perf_counter_ns() - self._t0)
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None


class SpeculativeSizingMiss(RuntimeError):
    """A deferred speculation guard came back false: some operator's
    capacity guess undershot and its output was truncated.  The session
    re-executes the query with speculation disabled (results built on a
    missed guess are never surfaced)."""


import itertools as _itertools

_CTX_IDS = _itertools.count()


class ExecContext:
    """Per-query execution context: conf + memory/semaphore hooks."""

    def __init__(self, conf: Optional[RapidsConf] = None):
        self.conf = conf or RapidsConf()
        self.task_context: Dict = {}
        # process-unique id: memo keys must never alias a recycled id()
        # of a dead context (e.g. IciExchangeExec's shard memo)
        self.uid = next(_CTX_IDS)
        # deferred speculation guards: device bool scalars that must ALL
        # be true for surfaced results to be valid.  They ride along with
        # the next batch fetch (zero extra round trips) and are verified
        # before data leaves the engine.
        self.spec_guards: List = []

    @property
    def speculation_enabled(self) -> bool:
        return not self.task_context.get("no_speculation", False)

    def add_spec_guard(self, guard) -> None:
        self.spec_guards.append(guard)

    def drain_spec_guards(self) -> List:
        g, self.spec_guards = self.spec_guards, []
        return g

    def verify_spec_guards(self) -> None:
        """Force any still-pending guards to host (one tiny transfer) and
        raise if any failed — the backstop for plans whose last fetch
        happened before the final guard was registered (e.g. early-exit
        limits)."""
        g = self.drain_spec_guards()
        if not g:
            return
        from ..columnar.fetch import fetch_ints
        vals = fetch_ints(g)  # one stacked transfer (columnar/fetch)
        failed = sum(1 for v in vals if not v)
        if failed:
            raise SpeculativeSizingMiss(
                f"{failed} speculation guard(s) failed")

    @property
    def capacity_buckets(self):
        return self.conf.capacity_buckets


CPU = "cpu"
TPU = "tpu"

# standard metric names (ref GpuExec.scala:45-104)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
OP_TIME = "opTime"


def _wrap_execute_partition(fn):
    """Route every operator's execute_partition through the flight
    recorder and the progress observatory: with a tracer installed the
    produced iterator is wrapped in a per-(operator, partition) span
    recording batches/rows/bytes and the exception on failure; with a
    progress handle bound to the thread the iterator also feeds the
    live view (partitions done, rows so far) and observes the
    cooperative cancel flag per batch.  The progress wrapper sits
    INSIDE the tracer wrapper so a cancel raised between batches
    propagates through trace_operator's error arm and closes the span
    immediately.  Without either, the original generator is returned
    untouched (two global reads per partition call)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, pid, ctx):
        from ..obs import progress as prog
        tr = _active_tracer()
        inner = fn(self, pid, ctx)
        handle = prog.current_handle()
        if handle is not None:
            inner = handle.observe_operator(self, pid, inner)
        if tr is None:
            return inner
        return tr.trace_operator(self, pid, inner)

    wrapper._obs_wrapped = True
    return wrapper


class Exec:
    """Base physical operator."""

    placement = CPU

    def __init_subclass__(cls, **kwargs):
        # every concrete operator's execute_partition gains the span
        # wrapper at class-creation time — one instrumentation point for
        # exec/, ops/, io/, shuffle/ and parallel/ alike, no per-
        # operator edits (the GpuExec-metrics-everywhere analog)
        super().__init_subclass__(**kwargs)
        fn = cls.__dict__.get("execute_partition")
        if fn is not None and not getattr(fn, "_obs_wrapped", False):
            cls.execute_partition = _wrap_execute_partition(fn)

    # Forced out-of-core budget (device bytes).  None = the operator's
    # normal in-core/out-of-core decision against the spill catalog's
    # budget; set by the TPU-L014 pre-flight repair
    # (analysis/lifetime.try_outofcore_repair) to bound the working set
    # of operators with a spill-managed fallback (sort, aggregate).
    oc_budget: Optional[int] = None

    def __init__(self, children: Sequence["Exec"]):
        self.children: List[Exec] = list(children)
        self.metrics: Dict[str, Metric] = {
            NUM_OUTPUT_ROWS: Metric(NUM_OUTPUT_ROWS, ESSENTIAL),
            NUM_OUTPUT_BATCHES: Metric(NUM_OUTPUT_BATCHES, MODERATE),
            OP_TIME: Metric(OP_TIME, MODERATE),
        }

    # -- schema -------------------------------------------------------------
    @property
    def output_names(self) -> List[str]:
        raise NotImplementedError

    @property
    def output_types(self) -> List[t.DataType]:
        raise NotImplementedError

    # -- partitioning --------------------------------------------------------
    @property
    def num_partitions(self) -> int:
        if self.children:
            return self.children[0].num_partitions
        return 1

    # -- interface requirements ----------------------------------------------
    def input_contracts(self):
        """Declared producer/consumer interface requirement for the
        flow-sensitive plan typechecker (analysis/interp.py): either
        None (no requirement beyond a bindable schema — the default) or
        an analysis.absdomain.Contract whose check() receives the
        children's inferred abstract states and returns violation
        strings.  Operators that assume a partitioning contract
        (colocated joins, FINAL-mode aggregates) override this; the
        interpreter enforces every declaration and the differential
        oracle (analysis/oracle.py) keeps the declarations honest
        against real execution."""
        return None

    def memory_effects(self, child_states, conf):
        """Declared device-memory behavior for the lifetime/peak pass
        (analysis/lifetime.py): either None (pure streaming — the
        working set is one output batch, nothing retained, no deferred
        handle protocol) or an analysis.lifetime.MemoryEffects.
        `child_states` are the children's inferred AbstractStates, so
        declarations can size themselves from the same cost model the
        CBO uses.  Operators that materialize (sort, aggregate, join
        builds), retain (pinned scans, exchange memos) or hand out
        catalog-registered handles (SpillBoundaryExec) override this;
        the runtime shadow ledger (memory/memsan.py) keeps the
        declarations honest against real execution."""
        return None

    def determinism(self):
        """Declared replay class for the determinism pass
        (analysis/determinism.py): either None (pure streaming — the
        output is a row-wise function of the input, indifferent to
        batch arrival order, wall clock and RNG: bit_exact) or an
        analysis.determinism.Determinism on the lattice
        bit_exact > order_stable > order_dependent > nondeterministic.
        Operators whose output row order or values follow batch
        arrival (hash aggregates, joins, unions), that select by input
        position (limits, offset-keyed sampling), or that run opaque
        user code (UDF boundaries) override this; the permuted-replay
        oracle (devtools/run_lint.py --dsan) keeps the declarations
        honest against real recomputation."""
        return None

    # -- statistics ----------------------------------------------------------
    def estimated_size_bytes(self) -> Optional[int]:
        """Rough output-size estimate for planning (broadcast decisions, CBO
        — the analog of Spark's logical-plan statistics the reference's
        broadcast threshold consults).  None = unknown."""
        sizes = [c.estimated_size_bytes() for c in self.children]
        if not sizes or any(s is None for s in sizes):
            return None
        return sum(sizes)

    # -- execution -----------------------------------------------------------
    def execute_partition(self, pid: int, ctx: ExecContext) -> Iterator[Batch]:
        """Produce batches for one partition.  Buffers are jnp arrays when
        self.placement == TPU, numpy arrays when CPU."""
        raise NotImplementedError

    def execute_collect(self, ctx: ExecContext) -> pa.Table:
        """Run all partitions and collect to an Arrow table (driver side).
        Each partition is a 'task': it holds the TPU semaphore while it
        runs (ref GpuSemaphore acquire/release around task device work)."""
        from ..memory.semaphore import TpuSemaphore
        from ..obs import progress as prog
        from ..obs.progress import (TpuQueryCancelled,
                                    TpuQueryDeadlineExceeded)
        sem = TpuSemaphore.get()
        out: List[pa.RecordBatch] = []
        for pid in range(self.num_partitions):
            # cooperative cancel checkpoint at the partition boundary:
            # nothing device-side is in flight here, so unwinding now
            # leaves only the release obligations the finally arms
            # below already discharge
            tok = prog.current_token()
            if tok is not None:
                if tok.cancelled:
                    raise TpuQueryCancelled(
                        tok.describe("partition", self.name),
                        query_id=tok.query_id, operator=self.name,
                        checkpoint="partition", cause=tok.cause)
                if tok.deadline_exceeded:
                    raise TpuQueryDeadlineExceeded(
                        tok.describe("partition", self.name),
                        query_id=tok.query_id, operator=self.name,
                        checkpoint="partition")
            sem.acquire_if_necessary(pid)
            try:
                for b in self.execute_partition(pid, ctx):
                    rb = to_host_batch(b, self.output_names)
                    if rb.num_rows:
                        out.append(rb)
            finally:
                sem.release_if_necessary(pid)
        ctx.verify_spec_guards()
        from ..columnar.interop import to_arrow_schema
        schema = to_arrow_schema(self.output_names, self.output_types)
        if not out:
            return schema.empty_table()
        return pa.Table.from_batches([b.cast(schema) for b in out])

    # -- display ------------------------------------------------------------
    @property
    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, level: int = 0) -> str:
        pad = "  " * level
        mark = "*" if self.placement == TPU else " "
        lines = [f"{pad}{mark}{self.describe()}"]
        for c in self.children:
            lines.append(c.tree_string(level + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name

    def with_new_children(self, children: Sequence["Exec"]) -> "Exec":
        import copy
        c = copy.copy(self)
        c.children = list(children)
        c.metrics = {k: Metric(k, m.level) for k, m in self.metrics.items()}
        return c

    def transform_up(self, fn):
        node = self
        new_children = [c.transform_up(fn) for c in self.children]
        if any(a is not b for a, b in zip(new_children, node.children)):
            node = node.with_new_children(new_children)
        return fn(node)

    def foreach(self, fn):
        fn(self)
        for c in self.children:
            c.foreach(fn)

    @property
    def xp(self):
        return jnp if self.placement == TPU else np


def to_host_batch(b: Batch, names: Sequence[str]) -> pa.RecordBatch:
    """Device/host batch -> Arrow."""
    nb = DeviceBatch(b.columns, b.num_rows, names)
    return batch_to_arrow(nb)


# ---------------------------------------------------------------------------
# Transitions (ref GpuRowToColumnarExec / GpuColumnarToRowExec)
# ---------------------------------------------------------------------------

class HostToDeviceExec(Exec):
    """Move a CPU child's batches onto the TPU (analog of
    GpuRowToColumnarExec + HostColumnarToGpu, ref GpuRowToColumnarExec.scala:830)."""

    placement = TPU

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def execute_partition(self, pid, ctx):
        for b in self.children[0].execute_partition(pid, ctx):
            with MetricTimer(self.metrics[OP_TIME]):
                yield jax.tree_util.tree_map(jnp.asarray, b)


class DeviceToHostExec(Exec):
    """Bring TPU batches back to host numpy (analog of GpuColumnarToRowExec,
    ref GpuColumnarToRowExec.scala:358)."""

    placement = CPU

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def execute_partition(self, pid, ctx):
        from ..columnar.fetch import fetch_batch
        for b in self.children[0].execute_partition(pid, ctx):
            with MetricTimer(self.metrics[OP_TIME]):
                guards = ctx.drain_spec_guards()
                if guards:
                    # speculation guards ride the batch's own sizes fetch
                    # — verification costs zero extra round trips
                    out, gvals = fetch_batch(b, extra_scalars=guards)
                    if not all(int(v) for v in gvals):
                        raise SpeculativeSizingMiss(
                            "join capacity guess undershot")
                else:
                    out = fetch_batch(b)
                self.metrics[NUM_OUTPUT_ROWS] += int(out.num_rows)
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out


def drain_plan_metrics(root: "Exec") -> None:
    """Resolve every pending device scalar of every metric in the plan
    through ONE columnar/fetch.fetch_ints crossing.  Reading each
    Metric.value individually pays one tunnel round trip per metric
    that accumulated device scalars; draining plan-wide first makes a
    full metrics_report cost a single transfer."""
    pending: List[Metric] = []

    def visit(node: "Exec"):
        for m in node.metrics.values():
            if m._pending:
                pending.append(m)

    root.foreach(visit)
    if not pending:
        return
    from ..columnar.fetch import fetch_ints
    vals = iter(fetch_ints([v for m in pending for v in m._pending]))
    for m in pending:
        m._value += sum(next(vals) for _ in m._pending)
        m._pending.clear()


def metrics_report(root: "Exec", level: str = MODERATE) -> List[Tuple[str, str, int]]:
    """Collect (operator, metric, value) at or below the verbosity level
    (ref GpuExec metrics levels feeding the Spark SQL UI)."""
    drain_plan_metrics(root)  # all deferred scalars: ONE device crossing
    out: List[Tuple[str, str, int]] = []
    cutoff = _LEVEL_ORDER[level]

    def visit(node: "Exec"):
        for m in node.metrics.values():
            if _LEVEL_ORDER[m.level] <= cutoff:
                out.append((type(node).__name__, m.name, m.value))

    root.foreach(visit)
    return out
