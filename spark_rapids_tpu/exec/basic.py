"""Basic physical operators: scan, project, filter, range, union, limits,
sample, coalesce-batches.

Ref: sql-plugin/.../basicPhysicalOperators.scala:140-592 (GpuProjectExec,
GpuFilterExec, GpuRangeExec, GpuUnionExec), limit.scala, GpuCoalesceBatches.

TPU realization: Project/Filter trace their whole expression tree into one
jitted function per (schema, capacity) signature — XLA fuses every
elementwise op into a handful of kernels, where the reference pays one JNI
kernel launch per expression node.
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import (DEFAULT_ROW_BUCKETS, DeviceBatch, DeviceColumn,
                               batch_to_device, bucket_for)
from ..expr.core import (EvalContext, Expression, bind_expression,
                         output_name)
from ..ops.gather import gather_batch
from .base import (CPU, NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU,
                   Batch, Exec, ExecContext, MetricTimer, maybe_sync,
                   process_jit, schema_sig, semantic_sig)


class LocalScanExec(Exec):
    """Scan over in-memory Arrow data split into partitions
    (analog of Spark's LocalTableScanExec feeding the plugin)."""

    def __init__(self, table: pa.Table, num_partitions: int = 1,
                 batch_rows: Optional[int] = None,
                 pin_cache: Optional[dict] = None):
        super().__init__([])
        self.table = table
        self._names = list(table.schema.names)
        from ..columnar.interop import from_arrow_type
        self._types = [from_arrow_type(f.type) for f in table.schema]
        self._num_partitions = max(1, num_partitions)
        self.batch_rows = batch_rows
        # upload pin cache owned by the logical LocalRelation node: keeps
        # device batches resident across collects so a cached DataFrame
        # never re-uploads (round-2 probe: re-upload was ~9% of q1's time
        # and forced an extra pipeline stall per query)
        self.pin_cache = pin_cache

    @property
    def output_names(self):
        return self._names

    @property
    def output_types(self):
        return self._types

    @property
    def num_partitions(self):
        return self._num_partitions

    def estimated_size_bytes(self):
        return self.table.nbytes

    def memory_effects(self, child_states, conf):
        """A device-placed scan with a pin cache keeps every uploaded
        batch HBM-resident across collects — sanctioned retention
        (evicted first under pressure), but real peak bytes."""
        from .. import config as cfg
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes,
                                         total_bytes)
        from .base import TPU as _TPU
        if self.pin_cache is None or self.placement != _TPU or \
                not conf.get(cfg.SCAN_PIN_DEVICE):
            return None
        from ..analysis.absdomain import AbstractState
        st = AbstractState(self._names, self._types,
                           rows=float(self.table.num_rows),
                           num_partitions=self._num_partitions)
        return MemoryEffects(hold=padded_partition_bytes(st),
                             retained=total_bytes(st),
                             note="pinned scan cache")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from .. import config as cfg
        key = (pid, self._num_partitions, self.batch_rows,
               self.placement)
        pin = self.pin_cache if (self.pin_cache is not None and
                                 ctx.conf.get(cfg.SCAN_PIN_DEVICE)) else None
        if pin is not None and key in pin:
            for b in pin[key]:
                # scan batches always carry a concrete row count
                self.metrics[NUM_OUTPUT_ROWS] += int(b.num_rows)
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield b
            return
        produced: List[Batch] = []
        for b in self._produce_partition(pid, ctx):
            if pin is not None:
                produced.append(b)
            yield b
        if pin is not None:
            pin[key] = produced
            if self.placement == TPU:
                # account pinned HBM against the spill budget; under
                # pressure the catalog evicts this entry (re-upload on
                # next miss).  CPU-engine pins are host numpy — cached
                # for conversion cost only, no HBM accounting.
                from ..memory.spill import SpillCatalog
                SpillCatalog.get().register_pinned(pin, key, produced)

    def _produce_partition(self, pid, ctx) -> Iterator[Batch]:
        n = self.table.num_rows
        per = -(-n // self._num_partitions)
        start = min(pid * per, n)
        length = min(per, n - start)
        chunk = self.table.slice(start, length)
        rows = self.batch_rows or max(length, 1)
        xp = self.xp
        offset = 0
        combined = chunk.combine_chunks()
        while offset < max(length, 1):
            piece = combined.slice(offset, min(rows, length - offset))
            rb = piece.to_batches()
            if rb:
                b = batch_to_device(pa.Table.from_batches(rb).combine_chunks()
                                    .to_batches()[0], xp=xp)
            else:
                b = batch_to_device(
                    pa.RecordBatch.from_pydict(
                        {n_: pa.array([], type=f.type)
                         for n_, f in zip(self._names, self.table.schema)}),
                    xp=xp)
            self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield b
            offset += rows
            if length == 0:
                break


class ProjectExec(Exec):
    """Columnar projection (ref GpuProjectExec, basicPhysicalOperators.scala:140)."""

    def __init__(self, exprs: Sequence[Expression], child: Exec):
        super().__init__([child])
        self.exprs = list(exprs)
        bound = [bind_expression(e, child.output_names,
                                 child.output_types)
                 for e in self.exprs]
        # hoist eligible constants to ParamLiteral slots: the jit key
        # drops the values, two projections differing only in literals
        # share one program (expr/params.py has the safety rules)
        from ..expr.params import parameterize_exprs
        self._bound, self._params = parameterize_exprs(bound)

    @property
    def output_names(self):
        return [output_name(e) for e in self.exprs]

    @property
    def output_types(self):
        return [b.data_type() for b in self._bound]

    def describe(self):
        return f"Project [{', '.join(e.sql() for e in self.exprs)}]"

    def _compute(self, xp, batch: Batch, row_base=0, params=None) -> Batch:
        ctx = EvalContext(xp, batch, row_base=row_base,
                          params=params if params is not None
                          else (self._params or None))
        cols = []
        for b in self._bound:
            v = b.eval(ctx)
            from ..expr.core import ColumnValue, ScalarValue
            if isinstance(v, ScalarValue):
                from ..expr.core import make_column
                v = make_column(ctx, b.data_type() if not isinstance(
                    b.data_type(), t.NullType) else t.NULL,
                    v.value if v.value is not None else 0,
                    None if v.value is not None else False)
            cols.append(v.col)
        return DeviceBatch(cols, batch.num_rows, self.output_names)

    @functools.cached_property
    def _jit_key(self):
        return ("ProjectExec", schema_sig(self.children[0]),
                tuple(self.output_names), semantic_sig(self._bound))

    @property
    def _jitted(self):
        if self._params:
            # params ride as traced scalar args: the value-free key is
            # only valid because the closure receives them at call time
            fn = process_jit(
                self._jit_key,
                lambda: lambda b, ps: self._compute(jnp, b, params=ps))
            return lambda b: fn(b, self._params)
        return process_jit(self._jit_key,
                           lambda: lambda b: self._compute(jnp, b))

    @property
    def _jitted_rowpos(self):
        if self._params:
            fn = process_jit(
                self._jit_key + ("rowpos",),
                lambda: lambda b, base, ps: self._compute(jnp, b, base,
                                                          params=ps))
            return lambda b, base: fn(b, base, self._params)
        return process_jit(self._jit_key + ("rowpos",),
                           lambda: lambda b, base: self._compute(jnp, b,
                                                                 base))

    @functools.cached_property
    def _needs_rowpos(self):
        return _exprs_need_rowpos(self._bound)

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        offset = 0
        for b in self.children[0].execute_partition(pid, ctx):
            with MetricTimer(self.metrics[OP_TIME]):
                if self._needs_rowpos:
                    base = (pid << 33) + offset
                    out = self._jitted_rowpos(b, jnp.int64(base)) \
                        if self.placement == TPU \
                        else self._compute(np, b, base)
                else:
                    out = self._jitted(b) if self.placement == TPU \
                        else self._compute(np, b)
                maybe_sync(out)
            if self._needs_rowpos:
                offset += int(b.num_rows)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield out


def _exprs_need_rowpos(bound_exprs) -> bool:
    """True when any expression depends on (partition, row-position)
    context — monotonically_increasing_id / spark_partition_id / rand."""
    from ..expr.hashfns import (MonotonicallyIncreasingID, Rand,
                                SparkPartitionID)
    kinds = (MonotonicallyIncreasingID, Rand, SparkPartitionID)
    for b in bound_exprs:
        if b.collect(lambda e: isinstance(e, kinds)):
            return True
    return False


class FilterExec(Exec):
    """Columnar filter with device-side compaction
    (ref GpuFilterExec, basicPhysicalOperators.scala:220).

    Compaction keeps static shapes: a stable argsort on the keep flag moves
    surviving rows to the front; num_rows shrinks to the survivor count."""

    def __init__(self, condition: Expression, child: Exec):
        super().__init__([child])
        self.condition = condition
        bound = bind_expression(condition, child.output_names,
                                child.output_types)
        from ..expr.params import parameterize_exprs
        trees, self._params = parameterize_exprs([bound])
        self._bound = trees[0]
        # armed by the TPU-L018 pre-flight repair
        # (analysis/hloaudit.try_rebucket_repair): shrink compacted
        # output to this bucket under a deferred speculation guard
        self.rebucket_cap: Optional[int] = None

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def describe(self):
        return f"Filter [{self.condition.sql()}]"

    def _compute(self, xp, batch: Batch, row_base=0, params=None) -> Batch:
        ctx = EvalContext(xp, batch, row_base=row_base,
                          params=params if params is not None
                          else (self._params or None))
        pred = self._bound.eval(ctx)
        from .filter_common import apply_filter
        return apply_filter(xp, batch, pred, self.output_names)

    @functools.cached_property
    def _jit_key(self):
        return ("FilterExec", schema_sig(self.children[0]),
                semantic_sig(self._bound))

    @property
    def _jitted(self):
        if self._params:
            fn = process_jit(
                self._jit_key,
                lambda: lambda b, ps: self._compute(jnp, b, params=ps))
            return lambda b: fn(b, self._params)
        return process_jit(self._jit_key,
                           lambda: lambda b: self._compute(jnp, b))

    @property
    def _jitted_rowpos(self):
        if self._params:
            fn = process_jit(
                self._jit_key + ("rowpos",),
                lambda: lambda b, base, ps: self._compute(jnp, b, base,
                                                          params=ps))
            return lambda b, base: fn(b, base, self._params)
        return process_jit(self._jit_key + ("rowpos",),
                           lambda: lambda b, base: self._compute(jnp, b,
                                                                 base))

    @functools.cached_property
    def _needs_rowpos(self):
        return _exprs_need_rowpos([self._bound])

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        offset = 0
        for b in self.children[0].execute_partition(pid, ctx):
            with MetricTimer(self.metrics[OP_TIME]):
                if self._needs_rowpos:
                    base = (pid << 33) + offset
                    out = self._jitted_rowpos(b, jnp.int64(base)) \
                        if self.placement == TPU \
                        else self._compute(np, b, base)
                else:
                    out = self._jitted(b) if self.placement == TPU \
                        else self._compute(np, b)
                cap = self.rebucket_cap
                if (cap is not None and self.placement == TPU and
                        ctx.speculation_enabled and cap < out.capacity):
                    # speculative re-bucket (TPU-L018 repair): survivors
                    # are compacted to the front, so slicing to the
                    # right-sized bucket is exact whenever the guard
                    # holds; a missed guess re-executes the query with
                    # speculation disabled before results surface
                    from ..columnar.device import shrink_batch
                    ctx.add_spec_guard(out.num_rows <= cap)
                    out = shrink_batch(out, cap)
                maybe_sync(out)
            if self._needs_rowpos:
                offset += int(b.num_rows)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield out


class RangeExec(Exec):
    """range(start, end, step) table generator (ref GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1, name: str = "id",
                 max_batch_rows: int = 1 << 20):
        super().__init__([])
        assert step != 0
        self.start, self.end, self.step = start, end, step
        self._name = name
        self._num_partitions = num_partitions
        self.max_batch_rows = max_batch_rows

    @property
    def output_names(self):
        return [self._name]

    @property
    def output_types(self):
        return [t.LONG]

    @property
    def num_partitions(self):
        return self._num_partitions

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        xp = self.xp
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self._num_partitions)
        lo = min(pid * per, total)
        hi = min(lo + per, total)
        i = lo
        while i < hi:
            n = min(self.max_batch_rows, hi - i)
            cap = bucket_for(n, DEFAULT_ROW_BUCKETS)
            vals = (xp.arange(cap, dtype=xp.int64) + np.int64(i)) * \
                np.int64(self.step) + np.int64(self.start)
            col = DeviceColumn(t.LONG, data=vals,
                               validity=xp.arange(cap) < n)
            b = DeviceBatch([col], n, [self._name])
            self.metrics[NUM_OUTPUT_ROWS] += n
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield b
            i += n
        if lo >= hi:
            return


class UnionExec(Exec):
    """Concatenation of children's partitions (ref GpuUnionExec)."""

    def __init__(self, children: Sequence[Exec]):
        super().__init__(children)

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    @property
    def num_partitions(self):
        return sum(c.num_partitions for c in self.children)

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "union interleaves child partitions: output "
            "row order follows child emission, content multiset is "
            "invariant")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        for c in self.children:
            if pid < c.num_partitions:
                yield from c.execute_partition(pid, ctx)
                return
            pid -= c.num_partitions


class LocalLimitExec(Exec):
    """Per-partition limit (ref limit.scala GpuLocalLimitExec)."""

    def __init__(self, limit: int, child: Exec):
        super().__init__([child])
        self.limit = limit

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def determinism(self):
        from ..analysis.determinism import BIT_EXACT, Determinism
        return Determinism(
            BIT_EXACT, "limit selects the first rows by input "
            "position", order_sensitive_selection=True)

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        remaining = self.limit
        xp = self.xp
        for b in self.children[0].execute_partition(pid, ctx):
            n = int(b.num_rows)
            take = min(n, remaining)
            if take < n:
                mask = xp.arange(b.capacity) < take
                cols = [DeviceColumn(c.dtype, data=c.data,
                                     validity=(c.validity & mask)
                                     if c.validity is not None else mask,
                                     offsets=c.offsets, data_hi=c.data_hi,
                                     children=c.children)
                        for c in b.columns]
                b = DeviceBatch(cols, take, b.names)
            remaining -= take
            yield b
            if remaining <= 0:
                return


class GlobalLimitExec(LocalLimitExec):
    """Whole-result limit; planner ensures single partition upstream."""


class SampleExec(Exec):
    """Bernoulli sampling (ref GpuSampleExec in basicPhysicalOperators).

    Deterministic: the keep decision hashes (seed, partition, global row
    index) with a splitmix-style mixer, so CPU and TPU engines sample the
    same rows — the property the differential harness relies on, the way
    Spark ties sampling to (seed, partitionId)."""

    def __init__(self, fraction: float, seed: int, child: Exec):
        super().__init__([child])
        assert 0.0 <= fraction <= 1.0
        self.fraction = float(fraction)
        self.seed = int(seed) & 0xFFFFFFFF

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def describe(self):
        return f"Sample fraction={self.fraction} seed={self.seed}"

    def determinism(self):
        from ..analysis.determinism import BIT_EXACT, Determinism
        return Determinism(
            BIT_EXACT, "seeded hash of (seed, partition, global row "
            "index): the keep decision follows the running row offset, "
            "i.e. input arrival order", order_sensitive_selection=True)

    def _keep_mask(self, xp, cap: int, row_offset: int, pid: int):
        idx = (xp.arange(cap, dtype=np.uint32) + np.uint32(row_offset))
        h = idx ^ np.uint32(self.seed * 0x9E3779B9 + pid * 0x85EBCA6B
                            & 0xFFFFFFFF)
        h = (h ^ (h >> 16)) * np.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * np.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        return (h & np.uint32(0xFFFFFF)).astype(np.float64) / float(1 << 24) \
            < self.fraction

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from .filter_common import compact
        xp = self.xp
        row_offset = 0
        for b in self.children[0].execute_partition(pid, ctx):
            with MetricTimer(self.metrics[OP_TIME]):
                keep = self._keep_mask(xp, b.capacity, row_offset, pid)
                live = b.row_mask()
                out = compact(xp, b, keep & live, self.output_names)
                maybe_sync(out)
            row_offset += int(b.num_rows)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield out


class CoalesceBatchesExec(Exec):
    """Concatenate small batches up to a target size goal
    (ref GpuCoalesceBatches.scala:519, CoalesceGoal)."""

    def __init__(self, child: Exec, target_rows: Optional[int] = None,
                 require_single_batch: bool = False):
        super().__init__([child])
        self.target_rows = target_rows
        self.require_single_batch = require_single_batch

    def memory_effects(self, child_states, conf):
        """Accumulates raw pending batches up to the target before each
        concat: the pending set plus its concatenated copy coexist."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes)
        if not child_states:
            return None
        return MemoryEffects(
            hold=2.0 * padded_partition_bytes(child_states[0]),
            note="raw pending concat")

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "re-batches in arrival order: batch "
            "boundaries follow arrival, row multiset is invariant")

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from .concat import concat_batches
        xp = self.xp
        pending: List[Batch] = []
        pending_rows = 0
        target = self.target_rows or (1 << 22)
        for b in self.children[0].execute_partition(pid, ctx):
            if isinstance(b.num_rows, (int, np.integer)):
                n = int(b.num_rows)
                if n == 0:
                    continue
            else:
                # device-resident row count (jitted producer / speculative
                # join): forcing it to host costs a tunnel round trip per
                # batch — account by capacity and keep the pipeline async
                n = b.capacity
            pending.append(b)
            pending_rows += n
            if not self.require_single_batch and pending_rows >= target:
                yield pending[0] if len(pending) == 1 else \
                    concat_batches(xp, pending, self.output_names,
                                   self.output_types)
                pending, pending_rows = [], 0
        if pending:
            yield pending[0] if len(pending) == 1 else \
                concat_batches(xp, pending, self.output_names,
                               self.output_types)
