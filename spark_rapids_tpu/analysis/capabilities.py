"""Machine-readable kernel capability table + gate cross-checker.

Round-5's biggest correctness class was planning-time gates admitting a
plan the runtime then crashed on: ``parallel/alltoall.py``'s
``exchange_supported`` admitted array/map aggregate buffers that
``allgather_batch`` raises ``NotImplementedError`` on mid-query.  The
root cause is structural — the admission predicate and the kernel's
dtype branches live far apart and drift independently.

This module closes that gap: every collective kernel in ``parallel/``
(and, as they grow capability-sensitive branches, the kernels in
``ops/``) registers a ``KernelCapability`` whose ``supports(dtype)``
mirrors the kernel's ACTUAL branch structure (the branch that raises is
the branch that returns False here).  ``verify_gates()`` then probes
every planning-time admission gate against the kernel it guards over a
representative dtype catalog: a gate that admits a dtype its kernel
raises on is a lint error (TPU-R004 in the repo lint; the plan lint's
TPU-L001 is the same check specialized to a concrete plan).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import types as t

# ---------------------------------------------------------------------------
# representative dtype catalog
# ---------------------------------------------------------------------------
# One probe per structurally-distinct dtype shape the engine models.  A
# gate/kernel mismatch on ANY real schema is a mismatch on one of these:
# the kernels branch on type STRUCTURE (flat / span / struct / nesting),
# never on widths beyond the flat/64-bit split the flat probes cover.

PROBE_TYPES: List[t.DataType] = [
    t.BOOLEAN, t.INT, t.LONG, t.DOUBLE, t.DATE, t.TIMESTAMP,
    t.DecimalType(18, 2), t.DecimalType(38, 2),
    t.STRING, t.BINARY,
    t.ArrayType(t.INT), t.ArrayType(t.STRING),
    t.ArrayType(t.ArrayType(t.INT)),
    t.MapType(t.INT, t.LONG), t.MapType(t.INT, t.STRING),
    t.StructType([t.StructField("f", t.INT)]),
    t.StructType([t.StructField("s", t.STRING)]),
    t.StructType([t.StructField("a", t.ArrayType(t.INT))]),
]


def _is_flat(dt: t.DataType) -> bool:
    return not isinstance(dt, (t.StringType, t.BinaryType, t.ArrayType,
                               t.MapType, t.StructType))


class KernelCapability:
    """Dtype coverage of one runtime kernel, mirroring its branch
    structure.  `supports(dt)` is True exactly when the kernel carries a
    column of that type without raising."""

    def __init__(self, name: str, module: str, doc: str,
                 supports: Callable[[t.DataType], bool]):
        self.name = name
        self.module = module
        self.doc = " ".join(doc.split())
        self.supports = supports

    def unsupported(self, dtypes: Sequence[t.DataType]) -> List[t.DataType]:
        return [dt for dt in dtypes if not self.supports(dt)]


# --- parallel/alltoall.py: exchange_by_pid -------------------------------
# move(): flat lanes ride directly; strings/binaries via the span packer;
# structs recurse per field; arrays/maps of FLAT elements via
# _flat_child_lanes (nested span elements raise NotImplementedError).

def _exchange_by_pid_supports(dt: t.DataType) -> bool:
    if isinstance(dt, (t.StringType, t.BinaryType)):
        return True
    if isinstance(dt, t.StructType):
        return all(_exchange_by_pid_supports(f.data_type)
                   for f in dt.fields)
    if isinstance(dt, t.ArrayType):
        return _is_flat(dt.element_type)
    if isinstance(dt, t.MapType):
        return _is_flat(dt.key_type) and _is_flat(dt.value_type)
    return True


# --- parallel/alltoall.py: allgather_batch -------------------------------
# gather_col(): flat lanes and strings/binaries ride; structs recurse;
# arrays/maps raise NotImplementedError unconditionally (the span
# receive layout is only implemented for exchange_by_pid).

def _allgather_batch_supports(dt: t.DataType) -> bool:
    if isinstance(dt, (t.ArrayType, t.MapType)):
        return False
    if isinstance(dt, t.StructType):
        return all(_allgather_batch_supports(f.data_type)
                   for f in dt.fields)
    return True


CAPABILITIES: Dict[str, KernelCapability] = {}


def _register(cap: KernelCapability) -> KernelCapability:
    CAPABILITIES[cap.name] = cap
    return cap


EXCHANGE_BY_PID = _register(KernelCapability(
    "exchange_by_pid", "spark_rapids_tpu/parallel/alltoall.py",
    "ICI all_to_all row redistribution: flat lanes, strings/binaries, "
    "structs of carried types, arrays/maps of flat elements.",
    _exchange_by_pid_supports))

ALLGATHER_BATCH = _register(KernelCapability(
    "allgather_batch", "spark_rapids_tpu/parallel/alltoall.py",
    "ICI replication (broadcast analog): flat lanes, strings/binaries, "
    "structs of carried types; NO arrays/maps (span receive layout not "
    "implemented for the gather path).",
    _allgather_batch_supports))


# ---------------------------------------------------------------------------
# gate cross-check
# ---------------------------------------------------------------------------

# a planning gate takes a dtype list and returns a fallback reason string
# (None = admitted), the exchange_supported convention
GateFn = Callable[[Sequence[t.DataType]], Optional[str]]


def gate_weaker_than_kernel(gate: GateFn, kernel: KernelCapability,
                            probes: Optional[Sequence[t.DataType]] = None
                            ) -> List[t.DataType]:
    """Dtypes the gate ADMITS but the kernel RAISES on — each one is a
    plan shape that passes planning and crashes mid-query.  Empty list =
    the gate is provably no weaker than the kernel over the catalog."""
    out = []
    for dt in (probes if probes is not None else PROBE_TYPES):
        if gate([dt]) is None and not kernel.supports(dt):
            out.append(dt)
    return out


def registered_gates() -> List[Tuple[str, GateFn, KernelCapability]]:
    """Every planning-time admission gate paired with the kernel whose
    coverage it promises.  New gates MUST register here — TPU-R004 fails
    the repo lint when a listed gate drifts weaker than its kernel."""
    from ..parallel.alltoall import allgather_supported, exchange_supported

    def ungrouped_aggregate_gate(dtypes) -> Optional[str]:
        # DistributedAggregate's construction gate for the ungrouped
        # (replicate) path: exchange admission AND allgather admission
        return exchange_supported(dtypes) or allgather_supported(dtypes)

    return [
        ("parallel.exchange_supported", exchange_supported,
         EXCHANGE_BY_PID),
        ("parallel.DistributedAggregate[ungrouped]",
         ungrouped_aggregate_gate, ALLGATHER_BATCH),
    ]


def verify_gates() -> List[Tuple[str, str, t.DataType]]:
    """Cross-check every registered gate: returns (gate, kernel, dtype)
    mismatches.  Empty = all planning admissions are runtime-safe."""
    out = []
    for name, gate, kernel in registered_gates():
        for dt in gate_weaker_than_kernel(gate, kernel):
            out.append((name, kernel.name, dt))
    return out


# ---------------------------------------------------------------------------
# device-kernel table (TPU-R017)
# ---------------------------------------------------------------------------
# The xp-parameterization convention keeps exec// ops/ backend-agnostic:
# kernels take `xp` and run identically on numpy for the host path.  The
# few entry points that NEED a jax-only primitive (today: lax.sort's
# multi-operand stable sort, which numpy has no analogue for — the host
# path branches around it) register here so the tpuxsan repo rule
# (TPU-R017, analysis/hloaudit.py) can tell a sanctioned kernel from an
# accidental bypass.  Keys are package-relative paths; values map the
# entry-point function name to the one-line reason it is device-only.
# Nested helpers inside a registered entry point are covered by it.

DEVICE_KERNELS: Dict[str, Dict[str, str]] = {
    "ops/carry.py": {
        "sort_rows": "multi-operand stable carry sort (lax.sort); host "
                     "path uses np.argsort + gather instead",
        "_sort_rows_lean": "compile-lean variant of sort_rows sharing "
                           "one lax.sort across key widths",
    },
    "ops/join_kernels.py": {
        "count_matches": "sort-based hash-match counting rides "
                         "lax.sort's multi-operand form",
    },
    "ops/segmented.py": {
        "lexsort": "multi-word lexicographic sort is lax.sort's "
                   "is_stable multi-operand mode",
    },
}


def device_kernel_functions(relpath: str) -> frozenset:
    """Sanctioned jnp/lax-calling entry points for one module, by
    package-relative path.  Empty for modules with no registration —
    every raw call there is a TPU-R017 finding."""
    return frozenset(DEVICE_KERNELS.get(relpath, ()))
