"""tmsan static side: buffer-lifetime + peak-HBM analysis over the Exec IR.

The reference plugin's single biggest operational failure class is
accelerator OOM and leaked/mis-tiered device buffers; RMM plus the
Arm.scala RAII discipline manage it at runtime.  Our ``memory/spill.py``
(SpillCatalog budgets, SpillableBatch lifecycle) and ``native/arena.py``
reproduce that role with zero *static* coverage — the typechecker built
in PRs 1-2 reasons about schema, residency and partitioning but is blind
to allocation lifetime and peak HBM.  This module closes that gap with
two artifacts sharing ONE source of truth:

  * the **ownership lattice** — per-buffer lifecycle states
    (allocated -> registered-spillable -> pinned -> spilled -> closed)
    and the legal-transition relation ``LIFECYCLE``.  The static pass
    checks declared operator protocols against it, and the runtime
    shadow ledger (``memory/memsan.py``) asserts the SAME relation on
    every real alloc/register/pin/spill/unspill/close event, so the
    machine can never drift from the engine (the
    ``capabilities.verify_gates()`` discipline applied to memory);

  * the **peak-device-bytes bound** — a bottom-up pass deriving, for
    every subtree, a conservative bound on simultaneously-live device
    bytes from the SAME row model the cost-based optimizer and
    L010/L012 already use (``plan/cost.estimate_rows`` via the
    interpreter's AbstractStates), widened by the engine's real batch
    padding (capacity buckets, validity lanes, span-buffer minimums).

Operators DECLARE their memory behavior via ``Exec.memory_effects()``
(the ``input_contracts()`` pattern): how many device bytes they hold
while streaming, what they retain after (pinned scans, exchange memos),
and whether they hand out catalog-registered *handles* whose close is
deferred to a declared consumer count.  Three rules evaluate the
declarations:

  TPU-L013  use-after-close / use-while-spilled hazard along some
            execution path: a handle-producing subtree is consumed by
            MORE parents than its declared consumer count — the extra
            consumer reads handles the last declared consumer already
            closed (the stale-rewrite sharing class, L009's sibling).
  TPU-L014  subtree peak-device-bytes bound exceeds the configured HBM
            budget (spark.rapids.tpu.memsan.hbmBudgetBytes): the OOM is
            predicted at plan time.  Repairable — the pre-flight either
            forces the operator's out-of-core path
            (``try_outofcore_repair``, exec/outofcore.py) or downgrades
            the subtree to host like L006/L011.
  TPU-L015  batch acquired but not closed/unregistered on every path: a
            handle producer declares MORE consumers than the plan has
            parents for it (close never fires), or declares it never
            closes at all — a plan-level leak.

``verify against the ledger``: devtools/run_lint.py --memsan replays the
golden corpus with the shadow ledger installed and asserts measured peak
device bytes <= the static bound and a clean ledger after every query;
tests/test_memsan.py adds the anti-vacuity injections.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .. import config as cfg
from .absdomain import AbstractState, schema_width
from .diagnostics import ERROR, Diagnostic, register_rule

# ---------------------------------------------------------------------------
# the ownership lattice (shared with memory/memsan.py's runtime ledger)
# ---------------------------------------------------------------------------

# states
UNBORN = "unborn"
ALLOCATED = "allocated"
REGISTERED = "registered"          # catalog-registered, spillable
PINNED = "pinned"                  # pin-cache resident, evictable
SPILLED = "spilled"                # demoted to host/disk tier
CLOSED = "closed"

# events
ALLOC = "alloc"
REGISTER = "register"
PIN = "pin"
SPILL = "spill"
UNSPILL = "unspill"
MATERIALIZE = "materialize"        # get_batch: read access to the payload
CLOSE = "close"
EVICT = "evict"                    # pin-cache eviction under pressure

# (state, event) -> next state.  A pair absent here is a lifecycle
# violation: the runtime ledger raises on it, the static pass reports
# the rule that predicts it (MATERIALIZE after CLOSE = TPU-L013's
# runtime shape; a terminal state that never reaches CLOSE/EVICT =
# TPU-L015's).
LIFECYCLE: Dict[tuple, str] = {
    (UNBORN, ALLOC): ALLOCATED,
    (ALLOCATED, REGISTER): REGISTERED,
    (ALLOCATED, PIN): PINNED,
    (ALLOCATED, MATERIALIZE): ALLOCATED,
    (ALLOCATED, CLOSE): CLOSED,
    (REGISTERED, MATERIALIZE): REGISTERED,
    (REGISTERED, SPILL): SPILLED,
    (REGISTERED, PIN): PINNED,
    (REGISTERED, CLOSE): CLOSED,
    (PINNED, MATERIALIZE): PINNED,
    (PINNED, EVICT): CLOSED,
    (PINNED, CLOSE): CLOSED,
    (SPILLED, SPILL): SPILLED,         # host tier -> disk tier
    (SPILLED, MATERIALIZE): SPILLED,   # read via deserialize is legal
    (SPILLED, UNSPILL): REGISTERED,
    (SPILLED, CLOSE): CLOSED,
}

# states whose payload occupies device memory (the ledger's accounting
# and the static bound agree on this set)
DEVICE_RESIDENT = frozenset({ALLOCATED, REGISTERED, PINNED})


def lifecycle_next(state: str, event: str) -> Optional[str]:
    """Next state, or None when (state, event) is a violation."""
    return LIFECYCLE.get((state, event))


# ---------------------------------------------------------------------------
# rule registrations
# ---------------------------------------------------------------------------

L013 = register_rule(
    "TPU-L013", ERROR, "use-after-close along an execution path",
    "A subtree that hands out catalog-registered batch handles is "
    "consumed by more parents than its declared consumer count "
    "(Exec.memory_effects): the last declared consumer closes the "
    "handles, so every later consumer materializes closed buffers — "
    "the shared-subtree flavor of the stale-rewrite class "
    "(with_new_children/reuse surgery duplicated a consumer without "
    "updating the producer's count).  The runtime shadow ledger "
    "(spark.rapids.tpu.memsan.enabled) catches the same violation as "
    "it happens; this rule predicts it at plan time.")

L014 = register_rule(
    "TPU-L014", ERROR, "subtree peak device bytes exceed the HBM budget",
    "The conservative peak-device-bytes bound for this subtree — "
    "derived from the same row model the cost-based optimizer uses, "
    "widened by real batch padding — exceeds "
    "spark.rapids.tpu.memsan.hbmBudgetBytes: the query would OOM "
    "mid-flight.  The pre-flight repairs it by forcing the operator's "
    "out-of-core path (a bounded spill budget) where one exists, or "
    "downgrading the subtree to the host engine.")

L015 = register_rule(
    "TPU-L015", ERROR, "batch acquired but never closed on some path",
    "A handle-producing operator declares a consumer count the plan "
    "never reaches (or declares it never closes at all): its "
    "registered device buffers survive the query — a plan-level leak "
    "the SpillCatalog leak tracker would only report after the damage. "
    "Re-derive the producer's consumer count from the plan, or route "
    "ownership to a consumer that closes.")


# ---------------------------------------------------------------------------
# byte model: the engine's REAL batch footprint for an abstract state
# ---------------------------------------------------------------------------

def padded_partition_bytes(st: AbstractState) -> float:
    """Device bytes of ONE partition's batch as the engine actually
    allocates it: rows padded to the capacity bucket, one validity lane
    per column, span buffers at least one char/row bucket.  This is what
    keeps the static bound >= the shadow ledger's measured bytes (which
    count padded leaf nbytes, not logical rows)."""
    from .. import types as t
    from ..columnar.device import DEFAULT_CHAR_BUCKETS, DEFAULT_ROW_BUCKETS, \
        bucket_for
    rows = st.rows if st.rows is not None else 0.0
    parts = st.num_partitions or 1
    rows_pp = max(rows / max(parts, 1), 1.0)
    cap = float(bucket_for(int(rows_pp), DEFAULT_ROW_BUCKETS))
    # +1 byte/row/column for the validity lane schema_width omits
    width = schema_width(st.dtypes) + len(st.dtypes)
    span_floor = sum(
        float(DEFAULT_CHAR_BUCKETS[0])
        for dt in st.dtypes
        if isinstance(dt, (t.StringType, t.BinaryType, t.ArrayType,
                           t.MapType)))
    return cap * width + span_floor


def total_bytes(st: AbstractState) -> float:
    parts = st.num_partitions or 1
    return padded_partition_bytes(st) * max(parts, 1)


def hbm_budget(conf: cfg.RapidsConf) -> int:
    """The TPU-L014 budget: explicit memsan budget, else the spill
    catalog's device budget, else the catalog's default."""
    b = conf.get(cfg.MEMSAN_HBM_BUDGET)
    if b is not None:
        return b
    b = conf.get(cfg.SPILL_DEVICE_BUDGET)
    if b is not None:
        return b
    return 8 << 30


def spill_budget(conf: cfg.RapidsConf) -> int:
    """The catalog threshold that bounds REGISTERED (spillable) device
    bytes — maybe_spill demotes past it, so spill-managed holds are
    capped here even when the raw input is not."""
    b = conf.get(cfg.SPILL_DEVICE_BUDGET)
    if b is not None:
        return b
    return 8 << 30


# ---------------------------------------------------------------------------
# operator declarations
# ---------------------------------------------------------------------------

class MemoryEffects:
    """Declared device-memory behavior of one operator (per partition
    where not stated otherwise).

    hold              device bytes the operator keeps live while it
                      streams, INCLUDING its in-flight output (None =
                      the default: one padded output batch);
    retained          bytes that stay device-resident AFTER the subtree
                      finished streaming (pinned scan caches, exchange
                      memos) — charged to every ancestor's peak;
    handles           True when the operator hands catalog-registered
                      SpillableBatch handles to a deferred close
                      protocol (SpillBoundaryExec);
    handle_consumers  how many full consumptions the producer waits for
                      before closing its handles;
    closes_handles    False = the operator declares it NEVER closes
                      (unconditional leak unless something else owns);
    note              human-readable model note for format_memory.
    """

    __slots__ = ("hold", "retained", "handles", "handle_consumers",
                 "closes_handles", "note")

    def __init__(self, hold: Optional[float] = None, retained: float = 0.0,
                 handles: bool = False, handle_consumers: int = 1,
                 closes_handles: bool = True, note: str = ""):
        self.hold = hold
        self.retained = retained
        self.handles = handles
        self.handle_consumers = handle_consumers
        self.closes_handles = closes_handles
        self.note = note


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

class MemState:
    """Memory facts for one subtree."""

    __slots__ = ("hold", "retained", "live", "note")

    def __init__(self, hold: float, retained: float, live: float,
                 note: str = ""):
        self.hold = hold          # node's own working set
        self.retained = retained  # node's own post-stream residue
        self.live = live          # subtree peak bound (inclusive)
        self.note = note


class MemResult:
    def __init__(self, budget: int):
        self.budget = budget
        self.states: Dict[int, MemState] = {}
        self.diags: List[Diagnostic] = []

    def state(self, node) -> Optional[MemState]:
        return self.states.get(id(node))

    def bound(self, node) -> Optional[float]:
        st = self.states.get(id(node))
        return st.live if st is not None else None


def _parent_counts(root) -> Dict[int, int]:
    """How many times each node OBJECT is consumed in the plan (a reused
    subtree appears under several parents; the root is consumed once by
    the collect)."""
    counts: Dict[int, int] = {id(root): 1}
    seen: set = set()

    def walk(node):
        for c in node.children:
            counts[id(c)] = counts.get(id(c), 0) + 1
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)

    walk(root)
    return counts


def analyze_memory(root, conf: cfg.RapidsConf,
                   interp=None) -> MemResult:
    """Run the lifetime/peak pass over a converted plan.  Pure — never
    mutates or executes the plan.  `interp` is an InterpResult from
    analysis.interp.infer_plan (computed here when absent): the byte
    model rides its AbstractStates, i.e. the same cost model everywhere.
    """
    from ..exec import base as eb
    if interp is None:
        from .interp import infer_plan
        interp = infer_plan(root, conf)
    budget = hbm_budget(conf)
    result = MemResult(budget)
    parents = _parent_counts(root)
    handle_checked: set = set()  # a shared node is analyzed once per path

    def state_of(node) -> AbstractState:
        st = interp.state(node)
        if st is not None:
            return st
        try:
            return AbstractState(node.output_names, node.output_types,
                                 num_partitions=node.num_partitions)
        except Exception:
            return AbstractState([], [])

    def up(node, path: str) -> MemState:
        here = f"{path} > {node.name}" if path else node.name
        child_mem = [up(c, here) for c in node.children]
        child_abs = [state_of(c) for c in node.children]
        try:
            eff = node.memory_effects(child_abs, conf)
        except Exception:
            eff = None
        if eff is None:
            eff = MemoryEffects()
        hold = eff.hold if eff.hold is not None \
            else padded_partition_bytes(state_of(node))
        live = hold + eff.retained + sum(m.live for m in child_mem)
        mem = MemState(hold, eff.retained, live, eff.note)
        result.states[id(node)] = mem

        # handle-protocol rules: the close is deferred to a declared
        # consumer count; the plan's actual parent count must MATCH it
        if eff.handles and id(node) not in handle_checked:
            handle_checked.add(id(node))
            n_parents = parents.get(id(node), 1)
            if not eff.closes_handles:
                result.diags.append(L015.diag(
                    f"{node.name} registers batch handles it declares "
                    f"it never closes and no consumer takes ownership: "
                    f"~{_kib(hold)} KiB of device buffers survive the "
                    f"query", loc=here, node=node))
            elif n_parents > eff.handle_consumers:
                result.diags.append(L013.diag(
                    f"{node.name} closes its handles after "
                    f"{eff.handle_consumers} consumption(s) but the "
                    f"plan consumes it {n_parents} times: consumer(s) "
                    f"{eff.handle_consumers + 1}..{n_parents} would "
                    f"materialize closed buffers — re-derive the "
                    f"consumer count after the rewrite that shared "
                    f"this subtree", loc=here, node=node))
            elif n_parents < eff.handle_consumers:
                result.diags.append(L015.diag(
                    f"{node.name} waits for {eff.handle_consumers} "
                    f"consumption(s) before closing but the plan only "
                    f"consumes it {n_parents} time(s): the close never "
                    f"fires and ~{_kib(hold)} KiB of registered device "
                    f"buffers leak", loc=here, node=node))
        return mem

    root_mem = up(root, "")

    # TPU-L014 at the deepest over-budget frontier: the node(s) whose own
    # contribution pushes the subtree over, not every ancestor above them
    if root_mem.live > budget:
        def frontier(node, path: str):
            here = f"{path} > {node.name}" if path else node.name
            mem = result.states[id(node)]
            if mem.live <= budget:
                return
            over_children = [c for c in node.children
                             if result.states[id(c)].live > budget]
            if over_children:
                for c in over_children:
                    frontier(c, here)
                return
            result.diags.append(L014.diag(
                f"{node.name} subtree peaks at ~{_kib(mem.live)} KiB "
                f"device bytes (own working set ~{_kib(mem.hold)} KiB) "
                f"against a {_kib(budget)} KiB HBM budget: predicted "
                f"mid-query OOM — force the out-of-core path or "
                f"downgrade the subtree", loc=here, node=node))

        frontier(root, "")
    return result


def _kib(b: float) -> int:
    return max(int(b) >> 10, 1)


# ---------------------------------------------------------------------------
# repair (the pre-flight's L014 path)
# ---------------------------------------------------------------------------

def try_outofcore_repair(root, node, conf: cfg.RapidsConf) -> bool:
    """Force `node`'s out-of-core path with a budget sized so the
    repaired bound fits: operators with a spill-managed fallback (sort,
    aggregate merge) get ``oc_budget`` set — their execute path then
    bounds registered device bytes at it (exec/outofcore.py enforces) —
    and the model's 3x working-set factor lands the subtree under the
    HBM budget.  Returns False when the node has no such path (the
    caller downgrades to host instead)."""
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.sort import SortExec
    if not isinstance(node, (SortExec, TpuHashAggregateExec)):
        return False
    res = analyze_memory(root, conf)
    mem = res.state(node)
    if mem is None:
        return False
    budget = res.budget
    below = mem.live - mem.hold - mem.retained  # children's live total
    slack = budget - below - mem.retained
    if slack <= 4096:
        return False  # even a minimal out-of-core chunk cannot fit
    # the out-of-core working set is ~3x the enforced budget (registered
    # runs at the budget + one raw merge group + its merged copy)
    node.oc_budget = int(slack // 4)
    return True


# ---------------------------------------------------------------------------
# CLI rendering (tools lint --plan --memsan)
# ---------------------------------------------------------------------------

def format_memory(root, result: MemResult) -> str:
    lines: List[str] = [
        f"memsan: HBM budget {_kib(result.budget)} KiB"]

    def walk(node, level: int):
        mem = result.state(node)
        if mem is None:
            desc = "(no state)"
        else:
            ret = f" retained=~{_kib(mem.retained)}KiB" if mem.retained \
                else ""
            note = f" [{mem.note}]" if mem.note else ""
            flag = " OVER-BUDGET" if mem.live > result.budget else ""
            desc = (f"hold=~{_kib(mem.hold)}KiB{ret} "
                    f"peak<=~{_kib(mem.live)}KiB{flag}{note}")
        lines.append(f"{'  ' * level}{node.name}: {desc}")
        for c in node.children:
            walk(c, level + 1)

    walk(root, 0)
    return "\n".join(lines) + "\n"
